"""Benchmark: joint LBFGS calibration throughput (north-star metric #1).

Workload: 62-station LOFAR-like array, 100 source clusters, one tile of
60 timeslots x 2 channels — the BASELINE.md north-star shape ("LBFGS
iters/sec/chip, 62-station, 100-cluster"; graded config 1 uses -t 60).
Each LBFGS iteration evaluates the full 100-cluster RIME model (predict
J C J^H summed over clusters) and its gradient by autodiff — the same
work the reference does per iteration with threaded C kernels
(/root/reference/src/lib/Dirac/robust_lbfgs.c:94,155; the joint pass of
lmfit.c:1019-1037).

``vs_baseline``: ratio against the same algorithm in float64 on the
host CPU via the JAX CPU backend (the reference is CPU double +
pthreads; no published numbers exist in the reference repo —
BASELINE.md).  The CPU figure was measured on this machine and is
pinned below so the driver run only measures the TPU.  Set
SAGECAL_BENCH_MEASURE_CPU=1 to re-measure it live in a subprocess.

Platform handling (round-2 fix): the axon sitecustomize force-selects
the TPU platform, and a wedged axon tunnel HANGS backend init (verify
skill gotchas 1 & 5).  main() probes the default backend in a
throwaway subprocess with a timeout and falls back to the CPU platform
— the benchmark always prints its JSON line, with a "platform" field
saying what it actually ran on.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# Measured 2026-07-30 on this container's CPU (JAX CPU backend, float64,
# same workload/shape as below, single run after compile):
#   python -c "import bench; print(bench._measure_cpu_subprocess(60))"
# pinned per workload shape (tilesz -> iters/sec, f64 CPU):
#   60 = the north-star shape (BASELINE.md graded config 1, -t 60);
#        re-measured SOLO with the round-5 trial-point value_and_grad
#        fusion: 0.0782 it/s (history: round-2 layout 0.0142,
#        rows-minor 0.0212, round-3 factored predict 0.0555, round-4
#        fused value_and_grad 0.0633 — every TPU-first restructuring
#        also sped up the CPU)
#    5 = the small shape used when falling back to the CPU platform
#        (re-measured same code: 1.0872; round-4 0.888, round-3 0.663,
#        round-1 0.407)
_CPU_BASELINE_PINNED = {60: 0.0782, 5: 1.0872}

# Our own solver at the north-star shape on this host's CPU, measured
# SOLO (f64 is the same measurement as the pinned baseline above; f32
# same program): recorded so the north-star-shape comparison vs the
# measured reference C rides in the bench artifact even when the TPU
# tunnel forces the small-shape fallback.
_OURS_CPU_NORTH_STAR = {"f64": _CPU_BASELINE_PINNED[60], "f32": 0.1441}

# The ACTUAL reference C solver timed at the north-star shape:
# bfgsfit_visibilities (lmfit.c:1126, robust R-LBFGS mode 2) on the
# channel-averaged tile, compiled from the mounted reference sources and
# measured SOLO on this host by `python ref_bench.py` 2026-07-30:
# 20 iterations in 1535 s = 0.013 it/s (overhead-subtracted; res
# 7.2e-3 -> 3.9e-4, rc=0).  Semantics caveats in ref_bench.py's
# docstring — chiefly that the reference evaluates ONE channel-averaged
# model per iteration vs our TWO channels, i.e. about half the
# model-evaluation work, and each code runs its own line search.
# tilesz=5 (the CPU-fallback shape) measured the same way:
# REF_BENCH_TILESZ=5 -> 20 iters in 82.9 s = 0.2411 it/s.
_REF_CPU_PINNED = {60: 0.013, 5: 0.2411}
_REF_CPU_THREADS = 1  # this container exposes a single core

# Cost-evaluation-equivalents the REFERENCE burns per LBFGS iteration:
# one hand-coded gradient (~1 cost-equivalent of threaded C,
# robust_lbfgs.c:155) plus the Fletcher/cubic line search's typical
# ~0.5 extra cost calls once bracketed (lbfgs.c:116-443).  Used for the
# equal-work ratio below.
_REF_COST_EVALS_PER_ITER = 1.5

# Ours, MEASURED (2026-07-31, instrumented 20-iteration run of this
# bench workload): 18/20 iterations accept the first Armijo trial (one
# fused value_and_grad = ~2 cost-equivalents); the 2 early rejections
# add 10 cost-only halvings + 2 extra (f, g) passes -> 2.70 effective
# cost-equivalents per iteration.  The ideal-accept floor is 2.1.
_OUR_COST_EVALS_PER_ITER_MEASURED = 2.7

NSTATIONS = 62
NCLUSTERS = 100
TILESZ = 60
NCHAN = 2
LBFGS_ITERS = 20
REPEATS = 3

# Device peaks live in sagecal_tpu/obs/roofline.py (PEAK_TABLE, keyed
# by jax device_kind) — the bench looks its own hardware up instead of
# assuming v5e, so a non-v5e backend never reports a silently-wrong MFU.

# Cost path selector, resolved ONCE so run() and the JSON record can't
# diverge: 1 = fused Pallas RIME kernel, 0 = XLA predict path.  Default
# (env unset): fused on the TPU — hardware-validated round 5 at 40.6
# it/s vs 14.8 for the XLA path — and XLA on the CPU fallback, where
# interpret-mode Pallas would be orders slower.  run() resolves the
# platform-dependent default itself (from the device it actually runs
# on), so importing bench and calling run() directly picks the same
# path main() would.
_FUSED_ENV = os.environ.get("SAGECAL_BENCH_FUSED")
FUSED = bool(int(_FUSED_ENV)) if _FUSED_ENV is not None else False

# Store the (static) coherency stack as bfloat16, upcast to f32 inside
# the jitted cost: halves the dominant HBM stream of the bandwidth-
# bound evaluation.  Gains/visibilities/accumulation stay f32.
# Accuracy note: bf16 has ~3 significant digits — fine for the bench's
# throughput claim and for early EM iterations, NOT for the final
# 1e-6-bar solve; production keeps f32 coherencies by default.
COH_BF16 = bool(int(os.environ.get("SAGECAL_BENCH_COH_BF16", "0")))


from sagecal_tpu.utils.platform import (  # noqa: E402
    cpu_device as _cpu_device,
    probe_default_backend as _probe_default_backend,
)


def build_workload(dtype=np.float32, tilesz=TILESZ):
    """Synthesize the 62-stn/100-cluster tile.  MUST run on the CPU
    backend: eager complex ops and complex host<->device transfers are
    unimplemented on the axon TPU backend (verify skill gotcha 3)."""
    import jax.numpy as jnp

    from sagecal_tpu.core.types import jones_to_params
    from sagecal_tpu.io.simulate import corrupt_and_observe, make_visdata, random_jones
    from sagecal_tpu.ops.rime import point_source_batch
    from sagecal_tpu.solvers.sage import build_cluster_data

    rng = np.random.default_rng(0)
    f0 = 150e6
    fdt = jnp.float32 if dtype == np.float32 else jnp.float64
    cdt = np.complex64 if dtype == np.float32 else np.complex128
    data = make_visdata(
        nstations=NSTATIONS, tilesz=tilesz, nchan=NCHAN, freq0=f0, dtype=dtype
    )
    ll = rng.uniform(-0.05, 0.05, NCLUSTERS)
    mm = rng.uniform(-0.05, 0.05, NCLUSTERS)
    flux = rng.uniform(0.5, 5.0, NCLUSTERS)
    clusters = [
        point_source_batch([ll[k]], [mm[k]], [flux[k]], f0=f0, dtype=fdt)
        for k in range(NCLUSTERS)
    ]
    jones = random_jones(NCLUSTERS, NSTATIONS, seed=1, amp=0.15, dtype=cdt)
    data = corrupt_and_observe(data, clusters, jones=jones, noise_sigma=1e-3)
    cdata = build_cluster_data(data, clusters, [1] * NCLUSTERS)
    p0 = jones_to_params(
        random_jones(NCLUSTERS, NSTATIONS, seed=2, amp=0.0, dtype=cdt)
    )[:, None, :]
    return data, cdata, p0


def make_step(data, cdata, nu=5.0):
    """Jitted LBFGS step over a REAL-array boundary: complex packed by
    CONCATENATING re/im along the component axis — (F, 8, rows) /
    (M, F, 8, rows), rows minor-most, so the TPU (8, 128) tile pads
    nothing (axon cannot transfer complex; a trailing re/im axis of 2
    would pad the buffer 64x — the round-2 HBM OOM)."""
    import jax
    import jax.numpy as jnp

    from sagecal_tpu.solvers.lbfgs import lbfgs_fit
    from sagecal_tpu.solvers.sage import predict_full_model

    M, nchunk, n8 = NCLUSTERS, 1, 8 * NSTATIONS

    # named so the lowered hlo_module ("jit_bench_step_xla") joins the
    # note_compile ledger row in `diag roofline` — the devprof parser
    # keys per-op device time by module name
    @jax.jit
    def bench_step_xla(vis_ri, mask, coh_ri, p0):
        # true-f32 linear algebra (TPU f32 matmuls default to bf16 MXU
        # passes; the production solver runs HIGHEST — bench the same)
        with jax.default_matmul_precision("highest"):
            vis = jax.lax.complex(vis_ri[:, :4, :], vis_ri[:, 4:, :])
            # upcast to the RUN dtype (bf16 -> f32 under COH_BF16;
            # keeps the f64 CPU-baseline path genuinely f64)
            coh_f = coh_ri.astype(vis_ri.dtype)
            coh = jax.lax.complex(coh_f[:, :, :4, :], coh_f[:, :, 4:, :])
            d = data.replace(vis=vis, mask=mask)
            c = cdata._replace(coh=coh)

            def cost_fn(pflat):
                pa = pflat.reshape(M, nchunk, n8)
                model = predict_full_model(pa, c, d)
                diff = (vis - model) * mask[:, None, :]
                e2 = jnp.real(diff) ** 2 + jnp.imag(diff) ** 2
                return jnp.sum(jnp.log1p(e2 / nu))

            fit = lbfgs_fit(cost_fn, None, p0.reshape(-1),
                            itmax=LBFGS_ITERS, M=7)
        return fit.p, fit.cost, fit.iterations

    return bench_step_xla


def make_fused_step(data, nu=5.0, tile=None):
    """LBFGS step whose VALUE AND GRAD run entirely inside the fused
    OBJECTIVE kernel (ops/rime_kernel.py fused_cost_packed_chunked):
    predict, masked residual, Student's-t weighting and the scalar
    reduction in one pass over the coherency stack — no model-sized
    buffer ever crosses HBM, forward or backward.  Returns (prep, step):
    ``prep`` pads rows/clusters to kernel alignment ONCE (run it before
    the timing loop, keep results device-resident); ``step`` takes the
    padded arrays.  Default on TPU since the round-5 hardware validation
    (SAGECAL_BENCH_FUSED=0 opts back to XLA).

    The antenna-index planes are packed on the host and transferred
    ONCE at make time (device-resident constants reused by every prep/
    step call — they were previously re-packed per prep call), and
    stop_gradient lives inside the kernel wrappers, not the step trace.

    tile defaults to FULL_CLUSTER_TILE (128, the largest tile whose
    BACKWARD kernel fits the v5e 16 MB scoped-VMEM limit at Mp=104 —
    hardware-verified round 5); rows are chunked into
    rime_kernel.MAX_GRID_ROWS blocks so each Mosaic grid stays short
    (north star: 4 chunks x 28416 rows = R=222 grids at tile 128,
    the configuration of the banked 40.6 it/s)."""
    import jax
    import jax.numpy as jnp

    from sagecal_tpu.core.types import params_to_jones
    from sagecal_tpu.ops.rime_kernel import (
        FULL_CLUSTER_TILE, chunked_rowsp, fused_cost_packed_chunked,
        pack_gain_tables, pad_to,
    )
    from sagecal_tpu.solvers.lbfgs import lbfgs_fit

    tile = FULL_CLUSTER_TILE if tile is None else tile
    M, n8 = NCLUSTERS, 8 * NSTATIONS
    mp = pad_to(M, 8)
    rows = data.vis.shape[-1]
    rowsp = chunked_rowsp(rows, tile)
    antp = np.zeros((1, rowsp), np.int32)
    antq = np.zeros((1, rowsp), np.int32)
    antp[0, :rows] = np.asarray(data.ant_p)
    antq[0, :rows] = np.asarray(data.ant_q)
    # hoisted device-resident constants: one 4-byte-per-row transfer at
    # make time instead of a re-pack on every prep call
    antp_d = jnp.asarray(antp)
    antq_d = jnp.asarray(antq)

    @jax.jit
    def prep(vis_ri, mask, coh_ri):
        vis_p = jnp.pad(vis_ri, ((0, 0), (0, 0), (0, rowsp - rows)))
        mask_p = jnp.pad(mask, ((0, 0), (0, rowsp - rows)))
        coh_p = jnp.pad(coh_ri, ((0, mp - M), (0, 0), (0, 0),
                                 (0, rowsp - rows)))
        return vis_p, mask_p, coh_p, antp_d, antq_d

    # named for the devprof trace <-> ledger join, like bench_step_xla
    @jax.jit
    def bench_step_fused(vis_p, mask_p, coh_p, antp_d, antq_d, p0):
        # kernel dots are HIGHEST internally; this covers the LBFGS
        # two-loop/line-search vector algebra (production precision).
        # coh/vis/mask stop_gradient happens inside the chunked cost
        # wrapper (they are constants of the solve).
        with jax.default_matmul_precision("highest"):

            def cost_fn(pflat):
                jones = params_to_jones(pflat.reshape(M, 1, n8))[:, 0]
                tre, tim = pack_gain_tables(jones, mp)
                return fused_cost_packed_chunked(
                    tre, tim, coh_p, antp_d, antq_d, vis_p, mask_p, nu,
                    tile)

            fit = lbfgs_fit(cost_fn, None, p0.reshape(-1),
                            itmax=LBFGS_ITERS, M=7)
        return fit.p, fit.cost, fit.iterations

    return prep, bench_step_fused


def analytic_flops_per_cost_eval(tilesz=TILESZ):
    """Analytic FLOPs of ONE cost evaluation (predict_full_model +
    robust cost), counting a complex multiply as 6 real FLOPs and a
    complex add as 2.  The driver-visible throughput derives from this,
    NOT from ``cost_analysis()`` — round 2 measured the axon backend
    reporting ~35 MFLOP for this ~2.5 GFLOP evaluation.

    Per (cluster, channel, row): 16 coefficient-x-coherency complex
    multiplies + 15 accumulate adds (the V = J_p C J_q^H expansion),
    plus 16 per-(cluster, row) coefficient products.
    """
    rows = NSTATIONS * (NSTATIONS - 1) // 2 * tilesz
    model = NCLUSTERS * NCHAN * rows * (16 * 6 + 15 * 2)
    coefs = NCLUSTERS * rows * 16 * 6
    residual = NCHAN * rows * 4 * 10  # diff, mask, |.|^2, log1p(approx)
    return model + coefs + residual


def hbm_bytes_per_cost_eval(tilesz=TILESZ, coh_bytes_per_cplx=8,
                            vis_bytes_per_cplx=8):
    """Minimum HBM traffic of one cost evaluation: the coherency stack
    read once + visibilities/mask — the workload is bandwidth-bound
    (elementwise VPU math; 2x2 RIME products never reach the MXU).
    Separate coh/vis byte widths: COH_BF16 halves only the stack."""
    rows = NSTATIONS * (NSTATIONS - 1) // 2 * tilesz
    coh = NCLUSTERS * NCHAN * 4 * rows * coh_bytes_per_cplx
    vis = NCHAN * 4 * rows * vis_bytes_per_cplx + NCHAN * rows * 4
    return coh + vis


def run(dtype=np.float32, repeats=REPEATS, want_flops=False, tilesz=TILESZ,
        measure_warm_start=False, coh_bf16=None):
    """One measured bench pass.  ``coh_bf16`` overrides the
    SAGECAL_BENCH_COH_BF16 env default so main() can re-run the bf16
    variant row in-process without env mutation."""
    import jax

    if coh_bf16 is None:
        coh_bf16 = COH_BF16

    with jax.default_device(_cpu_device()):
        data, cdata, p0 = build_workload(dtype, tilesz)
        # np conversions MUST stay inside the default_device block:
        # jax.default_device yields UNCOMMITTED arrays, so .real/.imag
        # outside it would dispatch to the axon TPU whose complex
        # host<->device transfer is unimplemented (the round-2 bench
        # failure, BENCH_r02.json)
        vis_ri = np.concatenate(
            [np.asarray(data.vis.real), np.asarray(data.vis.imag)], axis=-2
        )
        coh_ri = np.concatenate(
            [np.asarray(cdata.coh.real), np.asarray(cdata.coh.imag)], axis=-2
        )
        mask = np.asarray(data.mask)
        p0_h = np.asarray(p0)
    # Resident inputs: numpy arguments are RE-TRANSFERRED host->device on
    # every call — measured 26 s/call for the 726 MB coherency stack
    # through the axon tunnel vs 74 ms for the whole predict once the
    # arrays are device-resident.  device_put once, time steady state.
    dev = jax.devices()[0]
    # env unset -> platform-dependent default from the device this run
    # actually targets (fused Pallas on TPU, XLA on CPU)
    global FUSED
    if _FUSED_ENV is None:
        FUSED = dev.platform not in ("cpu",)
    if coh_bf16:
        import ml_dtypes

        # fused path: the kernel upcasts bf16 planes to f32 at the VMEM
        # load (rime_kernel._load_coh_planes); XLA path: make_step
        # upcasts the whole stack inside the jitted cost
        coh_ri = coh_ri.astype(ml_dtypes.bfloat16)
    args = tuple(jax.device_put(a, dev) for a in (vis_ri, mask, coh_ri, p0_h))
    # NOTE: block_until_ready is a NO-OP on axon; the transfers are
    # actually drained by the untimed warm-up call + host read below,
    # which is why the timing loop never observes them.
    jax.block_until_ready(args)
    if FUSED:
        prep, step = make_fused_step(data)
        args = (*prep(*args[:3]), args[3])
    else:
        step = make_step(data, cdata)
    from sagecal_tpu.obs.devprof import device_profile
    from sagecal_tpu.obs.perf import device_memory_snapshot, note_compile
    from sagecal_tpu.utils.profiling import trace

    perf = {"flops": None, "bytes_accessed": None,
            "peak_device_memory_bytes": None}
    if want_flops:
        # AOT-compile once and reuse the executable for the timing loop
        # (calling the jit wrapper after .lower().compile() would trace
        # and compile the identical program a second time).  The
        # cost_analysis() figures are recorded for transparency only —
        # round 2 measured flops untrustworthy on axon (35 MFLOP for a
        # ~2.5 GFLOP evaluation); the headline uses analytic FLOPs.
        try:
            t0 = time.perf_counter()
            lowered = step.lower(*args)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            perf["flops"] = float(cost.get("flops", 0.0)) or None
            perf["bytes_accessed"] = (
                float(cost.get("bytes accessed", 0.0)) or None
            )
            # report through the obs/perf channel so `diag perf` on the
            # bench event log attributes this compile like any other
            note_compile("bench_step_fused" if FUSED else "bench_step_xla",
                         t1 - t0, t2 - t1, perf["flops"],
                         perf["bytes_accessed"])
            step = compiled
        except Exception:
            pass
    # SAGECAL_PROFILE_DIR additionally captures an XLA trace of the
    # warm-up + timing loop (no-op when unset); SAGECAL_DEVICE_PROFILE /
    # --device-profile captures the devprof trace our own roofline
    # parser ingests (`diag roofline`).  Only one jax trace can be live
    # — device_profile skips itself (with a flight note) when the
    # TensorBoard trace already owns the profiler.
    with trace(), device_profile():
        out = step(*args)  # compile (if not AOT) + first run
        iters = int(np.asarray(out[2]))  # host read = the only real sync
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = step(*args)
            # Sync by transferring the SCALAR cost to host:
            # jax.block_until_ready is a NO-OP on the axon backend
            # (measured 0.2 ms for a 2.6 s computation) — only a host
            # read observes completion.  A 4-byte transfer adds ~ms of
            # tunnel RTT, negligible against the solve.
            float(np.asarray(out[1]))
            times.append(time.perf_counter() - t0)
    snap = device_memory_snapshot(dev)
    if snap.get("source") == "device":
        perf["peak_device_memory_bytes"] = snap.get("peak_bytes_in_use")
    dt = float(np.median(times))
    warm = None
    if measure_warm_start:
        # Elastic warm-start acceleration (ROADMAP item 4): iterations
        # to converge cold (from p0) vs warm (from the converged gains
        # plus 1% drift — the temporal smoothness a tile chain or a
        # resume exploits).  The f32 robust cost never reaches the 1e-9
        # gradient-norm stop, so convergence is COST-based: iterations
        # until the cost is within 5% of the fully chained optimum,
        # sampled in itmax-iteration blocks of the SAME compiled
        # program (no new compile classes near the tunnel).
        def _chain(p_start, blocks):
            costs, its, p_cur = [], [], p_start
            for _ in range(blocks):
                o = step(*args[:-1], p_cur)
                costs.append(float(np.asarray(o[1])))
                its.append(int(np.asarray(o[2])))
                p_cur = o[0].reshape(p0_h.shape).astype(p0_h.dtype)
            return costs, its, p_cur

        def _iters_to(costs, its, target):
            tot = 0
            for c, it in zip(costs, its):
                tot += max(it, 1)
                if c <= target:
                    return tot
            return tot

        # args[-1] is the initial-gains argument on both the XLA and
        # the fused (prep-rebound) paths
        costs_c, its_c, p_conv = _chain(args[-1], 10)
        target = min(costs_c) * 1.05
        p_host = np.asarray(p_conv)
        drift = np.random.default_rng(7).standard_normal(p_host.shape)
        p_warm = jax.device_put(
            (p_host + 0.01 * np.abs(p_host).mean() * drift)
            .astype(p0_h.dtype), dev)
        costs_w, its_w, _ = _chain(p_warm, 4)
        iters_cold = _iters_to(costs_c, its_c, target)
        iters_warm = _iters_to(costs_w, its_w, target)
        warm = {
            "iters_cold": iters_cold,
            "iters_warm": iters_warm,
            "speedup": round(max(iters_cold, 1) / max(iters_warm, 1), 3),
        }
    return max(iters, 1) / dt, iters, dt, perf, warm


def _measure_cpu_subprocess(tilesz=TILESZ, timeout=1800.0):
    """Re-measure the CPU f64 baseline in a fresh process (optional)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("SAGECAL_BENCH_FUSED", "SAGECAL_BENCH_COH_BF16")}
    code = (
        "import jax, numpy as np; jax.config.update('jax_platforms','cpu');"
        "jax.config.update('jax_enable_x64', True);"
        f"import bench; v,i,dt,_,_w = bench.run(np.float64, repeats=1, tilesz={tilesz});"
        "print('CPUBASE', v)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout, capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )
        for line in r.stdout.splitlines():
            if line.startswith("CPUBASE"):
                return float(line.split()[1])
    except Exception:
        pass
    return None


def _admm_comms_main(ndev=8, M=10, N=8, Nf=8, Npoly=2, nadmm=11,
                     cluster_groups=5):
    """Measure the mesh ADMM's per-round collective bytes, grouped vs
    transpose-reduced z-step (arXiv:1504.02147), by AOT-compiling both
    programs on ``ndev`` virtual CPU devices and walking the compiled
    HLO (obs/perf.collective_cost_analysis) — no execution, so the
    numbers are the program's actual collective schedule, not a timing.
    Runs in the comms-bench SUBPROCESS (see run_admm_comms_bench);
    prints one ADMMCOMMS JSON line."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    jax.config.update("jax_enable_x64", True)

    from sagecal_tpu.core.types import jones_to_params
    from sagecal_tpu.io.simulate import (
        corrupt_and_observe, make_visdata, random_jones,
    )
    from sagecal_tpu.obs.perf import collective_cost_analysis
    from sagecal_tpu.ops.rime import point_source_batch
    from sagecal_tpu.parallel import consensus
    from sagecal_tpu.parallel.mesh import make_admm_mesh_fn, stack_for_mesh
    from sagecal_tpu.solvers.lm import LMConfig
    from sagecal_tpu.solvers.sage import build_cluster_data

    freqs = np.linspace(120e6, 180e6, Nf)
    f0 = 150e6
    clusters = [
        point_source_batch([0.02 * k - 0.1], [0.01 * k], [1.0 + 0.1 * k],
                           f0=f0, dtype=jnp.float64)
        for k in range(M)
    ]
    bands, p0s = [], []
    for f in range(Nf):
        data = make_visdata(nstations=N, tilesz=2, nchan=1, freq0=f0,
                            seed=f, dtype=np.float64)
        jones = random_jones(M, N, seed=f, amp=0.2, dtype=np.complex128)
        data = corrupt_and_observe(data, clusters, jones=jnp.asarray(jones),
                                   noise_sigma=1e-4, seed=f)
        data = data.replace(freqs=jnp.asarray([freqs[f]], jnp.float64))
        bands.append((data, build_cluster_data(data, clusters, [1] * M)))
        p0s.append(jones_to_params(random_jones(
            M, N, seed=500, amp=0.0, dtype=np.complex128))[:, None, :])
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("freq",))
    B = consensus.setup_polynomials(freqs, f0, Npoly,
                                    consensus.POLY_ORDINARY)
    args = (stack_for_mesh([b[0] for b in bands]),
            stack_for_mesh([b[1] for b in bands]),
            jnp.stack(p0s), jnp.full((Nf, M), 20.0, jnp.float64),
            jnp.asarray(B))

    def bytes_of(ccfg):
        fn = make_admm_mesh_fn(mesh, nadmm=nadmm, max_emiter=1,
                               plain_emiter=1, lm_config=LMConfig(itmax=4),
                               bb_rho=False, consensus_cfg=ccfg)
        comp = fn.inner_jit.lower(*args).compile()
        return collective_cost_analysis(comp)

    g = bytes_of(None)
    r = bytes_of(consensus.ConsensusConfig(
        zstep="reduced", cluster_groups=cluster_groups))
    per_g = g["collective_bytes_per_round"]
    per_r = r["collective_bytes_per_round"]
    print("ADMMCOMMS " + json.dumps({
        "admm_collective_bytes_per_round": per_r,
        "admm_collective_bytes_per_round_grouped": per_g,
        "admm_collective_bytes_reduction": round(per_g / max(per_r, 1), 3),
        "admm_collective_ops_per_round": r["collective_ops_per_round"],
        "shape": {"ndev": ndev, "M": M, "N": N, "Nf": Nf, "Npoly": Npoly,
                  "nadmm": nadmm, "cluster_groups": cluster_groups},
    }))


def run_admm_comms_bench(timeout=900.0):
    """The mesh-consensus communication row: per-round collective bytes
    of the transpose-reduced z-step and its reduction over the grouped
    baseline, at the 8-band shape the ISSUE gates on.  Pure AOT HLO
    accounting in a fresh subprocess (8 virtual CPU devices — the
    collective schedule is platform-independent program structure), so
    the row is deterministic and rides CPU-fallback bench runs too.
    Returns the ADMMCOMMS record dict or None."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    code = "import bench; bench._admm_comms_main()"
    try:
        rr = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout, capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=env,
        )
        for line in rr.stdout.splitlines():
            if line.startswith("ADMMCOMMS "):
                return json.loads(line[len("ADMMCOMMS "):])
        sys.stderr.write(
            f"bench: admm comms bench produced no row "
            f"(rc {rr.returncode}): {rr.stderr[-400:]}\n")
    except Exception as exc:
        sys.stderr.write(f"bench: admm comms bench failed: {exc}\n")
    return None


def run_serve_bench(batch=8, repeats=5, device=None,
                    nstations=62, tilesz=1, nclusters=2,
                    fused=False, coh_dtype="f32"):
    """Serve-path throughput: ``batch`` independent same-shape solves
    dispatched as ONE vmapped program (through the serve executable
    cache) vs the same solves as a sequential ``solve_tile`` loop.

    The GATED shape is N=62 stations (one timeslot per tile) — the
    north-star station count, so the serving win is guarded in the
    regime the paper claims, not only in the tiny overhead-bound class.
    The historical N=16 shape (each solve too small to cover the
    per-dispatch floor; batching measured ~5x there on this host's
    single CPU core) still rides every bench run as an UNGATED history
    row — the bucketer decides per request, the bench pins both
    classes.  Both sides are timed WARM (compiles excluded) and both
    include their host-side packing — the sequential loop packs per
    call, the batched path stacks the whole bucket — so the ratio is
    the end-to-end serve win, not a kernel-only number.

    ``fused``/``coh_dtype`` thread the serve routing knobs through:
    the batch is dispatched through :func:`sagecal_tpu.solvers.batched.
    choose_batched_path` exactly like the service, and the record
    stamps the kernel path that ACTUALLY executed (``kernel_path``:
    xla / fused / fused_batch, with the routing reason) so a silent
    capability fallback can never be mistaken for a kernel win.

    Returns a record dict: ``solves_per_sec_per_chip`` (batched,
    higher-better), ``serve_batch_speedup`` (batched vs sequential
    throughput, higher-better), ``serve_p50_latency_s`` (median batch
    dispatch wall time, lower-better) — all gate-able via `diag gate`.
    """
    import statistics
    import time as _time

    import jax
    import jax.numpy as jnp

    from sagecal_tpu.core.types import jones_to_params
    from sagecal_tpu.io.simulate import corrupt_and_observe, make_visdata, random_jones
    from sagecal_tpu.ops.rime import point_source_batch
    from sagecal_tpu.serve.bucket import bucket_of
    from sagecal_tpu.serve.cache import ExecutableCache
    from sagecal_tpu.solvers.batched import choose_batched_path
    from sagecal_tpu.solvers.sage import SageConfig, build_cluster_data, solve_tile

    # ---- build `batch` distinct small workloads (CPU backend: eager
    # complex ops are unimplemented on the axon TPU — same constraint
    # as build_workload)
    rng = np.random.default_rng(11)
    f0 = 150e6
    entries = []
    with jax.default_device(_cpu_device()):
        for b in range(batch):
            data = make_visdata(nstations=nstations, tilesz=tilesz,
                                nchan=1, freq0=f0, dtype=np.float32)
            ll = rng.uniform(-0.05, 0.05, nclusters)
            mm = rng.uniform(-0.05, 0.05, nclusters)
            flux = rng.uniform(0.5, 5.0, nclusters)
            clusters = [
                point_source_batch([ll[k]], [mm[k]], [flux[k]], f0=f0,
                                   dtype=jnp.float32)
                for k in range(nclusters)
            ]
            jones = random_jones(nclusters, nstations, seed=100 + b,
                                 amp=0.15, dtype=np.complex64)
            data = corrupt_and_observe(data, clusters, jones=jones,
                                       noise_sigma=1e-3)
            cdata = build_cluster_data(data, clusters, [1] * nclusters)
            p0 = np.asarray(jones_to_params(
                random_jones(nclusters, nstations, seed=0, amp=0.0,
                             dtype=np.complex64))[:, None, :])
            key = np.asarray(jax.random.PRNGKey(200 + b))
            entries.append((data, cdata, p0, key))

    cfg = SageConfig(max_emiter=1, max_iter=2, max_lbfgs=4,
                     solver_mode=1, collect_telemetry=False,
                     collect_quality=False,
                     use_fused_predict=fused, coh_dtype=coh_dtype)
    valid = np.ones(batch, bool)  # every bench lane is a real request

    def stack_bucket():
        data_b = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]),
            *[e[0].replace(vis=None) for e in entries])
        cdata_b = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x) for x in xs]),
            *[e[1]._replace(coh=None) for e in entries])
        vis = np.stack([np.asarray(e[0].vis) for e in entries])
        coh = np.stack([np.asarray(e[1].coh) for e in entries])
        p0 = np.stack([e[2] for e in entries])
        keys = np.stack([e[3] for e in entries])
        return data_b, cdata_b, vis, coh, p0, keys

    def run_sequential():
        t0 = _time.perf_counter()
        for data, cdata, p0, key in entries:
            out = solve_tile(data, cdata, p0.copy(), cfg, key=key,
                             device=device)
            np.asarray(out.p)  # host materialize = request completion
        return _time.perf_counter() - t0

    def run_batched(fn):
        t0 = _time.perf_counter()
        data_b, cdata_b, vis, coh, p0, keys = stack_bucket()
        args = (data_b, cdata_b, vis.real, vis.imag, coh.real, coh.imag,
                p0, cfg, keys, valid)
        if device is not None:
            args = jax.device_put(args, device)
        out = fn(*args)
        np.asarray(out.p)
        return _time.perf_counter() - t0

    # route exactly like the service: host-side capability check, path
    # baked into the cache entry, decision + reason stamped in the record
    data_b, cdata_b, _, _, p0_b, _ = stack_bucket()
    kernel_path, path_reason = choose_batched_path(data_b, cdata_b, p0_b,
                                                   cfg)
    cache = ExecutableCache()
    bucket = bucket_of(entries[0][0], entries[0][1], entries[0][2])
    fn, _ = cache.get_with_status(
        bucket, "bench", batched_fused=kernel_path == "fused_batch")

    # warm both programs (compile excluded from the timed passes)
    run_sequential()
    run_batched(fn)

    seq_dts = [run_sequential() for _ in range(repeats)]
    bat_dts = [run_batched(fn) for _ in range(repeats)]
    dt_seq = statistics.median(seq_dts)
    dt_bat = statistics.median(bat_dts)
    n_chips = 1  # the batched program occupies exactly one chip

    return {
        "batch": batch,
        "repeats": repeats,
        "shape": bucket.short(),
        "nstations": nstations,
        "kernel_path": kernel_path,
        "kernel_path_reason": path_reason,
        "sequential_solves_per_sec": round(batch / dt_seq, 3),
        "batched_solves_per_sec": round(batch / dt_bat, 3),
        "solves_per_sec_per_chip": round(batch / dt_bat / n_chips, 3),
        "serve_batch_speedup": round(dt_seq / dt_bat, 3),
        "serve_p50_latency_s": round(dt_bat, 5),
        "cache": cache.stats(),
    }


def run_refine_bench(outer_iters=3, nstations=5, tilesz=2):
    """Sky-model refinement row: the bilevel outer loop (implicit
    IFT-adjoint route) recovering a 15%-perturbed source flux through
    the inner gain solve, on the shared simulated-sky fixture.

    Two gate-able numbers (obs/perf.py knows the directions):
    ``refine_flux_err`` — recovered relative flux error after
    ``outer_iters`` outer steps (lower-better; the <1% acceptance bar
    from the refine smoke) — and ``refine_outer_iters_per_sec``
    (higher-better).  Timing includes the compiles: a refine run pays
    them once up front, and three outer steps is exactly the cold-run
    shape the smoke test uses, so the pinned number is an end-to-end
    figure, not a warm-kernel one.  Runs f64 on the CPU backend — the
    gradient acceptance criteria are defined there (implicit-vs-FD at
    <=1e-3 rel needs f64; see USER_MANUAL).
    """
    import time as _time

    import jax

    from sagecal_tpu.data import make_sky, perturb_flux
    from sagecal_tpu.refine import RefineProblem, SkySpec, run_refine

    old_x64 = bool(jax.config.jax_enable_x64)
    jax.config.update("jax_enable_x64", True)
    try:
        with jax.default_device(_cpu_device()):
            sky = make_sky(nstations=nstations, tilesz=tilesz, nchan=1,
                           nclusters=2, sources_per_cluster=2,
                           gain_amp=0.08, noise_sigma=0.0, seed=3,
                           dtype=np.float64)
            clusters = perturb_flux(sky, factor=1.15, cluster=0, source=0)
            problem = RefineProblem(data=sky.data, clusters=clusters,
                                    tables=sky.shapelet_tables,
                                    spec=SkySpec(flux=[(0, 0)]),
                                    ridge=1e-2)
            t0 = _time.perf_counter()
            res = run_refine(problem, outer_iters=outer_iters,
                             gradient="implicit", inner_iters=8,
                             cg_iters=30, damping=1e-6,
                             adjoint_cg_iters=60)
            dt = _time.perf_counter() - t0
        true_flux = float(sky.true_flux[0][0])
        err = abs(float(res.theta[0]) - true_flux) / true_flux
    finally:
        jax.config.update("jax_enable_x64", old_x64)
    return {
        "outer_iters": outer_iters,
        "nstations": nstations,
        "gradient": "implicit",
        "refine_flux_err": float(err),
        "refine_outer_iters_per_sec": round(outer_iters / dt, 4),
        "refine_wall_s": round(dt, 3),
    }


def run_stream_bench(nstations=24, ntime=8, nchan=2, windows=5):
    """Streaming-calibration row: latency-to-first-solution of the
    warm-start chain vs the cold baseline on one synthetic stream.

    Each sliding window is one request whose answer the telescope is
    waiting on, so the serving number is the per-window wall time once
    the chain is warm — ``latency_to_first_solution_s`` is the warm
    chain's steady-state latency (median over the post-compile
    windows; lower-better, gated), and ``stream_warm_speedup`` is the
    cold baseline's steady state over the warm one (higher-better).
    The warm chain must win on BOTH fewer iterations (warm budgets
    e=1/l=4 vs cold e=3/l=10, the realistic asymmetry: a window that
    starts at the previous window's solution needs a fraction of the
    cold budget) and the carried-solution start; a regression in either
    the executable reuse or the chain plumbing shows up here.  Runs on
    the CPU backend (the chain math is f64 there, matching the stream
    smoke's acceptance environment).
    """
    import shutil
    import tempfile

    import jax

    from sagecal_tpu.apps.config import StreamConfig
    from sagecal_tpu.fleet.stream import StreamCalibrator, make_synthetic_stream

    workdir = tempfile.mkdtemp(prefix="sagecal-stream-bench-")
    try:
        ds, sky, cluster = make_synthetic_stream(
            workdir, nstations=nstations, ntime=ntime, nchan=nchan,
            noise_sigma=0.0, seed=7)

        def one(warm: bool):
            cfg = StreamConfig(
                dataset=ds, sky_model=sky, cluster_file=cluster,
                out_dir=os.path.join(
                    workdir, "warm" if warm else "cold"),
                window=2, hop=1, max_windows=windows,
                warm_start=warm, warm_emiter=1, warm_lbfgs=4,
                max_emiter=3, max_iter=2, max_lbfgs=10,
                solver_mode=1, use_f64=True)
            with jax.default_device(_cpu_device()):
                return StreamCalibrator(
                    cfg, log=lambda *a: None).run()

        cold = one(False)
        warm = one(True)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return {
        "nstations": nstations,
        "windows": warm["windows"],
        "resets": warm["resets"],
        "latency_to_first_solution_s": round(
            warm["latency_to_first_solution_s"], 5),
        "cold_latency_to_first_solution_s": round(
            cold["latency_to_first_solution_s"], 5),
        "stream_warm_speedup": round(
            cold["latency_to_first_solution_s"]
            / max(warm["latency_to_first_solution_s"], 1e-9), 3),
        "first_window_latency_s": round(
            warm["first_window_latency_s"], 3),
    }


def run_fleet_bench(n_requests=6, workers=2, timeout=1200.0):
    """Fleet-serving row: end-to-end throughput of a WARM two-worker
    fleet over a mixed-shape synthetic workload.

    Two coordinator runs over the same request manifest share one AOT
    artifact store: the first run pays every compile and populates the
    store; the second is the steady-state fleet — every worker loads
    its executables (zero compiles, counter-checked from the merged
    metrics snapshots) and the measured wall covers seed + spawn +
    claim + solve + manifest for all ``n_requests`` requests.
    ``fleet_solves_per_sec_2workers`` (higher-better, gated) is
    requests/wall of that warm run.  Subprocess CPU workers — the same
    deployment the fleet smoke exercises.
    """
    import shutil
    import tempfile
    import time as _time

    from sagecal_tpu.obs.aggregate import (
        dedupe_snapshots, merge_states, read_metrics_snapshots,
        state_counter_total,
    )
    from sagecal_tpu.serve.synthetic import make_synthetic_workload

    workdir = tempfile.mkdtemp(prefix="sagecal-fleet-bench-")
    try:
        requests = make_synthetic_workload(
            os.path.join(workdir, "data"), n_requests, n_tenants=2)
        store = os.path.join(workdir, "aot-store")

        def one(tag: str):
            out = os.path.join(workdir, tag)
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       SAGECAL_TELEMETRY="1")
            t0 = _time.perf_counter()
            proc = subprocess.run(
                [sys.executable, "-m", "sagecal_tpu.apps.fleet",
                 "--requests", requests, "--out-dir", out,
                 "--aot-store", store, "--workers", str(workers),
                 "--batch", "4", "-e", "1", "-g", "2", "-l", "4",
                 "-j", "1", "--max-idle", "6", "--f32"],
                env=env, timeout=timeout, capture_output=True)
            dt = _time.perf_counter() - t0
            if proc.returncode != 0:
                raise RuntimeError(
                    f"fleet bench ({tag}) exited "
                    f"{proc.returncode}: {proc.stderr.decode()[-800:]}")
            state = merge_states(
                d["state"] for d in dedupe_snapshots(
                    read_metrics_snapshots(out)))
            return dt, state

        dt_cold, _ = one("cold")
        dt_warm, state = one("warm")
        compiles = state_counter_total(
            state, "serve_executable_cache_compiles_total")
        aot_hits = state_counter_total(
            state, "serve_executable_cache_aot_hits_total")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    return {
        "requests": n_requests,
        "workers": workers,
        "cold_wall_s": round(dt_cold, 2),
        "warm_wall_s": round(dt_warm, 2),
        "fleet_solves_per_sec_2workers": round(n_requests / dt_warm, 4),
        "fleet_warm_compiles": compiles,
        "fleet_warm_aot_hits": aot_hits,
        "fleet_warm_speedup": round(dt_cold / dt_warm, 3),
    }


def run_load_bench(rates=(0.5, 1.5, 6.0), step_s=20.0, workers=2,
                   timeout=1200.0):
    """Fleet load/capacity row: a seeded stepped-ramp load run
    (apps/load.py) against a real two-worker fleet, analysed by
    obs/capacity.py.

    Two load runs share one AOT artifact store: a short warm-up pass
    pays every compile (both tenant buckets), then the MEASURED
    stepped run offers ``rates`` (solves/s) for ``step_s`` each —
    straddling the warm fleet's CPU capacity so the top step genuinely
    overloads (tight SLO deadlines + shed admission policy).  Banked
    gateable headlines, all cpu-wallclock evidence:

    - ``saturation_throughput_solves_per_sec``: best served rate on
      the offered-load curve (the capacity estimate);
    - ``shed_rate_under_overload``: shed fraction of dispositions at
      the highest offered step;
    - ``goodput_fraction_at_saturation``: deadline-met fraction of
      served work at the saturation step.
    """
    import shutil
    import tempfile
    import time as _time

    workdir = tempfile.mkdtemp(prefix="sagecal-load-bench-")
    try:
        store = os.path.join(workdir, "aot-store")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SAGECAL_TELEMETRY="1")

        def one(tag: str, rates_s: str, step: float, drain: float):
            out = os.path.join(workdir, tag)
            proc = subprocess.run(
                [sys.executable, "-m", "sagecal_tpu.apps.cli", "load",
                 "--out-dir", out, "--aot-store", store,
                 "--workers", str(workers), "--rates", rates_s,
                 "--step", str(step), "--tenants", "2", "--seed", "23",
                 "--warmup", "12", "--drain-timeout", str(drain)],
                env=env, timeout=timeout, capture_output=True)
            if proc.returncode not in (0, 4):
                raise RuntimeError(
                    f"load bench ({tag}) exited {proc.returncode}: "
                    f"{proc.stderr.decode()[-800:]}")
            with open(os.path.join(out, "load_report.json")) as f:
                return json.load(f), proc.returncode

        # warm-up: low rate, one step — populates the store so the
        # measured run sees zero compiles and the curve reflects
        # steady-state capacity, not compile stalls
        t0 = _time.perf_counter()
        one("warm", "0.4", 30.0, 300.0)
        warm_s = _time.perf_counter() - t0
        report, rc = one("measured",
                         ",".join(str(r) for r in rates),
                         step_s, 300.0)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    knee = report.get("knee") or {}
    ll = report.get("littles_law") or {}
    return {
        "workers": workers,
        "rates": list(rates),
        "step_s": step_s,
        "warmup_wall_s": round(warm_s, 2),
        "drained": bool(report.get("drained", rc == 0)),
        "manifests": report.get("manifests"),
        "served": report.get("served"),
        "shed": report.get("shed"),
        "saturation_throughput_solves_per_sec": round(
            float(report["saturation_throughput_solves_per_sec"]), 4),
        "shed_rate_under_overload": round(
            float(report["shed_rate_under_overload"]), 4),
        "goodput_fraction_at_saturation": round(
            float(report["goodput_fraction_at_saturation"]), 4),
        "knee_offered_rate": knee.get("knee_offered_rate"),
        "littles_law_ok": bool(ll.get("live_ok"))
        and bool(ll.get("posthoc_ok")),
    }


def run_shadow_drift_bench(n_requests=4, timeout=900.0):
    """Numerical-truth row: REAL cross-path drift distributions from
    live shadow-audited serve runs (obs/shadow.py), banked as
    gate-able p99 upper bounds.

    Two small synthetic serve runs at ``--shadow-rate 1.0`` (every
    request re-solved on the xla/f32 reference path after its manifest
    lands), both routed through the fused batched kernels:

    - ``shadow_drift_batched_vs_xla_p99``: fused_batch/f32 production
      vs the reference — the pure KERNEL-PATH disagreement (vmap
      batching + Pallas accumulation order);
    - ``shadow_drift_bf16_vs_f32_p99``: fused_batch/bf16 production vs
      the same reference — the bf16 coherency storage trade measured
      on live traffic, the number the precision schedule (ROADMAP
      item 1) wants watched continuously.

    Both are the p99 upper BOUND of the max per-station gain relative
    error, lifted from the ledger's merged histograms
    (obs/drift.aggregate_drift) — the provable-interval discipline: the
    bound provably contains the exact sampled max (pinned in
    tests/test_drift.py).  Lower-better, cpu-wallclock evidence (the
    drift RATIO is dtype/kernel truth, but it is measured on the CPU
    interpret-mode kernels — a TPU MXU pass may differ; honest class
    over flattering class).

    Subprocess serve runs (like run_load_bench) with telemetry OFF:
    ``SageConfig.collect_telemetry`` is a capability gate of the fused
    batched path, and the bench must measure the path it names.
    """
    import shutil
    import tempfile

    from sagecal_tpu.obs.drift import aggregate_drift, drift_quantiles
    from sagecal_tpu.obs.shadow import (
        drift_path,
        read_drift,
        validate_drift,
    )

    workdir = tempfile.mkdtemp(prefix="sagecal-shadow-bench-")
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        # telemetry collection forces the xla path (capability gate);
        # a stray injected-drift env would poison the banked numbers
        env.pop("SAGECAL_TELEMETRY", None)
        env.pop("SAGECAL_SHADOW_INJECT_DRIFT", None)

        def one(tag: str, coh_dtype: str):
            out = os.path.join(workdir, tag)
            proc = subprocess.run(
                [sys.executable, "-m", "sagecal_tpu.apps.cli", "serve",
                 "--synthetic", str(n_requests), "--tenants", "1",
                 "--batch", "2", "--out-dir", out, "--f32", "--fused",
                 "--coh-dtype", coh_dtype, "--shadow-rate", "1.0",
                 "--shadow-budget-s", str(timeout)],
                env=env, timeout=timeout, capture_output=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"shadow bench ({tag}) exited {proc.returncode}: "
                    f"{proc.stderr.decode()[-800:]}")
            rows = read_drift(drift_path(out))
            problems = validate_drift(rows)
            if problems or len(rows) != n_requests:
                raise RuntimeError(
                    f"shadow bench ({tag}) ledger invalid: "
                    f"{len(rows)}/{n_requests} records, {problems}")
            return rows

        rows_f32 = one("f32", "f32")
        rows_bf16 = one("bf16", "bf16")
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    def p99_bound(rows):
        groups = aggregate_drift(rows)
        quant = drift_quantiles(groups)
        hi = max(quant[k]["gain_rel_err_max"]["p99"][1] for k in groups)
        exact_max = max(float(r["gain_rel_err_max"]) for r in rows)
        assert exact_max <= hi, (exact_max, hi)  # provable interval
        return hi, exact_max

    hi_f32, max_f32 = p99_bound(rows_f32)
    hi_bf16, max_bf16 = p99_bound(rows_bf16)
    return {
        "n_requests": n_requests,
        "kernel_path": rows_f32[0].get("kernel_path"),
        "path_pairs": sorted({r["path_pair"]
                              for r in rows_f32 + rows_bf16}),
        "shadow_drift_batched_vs_xla_p99": float(f"{hi_f32:.3e}"),
        "shadow_drift_bf16_vs_f32_p99": float(f"{hi_bf16:.3e}"),
        "batched_gain_rel_err_exact_max": float(f"{max_f32:.3e}"),
        "bf16_gain_rel_err_exact_max": float(f"{max_bf16:.3e}"),
        "exceeded": sum(1 for r in rows_f32 + rows_bf16
                        if r.get("verdict") != "ok"),
        "shadow_s_total": round(sum(float(r.get("shadow_s", 0.0))
                                    for r in rows_f32 + rows_bf16), 2),
    }


def run_widefield_bench(nsources=10000, nblobs=40, nstations=40,
                        order=8, theta=1.5, repeats=5, seed=3):
    """Wide-field hierarchical-predict row: compiled memory traffic and
    wall clock of ``predict_coherencies_hier`` vs the exact predict at
    the 10k-source shape, plus the sampled a-posteriori error.

    The gated headline is ``hier_predict_speedup`` = exact/hier
    compiled BYTES ACCESSED from AOT ``cost_analysis()`` — deterministic
    and host-load-independent, unlike wall clock (recorded alongside as
    ``wall_speedup``).  The exact side is lowered with
    ``source_chunk = nsources`` (a single chunk): XLA's cost analysis
    counts a scan body ONCE regardless of trip count, so a chunked
    lowering under-reports the exact path's true traffic by the trip
    count — the single-chunk program is the chunk-size-invariant total.
    ``hier_predict_max_rel_err`` (lower-better, gated) is the sampled
    error of the hier stack vs exact rows at the DEFAULT knob
    (order=8, theta=1.5; a-priori bound 1.06e-4).

    Geometry is the compact-array / low-frequency / wide-fov regime
    (60 m stations, 30 MHz, ~1.1 rad field) — the regime the expansion
    targets: admissibility needs ``2*pi*f*|b|*r_node <= theta``, which
    a km-scale array at 150 MHz never satisfies.  f64 via the scoped
    x64 context so the row is independent of the headline dtype.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    with enable_x64(), jax.default_device(_cpu_device()):
        from sagecal_tpu.io.simulate import make_visdata
        from sagecal_tpu.ops.rime import (
            point_source_batch,
            predict_coherencies,
        )
        from sagecal_tpu.sky.predict import (
            _hier_core,
            build_hier_plan,
            predict_coherencies_hier,
            sampled_error_estimate,
        )

        data = make_visdata(nstations=nstations, tilesz=2, nchan=1,
                            freq0=30e6, seed=1, dtype=np.float64,
                            extent_m=60.0)
        rng = np.random.default_rng(seed)
        per = np.full(nblobs, nsources // nblobs)
        per[: nsources % nblobs] += 1
        cx = rng.uniform(-0.55, 0.55, nblobs)
        cy = rng.uniform(-0.55, 0.55, nblobs)
        ll = np.concatenate([c + 0.004 * rng.standard_normal(n)
                             for c, n in zip(cx, per)])
        mm = np.concatenate([c + 0.004 * rng.standard_normal(n)
                             for c, n in zip(cy, per)])
        keep = ll * ll + mm * mm < 0.95
        ll, mm = ll[keep], mm[keep]
        flux = 0.1 * rng.pareto(2.0, ll.shape[0]) + 0.05
        src = point_source_batch(ll, mm, flux, f0=30e6, dtype=jnp.float64)
        S = int(ll.shape[0])

        plan = build_hier_plan(data.u, data.v, data.w, data.freqs, src,
                               theta=theta)
        T, R = plan.routing.ntiles, plan.routing.tile_rows
        rows = plan.routing.rows
        pad = T * R - rows
        u_t = jnp.pad(data.u[plan.row_perm], (0, pad)).reshape(T, R)
        v_t = jnp.pad(data.v[plan.row_perm], (0, pad)).reshape(T, R)
        w_t = jnp.pad(data.w[plan.row_perm], (0, pad)).reshape(T, R)

        def aot_bytes(lowered):
            cost = lowered.compile().cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            return float(cost.get("bytes accessed", 0.0))

        hier_bytes = aot_bytes(jax.jit(
            _hier_core.__wrapped__,
            static_argnums=(11, 12, 13, 14, 15, 16, 17),
        ).lower(
            u_t, v_t, w_t, data.freqs, src,
            plan.node_of_source, plan.node_center,
            plan.far_idx, plan.far_valid, plan.near_src, plan.near_valid,
            order, plan.nnodes, 0.0, 32, plan.use_far, plan.use_near,
            plan.npol))
        exact_bytes = aot_bytes(jax.jit(
            lambda u, v, w, f, s: predict_coherencies(
                u, v, w, f, s, 0.0, S,
                has_extended=False, has_shapelet=False),
        ).lower(data.u, data.v, data.w, data.freqs, src))

        def timed(fn):
            fn().block_until_ready()  # warm the jit cache
            best = min(
                _timeit(lambda: fn().block_until_ready())
                for _ in range(repeats))
            return best

        def _timeit(fn):
            t0 = time.perf_counter()
            fn()
            return time.perf_counter() - t0

        hier_wall = timed(lambda: predict_coherencies_hier(
            data.u, data.v, data.w, data.freqs, src,
            order=order, theta=theta, plan=plan))
        # deployed chunking on the exact side (source_chunk=256): wall
        # clock reflects what callers actually run, unlike the
        # single-chunk lowering used for the traffic total
        exact_wall = timed(lambda: predict_coherencies(
            data.u, data.v, data.w, data.freqs, src, 0.0, 256,
            has_extended=False, has_shapelet=False))

        coh = predict_coherencies_hier(
            data.u, data.v, data.w, data.freqs, src,
            order=order, theta=theta, plan=plan)
        est = sampled_error_estimate(
            data.u, data.v, data.w, data.freqs, src, coh,
            nsample=256, seed=0)
    st = plan.stats()
    return {
        "nsources": S,
        "rows": rows,
        "order": order,
        "theta": theta,
        "tree_depth": st["depth"],
        "far_pairs": st["far_pairs"],
        "near_sources_total": st["near_sources_total"],
        "npol": plan.npol,
        "hier_aot_bytes": hier_bytes,
        "exact_aot_bytes_single_chunk": exact_bytes,
        "hier_predict_speedup": round(exact_bytes / hier_bytes, 3),
        "hier_wall_s": round(hier_wall, 5),
        "exact_wall_s": round(exact_wall, 5),
        "wall_speedup": round(exact_wall / max(hier_wall, 1e-9), 3),
        "hier_predict_max_rel_err": float(est["rel_err"]),
        "error_nsample": int(est["nsample"]),
    }


def _latest_flight_dump():
    """Newest flight-recorder dump matching the configured dump path, so
    the recovery event links straight to the forensics artifact."""
    import glob

    base = os.environ.get("SAGECAL_FLIGHT_DUMP", "flight_dump.json")
    root, ext = os.path.splitext(base)
    cands = sorted(set(glob.glob(base) + glob.glob(root + "*" + ext)))
    if not cands:
        return None
    try:
        return os.path.abspath(max(cands, key=os.path.getmtime))
    except OSError:
        return os.path.abspath(cands[-1])


def _latest_devprof_trace():
    """Newest device-profile trace: this process's capture if one
    landed, else the newest trace under the configured capture dir (a
    previous wedged run's forensics) — attached to the recovery event
    alongside the flight dump."""
    from sagecal_tpu.obs.devprof import last_trace_path, newest_trace_path

    path = last_trace_path()
    if path:
        return os.path.abspath(path)
    root = os.environ.get("SAGECAL_DEVICE_PROFILE")
    if root and os.path.isdir(root):
        found = newest_trace_path(root)
        if found:
            return os.path.abspath(found)
    return None


def main(argv=None):
    import argparse
    import uuid

    import jax

    ap = argparse.ArgumentParser(
        prog="bench.py",
        description="north-star LBFGS calibration bench + satellite rows")
    ap.add_argument("--device-profile", default=None, metavar="DIR",
                    help="capture a device-profiler trace of the timing "
                         "loop into DIR for `diag roofline` (same as "
                         "SAGECAL_DEVICE_PROFILE=DIR)")
    args = ap.parse_args(argv)
    if args.device_profile:
        os.environ["SAGECAL_DEVICE_PROFILE"] = args.device_profile

    # persistent compile cache: a prior successful TPU compile (e.g. the
    # recovery watcher's banked run) makes later runs start in seconds.
    # SAGECAL_COMPILE_CACHE overrides; the obs/perf helper also installs
    # the cache-hit listener so the record can split warm/cold compiles.
    from sagecal_tpu.obs.perf import enable_persistent_compilation_cache

    enable_persistent_compilation_cache(
        os.environ.get("SAGECAL_COMPILE_CACHE")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
    )

    # crash forensics + tracing for the bench itself: heartbeat while the
    # (possibly wedged-tunnel) TPU work runs, stall dump if it hangs.
    # The run_id is minted here and handed to the manifest later so the
    # span file and the event log correlate.
    from sagecal_tpu.obs.flight import (
        close_flight_recorder,
        get_flight_recorder,
        install_crash_handlers,
        register_event_log,
        unregister_event_log,
    )
    from sagecal_tpu.obs.trace import close_tracer, configure_tracer, get_tracer

    run_id = uuid.uuid4().hex[:12]
    install_crash_handlers()
    get_flight_recorder(run_id=run_id)
    configure_tracer(run_id=run_id)
    tracer = get_tracer()

    probe_ok = _probe_default_backend()
    probe_failed_initially = not probe_ok
    recovery_attempted = False
    if not probe_ok:
        # one BOUNDED recovery attempt before giving up on the TPU: run
        # the tunnel-recovery watcher under a hard timeout (its own loop
        # waits hours; we only borrow its heal-and-bank sequence for a
        # few minutes), then re-probe.  SAGECAL_BENCH_NO_RECOVER=1 skips
        # it; SAGECAL_BENCH_RECOVER_TIMEOUT bounds it (seconds).
        recover = os.environ.get(
            "SAGECAL_BENCH_RECOVER",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tpu_recover.sh"),
        )
        if (os.path.exists(recover)
                and not os.environ.get("SAGECAL_BENCH_NO_RECOVER")):
            recovery_attempted = True
            bound = float(
                os.environ.get("SAGECAL_BENCH_RECOVER_TIMEOUT", "300")
            )
            sys.stderr.write(
                f"bench: TPU probe failed; attempting one recovery via "
                f"{recover} (bounded {bound:.0f}s)\n"
            )
            try:
                subprocess.run(["bash", recover], timeout=bound,
                               capture_output=True)
            except (subprocess.TimeoutExpired, OSError):
                pass
            probe_ok = _probe_default_backend()
    if not probe_ok:
        sys.stderr.write(
            "bench: default (axon TPU) backend unavailable or wedged; "
            "falling back to CPU platform\n"
        )
        jax.config.update("jax_platforms", "cpu")

    init_failed = False
    try:
        platform = jax.devices()[0].platform
    except RuntimeError:
        init_failed = True
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform

    # north-star shape on the TPU; on the CPU-fallback path drop to the
    # small tilesz-5 shape (the full shape takes tens of minutes per
    # LBFGS solve on this single-core host) and compare against its own
    # pinned baseline.  run() resolves the FUSED default from the
    # device it targets.
    on_tpu = platform not in ("cpu",)
    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:
        device_kind = None
    tilesz = TILESZ if on_tpu else 5
    repeats = REPEATS if on_tpu else 1
    with tracer.span("bench", kind="run", platform=platform,
                     tilesz=tilesz, repeats=repeats):
        value, iters, dt, perf, warm = run(
            np.float32, repeats=repeats, want_flops=True, tilesz=tilesz,
            measure_warm_start=True,
        )
    xla_flops = perf.get("flops")

    # bf16-coherency variant row: re-run the fused-objective step with
    # the coherency stack stored bfloat16 (f32 accumulation) so the
    # stream-halving knob is regression-guarded by `diag gate` alongside
    # the f32 headline.  Fused path only (the knob halves the kernel's
    # HBM stream; the XLA path would re-measure a different program),
    # and skipped when the whole run IS the bf16 run.
    bf16_variant = None
    if FUSED and not COH_BF16:
        with tracer.span("bench", kind="run", platform=platform,
                         tilesz=tilesz, repeats=1, variant="coh_bf16"):
            bf16_variant = run(
                np.float32, repeats=1, want_flops=True, tilesz=tilesz,
                coh_bf16=True,
            )

    # serve-path throughput row: K same-shape solves as one vmapped
    # program (through the serve executable cache) vs the sequential
    # one-at-a-time loop.  Cheap (sub-minute small shape), so it rides
    # every bench run and `diag gate` guards the serving win alongside
    # the single-solve headline.  SAGECAL_BENCH_NO_SERVE=1 skips it.
    serve_rec = None
    serve_rec_n16 = None
    if not os.environ.get("SAGECAL_BENCH_NO_SERVE"):
        serve_dev = jax.devices()[0] if on_tpu else None
        serve_coh = "bf16" if COH_BF16 else "f32"
        # gated row: N=62 stations — the north-star station count, so
        # `diag gate` guards the serving win where the paper claims it
        with tracer.span("bench", kind="run", variant="serve"):
            try:
                serve_rec = run_serve_bench(
                    batch=8, repeats=3, nstations=NSTATIONS,
                    device=serve_dev, fused=FUSED, coh_dtype=serve_coh)
            except Exception as exc:  # never sink the headline bench
                sys.stderr.write(f"bench: serve bench failed: {exc}\n")
        # ungated history row: the historical N=16 overhead-bound class
        # (trend visibility in BENCH_HISTORY.jsonl, no gate)
        with tracer.span("bench", kind="run", variant="serve_n16"):
            try:
                serve_rec_n16 = run_serve_bench(
                    batch=8, repeats=5, nstations=16,
                    device=serve_dev, fused=FUSED, coh_dtype=serve_coh)
            except Exception as exc:
                sys.stderr.write(f"bench: serve n16 bench failed: {exc}\n")

    # mesh-consensus communication row: per-round collective bytes of
    # the transpose-reduced z-step vs grouped, from AOT HLO accounting
    # in a subprocess (deterministic — no timing).  `diag gate` guards
    # both directions: bytes/round must not grow, the reduction ratio
    # must not shrink.  SAGECAL_BENCH_NO_COMMS=1 skips it.
    comms_rec = None
    if not os.environ.get("SAGECAL_BENCH_NO_COMMS"):
        with tracer.span("bench", kind="run", variant="admm_comms"):
            comms_rec = run_admm_comms_bench()

    # sky-model refinement row: bilevel flux recovery + outer-loop
    # throughput on the simulated-sky fixture (f64 CPU — the regime the
    # gradient acceptance bounds are defined in).
    # SAGECAL_BENCH_NO_REFINE=1 skips it.
    refine_rec = None
    if not os.environ.get("SAGECAL_BENCH_NO_REFINE"):
        with tracer.span("bench", kind="run", variant="refine"):
            try:
                refine_rec = run_refine_bench()
            except Exception as exc:  # never sink the headline bench
                sys.stderr.write(f"bench: refine bench failed: {exc}\n")

    # streaming-calibration row: warm-chain steady-state latency-to-
    # first-solution vs the cold baseline (CPU f64, the stream smoke's
    # acceptance environment).  SAGECAL_BENCH_NO_STREAM=1 skips it.
    stream_rec = None
    if not os.environ.get("SAGECAL_BENCH_NO_STREAM"):
        with tracer.span("bench", kind="run", variant="stream"):
            try:
                stream_rec = run_stream_bench()
            except Exception as exc:  # never sink the headline bench
                sys.stderr.write(f"bench: stream bench failed: {exc}\n")

    # fleet-serving row: warm two-worker throughput over a shared AOT
    # artifact store (subprocess CPU workers).
    # SAGECAL_BENCH_NO_FLEET=1 skips it.
    fleet_rec = None
    if not os.environ.get("SAGECAL_BENCH_NO_FLEET"):
        with tracer.span("bench", kind="run", variant="fleet"):
            try:
                fleet_rec = run_fleet_bench()
            except Exception as exc:  # never sink the headline bench
                sys.stderr.write(f"bench: fleet bench failed: {exc}\n")

    # fleet load/capacity row: stepped-ramp offered load vs a warm
    # two-worker fleet (subprocess CPU workers); banks the saturation
    # throughput, overload shed rate and goodput-at-saturation.
    # SAGECAL_BENCH_NO_LOAD=1 skips it.
    load_rec = None
    if not os.environ.get("SAGECAL_BENCH_NO_LOAD"):
        with tracer.span("bench", kind="run", variant="load"):
            try:
                load_rec = run_load_bench()
            except Exception as exc:  # never sink the headline bench
                sys.stderr.write(f"bench: load bench failed: {exc}\n")

    # numerical-truth row: live shadow-audited serve runs (fused f32 +
    # fused bf16 vs the xla/f32 reference) banking real cross-path
    # drift distributions.  SAGECAL_BENCH_NO_SHADOW=1 skips it.
    shadow_rec = None
    if not os.environ.get("SAGECAL_BENCH_NO_SHADOW"):
        with tracer.span("bench", kind="run", variant="shadow"):
            try:
                shadow_rec = run_shadow_drift_bench()
            except Exception as exc:  # never sink the headline bench
                sys.stderr.write(
                    f"bench: shadow-drift bench failed: {exc}\n")

    # wide-field hierarchical-predict row: compiled-traffic ratio vs the
    # exact predict at the 10k-source shape + sampled error at the
    # default (order, theta) knob.  SAGECAL_BENCH_NO_WIDEFIELD=1 skips.
    widefield_rec = None
    if not os.environ.get("SAGECAL_BENCH_NO_WIDEFIELD"):
        with tracer.span("bench", kind="run", variant="widefield"):
            try:
                widefield_rec = run_widefield_bench()
            except Exception as exc:  # never sink the headline bench
                sys.stderr.write(f"bench: widefield bench failed: {exc}\n")

    cpu_measured = None
    if os.environ.get("SAGECAL_BENCH_MEASURE_CPU"):
        cpu_measured = _measure_cpu_subprocess(tilesz)
    base = cpu_measured or _CPU_BASELINE_PINNED[tilesz]
    vs = value / base if base else None
    ref_c = _REF_CPU_PINNED.get(tilesz)
    vs_ref = value / ref_c if ref_c else None

    # Equal-work ratio (the honesty prose of ref_bench.py moved into
    # the artifact): an LBFGS iteration is the unit of convergence
    # progress in both codes, but ours is the costlier iteration —
    # the MEASURED 2.7 cost-equivalents per iteration
    # (_OUR_COST_EVALS_PER_ITER_MEASURED, incl. line-search
    # rejections) vs the reference's ~1.5 (_REF_COST_EVALS_PER_ITER).
    # Charge us for the extra evaluations and do NOT credit that each
    # of our evaluations covers NCHAN=2 channel models vs the
    # reference's single channel-averaged model (lmfit.c:1140-1158) —
    # i.e. this is the CONSERVATIVE ratio; the uncredited channel
    # factor (2x in our favor) is recorded alongside.
    our_evals_per_iter = _OUR_COST_EVALS_PER_ITER_MEASURED
    vs_ref_equal = (
        vs_ref * _REF_COST_EVALS_PER_ITER / our_evals_per_iter
        if vs_ref else None
    )

    # throughput roofline from ANALYTIC counts (see
    # analytic_flops_per_cost_eval).  Cost-equivalents per LBFGS
    # iteration after the round-5 trial-point fusion (value_and_grad
    # evaluated AT the first Armijo trial, accepted in the common
    # case): one fused (f, g) pass (~2x a cost eval) per iteration;
    # +2 per fit for the initial value_and_grad (the final cost is
    # carried, not re-evaluated).  Lower bound: line-search rejections
    # (extra cost-only halvings + one extra (f, g)) are not counted.
    cost_evals = 2 * iters + 2
    fl_eval = analytic_flops_per_cost_eval(tilesz)
    by_eval = hbm_bytes_per_cost_eval(
        tilesz, coh_bytes_per_cplx=4 if COH_BF16 else 8
    )
    flops_per_sec = cost_evals * fl_eval / dt
    gbytes_per_sec = cost_evals * by_eval / dt / 1e9

    # measured-vs-peak utilization against THIS hardware's peak-table
    # entry (obs/roofline.py), not a hardcoded v5e constant; None when
    # the device kind has no entry — an honest gap beats a wrong MFU
    from sagecal_tpu.obs.devprof import last_trace_path
    from sagecal_tpu.obs.evidence import (
        bench_evidence_classes,
        wallclock_evidence,
    )
    from sagecal_tpu.obs.roofline import bw_util as _roof_bw
    from sagecal_tpu.obs.roofline import mfu as _roof_mfu

    mfu_val = _roof_mfu(flops_per_sec, device_kind, dtype="bf16")
    bw_val = _roof_bw(gbytes_per_sec * 1e9, device_kind)

    rec = {
        "metric": "lbfgs_cal_iters_per_sec",
        "value": round(value, 3),
        "unit": f"iter/s (62 stn, 100 clusters, {tilesz} ts x {NCHAN} ch)",
        "vs_baseline": round(vs, 3) if vs else None,
        "platform": platform,
        "fused_kernel": FUSED,
        # the path the headline step ACTUALLY ran: run() resolves FUSED
        # from the device before building the step, and make_fused_step
        # raises rather than silently falling back — so post-run FUSED
        # is the executed path, not the requested one.  The serve row
        # records its own executed path (xla / fused / fused_batch)
        # from choose_batched_path.
        "kernel_path": "fused" if FUSED else "xla",
        "coh_bf16": COH_BF16,
        "cpu_baseline_iters_per_sec": base,
        "cpu_baseline_source": "measured-live" if cpu_measured else "pinned",
        "vs_reference_cpu": round(vs_ref, 3) if vs_ref else None,
        "vs_reference_cpu_equal_work": (
            round(vs_ref_equal, 3) if vs_ref_equal else None
        ),
        "equal_work_model": (
            f"ratio x {_REF_COST_EVALS_PER_ITER}/"
            f"{round(our_evals_per_iter, 2)} cost-evals per iter; "
            f"our {NCHAN}-channels-per-eval vs reference's 1 "
            "channel-averaged model NOT credited (2x in our favor)"
        ) if vs_ref_equal else None,
        "ref_cpu_iters_per_sec": ref_c,
        "ref_cpu_threads": _REF_CPU_THREADS if ref_c else None,
        "ref_threads_caveat": (
            "reference pinned single-core on this 1-core host; its hot "
            "loops are pthread-parallel, so vs_reference_cpu is "
            "per-chip vs per-core, scaling ~1/k on a k-core host"
        ) if ref_c else None,
        "north_star_shape": tilesz == TILESZ,
        "recovery_attempted": recovery_attempted,
        "analytic_tflops_per_sec": round(flops_per_sec / 1e12, 4),
        "analytic_hbm_gb_per_sec": round(gbytes_per_sec, 1),
        "mfu_vs_device_peak": round(mfu_val, 5) if mfu_val else None,
        "bw_util_vs_device_peak": round(bw_val, 4) if bw_val else None,
        "device_kind": device_kind,
        # evidence ledger (obs/evidence.py): the record-level class of
        # the wall-clock rows + the per-metric override map for the
        # satellite rows measured another way (AOT bytes/HLO, CPU
        # subprocess harnesses) — what `diag gate` / bench_trend use to
        # refuse cross-evidence comparisons
        "evidence": wallclock_evidence(platform),
        "evidence_classes": bench_evidence_classes(platform),
    }
    dp_trace = last_trace_path()
    if dp_trace:
        # the devprof capture of this run's timing loop — feed it to
        # `diag roofline` (flight dumps carry the same path)
        rec["device_profile_trace"] = dp_trace
    if warm is not None:
        # elastic warm-start acceleration: gate-able, higher is better
        # (diag gate knows the direction via obs/perf.py)
        rec["warm_start_iters_cold"] = warm["iters_cold"]
        rec["warm_start_iters_warm"] = warm["iters_warm"]
        rec["warm_start_speedup"] = warm["speedup"]
    if comms_rec is not None:
        # gate-able consensus-comms rows (obs/perf.py knows directions):
        # bytes/round lower-better, reduction ratio higher-better
        rec["admm_collective_bytes_per_round"] = (
            comms_rec["admm_collective_bytes_per_round"])
        rec["admm_collective_bytes_reduction"] = (
            comms_rec["admm_collective_bytes_reduction"])
        rec["admm_comms_bench"] = comms_rec
    if serve_rec is not None:
        # gate-able serve row (obs/perf.py knows the directions):
        # throughput + batch speedup higher-better, p50 lower-better.
        # Gated at N=62 since the batched-fused-kernel round; the
        # history row stamps the batch width and the kernel path that
        # actually executed (xla / fused / fused_batch)
        rec["solves_per_sec_per_chip"] = serve_rec["solves_per_sec_per_chip"]
        rec["serve_batch_speedup"] = serve_rec["serve_batch_speedup"]
        rec["serve_p50_latency_s"] = serve_rec["serve_p50_latency_s"]
        rec["serve_batch_width"] = serve_rec["batch"]
        rec["serve_kernel_path"] = serve_rec["kernel_path"]
        rec["serve_bench"] = serve_rec
    if serve_rec_n16 is not None:
        # UNGATED history row: the N=16 overhead-bound class rides the
        # artifact (and BENCH_HISTORY.jsonl) for trend visibility only
        rec["serve_bench_n16"] = serve_rec_n16
    if refine_rec is not None:
        # gate-able refine rows (obs/perf.py knows the directions):
        # flux error lower-better, outer throughput higher-better
        rec["refine_flux_err"] = refine_rec["refine_flux_err"]
        rec["refine_outer_iters_per_sec"] = (
            refine_rec["refine_outer_iters_per_sec"])
        rec["refine_bench"] = refine_rec
    if stream_rec is not None:
        # gate-able streaming row (obs/perf.py knows the directions):
        # steady-state latency lower-better, warm speedup higher-better
        rec["latency_to_first_solution_s"] = (
            stream_rec["latency_to_first_solution_s"])
        rec["stream_warm_speedup"] = stream_rec["stream_warm_speedup"]
        rec["stream_bench"] = stream_rec
    if fleet_rec is not None:
        # gate-able fleet row (obs/perf.py knows the direction):
        # warm two-worker throughput higher-better
        rec["fleet_solves_per_sec_2workers"] = (
            fleet_rec["fleet_solves_per_sec_2workers"])
        rec["fleet_bench"] = fleet_rec
    if load_rec is not None:
        # gate-able load/capacity rows (obs/perf.py knows the
        # directions): saturation throughput + goodput higher-better,
        # overload shed rate lower-better (opt-in gate — policy-shaped)
        rec["saturation_throughput_solves_per_sec"] = (
            load_rec["saturation_throughput_solves_per_sec"])
        rec["shed_rate_under_overload"] = (
            load_rec["shed_rate_under_overload"])
        rec["goodput_fraction_at_saturation"] = (
            load_rec["goodput_fraction_at_saturation"])
        rec["load_bench"] = load_rec
    if shadow_rec is not None:
        # gate-able numerical-truth rows (obs/perf.py knows the
        # directions, both lower-better): p99 upper bounds of the max
        # per-station gain relative error, production vs xla/f32
        # reference, from live shadow-audited runs
        rec["shadow_drift_batched_vs_xla_p99"] = (
            shadow_rec["shadow_drift_batched_vs_xla_p99"])
        rec["shadow_drift_bf16_vs_f32_p99"] = (
            shadow_rec["shadow_drift_bf16_vs_f32_p99"])
        rec["shadow_drift_bench"] = shadow_rec
    if widefield_rec is not None:
        # gate-able wide-field hierarchical-predict rows (obs/perf.py
        # knows the directions): compiled-traffic ratio higher-better,
        # sampled error lower-better
        rec["hier_predict_speedup"] = widefield_rec["hier_predict_speedup"]
        rec["hier_predict_max_rel_err"] = (
            widefield_rec["hier_predict_max_rel_err"])
        rec["widefield_bench"] = widefield_rec
    if bf16_variant is not None:
        # gate-able bf16-coherency row (obs/perf.py knows directions):
        # throughput higher-better, compiled bytes accessed lower-better
        v_b, _, _, perf_b, _ = bf16_variant
        rec["coh_bf16_iters_per_sec"] = round(v_b, 3)
        if perf_b.get("bytes_accessed"):
            rec["coh_bf16_xla_cost_analysis_bytes_accessed"] = (
                perf_b["bytes_accessed"])
    if xla_flops:
        rec["xla_cost_analysis_tflops_per_sec"] = round(xla_flops / dt / 1e12, 4)
    # gate-able absolutes (diag gate): compiled-program bytes accessed
    # and the device allocator's peak watermark for the bench process
    if perf.get("bytes_accessed"):
        rec["xla_cost_analysis_bytes_accessed"] = perf["bytes_accessed"]
    if perf.get("peak_device_memory_bytes"):
        rec["peak_device_memory_bytes"] = perf["peak_device_memory_bytes"]
    # North-star-shape same-core evidence, in the artifact rather than
    # round-notes prose: both sides measured solo on this host's single
    # core (ref_bench.py / _measure_cpu_subprocess, 2026-07-30).
    ref_ns = _REF_CPU_PINNED[TILESZ]
    rec["north_star_cpu_pinned"] = {
        "ours_f64_iters_per_sec": _OURS_CPU_NORTH_STAR["f64"],
        "ours_f32_iters_per_sec": _OURS_CPU_NORTH_STAR["f32"],
        "ref_c_iters_per_sec": ref_ns,
        "vs_ref_same_core_f64": round(_OURS_CPU_NORTH_STAR["f64"] / ref_ns, 3),
        "vs_ref_same_core_f64_equal_work": round(
            _OURS_CPU_NORTH_STAR["f64"] / ref_ns
            * _REF_COST_EVALS_PER_ITER / our_evals_per_iter, 3
        ),
    }
    # telemetry (SAGECAL_TELEMETRY=1): the bench outcome + any probe
    # failure / CPU fallback land in the JSONL event log with a full
    # RunManifest header
    from sagecal_tpu.obs import RunManifest, default_event_log

    elog = default_event_log(manifest=RunManifest.collect(
        kernel_path="fused" if FUSED else "xla", app="bench",
        run_id=run_id,
    ))
    if elog is not None:
        register_event_log(elog)
        if probe_failed_initially:
            elog.emit("tpu_probe_failed", recovered=probe_ok)
        if recovery_attempted:
            elog.emit("tpu_recovery_attempted", succeeded=probe_ok,
                      flight_dump=_latest_flight_dump(),
                      device_profile_trace=_latest_devprof_trace())
        if not probe_ok or init_failed:
            elog.emit("fallback_to_cpu", platform=platform,
                      backend_init_failed=init_failed)
        from sagecal_tpu.obs.perf import emit_perf_events

        emit_perf_events(elog)
        elog.emit("bench_result", **rec)
        elog.close()
        unregister_event_log(elog)
    close_tracer()
    # every mode (TPU, CPU fallback, fused or xla) appends one row to
    # BENCH_HISTORY.jsonl so `diag serve` can render trend deltas;
    # history is an append-only convenience, never fatal
    try:
        from sagecal_tpu.obs.perf import append_bench_history

        append_bench_history(rec)
    except Exception as e:  # noqa: BLE001 — read-only FS, odd cwd, ...
        print(f"bench history append skipped: {e}", file=sys.stderr)
    # success path only: leaves the final "closed" heartbeat; a crash
    # keeps the recorder alive for the excepthook's dump
    close_flight_recorder()
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
