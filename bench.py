"""Benchmark: joint LBFGS calibration throughput (north-star metric #1).

Workload: 62-station LOFAR-like array, 100 source clusters, one tile of
5 timeslots x 2 channels — the robust joint-LBFGS pass that closes every
SAGE iteration (``lbfgs_fit_robust_wrapper``, /root/reference/src/lib/
Dirac/lmfit.c:1019-1037), which is the dominant full-parameter solver
in both the fullbatch and stochastic modes (BASELINE.md north-star:
"LBFGS iters/sec/chip, 62-station, 100-cluster").

Each LBFGS iteration evaluates the full 100-cluster RIME model
(predict J C J^H summed over clusters) and its gradient by autodiff —
the same work the reference does per iteration with threaded C kernels
(robust_lbfgs.c:94,155).

``vs_baseline``: ratio against the same algorithm in float64 on the
host CPU via the JAX CPU backend (the reference is CPU double +
pthreads; no published numbers exist in the reference repo —
BASELINE.md).  The CPU figure was measured on this machine and is
pinned below so the driver run only measures the TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

# Measured 2026-07-29 on this container's CPU (JAX CPU backend, float64,
# same workload as below, median of 3 runs after compile):
#   python -c "import bench, numpy as np; print(bench.run(np.float64))"
# with JAX_PLATFORMS=cpu and x64 enabled -> 0.407 iters/sec.
CPU_BASELINE_ITERS_PER_SEC = 0.407

NSTATIONS = 62
NCLUSTERS = 100
TILESZ = 5
NCHAN = 2
LBFGS_ITERS = 20
REPEATS = 3


def build_workload(dtype=np.float32):
    """Synthesize the 62-stn/100-cluster tile.  MUST run on the CPU
    backend: eager complex ops and complex host<->device transfers are
    unimplemented on the axon TPU backend (verify skill gotchas 3)."""
    import jax.numpy as jnp

    from sagecal_tpu.core.types import jones_to_params
    from sagecal_tpu.io.simulate import corrupt_and_observe, make_visdata, random_jones
    from sagecal_tpu.ops.rime import point_source_batch
    from sagecal_tpu.solvers.sage import build_cluster_data

    rng = np.random.default_rng(0)
    f0 = 150e6
    fdt = jnp.float32 if dtype == np.float32 else jnp.float64
    cdt = np.complex64 if dtype == np.float32 else np.complex128
    data = make_visdata(
        nstations=NSTATIONS, tilesz=TILESZ, nchan=NCHAN, freq0=f0, dtype=dtype
    )
    ll = rng.uniform(-0.05, 0.05, NCLUSTERS)
    mm = rng.uniform(-0.05, 0.05, NCLUSTERS)
    flux = rng.uniform(0.5, 5.0, NCLUSTERS)
    clusters = [
        point_source_batch([ll[k]], [mm[k]], [flux[k]], f0=f0, dtype=fdt)
        for k in range(NCLUSTERS)
    ]
    jones = random_jones(NCLUSTERS, NSTATIONS, seed=1, amp=0.15, dtype=cdt)
    data = corrupt_and_observe(data, clusters, jones=jones, noise_sigma=1e-3)
    cdata = build_cluster_data(data, clusters, [1] * NCLUSTERS)
    p0 = jones_to_params(
        random_jones(NCLUSTERS, NSTATIONS, seed=2, amp=0.0, dtype=cdt)
    )[:, None, :]
    return data, cdata, p0


def make_step(data, cdata, nu=5.0):
    """Jitted LBFGS step over a REAL-array boundary (complex packed as a
    trailing re/im axis — axon cannot transfer complex)."""
    import jax
    import jax.numpy as jnp

    from sagecal_tpu.solvers.lbfgs import lbfgs_fit
    from sagecal_tpu.solvers.sage import predict_full_model

    M, nchunk, n8 = NCLUSTERS, 1, 8 * NSTATIONS

    @jax.jit
    def step(vis_ri, mask, coh_ri, p0):
        vis = jax.lax.complex(vis_ri[..., 0], vis_ri[..., 1])
        coh = jax.lax.complex(coh_ri[..., 0], coh_ri[..., 1])
        d = data.replace(vis=vis, mask=mask)
        c = cdata._replace(coh=coh)

        def cost_fn(pflat):
            pa = pflat.reshape(M, nchunk, n8)
            model = predict_full_model(pa, c, d)
            diff = (vis - model) * mask[..., None, None]
            e2 = jnp.real(diff) ** 2 + jnp.imag(diff) ** 2
            return jnp.sum(jnp.log1p(e2 / nu))

        fit = lbfgs_fit(cost_fn, None, p0.reshape(-1), itmax=LBFGS_ITERS, M=7)
        return fit.p, fit.cost, fit.iterations

    return step


def run(dtype=np.float32):
    import jax

    with jax.default_device(jax.devices("cpu")[0]):
        data, cdata, p0 = build_workload(dtype)
    vis_ri = np.stack([np.asarray(data.vis.real), np.asarray(data.vis.imag)], -1)
    coh_ri = np.stack([np.asarray(cdata.coh.real), np.asarray(cdata.coh.imag)], -1)
    mask = np.asarray(data.mask)
    p0_h = np.asarray(p0)
    step = make_step(data, cdata)
    args = (vis_ri, mask, coh_ri, p0_h)
    out = step(*args)  # compile + first run
    jax.block_until_ready(out)
    iters = int(np.asarray(out[2]))
    times = []
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        out = step(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    dt = float(np.median(times))
    return max(iters, 1) / dt, iters


def main():
    value, iters = run(np.float32)
    vs = value / CPU_BASELINE_ITERS_PER_SEC if CPU_BASELINE_ITERS_PER_SEC else None
    print(
        json.dumps(
            {
                "metric": "lbfgs_cal_iters_per_sec",
                "value": round(value, 3),
                "unit": "iter/s (62 stn, 100 clusters, 5 ts x 2 ch)",
                "vs_baseline": round(vs, 3) if vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
