"""Bisect which kernel feature stalls the axon Mosaic remote compile.

Tiny shapes throughout; variants ordered by increasing complexity.  Run:
    python kbisect.py c b a d
Each variant prints before/after; the first one that never prints "ok"
is the culprit.  Keep timeouts short — a stalled compile serializes the
relay for every later process.
"""
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

T, MP, NPAD, F, R = 256, 8, 128, 1, 2
INTERP = jax.default_backend() not in ("tpu",)


def variant_c():
    """No grid: one block, MXU dot + sublane reshape-slice + reduce."""
    def k(tab_ref, oh_ref, out_ref):
        g = jnp.dot(tab_ref[:], oh_ref[:], preferred_element_type=jnp.float32)
        comps = [g.reshape(MP, 4, T)[:, kk, :] for kk in range(4)]
        s = comps[0] * comps[1] + comps[2] * comps[3]
        out_ref[:] = jnp.sum(s, axis=0, keepdims=True)

    rng = np.random.default_rng(0)
    tab = rng.standard_normal((4 * MP, NPAD)).astype(np.float32)
    oh = rng.standard_normal((NPAD, T)).astype(np.float32)

    @jax.jit
    def f(tab, oh):
        return jnp.sum(pl.pallas_call(
            k,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((1, T), jnp.float32),
            interpret=INTERP,
        )(tab, oh))

    return f, (tab, oh)


def variant_b():
    """Grid over rows, 4D coh block + middle-index slicing + reduce."""
    def k(coh_ref, out_ref):
        sums = []
        for kk in range(8):
            x = coh_ref[:, 0, kk, :]  # (MP, T)
            sums.append(jnp.sum(x * x, axis=0, keepdims=True))
        out_ref[:] = jnp.concatenate(sums, axis=0)[None]

    rng = np.random.default_rng(0)
    coh = rng.standard_normal((MP, F, 8, R * T)).astype(np.float32)

    @jax.jit
    def f(coh):
        return jnp.sum(pl.pallas_call(
            k,
            grid=(R,),
            in_specs=[pl.BlockSpec((MP, F, 8, T), lambda r: (0, 0, 0, r),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((F, 8, T), lambda r: (0, 0, r),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((F, 8, R * T), jnp.float32),
            interpret=INTERP,
        )(coh))

    return f, (coh,)


def variant_a():
    """int32 input + in-kernel iota one-hot + dot + output revisit
    accumulation across the grid."""
    def k(antp_ref, tab_ref, out_ref):
        r = pl.program_id(0)
        n_iota = jax.lax.broadcasted_iota(jnp.int32, (NPAD, T), 0)
        oh = (n_iota == antp_ref[:]).astype(jnp.float32)
        g = jnp.dot(tab_ref[:], oh, preferred_element_type=jnp.float32)
        acc = jnp.sum(g.reshape(MP, 4, T), axis=0)[None]  # (1, 4, T)

        @pl.when(r == 0)
        def _i():
            out_ref[:] = acc

        @pl.when(r != 0)
        def _a():
            out_ref[:] = out_ref[:] + acc

    rng = np.random.default_rng(0)
    antp = rng.integers(0, 62, (1, R * T)).astype(np.int32)
    tab = rng.standard_normal((4 * MP, NPAD)).astype(np.float32)

    @jax.jit
    def f(antp, tab):
        return jnp.sum(pl.pallas_call(
            k,
            grid=(R,),
            in_specs=[
                pl.BlockSpec((1, T), lambda r: (0, r),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((4 * MP, NPAD), lambda r: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, 4, T), lambda r: (0, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((1, 4, T), jnp.float32),
            interpret=INTERP,
        )(antp, tab))

    return f, (antp, tab)


def variant_d():
    """The actual v2 forward kernel at tiny shape."""
    from sagecal_tpu.ops.rime_kernel import fused_predict_packed

    rng = np.random.default_rng(0)
    coh = rng.standard_normal((MP, F, 8, R * T)).astype(np.float32)
    tre = rng.standard_normal((4, MP, NPAD)).astype(np.float32)
    tim = rng.standard_normal((4, MP, NPAD)).astype(np.float32)
    antp = rng.integers(0, 62, (1, R * T)).astype(np.int32)
    antq = rng.integers(0, 62, (1, R * T)).astype(np.int32)

    @jax.jit
    def f(tre, tim, coh, antp, antq):
        return jnp.sum(fused_predict_packed(tre, tim, coh, antp, antq, T))

    return f, (tre, tim, coh, antp, antq)


def variant_e():
    """The actual v2 backward kernel at tiny shape."""
    from sagecal_tpu.ops.rime_kernel import fused_predict_packed

    rng = np.random.default_rng(0)
    coh = rng.standard_normal((MP, F, 8, R * T)).astype(np.float32)
    tre = rng.standard_normal((4, MP, NPAD)).astype(np.float32)
    tim = rng.standard_normal((4, MP, NPAD)).astype(np.float32)
    antp = rng.integers(0, 62, (1, R * T)).astype(np.int32)
    antq = rng.integers(0, 62, (1, R * T)).astype(np.int32)

    @jax.jit
    def f(tre, tim, coh, antp, antq):
        def loss(a, b):
            return jnp.sum(fused_predict_packed(a, b, coh, antp, antq, T))
        ga, gb = jax.grad(loss, argnums=(0, 1))(tre, tim)
        return jnp.sum(ga) + jnp.sum(gb)

    return f, (tre, tim, coh, antp, antq)


def variant_f():
    """Reshape-free gains: component-major tables, one dot per comp."""
    def k(antp_ref, tab_ref, out_ref):
        n_iota = jax.lax.broadcasted_iota(jnp.int32, (NPAD, T), 0)
        oh = (n_iota == antp_ref[:]).astype(jnp.float32)
        comps = []
        for kk in range(4):
            g = jnp.dot(tab_ref[kk], oh, preferred_element_type=jnp.float32)
            comps.append(g)  # (MP, T)
        s = comps[0] * comps[1] + comps[2] * comps[3]
        out_ref[:] = jnp.sum(s, axis=0, keepdims=True)

    rng = np.random.default_rng(0)
    antp = rng.integers(0, 62, (1, T)).astype(np.int32)
    tab = rng.standard_normal((4, MP, NPAD)).astype(np.float32)

    @jax.jit
    def f(antp, tab):
        return jnp.sum(pl.pallas_call(
            k,
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((1, T), jnp.float32),
            interpret=INTERP,
        )(antp, tab))

    return f, (antp, tab)


VARIANTS = {"a": variant_a, "b": variant_b, "c": variant_c,
            "d": variant_d, "e": variant_e, "f": variant_f}

if __name__ == "__main__":
    for name in sys.argv[1:]:
        print(f"[{name}] building...", flush=True)
        f, args = VARIANTS[name]()
        dev = jax.devices()[0]
        args = tuple(jax.device_put(a, dev) for a in args)
        t0 = time.time()
        v = float(np.asarray(f(*args)))
        print(f"[{name}] ok: {time.time()-t0:.1f}s val={v:.5g}", flush=True)
