import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from sagecal_tpu.ops.rime_kernel import fused_predict_packed  # noqa: E402

TILE, MC = 512, 8


def run(mp, F, rowsp, ns=62):
    rng = np.random.default_rng(0)
    coh = rng.standard_normal((mp, F, 8, rowsp)).astype(np.float32)
    tre = rng.standard_normal((4, mp, 128)).astype(np.float32)
    tim = rng.standard_normal((4, mp, 128)).astype(np.float32)
    antp = rng.integers(0, ns, (1, rowsp)).astype(np.int32)
    antq = rng.integers(0, ns, (1, rowsp)).astype(np.int32)
    dev = jax.devices()[0]
    coh, tre, tim, antp, antq = (
        jax.device_put(a, dev) for a in (coh, tre, tim, antp, antq)
    )

    @jax.jit
    def f(tre, tim):
        return jnp.sum(fused_predict_packed(tre, tim, coh, antp, antq, TILE))

    t0 = time.time()
    v = float(np.asarray(f(tre, tim)))
    print(f"mp={mp} F={F} rowsp={rowsp}: compile+run {time.time()-t0:.1f}s "
          f"val={v:.4g}", flush=True)
    ts = []
    for _ in range(3):
        t0 = time.time()
        float(np.asarray(f(tre, tim)))
        ts.append(time.time() - t0)
    dt = sorted(ts)[1]
    print(f"  steady {dt*1e3:.2f} ms  BW {coh.size*4/dt/1e9:.0f} GB/s",
          flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "small"
    if which == "small":
        run(8, 2, 4096)
    elif which == "mid":
        run(40, 2, 32768)
    elif which == "full":
        run(104, 2, 113664)  # north-star padded shape
