"""On-chip fused-kernel shape ladder (round-5 hardware findings baked in).

Hardware-measured compile behavior on the axon remote-compile relay
(v5e, 2026-07-31):
  - scoped-VMEM stack scales with Mp * tile: at mp=104 the FORWARD
    OOMs the 16 MB limit at tile=512 (20.9 MB; 256 fits at ~10.5 MB)
    and the BACKWARD OOMs at tile=256 (19.7 MB; 128 fits) — hence
    FULL_CLUSTER_TILE = 128 for any differentiated path.
  - what looked like compile time growing with grid length was mostly
    the axon AOT relay ingesting jit CLOSURE constants at ~2 MB/s
    (726 MB of captured coherencies = ~6 min before Mosaic starts);
    with arrays passed as arguments the full chunked forward compiles
    in ~31 s.  Rows are still chunked (lax.map over MAX_GRID_ROWS
    blocks) to keep each Mosaic grid short.
  - steady-state dispatch has a ~65 ms floor (tunnel round-trip), so
    per-call timings here are upper bounds on kernel compute.
"full" runs the north-star shape the way the bench does: tile=128,
4 chunks x 28416 rows (R=222 per grid).
"""
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from sagecal_tpu.ops.rime_kernel import (  # noqa: E402
    FULL_CLUSTER_TILE,
    chunked_rowsp,
    fused_predict_packed,
    fused_predict_packed_chunked,
)

TILE, MC = 512, 8


def run(mp, F, rowsp, ns=62, tile=TILE, chunked=False):
    rng = np.random.default_rng(0)
    coh = rng.standard_normal((mp, F, 8, rowsp)).astype(np.float32)
    tre = rng.standard_normal((4, mp, 128)).astype(np.float32)
    tim = rng.standard_normal((4, mp, 128)).astype(np.float32)
    antp = rng.integers(0, ns, (1, rowsp)).astype(np.int32)
    antq = rng.integers(0, ns, (1, rowsp)).astype(np.int32)
    dev = jax.devices()[0]
    coh, tre, tim, antp, antq = (
        jax.device_put(a, dev) for a in (coh, tre, tim, antp, antq)
    )
    predict = fused_predict_packed_chunked if chunked else fused_predict_packed

    # Big arrays enter as ARGUMENTS, not closure constants: the axon AOT
    # relay ingests closure constants at ~2 MB/s (round-5 finding — the
    # "compile-time grid scaling" was really closure size: 726 MB of
    # captured coherencies = ~6 min before Mosaic even starts).
    @jax.jit
    def f(tre, tim, coh, antp, antq):
        return jnp.sum(predict(tre, tim, coh, antp, antq, tile))

    t0 = time.time()
    v = float(np.asarray(f(tre, tim, coh, antp, antq)))
    print(f"mp={mp} F={F} rowsp={rowsp} tile={tile} chunked={chunked}: "
          f"compile+run {time.time()-t0:.1f}s val={v:.4g}", flush=True)
    ts = []
    for _ in range(3):
        t0 = time.time()
        float(np.asarray(f(tre, tim, coh, antp, antq)))
        ts.append(time.time() - t0)
    dt = sorted(ts)[1]
    print(f"  steady {dt*1e3:.2f} ms  BW {coh.size*4/dt/1e9:.0f} GB/s",
          flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "small"
    if which == "small":
        run(8, 2, 4096)
    elif which == "mid":
        run(40, 2, 32768)
    elif which == "full":
        # north-star shape, production configuration: 113664 rows =
        # 4 chunks x 28416 (R=222 per grid at tile=128), Mp=104.
        run(104, 2, chunked_rowsp(113460), tile=FULL_CLUSTER_TILE,
            chunked=True)
    elif which == "full1":
        # single-grid full shape (R=888 at tile=128) — exceeds
        # practical compile time; kept for relay regression probing.
        run(104, 2, 113664, tile=FULL_CLUSTER_TILE)
