// Native core for the buildsky tool: connected-component island
// labeling and weighted k-means sky clustering.
//
// Role of the reference's embedded C Clustering Library + island walk
// (/root/reference/src/buildsky/cluster.c, scluster.c:675-941,
// buildsky.c island scans) — reimplemented from scratch as a small
// C++ library with a C ABI for ctypes loading.  The numeric behavior
// follows the standard algorithms (8-connected flood fill; Lloyd
// iterations with flux-weighted centroids), not the reference's code.
//
// Build:  g++ -O2 -shared -fPIC -o libsagecal_native.so clusterlib.cpp

#include <cstdint>
#include <cstring>
#include <cmath>
#include <vector>
#include <queue>
#include <random>

extern "C" {

// 8-connected component labeling of mask (ny*nx int8), labels out
// (ny*nx int32, 0 = background, islands numbered from 1).  Returns the
// island count.
int label_islands(const int8_t *mask, int ny, int nx, int32_t *labels) {
  std::memset(labels, 0, sizeof(int32_t) * (size_t)ny * nx);
  int next = 0;
  std::queue<int> q;
  const int dy[8] = {-1, -1, -1, 0, 0, 1, 1, 1};
  const int dx[8] = {-1, 0, 1, -1, 1, -1, 0, 1};
  for (int y = 0; y < ny; y++) {
    for (int x = 0; x < nx; x++) {
      int idx = y * nx + x;
      if (!mask[idx] || labels[idx]) continue;
      next++;
      labels[idx] = next;
      q.push(idx);
      while (!q.empty()) {
        int cur = q.front();
        q.pop();
        int cy = cur / nx, cx = cur % nx;
        for (int k = 0; k < 8; k++) {
          int yy = cy + dy[k], xx = cx + dx[k];
          if (yy < 0 || yy >= ny || xx < 0 || xx >= nx) continue;
          int nidx = yy * nx + xx;
          if (mask[nidx] && !labels[nidx]) {
            labels[nidx] = next;
            q.push(nidx);
          }
        }
      }
    }
  }
  return next;
}

// Weighted k-means over 2-D points (the sky-clustering core,
// scluster.c kmeans_clustering role): n points (x, y) with weights w,
// k clusters, niter Lloyd iterations, deterministic seeded k-means++
// init.  Outputs assignment (n int32) and centers (k*2 double).
// Returns the number of non-empty clusters.
int kmeans_weighted(const double *x, const double *y, const double *w,
                    int n, int k, int niter, uint64_t seed,
                    int32_t *assign, double *centers) {
  if (n <= 0 || k <= 0) return 0;
  if (k > n) k = n;
  std::mt19937_64 rng(seed);
  std::vector<double> cx(k), cy(k);
  // k-means++ init on weighted distances
  std::vector<double> d2(n, 1e300);
  {
    std::uniform_int_distribution<int> pick(0, n - 1);
    int first = pick(rng);
    cx[0] = x[first];
    cy[0] = y[first];
    for (int c = 1; c < k; c++) {
      double total = 0.0;
      for (int i = 0; i < n; i++) {
        double dx = x[i] - cx[c - 1], dy = y[i] - cy[c - 1];
        double d = dx * dx + dy * dy;
        if (d < d2[i]) d2[i] = d;
        total += d2[i] * (w ? w[i] : 1.0);
      }
      std::uniform_real_distribution<double> u(0.0, total);
      double r = u(rng), acc = 0.0;
      int chosen = n - 1;
      for (int i = 0; i < n; i++) {
        acc += d2[i] * (w ? w[i] : 1.0);
        if (acc >= r) { chosen = i; break; }
      }
      cx[c] = x[chosen];
      cy[c] = y[chosen];
    }
  }
  std::vector<double> sw(k), sx(k), sy(k);
  for (int it = 0; it < niter; it++) {
    for (int c = 0; c < k; c++) sw[c] = sx[c] = sy[c] = 0.0;
    for (int i = 0; i < n; i++) {
      double best = 1e300;
      int bc = 0;
      for (int c = 0; c < k; c++) {
        double dx = x[i] - cx[c], dy = y[i] - cy[c];
        double d = dx * dx + dy * dy;
        if (d < best) { best = d; bc = c; }
      }
      assign[i] = bc;
      double wi = w ? w[i] : 1.0;
      sw[bc] += wi;
      sx[bc] += wi * x[i];
      sy[bc] += wi * y[i];
    }
    for (int c = 0; c < k; c++) {
      if (sw[c] > 0.0) {
        cx[c] = sx[c] / sw[c];
        cy[c] = sy[c] / sw[c];
      }
    }
  }
  int nonempty = 0;
  for (int c = 0; c < k; c++) {
    centers[2 * c] = cx[c];
    centers[2 * c + 1] = cy[c];
    if (sw[c] > 0.0) nonempty++;
  }
  return nonempty;
}

}  // extern "C"
