"""On-chip timing of the fused RIME kernel vs the XLA predict path."""

import time

import numpy as np

import bench


def _timeit(fn, args, repeats=3, label=""):
    float(np.asarray(fn(*args)))  # compile + run (host read = real sync)
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        v = float(np.asarray(fn(*args)))
        ts.append(time.perf_counter() - t0)
    dt = float(np.median(ts))
    print(f"{label:38s} {dt * 1e3:9.2f} ms   (={v:.6g})")
    return dt


def main():
    import jax
    import jax.numpy as jnp

    from sagecal_tpu.core.types import params_to_jones
    from sagecal_tpu.ops.rime_kernel import (
        fused_predict_packed, pack_gain_tables, pad_to,
    )
    from sagecal_tpu.solvers.lbfgs import lbfgs_fit
    from sagecal_tpu.utils.platform import cpu_device

    TILE, MC = 512, 8
    with jax.default_device(cpu_device()):
        data, cdata, p0 = bench.build_workload(np.float32, bench.TILESZ)
        M = bench.NCLUSTERS
        F = data.vis.shape[0]
        rows = data.vis.shape[-1]
        mp = pad_to(M, MC)
        rowsp = pad_to(rows, TILE)
        coh_ri = np.zeros((mp, F, 8, rowsp), np.float32)
        coh_ri[:M, :, :4, :rows] = np.asarray(cdata.coh.real)
        coh_ri[:M, :, 4:, :rows] = np.asarray(cdata.coh.imag)
        vis_ri = np.zeros((F, 8, rowsp), np.float32)
        vis_ri[:, :4, :rows] = np.asarray(data.vis.real)
        vis_ri[:, 4:, :rows] = np.asarray(data.vis.imag)
        maskp = np.zeros((F, rowsp), np.float32)
        maskp[:, :rows] = np.asarray(data.mask)
        antp = np.zeros((1, rowsp), np.int32)
        antq = np.zeros((1, rowsp), np.int32)
        antp[0, :rows] = np.asarray(data.ant_p)
        antq[0, :rows] = np.asarray(data.ant_q)
        p0_h = np.asarray(p0)

    dev = jax.devices()[0]
    print("platform:", dev.platform)
    coh_ri, vis_ri, maskp, antp, antq, p0_d = (
        jax.device_put(a, dev)
        for a in (coh_ri, vis_ri, maskp, antp, antq, p0_h)
    )
    N = bench.NSTATIONS
    nu = 5.0

    @jax.jit
    def predict_fused(p):
        jones = params_to_jones(p.reshape(M, 1, 8 * N))[:, 0]
        tre, tim = pack_gain_tables(jones, mp)
        m = fused_predict_packed(tre, tim, coh_ri, antp, antq, TILE)
        return jnp.sum(m)

    def cost_fn(pflat):
        jones = params_to_jones(pflat.reshape(M, 1, 8 * N))[:, 0]
        tre, tim = pack_gain_tables(jones, mp)
        model = fused_predict_packed(
            tre, tim, jax.lax.stop_gradient(coh_ri), antp, antq, TILE
        )
        d = (vis_ri - model) * maskp[:, None, :]
        e2 = d[:, :4, :] ** 2 + d[:, 4:, :] ** 2
        return jnp.sum(jnp.log1p(e2 / nu))

    @jax.jit
    def cost_only(p):
        return cost_fn(p.reshape(-1))

    @jax.jit
    def cost_and_grad(p):
        c, g = jax.value_and_grad(cost_fn)(p.reshape(-1))
        return c + jnp.sum(g * g)

    @jax.jit
    def solve(p):
        fit = lbfgs_fit(cost_fn, None, p.reshape(-1),
                        itmax=bench.LBFGS_ITERS, M=7)
        return fit.cost + fit.iterations

    t_pred = _timeit(predict_fused, (p0_d,), label="fused predict fwd")
    t_cost = _timeit(cost_only, (p0_d,), label="fused cost eval")
    t_vg = _timeit(cost_and_grad, (p0_d,), label="fused cost+grad")
    t_solve = _timeit(solve, (p0_d,), label="full 20-iter LBFGS (fused)")
    print(f"\nper-iter {t_solve / bench.LBFGS_ITERS * 1e3:.2f} ms "
          f"(XLA path measured 130.4 ms/iter)")
    coh_bytes = coh_ri.size * 4
    print(f"implied BW in fused fwd: {coh_bytes / t_pred / 1e9:.0f} GB/s "
          f"of 819 GB/s")


if __name__ == "__main__":
    main()
