"""On-chip phase profiling of the bench step (round-3 perf work).

Times the pieces of one LBFGS iteration at the north-star shape to find
the wall: predict forward, cost, cost+grad, and the full 20-iter solve.
"""

import time

import numpy as np

import bench


def _time(fn, args, repeats=3, label=""):
    """Time a jitted fn that returns a SCALAR.  Sync by transferring the
    scalar to host: jax.block_until_ready is a NO-OP on the axon backend
    (measured 0.2 ms for a 2.6 s computation), so only a host read
    observes completion."""
    float(np.asarray(fn(*args)))  # compile + run
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        v = float(np.asarray(fn(*args)))
        ts.append(time.perf_counter() - t0)
    dt = float(np.median(ts))
    print(f"{label:34s} {dt * 1e3:9.2f} ms   (={v:.6g})")
    return dt


def main():
    import jax
    import jax.numpy as jnp

    from sagecal_tpu.solvers.sage import predict_full_model
    from sagecal_tpu.utils.platform import cpu_device

    with jax.default_device(cpu_device()):
        data, cdata, p0 = bench.build_workload(np.float32, bench.TILESZ)
        vis_ri = np.concatenate(
            [np.asarray(data.vis.real), np.asarray(data.vis.imag)], axis=-2
        )
        coh_ri = np.concatenate(
            [np.asarray(cdata.coh.real), np.asarray(cdata.coh.imag)], axis=-2
        )
        mask = np.asarray(data.mask)
        p0_h = np.asarray(p0)

    dev = jax.devices()[0]
    print("platform:", dev.platform)
    vis_ri, mask, coh_ri, p0_d = (
        jax.device_put(a, dev) for a in (vis_ri, mask, coh_ri, p0_h)
    )
    jax.block_until_ready((vis_ri, mask, coh_ri, p0_d))

    M, nchunk, n8 = bench.NCLUSTERS, 1, 8 * bench.NSTATIONS
    nu = 5.0

    def unpack(vr, cr):
        vis = jax.lax.complex(vr[:, :4, :], vr[:, 4:, :])
        coh = jax.lax.complex(cr[:, :, :4, :], cr[:, :, 4:, :])
        return vis, coh

    @jax.jit
    def predict_only(vr, mk, cr, p):
        vis, coh = unpack(vr, cr)
        d = data.replace(vis=vis, mask=mk)
        c = cdata._replace(coh=coh)
        m = predict_full_model(p.reshape(M, nchunk, n8), c, d)
        return jnp.sum(jnp.real(m)) + jnp.sum(jnp.imag(m))

    def make_cost(vr, mk, cr):
        vis, coh = unpack(vr, cr)
        d = data.replace(vis=vis, mask=mk)
        c = cdata._replace(coh=coh)

        def cost_fn(pflat):
            model = predict_full_model(pflat.reshape(M, nchunk, n8), c, d)
            diff = (vis - model) * mk[:, None, :]
            e2 = jnp.real(diff) ** 2 + jnp.imag(diff) ** 2
            return jnp.sum(jnp.log1p(e2 / nu))

        return cost_fn

    @jax.jit
    def cost_only(vr, mk, cr, p):
        return make_cost(vr, mk, cr)(p.reshape(-1))

    @jax.jit
    def cost_and_grad(vr, mk, cr, p):
        c, g = jax.value_and_grad(make_cost(vr, mk, cr))(p.reshape(-1))
        return c + jnp.sum(g * g)

    args = (vis_ri, mask, coh_ri, p0_d)
    t_pred = _time(predict_only, args, label="predict_full_model fwd")
    t_cost = _time(cost_only, args, label="cost eval")
    t_vg = _time(cost_and_grad, args, label="cost+grad (value_and_grad)")

    step0 = bench.make_step(data, cdata)

    @jax.jit
    def step_scalar(vr, mk, cr, p):
        _, cost, its = step0(vr, mk, cr, p)
        return cost + its

    t_step = _time(step_scalar, args, label=f"full {bench.LBFGS_ITERS}-iter LBFGS solve")
    iters = bench.LBFGS_ITERS
    print(
        f"\nper-iter {t_step / iters * 1e3:.2f} ms; "
        f"as cost-equivalents: step/(4*it+3) = "
        f"{t_step / (4 * iters + 3) * 1e3:.2f} ms vs cost {t_cost * 1e3:.2f} ms"
    )
    coh_bytes = coh_ri.size * 4
    print(
        f"coh stack {coh_bytes / 1e6:.0f} MB; single-read roofline "
        f"{coh_bytes / 819e9 * 1e3:.2f} ms @819 GB/s"
    )
    print(f"implied BW in predict fwd: {coh_bytes / t_pred / 1e9:.0f} GB/s")


if __name__ == "__main__":
    main()
