"""Time the ACTUAL reference C solver at the north-star workload.

Compiles the reference's CPU ``libdirac`` from its mounted sources
(tests/ref_oracle.py) and times ``bfgsfit_visibilities``
(/root/reference/src/lib/Dirac/lmfit.c:1126) — the joint robust-LBFGS
fit over all 8*N*M parameters, the same per-iteration work bench.py
times on the TPU — at the BASELINE.md north-star shape: 62 stations,
100 clusters, one tile of 60 timeslots.

Semantics caveats, stated so the ratio is honest:
  * the reference's joint LBFGS operates on the channel-averaged data
    at freq0 (one effective channel; lmfit.c:1140-1158) while bench.py
    evaluates the model on NCHAN=2 channels — the reference does about
    HALF the model-evaluation work per iteration;
  * each code runs its own line search (Fletcher + cubic interpolation
    in the reference, lbfgs.c:116-443; Armijo backtracking here), both
    with memory M=7, one curvature pair per iteration;
  * Nt is a thread count, but this container exposes a single core
    (the JSON records both).
The LBFGS cost is isolated by timing max_lbfgs=ITERS minus a
max_lbfgs=0 run (the two full-model residual evaluations around the
fit, lmfit.c:1177-1200, are identical in both).

Prints one JSON line; ``python ref_bench.py`` takes ~5-15 min on this
host.  The measured number is pinned into bench.py as
``_REF_CPU_PINNED`` so the driver's TPU bench can report
``vs_reference_cpu`` without rebuilding/re-timing the C library.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))

import bench  # noqa: E402  (workload construction + shape constants)


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import ref_oracle

    lib = ref_oracle.load_lib()
    if lib is None:
        print(json.dumps({"error": "reference library unavailable"}))
        return 1

    tilesz_req = int(os.environ.get("REF_BENCH_TILESZ", bench.TILESZ))
    data, cdata, p0 = bench.build_workload(dtype=np.float64,
                                           tilesz=tilesz_req)
    rows = data.vis.shape[-1]
    nbase = data.nbase
    tilesz = data.tilesz
    # channel-average the 2-channel data the way the reference's x is
    # (data.cpp:665-696 averaging into x)
    x = np.asarray(data.vis).mean(axis=0)          # (4, rows)
    coh = np.asarray(cdata.coh).mean(axis=1)       # (M, 4, rows)
    u = np.asarray(data.u, np.float64)
    v = np.asarray(data.v, np.float64)
    w = np.asarray(data.w, np.float64)
    sta1 = np.asarray(data.ant_p)
    sta2 = np.asarray(data.ant_q)

    from sagecal_tpu.core.types import params_to_jones

    j0 = np.asarray(params_to_jones(p0[:, 0]))     # (M, N, 2, 2)

    nthreads = os.cpu_count() or 1
    iters = int(os.environ.get("REF_BENCH_ITERS", bench.LBFGS_ITERS))

    def run(max_lbfgs):
        t0 = time.perf_counter()
        _, r0, r1, rv = ref_oracle.ref_bfgsfit(
            u, v, w, x, bench.NSTATIONS, nbase, tilesz, sta1, sta2,
            coh, bench.NCLUSTERS, j0,
            freq0=float(data.freq0), fdelta=float(data.deltaf),
            nthreads=nthreads, max_lbfgs=max_lbfgs, lbfgs_m=7,
            solver_mode=2, mean_nu=5.0,
        )
        return time.perf_counter() - t0, r0, r1, rv

    t_base, r0b, r1b, _ = run(0)          # overhead: 2 full-model residuals
    t_full, r0, r1, rv = run(iters)
    t_lbfgs = max(t_full - t_base, 1e-9)
    its = iters / t_lbfgs
    print(json.dumps({
        "metric": "ref_cpu_lbfgs_cal_iters_per_sec",
        "value": round(its, 4),
        "unit": f"iter/s (62 stn, 100 clusters, {tilesz} ts, "
                "chan-averaged, reference C bfgsfit_visibilities)",
        "threads": nthreads,
        "t_lbfgs_s": round(t_lbfgs, 2),
        "t_overhead_s": round(t_base, 2),
        "res_0": r0, "res_1": r1, "retval": rv,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
