"""sagecal-tpu: TPU-native direction-dependent radio-interferometric calibration.

A ground-up JAX/XLA re-design of the capabilities of SAGECal
(nlesc-dirac/sagecal): per-station, per-direction 2x2 Jones calibration by
SAGE/EM-partitioned Levenberg-Marquardt, (stochastic) LBFGS and Riemannian
trust-region solvers, Gaussian / robust Student's-t noise models, sky-model
prediction (point/Gaussian/disk/ring/shapelet sources, station + element
beams), multi-frequency consensus ADMM over a device mesh, spatial
regularization, and federated calibration.

Layering (mirrors the reference's libdirac / libdirac-radio / apps split,
reference SURVEY.md section 1):

- ``sagecal_tpu.core``     data model: visibilities, baselines, Jones layout
- ``sagecal_tpu.ops``      RIME prediction, beams, shapelets, special functions
- ``sagecal_tpu.solvers``  LM / LBFGS / RTR / NSD / robust EM / SAGE driver
- ``sagecal_tpu.parallel`` mesh, consensus ADMM, manifold averaging, federated
- ``sagecal_tpu.io``       MS-like data access, sky-model / solution files
- ``sagecal_tpu.apps``     calibration pipelines and CLI
"""

__version__ = "0.1.0"

from sagecal_tpu.core import types as _types  # noqa: F401
