"""jaxlint: AST-based JAX-discipline static analysis for sagecal-tpu.

The correctness-critical invariants of the TPU port — no recompile
hazards in jitted hot paths, fixed shapes, the float32/complex64
precision policy, no hidden host<->device syncs, collectives confined
to the parallel layer — were until now enforced only by hand-pinned
tests (the zero-recompile pins in tests/test_quality.py and
tests/test_perf_obs.py).  This package turns them into a mechanical,
repo-wide gate:

- :mod:`sagecal_tpu.analysis.callgraph` parses every module with stdlib
  ``ast``, resolves imports, and computes the set of *jit-reachable*
  functions by walking references outward from every ``jax.jit`` /
  ``instrumented_jit`` wrap site (decorators, ``x_jit = jit(f)``
  assignments, ``partial(jax.jit, ...)``, and one-level pass-through
  wrappers like ``shard_map(f, ...)``).
- :mod:`sagecal_tpu.analysis.rules` hosts one module per rule:
  JL001 traced-value Python control flow, JL002 host-sync calls,
  JL003 recompile hazards (undeclared static args), JL004 64-bit dtype
  policy violations, JL005 data-dependent shapes in jit, JL006
  collectives outside the parallel layer, JL007 undonated carry
  buffers, the fleet-era rules — JL008 non-atomic writes to protocol
  state (manifests/leases/queues/checkpoints must go through
  tmp+rename or exclusive link publish), JL009 ``pickle.load`` without
  a version-header gate (the serve/aot_store.py pattern is mandatory),
  JL010 raw ``time.time()`` inside lease/deadline logic instead of an
  injectable clock, JL011 use of a donated buffer after the jit call
  that consumed it — and the report-only JL900 dead-import sweep.
- :mod:`sagecal_tpu.analysis.fsmodel` +
  :mod:`sagecal_tpu.analysis.protocol_check` go beyond linting: a
  deterministic simulated filesystem (exact atomicity semantics,
  crash = loss of unstaged state) and an explicit-state model checker
  that drives the REAL fleet lease queue and stream owner-lease code
  through every interleaving of 2-3 logical workers with crash
  injection at each fs-op boundary, asserting the protocol invariants
  at every reachable state.  Run it as ``sagecal-tpu diag protocol``.
- :mod:`sagecal_tpu.analysis.engine` runs the rules, applies per-line
  ``# jaxlint: disable=RULE`` suppression pragmas, and formats
  text/JSON reports.
- :mod:`sagecal_tpu.analysis.baseline` grandfathers pre-existing
  findings through a committed JSON baseline so the gate only fails on
  NEW findings.

Run it as ``python -m sagecal_tpu.analysis sagecal_tpu/`` or via the
CLI: ``sagecal-tpu diag lint sagecal_tpu/``.  Zero dependencies beyond
the stdlib — importing this package never imports jax or numpy, so the
gate runs on any host, backend or no backend.

The static rules pair with a *runtime* contract layer
(:mod:`sagecal_tpu.obs.contracts`): ``SAGECAL_CHECKIFY=1`` wraps the
solver jit entries in ``jax.experimental.checkify`` NaN/div/index
checks and surfaces failures as structured ``contract_violation``
events.
"""

from sagecal_tpu.analysis.engine import (  # noqa: F401
    Finding,
    analyze_paths,
)
from sagecal_tpu.analysis.cli import main  # noqa: F401
