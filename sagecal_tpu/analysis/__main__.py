"""``python -m sagecal_tpu.analysis [paths...]`` — run the lint gate."""

import sys

from sagecal_tpu.analysis.cli import run

if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
