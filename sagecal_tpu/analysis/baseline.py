"""Baseline file: grandfather pre-existing findings.

The baseline is a committed JSON file mapping finding keys (rule, path,
symbol, message) to an occurrence count.  The gate only fails on *new*
findings — keys absent from the baseline, or present more often than the
baseline allows.  Counts (rather than a set) make two identical findings
in one file distinguishable from one.

The repo ships a baseline with **zero gate findings** (every gating
finding is fixed or carries a reasoned pragma); the mechanism exists so
future adopters of new rules can land the rule and burn down findings
incrementally.  Report-only findings are also recorded: they never
gate, but a committed record of each deliberate one (e.g. a JL007
carry whose callers reuse the args tuple, so donation would be unsafe)
lets the acceptance test distinguish "known and decided" from "new and
undecided".
"""

from __future__ import annotations

import collections
import json
import os
from typing import Counter, Iterable, List, Tuple

from sagecal_tpu.analysis.engine import Finding

_SEP = "\x1f"


def _encode(key: Tuple[str, str, str, str]) -> str:
    return _SEP.join(key)


def _decode(s: str) -> Tuple[str, str, str, str]:
    parts = s.split(_SEP)
    while len(parts) < 4:
        parts.append("")
    return tuple(parts[:4])


def load_baseline(path: str) -> Counter:
    """Counter of finding keys; an absent file is an empty baseline."""
    if not path or not os.path.isfile(path):
        return collections.Counter()
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: Counter = collections.Counter()
    for rec in data.get("findings", []):
        key = (rec["rule"], rec["path"], rec.get("symbol", ""),
               rec["message"])
        out[key] += int(rec.get("count", 1))
    return out


def _existing_whys(path: str) -> dict:
    """key -> "why" justification from the committed baseline, so an
    --update-baseline rewrite never drops the reasoning attached to a
    deliberate finding (e.g. the stream solutions append-chain)."""
    if not path or not os.path.isfile(path):
        return {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    out = {}
    for rec in data.get("findings", []):
        if rec.get("why"):
            key = (rec["rule"], rec["path"], rec.get("symbol", ""),
                   rec["message"])
            out[key] = rec["why"]
    return out


def save_baseline(path: str, findings: Iterable[Finding]) -> None:
    # report-only findings are recorded too (see module docstring);
    # partition() still never gates them
    whys = _existing_whys(path)
    counts: Counter = collections.Counter(f.key() for f in findings)
    recs = []
    for k, n in sorted(counts.items()):
        rec = {"rule": k[0], "path": k[1], "symbol": k[2],
               "message": k[3], "count": n}
        if k in whys:
            rec["why"] = whys[k]
        recs.append(rec)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": recs}, f, indent=2,
                  sort_keys=True)
        f.write("\n")


def partition(findings: Iterable[Finding], baseline: Counter):
    """Split gate-relevant findings into (new, grandfathered) lists.

    Report-only findings are never gated and appear in neither list."""
    remaining = collections.Counter(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        if f.report_only:
            continue
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
