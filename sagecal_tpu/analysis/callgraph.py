"""Module parsing + the jit-reachability call graph.

Everything downstream (the JL rules) keys off two questions this module
answers mechanically, per the repo's layering:

1. *What does this dotted name mean here?* — per-module import tables
   map local aliases to fully qualified names (``jnp`` -> ``jax.numpy``,
   ``instrumented_jit`` -> ``sagecal_tpu.obs.perf.instrumented_jit``),
   so rules match on canonical names, never on spelling.
2. *Can this statement execute inside a jit trace?* — jit-roots are
   collected from decorator and call-site wrap forms, then closed over
   the reference graph (any Name/Attribute in a function body that
   resolves to a known function is an edge; lexically nested functions
   of a reachable function are reachable).  This over-approximates —
   a reference passed to ``lax.scan``/``vmap`` is an edge even without
   a direct call — which is the right bias for a lint gate: reachable
   code that is *actually* host-only gets a pragma with a reason.

Stdlib ``ast`` only; no imports are executed.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

# canonical qualified names that create a jit boundary when they wrap a
# function.  instrumented_jit (obs/perf.py) is the repo's jax.jit
# drop-in; its static_argnums/static_argnames kwargs carry the same
# semantics, so JL003 cross-checks against both uniformly.
JIT_WRAPPERS = frozenset({
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
    "sagecal_tpu.obs.perf.instrumented_jit",
})

# wrappers that forward their first argument's body into the trace:
# jit(shard_map(f)) / jit(vmap(f)) must mark f (and what f references)
# jit-reachable
PASSTHROUGH_WRAPPERS = frozenset({
    "jax.vmap",
    "jax.pmap",
    "jax.grad",
    "jax.value_and_grad",
    "jax.checkpoint",
    "jax.remat",
    "jax.experimental.shard_map.shard_map",
    "functools.partial",
})

_PRAGMA_RE = re.compile(
    r"#\s*jaxlint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)


@dataclasses.dataclass
class FuncInfo:
    """One function/method definition (top-level, nested, or class)."""

    qualname: str  # "<module>.<outer>.<name>"
    module: str
    name: str
    node: ast.AST
    lineno: int
    parent: Optional[str] = None  # enclosing function qualname
    children: List[str] = dataclasses.field(default_factory=list)
    jit_root: bool = False
    static_argnames: Set[str] = dataclasses.field(default_factory=set)
    static_argnums: Set[int] = dataclasses.field(default_factory=set)
    donate_argnames: Set[str] = dataclasses.field(default_factory=set)
    donate_argnums: Set[int] = dataclasses.field(default_factory=set)
    wrap_sites: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)  # (module, lineno) of each jit wrap
    refs: Set[str] = dataclasses.field(default_factory=set)  # raw dotted


@dataclasses.dataclass
class ModuleInfo:
    path: str  # as discovered (relative to cwd when possible)
    name: str  # dotted module name
    tree: Optional[ast.Module]
    lines: List[str]
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    import_lines: Set[int] = dataclasses.field(default_factory=set)
    toplevel: Set[str] = dataclasses.field(default_factory=set)
    functions: Dict[str, FuncInfo] = dataclasses.field(default_factory=dict)
    func_by_node: Dict[int, FuncInfo] = dataclasses.field(
        default_factory=dict)  # id(node) -> FuncInfo
    pragmas: Dict[int, Set[str]] = dataclasses.field(default_factory=dict)
    file_pragmas: Set[str] = dataclasses.field(default_factory=set)
    parse_error: Optional[str] = None

    def enclosing_function(self, node: ast.AST) -> Optional[FuncInfo]:
        """Innermost function containing ``node`` (via parent links)."""
        cur = getattr(node, "_jaxlint_parent", None)
        while cur is not None:
            fi = self.func_by_node.get(id(cur))
            if fi is not None:
                return fi
            cur = getattr(cur, "_jaxlint_parent", None)
        return None


def qual_of(node: ast.AST, imports: Dict[str, str],
            toplevel: Optional[Set[str]] = None,
            module: str = "") -> Optional[str]:
    """Canonical dotted name of a Name/Attribute chain, alias-expanded.

    ``jnp.where`` -> ``jax.numpy.where``; a module-local top-level name
    gets the module prefix so it matches the function table."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    head = node.id
    if head in imports:
        base = imports[head]
    elif toplevel is not None and head in toplevel and module:
        base = f"{module}.{head}"
    else:
        base = head
    parts.append(base)
    return ".".join(reversed(parts))


def _module_name_for(path: str) -> str:
    """Dotted module name by climbing the package (__init__.py) chain."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    parts = [] if stem == "__init__" else [stem]
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(parts) if parts else stem


def _scan_pragmas(lines: Sequence[str]) -> Tuple[Dict[int, Set[str]],
                                                 Set[str]]:
    per_line: Dict[int, Set[str]] = {}
    per_file: Set[str] = set()
    for i, line in enumerate(lines, start=1):
        if "jaxlint" not in line:
            continue
        for m in _PRAGMA_RE.finditer(line):
            rules = {r.strip().upper()
                     for r in m.group("rules").split(",") if r.strip()}
            if m.group("file"):
                per_file |= rules
            else:
                per_line.setdefault(i, set()).update(rules)
    return per_line, per_file


def _collect_imports(tree: ast.Module, modname: str,
                     is_pkg_init: bool) -> Tuple[Dict[str, str], Set[int]]:
    imports: Dict[str, str] = {}
    import_lines: Set[int] = set()
    # the package a relative import is relative to
    pkg_parts = modname.split(".") if is_pkg_init else modname.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            import_lines.add(node.lineno)
            for a in node.names:
                if a.asname:
                    imports[a.asname] = a.name
                else:
                    # "import jax.numpy" binds "jax"
                    imports.setdefault(a.name.split(".")[0],
                                       a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            import_lines.add(node.lineno)
            if node.module == "__future__":
                continue
            if node.level:
                base_parts = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(base_parts + (
                    node.module.split(".") if node.module else []))
            else:
                base = node.module or ""
            for a in node.names:
                if a.name == "*":
                    continue
                imports[a.asname or a.name] = (
                    f"{base}.{a.name}" if base else a.name)
    return imports, import_lines


def _link_parents(tree: ast.Module) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._jaxlint_parent = node


class CallGraph:
    """All analyzed modules + the jit-reachability closure."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}  # dotted name -> info
        self.modules_by_path: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.reachable: Set[str] = set()
        # pending jit/passthrough wrap call sites:
        # (modname, scope_qual, target_expr, static_names, static_nums,
        #  donate_names, donate_nums, lineno)
        self._wrap_calls: List[tuple] = []
        # (scope_qual or "", name) -> first-arg expr of a passthrough call
        self._assign_chain: Dict[Tuple[str, str], ast.AST] = {}

    # ------------------------------------------------------------ build
    def add_file(self, path: str) -> ModuleInfo:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        lines = source.splitlines()
        name = _module_name_for(path)
        per_line, per_file = _scan_pragmas(lines)
        mi = ModuleInfo(path=path, name=name, tree=None, lines=lines,
                        pragmas=per_line, file_pragmas=per_file)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            mi.parse_error = f"{type(e).__name__}: {e.msg} (line {e.lineno})"
            self._register(mi)
            return mi
        mi.tree = tree
        _link_parents(tree)
        is_pkg_init = os.path.basename(path) == "__init__.py"
        mi.imports, mi.import_lines = _collect_imports(tree, name,
                                                       is_pkg_init)
        mi.toplevel = {
            n.name for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef))
        } | {
            t.id for n in tree.body if isinstance(n, ast.Assign)
            for t in n.targets if isinstance(t, ast.Name)
        }
        self._collect_functions(mi)
        self._collect_wraps_and_refs(mi)
        self._register(mi)
        return mi

    def _register(self, mi: ModuleInfo) -> None:
        self.modules[mi.name] = mi
        self.modules_by_path[mi.path] = mi
        for q, fi in mi.functions.items():
            self.functions[q] = fi

    def _collect_functions(self, mi: ModuleInfo) -> None:
        def visit(node: ast.AST, prefix: str, parent_fn: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{prefix}.{child.name}"
                    fi = FuncInfo(qualname=q, module=mi.name,
                                  name=child.name, node=child,
                                  lineno=child.lineno, parent=parent_fn)
                    mi.functions[q] = fi
                    mi.func_by_node[id(child)] = fi
                    if parent_fn is not None:
                        mi.functions[parent_fn].children.append(q)
                    self._check_jit_decorators(mi, fi, child)
                    visit(child, q, q)
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}.{child.name}", parent_fn)
                else:
                    visit(child, prefix, parent_fn)

        visit(mi.tree, mi.name, None)

    def _statics_from_keywords(self, keywords) -> Tuple[Set[str], Set[int]]:
        names: Set[str] = set()
        nums: Set[int] = set()
        for kw in keywords or ():
            if kw.arg == "static_argnames":
                for el in self._const_elts(kw.value):
                    if isinstance(el, str):
                        names.add(el)
            elif kw.arg == "static_argnums":
                for el in self._const_elts(kw.value):
                    if isinstance(el, int):
                        nums.add(el)
        return names, nums

    def _donates_from_keywords(self, keywords) -> Tuple[Set[str], Set[int]]:
        names: Set[str] = set()
        nums: Set[int] = set()
        for kw in keywords or ():
            if kw.arg == "donate_argnames":
                for el in self._const_elts(kw.value):
                    if isinstance(el, str):
                        names.add(el)
            elif kw.arg == "donate_argnums":
                for el in self._const_elts(kw.value):
                    if isinstance(el, int):
                        nums.add(el)
        return names, nums

    @staticmethod
    def _const_elts(node: ast.AST) -> List:
        if isinstance(node, ast.Constant):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return [e.value for e in node.elts
                    if isinstance(e, ast.Constant)]
        return []

    def _check_jit_decorators(self, mi: ModuleInfo, fi: FuncInfo,
                              node) -> None:
        for dec in node.decorator_list:
            call = dec if isinstance(dec, ast.Call) else None
            target = call.func if call is not None else dec
            q = qual_of(target, mi.imports, mi.toplevel, mi.name)
            if q in JIT_WRAPPERS:
                fi.jit_root = True
                fi.wrap_sites.append((mi.name, dec.lineno))
                if call is not None:
                    names, nums = self._statics_from_keywords(call.keywords)
                    fi.static_argnames |= names
                    fi.static_argnums |= nums
                    dnames, dnums = self._donates_from_keywords(call.keywords)
                    fi.donate_argnames |= dnames
                    fi.donate_argnums |= dnums
            elif (call is not None and q in ("functools.partial", "partial")
                  and call.args):
                inner_q = qual_of(call.args[0], mi.imports, mi.toplevel,
                                  mi.name)
                if inner_q in JIT_WRAPPERS:
                    fi.jit_root = True
                    fi.wrap_sites.append((mi.name, dec.lineno))
                    names, nums = self._statics_from_keywords(call.keywords)
                    fi.static_argnames |= names
                    fi.static_argnums |= nums
                    dnames, dnums = self._donates_from_keywords(call.keywords)
                    fi.donate_argnames |= dnames
                    fi.donate_argnums |= dnums

    def _collect_wraps_and_refs(self, mi: ModuleInfo) -> None:
        for node in ast.walk(mi.tree):
            scope_fi = mi.enclosing_function(node)
            scope = scope_fi.qualname if scope_fi is not None else ""
            if isinstance(node, ast.Call):
                q = qual_of(node.func, mi.imports, mi.toplevel, mi.name)
                if q in JIT_WRAPPERS and node.args:
                    names, nums = self._statics_from_keywords(node.keywords)
                    dnames, dnums = self._donates_from_keywords(node.keywords)
                    self._wrap_calls.append(
                        (mi.name, scope, node.args[0], names, nums,
                         dnames, dnums, node.lineno))
                elif (q in ("functools.partial", "partial")
                      and len(node.args) >= 2):
                    inner_q = qual_of(node.args[0], mi.imports, mi.toplevel,
                                      mi.name)
                    if inner_q in JIT_WRAPPERS:
                        names, nums = self._statics_from_keywords(
                            node.keywords)
                        dnames, dnums = self._donates_from_keywords(
                            node.keywords)
                        self._wrap_calls.append(
                            (mi.name, scope, node.args[1], names, nums,
                             dnames, dnums, node.lineno))
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                # remember `fn = shard_map(local_fit, ...)`-style bindings
                # so a later jit(fn) chases through to local_fit
                q = qual_of(node.value.func, mi.imports, mi.toplevel,
                            mi.name)
                if q is not None and (
                        q in PASSTHROUGH_WRAPPERS
                        or q.split(".")[-1] == "shard_map"):
                    if node.value.args:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                self._assign_chain[(scope, t.id)] = \
                                    node.value.args[0]
            if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(node, "ctx", None), ast.Load):
                if scope_fi is None:
                    continue
                q = qual_of(node, mi.imports, mi.toplevel, mi.name)
                if q:
                    scope_fi.refs.add(q)

    # ---------------------------------------------------------- resolve
    def _resolve_target(self, modname: str, scope: str, expr: ast.AST,
                        depth: int = 0) -> Optional[FuncInfo]:
        """Resolve a jit-wrap target expression to a FuncInfo, chasing
        one-level pass-through wrappers (shard_map/vmap/partial)."""
        if depth > 4:
            return None
        mi = self.modules.get(modname)
        if mi is None:
            return None
        if isinstance(expr, ast.Call):
            q = qual_of(expr.func, mi.imports, mi.toplevel, mi.name)
            if q is not None and (q in PASSTHROUGH_WRAPPERS
                                  or q.split(".")[-1] == "shard_map"):
                if expr.args:
                    return self._resolve_target(modname, scope, expr.args[0],
                                                depth + 1)
            return None
        q = qual_of(expr, mi.imports, mi.toplevel, mi.name)
        if q is None:
            return None
        fi = self._lookup(q, modname, scope)
        if fi is not None:
            return fi
        # a bare local name bound from a pass-through wrapper call
        if isinstance(expr, ast.Name):
            s = scope
            while True:
                chained = self._assign_chain.get((s, expr.id))
                if chained is not None:
                    return self._resolve_target(modname, s, chained,
                                                depth + 1)
                if not s:
                    break
                parent = self.functions.get(s)
                s = parent.parent if parent is not None and parent.parent \
                    else ""
        return None

    def _lookup(self, q: str, modname: str, scope: str) -> Optional[FuncInfo]:
        if q in self.functions:
            return self.functions[q]
        # scope-local nested name, walking the enclosing chain out
        s = scope
        while s:
            cand = f"{s}.{q}"
            if cand in self.functions:
                return self.functions[cand]
            parent = self.functions.get(s)
            s = parent.parent if parent is not None and parent.parent else ""
        cand = f"{modname}.{q}"
        return self.functions.get(cand)

    def finalize(self) -> None:
        """Resolve wrap call-sites, then close reachability."""
        for (modname, scope, expr, names, nums, dnames, dnums,
             lineno) in self._wrap_calls:
            fi = self._resolve_target(modname, scope, expr)
            if fi is None:
                continue
            fi.jit_root = True
            fi.wrap_sites.append((modname, lineno))
            fi.static_argnames |= names
            fi.static_argnums |= nums
            fi.donate_argnames |= dnames
            fi.donate_argnums |= dnums
        # BFS over reference edges + lexical nesting
        queue = [q for q, fi in self.functions.items() if fi.jit_root]
        seen: Set[str] = set()
        while queue:
            q = queue.pop()
            if q in seen:
                continue
            seen.add(q)
            fi = self.functions[q]
            for child in fi.children:
                if child not in seen:
                    queue.append(child)
            for ref in fi.refs:
                target = self._lookup(ref, fi.module, fi.qualname)
                if target is not None and target.qualname not in seen:
                    queue.append(target.qualname)
        self.reachable = seen

    # ------------------------------------------------------------ query
    def is_reachable(self, fi: Optional[FuncInfo]) -> bool:
        return fi is not None and fi.qualname in self.reachable

    def stmt_reachable(self, mi: ModuleInfo, node: ast.AST) -> \
            Optional[FuncInfo]:
        """The innermost *jit-reachable* function containing ``node``
        (itself or any lexical ancestor), or None."""
        fi = mi.enclosing_function(node)
        while fi is not None:
            if fi.qualname in self.reachable:
                return fi
            fi = self.functions.get(fi.parent) if fi.parent else None
        return None


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted list of .py files."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if not d.startswith(".") and d != "__pycache__")
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
    return out


def build_callgraph(files: Sequence[str]) -> CallGraph:
    g = CallGraph()
    for f in files:
        g.add_file(f)
    g.finalize()
    return g
