"""jaxlint command line: argument parsing, baseline gate, exit codes.

Exit codes: 0 clean (or all findings baselined / report-only), 1 new
findings, 2 usage error.  Reached three ways with identical semantics:

- ``python -m sagecal_tpu.analysis [paths...]``
- ``python tools/jaxlint.py [paths...]``
- ``sagecal-tpu diag lint [paths...]``
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence

from sagecal_tpu.analysis import baseline as baseline_mod
from sagecal_tpu.analysis import engine

DEFAULT_BASELINE = "jaxlint_baseline.json"


def _default_paths() -> List[str]:
    """Lint the installed package when invoked with no paths."""
    import sagecal_tpu

    return [os.path.dirname(os.path.abspath(sagecal_tpu.__file__))]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="jaxlint",
        description="AST-based JAX-discipline analyzer for sagecal-tpu",
    )
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint "
                        "(default: the sagecal_tpu package)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="report format (default: text)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline JSON of grandfathered findings "
                        f"(default: ./{DEFAULT_BASELINE} if present)")
    p.add_argument("--update-baseline", action="store_true",
                   help="write current findings to the baseline file "
                        "and exit 0")
    p.add_argument("--rules", default=None, metavar="IDS",
                   help="comma-separated rule ids to run "
                        "(default: all, e.g. JL001,JL004)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def _select_rules(spec: Optional[str]):
    rules = engine.default_rules()
    if spec is None:
        return rules
    wanted = {r.strip().upper() for r in spec.split(",") if r.strip()}
    known = {r.id for r in rules}
    unknown = wanted - known
    if unknown:
        raise SystemExit(
            f"jaxlint: unknown rule id(s): {', '.join(sorted(unknown))} "
            f"(known: {', '.join(sorted(known))})")
    return [r for r in rules if r.id in wanted]


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for r in engine.default_rules():
            tag = " [report-only]" if r.report_only else ""
            print(f"{r.id}  {r.title}{tag}")
        return 0

    try:
        rules = _select_rules(args.rules)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    paths = list(args.paths) or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"jaxlint: no such path: {p}", file=sys.stderr)
            return 2

    findings, stats, _graph = engine.analyze_paths(paths, rules)

    baseline_path = args.baseline
    if baseline_path is None and os.path.isfile(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    if args.update_baseline:
        out = args.baseline or DEFAULT_BASELINE
        baseline_mod.save_baseline(out, findings)
        n = sum(1 for f in findings if not f.report_only)
        n_report = len(findings) - n
        print(f"jaxlint: wrote {n} finding(s) + {n_report} "
              f"report-only to {out}")
        return 0

    bl = baseline_mod.load_baseline(baseline_path) if baseline_path \
        else None
    if bl is not None:
        new, old = baseline_mod.partition(findings, bl)
        new_keys = {f.key() for f in new}
        n_baselined = len(old)
    else:
        new = [f for f in findings if not f.report_only]
        new_keys, n_baselined = None, 0

    if args.format == "json":
        print(engine.format_json(findings, stats, new_keys, n_baselined))
    else:
        print(engine.format_text(findings, stats, new_keys, n_baselined))

    return 1 if new else 0


def run(argv: Optional[Sequence[str]] = None) -> int:
    """Console entry: :func:`main`, but a closed stdout pipe
    (``jaxlint ... | head``) exits 141 instead of a traceback."""
    try:
        return main(argv if argv is not None else sys.argv[1:])
    except BrokenPipeError:
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 141
