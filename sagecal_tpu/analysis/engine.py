"""Rule engine: run rules over the call graph, apply pragmas, report.

A :class:`Finding` is identified for baseline purposes by
``(rule, path, symbol, message)`` — deliberately *without* the line
number, so unrelated edits above a grandfathered finding don't churn
the baseline.  Suppression is per-line: a ``# jaxlint: disable=RULE``
comment on the flagged line (reasons after an em-dash are encouraged
and ignored by the parser), or ``# jaxlint: disable-file=RULE``
anywhere for whole-file suppression.  ``# noqa`` on the flagged line
also suppresses the report-only JL900 (matching flake8 convention for
re-export imports).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import time
from typing import Iterable, Iterator, List, Optional, Sequence, Set

from sagecal_tpu.analysis.callgraph import (
    CallGraph,
    ModuleInfo,
    build_callgraph,
    collect_files,
    qual_of,
)

# canonical prefixes of traced-array-producing namespaces: a call into
# any of these yields a tracer inside jit-reachable code
JNP_CALL_PREFIXES = (
    "jax.numpy.",
    "jax.lax.",
    "jax.scipy.",
    "jax.nn.",
    "jax.random.",
    "jax.tree_util.",
)


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    symbol: str = ""
    report_only: bool = False

    def key(self):
        """Baseline identity (line-independent, see module doc)."""
        return (self.rule, _posix(self.path), self.symbol, self.message)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": _posix(self.path), "line": self.line,
            "col": self.col, "message": self.message, "symbol": self.symbol,
            "report_only": self.report_only,
        }


def _posix(path: str) -> str:
    return path.replace(os.sep, "/")


class Rule:
    """Base class: one diagnostic, one module, fixture-tested."""

    id = "JL000"
    title = ""
    report_only = False

    def check(self, graph: CallGraph) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mi: ModuleInfo, node: ast.AST, message: str,
                symbol: str = "") -> Finding:
        return Finding(
            path=mi.path, line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0), rule=self.id,
            message=message, symbol=symbol, report_only=self.report_only,
        )


# --------------------------------------------------- shared AST helpers


def iter_calls(node: ast.AST) -> Iterator[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def is_jnp_call(call: ast.Call, mi: ModuleInfo) -> bool:
    q = qual_of(call.func, mi.imports, mi.toplevel, mi.name)
    return q is not None and q.startswith(JNP_CALL_PREFIXES)


def contains_jnp_call(node: ast.AST, mi: ModuleInfo,
                      tainted: Optional[Set[str]] = None) -> bool:
    """True when any sub-expression calls into a jnp/lax namespace or
    reads a local known to hold a traced value."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and is_jnp_call(n, mi):
            # jnp.real(x).dtype and friends are static metadata reads
            parent = getattr(n, "_jaxlint_parent", None)
            if isinstance(parent, ast.Attribute) and parent.attr in (
                    "shape", "dtype", "ndim", "size", "sharding"):
                continue
            return True
        if (tainted and isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load) and n.id in tainted):
            # x.shape / x.dtype / x.ndim are static at trace time —
            # reading them off a traced local is legal Python
            parent = getattr(n, "_jaxlint_parent", None)
            if isinstance(parent, ast.Attribute) and parent.attr in (
                    "shape", "dtype", "ndim", "size", "sharding"):
                continue
            return True
    return False


def tainted_locals(fn_node: ast.AST, mi: ModuleInfo) -> Set[str]:
    """Local names assigned (directly) from jnp/lax-calling expressions
    — a one-level, no-fixpoint taint that keeps precision high: static
    config locals never enter, so ``if collect_trace:`` stays legal."""
    tainted: Set[str] = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Assign) and contains_jnp_call(n.value, mi):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    tainted.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    for el in t.elts:
                        if isinstance(el, ast.Name):
                            tainted.add(el.id)
        elif isinstance(n, ast.AugAssign) and isinstance(
                n.target, ast.Name) and contains_jnp_call(n.value, mi):
            tainted.add(n.target.id)
    return tainted


def path_segments(path: str) -> Set[str]:
    return set(_posix(path).split("/"))


# --------------------------------------------------------------- engine


def default_rules() -> List[Rule]:
    from sagecal_tpu.analysis.rules import all_rules

    return [cls() for cls in all_rules()]


def _suppressed(f: Finding, graph: CallGraph) -> bool:
    mi = graph.modules_by_path.get(f.path)
    if mi is None:
        return False
    if f.rule in mi.file_pragmas or "ALL" in mi.file_pragmas:
        return True
    tags = mi.pragmas.get(f.line, ())
    if f.rule in tags or "ALL" in tags:
        return True
    if f.report_only and f.line <= len(mi.lines) \
            and "# noqa" in mi.lines[f.line - 1]:
        return True
    return False


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Sequence[Rule]] = None):
    """Run the rules over ``paths``.  Returns ``(findings, stats)``:
    pragma-suppressed findings are already removed; baseline handling is
    the caller's (cli.py)."""
    t0 = time.perf_counter()
    files = collect_files(paths)
    graph = build_callgraph(files)
    rules = list(rules) if rules is not None else default_rules()
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(graph))
    kept = sorted(f for f in findings if not _suppressed(f, graph))
    parse_errors = [
        Finding(path=mi.path, line=1, col=0, rule="JL000",
                message=f"could not parse: {mi.parse_error}")
        for mi in graph.modules.values() if mi.parse_error
    ]
    stats = {
        "files": len(files),
        "jit_roots": sum(1 for fi in graph.functions.values()
                         if fi.jit_root),
        "jit_reachable": len(graph.reachable),
        "elapsed_seconds": round(time.perf_counter() - t0, 3),
        "rules": [r.id for r in rules],
    }
    return sorted(parse_errors) + kept, stats, graph


# -------------------------------------------------------------- reports


def format_text(findings: Iterable[Finding], stats: dict,
                new_keys: Optional[Set] = None,
                baselined: int = 0) -> str:
    lines = []
    for f in findings:
        mark = ""
        if f.report_only:
            mark = " [report-only]"
        elif new_keys is not None and f.key() not in new_keys:
            mark = " [baselined]"
        sym = f" in `{f.symbol.split('.')[-1]}`" if f.symbol else ""
        lines.append(
            f"{_posix(f.path)}:{f.line}:{f.col}: {f.rule} {f.message}"
            f"{sym}{mark}"
        )
    fs = list(findings)
    n_report = sum(1 for f in fs if f.report_only)
    n_gate = len(fs) - n_report
    n_new = len(new_keys) if new_keys is not None else n_gate
    lines.append(
        f"jaxlint: {n_gate} finding(s) ({n_new} new, {baselined} "
        f"baselined) + {n_report} report-only over {stats['files']} "
        f"file(s), {stats['jit_reachable']} jit-reachable function(s), "
        f"{stats['elapsed_seconds']}s"
    )
    if n_new:
        lines.append(
            "fix each finding, or suppress a deliberate one with "
            "`# jaxlint: disable=RULE — reason`, or grandfather with "
            "--update-baseline"
        )
    return "\n".join(lines)


def format_json(findings: Iterable[Finding], stats: dict,
                new_keys: Optional[Set] = None,
                baselined: int = 0) -> str:
    fs = list(findings)
    recs = []
    for f in fs:
        d = f.to_dict()
        if new_keys is not None and not f.report_only:
            d["new"] = f.key() in new_keys
        recs.append(d)
    n_report = sum(1 for f in fs if f.report_only)
    n_gate = len(fs) - n_report
    payload = {
        "version": 1,
        "findings": recs,
        "summary": {
            "total": n_gate,
            "new": len(new_keys) if new_keys is not None else n_gate,
            "baselined": baselined,
            "report_only": n_report,
        },
        "stats": stats,
    }
    return json.dumps(payload, indent=2, sort_keys=True)
