"""Deterministic simulated filesystem for protocol model checking.

:class:`SimFS` implements exactly the surface the fleet protocol code
relies on (the :class:`sagecal_tpu.fleet.queue.RealFS` contract) with
exactly the atomicity semantics the real code assumes of a POSIX
filesystem:

- ``publish_excl`` — stage + fsync + hard-link: the name appears with
  its full content in one indivisible step, exactly one publisher wins
  (``EEXIST``), and a crash loses only invisible tmp state;
- ``open_excl`` — ``O_CREAT|O_EXCL``: exactly one creator wins, but
  the file is *visible and empty* until ``commit`` — the torn-window
  primitive the shipped protocol deliberately avoids (the seeded
  ``torn-publish`` mutation uses it to re-introduce the bug);
- ``write_atomic`` — the tmp + fsync + ``os.replace`` idiom as one
  indivisible transition.  The real sequence stages a uniquely-named
  tmp file first; since no reader and no recovery path ever opens a
  tmp name, every intermediate state is observably identical to
  "nothing happened yet", and collapsing the staging into a single
  transition loses no distinguishable state.  Crash-before ≡ the op
  never ran (un-renamed tmp state is arbitrary lost garbage, exactly
  the POSIX contract); crash-after ≡ the file is durably replaced;
- ``unlink`` / ``unlink_matching`` / ``listdir`` / ``read_text`` /
  ``exists`` — plain name-space ops, each one transition.

Every public operation first calls the installed :attr:`SimFS.gate`
hook (when set) — the interleaving explorer's scheduling point.  The
hook may raise :class:`SimCrash` to crash the calling logical worker
*at that boundary*: the op does not execute, the worker's stack
unwinds (``SimCrash`` derives from ``BaseException`` so the protocol
code's ``except OSError`` clauses cannot swallow it), and any file the
worker had ``open_excl``-created but not yet committed stays behind
torn.  That is precisely "crash injection at every fs-operation
boundary".

The simulator is fully deterministic: ``unique_suffix`` is a counter,
there is no wall clock (logical time lives in :class:`SimClock`), and
``listdir`` is sorted.  ``tests/test_protocol.py`` runs the same
lease-protocol script against a tmpdir (``RealFS``) and this simulator
and pins identical observable outcomes on crash-free schedules.

Stdlib only; importing this module never imports jax or numpy (the
checker must run on any host, backend or no backend — same contract as
the rest of :mod:`sagecal_tpu.analysis`).
"""

from __future__ import annotations

import posixpath
from typing import Callable, Dict, List, Optional, Tuple


class SimCrash(BaseException):
    """Injected fail-stop crash of one logical worker at an
    fs-operation boundary.  Derives from ``BaseException`` on purpose:
    the protocol code's defensive ``except OSError`` / ``except
    Exception`` clauses must not be able to swallow a crash."""


class SimClock:
    """Logical time: advances only when the explorer says so."""

    def __init__(self, t: float = 1000.0):
        self.t = float(t)

    def now(self) -> float:
        return self.t

    def advance_to(self, t: float) -> None:
        if t < self.t:
            raise ValueError(f"clock cannot go backward "
                             f"({t} < {self.t})")
        self.t = float(t)


class _SimFD:
    """One open ``open_excl`` handle (torn until committed)."""

    __slots__ = ("path", "open")

    def __init__(self, path: str):
        self.path = path
        self.open = True


class SimFS:
    """In-memory filesystem with the RealFS op surface.

    ``files`` maps path -> text; a path created by ``open_excl`` holds
    ``""`` until its fd is committed (the torn-file state).  ``gate``
    (when set) is invoked as ``gate(op_name, detail)`` before every
    operation executes.
    """

    def __init__(self, gate: Optional[Callable[[str, str], None]] = None):
        self.files: Dict[str, str] = {}
        self.dirs = {"/"}
        self.gate = gate
        self._seq = 0

    # -- explorer plumbing (not part of the fs op surface) ------------

    def _op(self, name: str, detail: str = "") -> None:
        if self.gate is not None:
            self.gate(name, detail)

    def snapshot(self) -> Tuple[Dict[str, str], set]:
        return dict(self.files), set(self.dirs)

    def restore(self, snap: Tuple[Dict[str, str], set]) -> None:
        self.files, self.dirs = dict(snap[0]), set(snap[1])

    def clone(self) -> "SimFS":
        c = SimFS()
        c.files = dict(self.files)
        c.dirs = set(self.dirs)
        c._seq = self._seq
        return c

    def fingerprint(self) -> Tuple:
        """Visible state only — open fds and the suffix counter do not
        influence what any worker can observe from here on."""
        return tuple(sorted(self.files.items()))

    # -- the RealFS contract ------------------------------------------

    def makedirs(self, path: str) -> None:
        self._op("makedirs", path)
        self.dirs.add(posixpath.normpath(path))

    def exists(self, path: str) -> bool:
        self._op("exists", path)
        return path in self.files \
            or posixpath.normpath(path) in self.dirs

    def listdir(self, path: str) -> List[str]:
        self._op("listdir", path)
        d = posixpath.normpath(path)
        if d not in self.dirs:
            raise FileNotFoundError(f"[sim] no such directory: {path}")
        return sorted(posixpath.basename(p) for p in self.files
                      if posixpath.dirname(posixpath.normpath(p)) == d)

    def read_text(self, path: str) -> str:
        self._op("read_text", path)
        if path not in self.files:
            raise FileNotFoundError(f"[sim] no such file: {path}")
        return self.files[path]

    def open_excl(self, path: str) -> _SimFD:
        self._op("open_excl", path)
        if path in self.files:
            raise FileExistsError(f"[sim] exists: {path}")
        self.files[path] = ""  # visible and torn until commit
        return _SimFD(path)

    def create(self, path: str) -> _SimFD:
        """Plain truncating create (``O_CREAT|O_TRUNC``) — exists so
        seeded mutations can model a claim that skips ``O_EXCL``."""
        self._op("create", path)
        self.files[path] = ""
        return _SimFD(path)

    def commit(self, fd: _SimFD, text: str) -> None:
        self._op("commit", fd.path)
        if not fd.open:
            raise OSError(f"[sim] fd already closed: {fd.path}")
        fd.open = False
        if fd.path in self.files:
            self.files[fd.path] = text

    def publish_excl(self, path: str, text: str) -> None:
        self._op("publish_excl", path)
        if path in self.files:
            raise FileExistsError(f"[sim] exists: {path}")
        self.files[path] = text

    def write_atomic(self, path: str, text: str) -> None:
        self._op("write_atomic", path)
        self.files[path] = text

    def unlink(self, path: str) -> None:
        self._op("unlink", path)
        if path not in self.files:
            raise FileNotFoundError(f"[sim] no such file: {path}")
        del self.files[path]

    def unlink_matching(self, dirpath: str, prefix: str) -> int:
        self._op("unlink_matching", f"{dirpath}/{prefix}*")
        d = posixpath.normpath(dirpath)
        victims = [p for p in self.files
                   if posixpath.dirname(posixpath.normpath(p)) == d
                   and posixpath.basename(p).startswith(prefix)]
        for p in victims:
            del self.files[p]
        return len(victims)

    def unique_suffix(self) -> str:
        # pure naming, not a scheduling point: the name never escapes
        # to another worker before the op that publishes it
        self._seq += 1
        return f"sim{self._seq:06d}"
