"""Kernel contract checker: commit-time proofs of the Pallas grids'
hardware invariants.

The VMEM footprint model (:mod:`sagecal_tpu.analysis.kernelmodel`)
turns ``ops/rime_kernel.py``'s BlockSpecs and scratch census into a
closed-form per-grid-step residency.  This module runs the full
contract suite over it and over the kernel-aware lint rules, producing
a machine-readable violation list with stable *kinds*:

======================  =================================================
kind                    meaning
======================  =================================================
``model-extraction``    the symbolic interpreter could not extract a
                        grid from the kernel source (structural drift —
                        the model must be taught the new idiom before
                        any VMEM claim can be trusted)
``vmem-ceiling``        a shipped operating point's modeled footprint
                        exceeds the backend's scoped-VMEM ceiling
``tile-bound``          ``FULL_CLUSTER_TILE`` exceeds the largest tile
                        the model proves feasible for every
                        differentiated kernel family
``batch-rows-bound``    ``_BATCH_ROWS_MAX`` (solvers/batched.py)
                        exceeds the model's proven-envelope row bound
``grid-coverage``       a grid's index sequence does not tile an
                        operand exactly (rank mismatch, uncovered
                        padded extent)
``table-stale``         ``KERNEL_VMEM_TABLE.json`` no longer matches
                        the model (regenerate with
                        ``tools/kernel_vmem_table.py``)
``crosscheck``          model HBM accounting disagrees with a compiled
                        ``memory_analysis()`` beyond tolerance
``JL013``/``JL014``/\
``JL015``               a kernel-aware lint finding (cotangent
                        completeness / precision flow / BlockSpec
                        hazards)
======================  =================================================

Exit codes (CLI / ``diag kernelcheck``): 0 all contracts hold, 1 at
least one violation, 2 internal/usage error.

``run_kernel_check`` accepts path overrides for the kernel and batched
sources so the seeded-mutation tests (tests/test_kernelmodel.py) can
prove each contract actually *fires* without touching the real tree.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from sagecal_tpu.analysis import kernelmodel as km
from sagecal_tpu.analysis.kernelmodel import (
    CEILINGS, DEFAULT_BACKEND, DIFFERENTIATED_FAMILIES, FAMILIES,
    KernelConfig, ModelExtractionError, NORTH_STAR,
    PROVEN_BATCH_ENVELOPE, default_kernel_path, load_model)

# model-vs-compiled HBM accounting tolerance for --crosscheck: the
# model counts exact operand/output bytes; XLA may pad small buffers
CROSSCHECK_RTOL = 0.02

# forward families whose impls lower cleanly on CPU interpret mode —
# the --crosscheck sample set.  The bool is check_outputs: the cost
# impls reduce the grid output to a scalar AFTER the pallas call, so
# only their operand accounting is comparable against the compiled
# program; predict returns the grid output unreduced.
CROSSCHECK_CONFIGS = (
    ("predict_fwd", dict(Mp=8, F=2, tile=128, rowsp=256), True),
    ("cost_fwd", dict(Mp=8, F=2, tile=128, rowsp=256), False),
    ("cost_batch_fwd", dict(Mp=8, B=2, F=2, tile=128, rowsp=256), False),
)


def default_batched_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "solvers", "batched.py")


def default_table_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "KERNEL_VMEM_TABLE.json")


def shipped_batch_rows_max(batched_path: str) -> Optional[int]:
    """The ``_BATCH_ROWS_MAX`` constant as shipped (AST, no import —
    the checker must see the mutated source, not the loaded module)."""
    import ast
    try:
        with open(batched_path, "r") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_BATCH_ROWS_MAX"
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            return node.value.value
    return None


def _violation(kind: str, message: str, **detail: Any) -> Dict[str, Any]:
    v: Dict[str, Any] = {"kind": kind, "message": message}
    if detail:
        v["detail"] = detail
    return v


def _check_model_contracts(model: km.KernelModel, backend: str,
                           batched_path: str) -> List[Dict[str, Any]]:
    violations: List[Dict[str, Any]] = []
    ceiling = CEILINGS[backend]
    shipped_tile = int(model.consts.get("FULL_CLUSTER_TILE", 0))
    derived_tile = model.derived_full_cluster_tile(backend)
    if shipped_tile > derived_tile:
        violations.append(_violation(
            "tile-bound",
            "FULL_CLUSTER_TILE=%d exceeds the largest tile (%d) the "
            "VMEM model proves feasible for all differentiated kernel "
            "families on %s" % (shipped_tile, derived_tile, backend),
            shipped=shipped_tile, derived=derived_tile))
    # shipped operating points must fit the ceiling outright
    for fam in DIFFERENTIATED_FAMILIES:
        cfg = KernelConfig(Mp=NORTH_STAR["Mp"], F=NORTH_STAR["F"],
                           tile=shipped_tile or 128)
        fp = model.footprint(fam, cfg)
        if fp.total_bytes > ceiling:
            violations.append(_violation(
                "vmem-ceiling",
                "%s at FULL_CLUSTER_TILE=%d, Mp=%d needs %.2f MiB > "
                "%.0f MiB ceiling (%s)" % (
                    fam, cfg.tile, cfg.Mp, fp.mib,
                    ceiling / (1024.0 * 1024.0), backend),
                family=fam, bytes=fp.total_bytes, ceiling=ceiling))
    shipped_rows = shipped_batch_rows_max(batched_path)
    if shipped_rows is not None:
        env_tile = int(PROVEN_BATCH_ENVELOPE["tile"])
        model_rows = model.batch_rows_max(env_tile, "f32", backend)
        if shipped_rows > model_rows:
            violations.append(_violation(
                "batch-rows-bound",
                "_BATCH_ROWS_MAX=%d exceeds the model's proven-"
                "envelope bound of %d rows (f32, tile %d, %s)" % (
                    shipped_rows, model_rows, env_tile, backend),
                shipped=shipped_rows, model=model_rows))
        env_cfg = KernelConfig(
            Mp=8, B=max(1, shipped_rows // 8), F=NORTH_STAR["F"],
            tile=env_tile)
        fp = model.footprint("cost_batch_bwd", env_cfg)
        if fp.total_bytes > ceiling:
            violations.append(_violation(
                "vmem-ceiling",
                "batched backward at _BATCH_ROWS_MAX=%d rows needs "
                "%.2f MiB > %.0f MiB ceiling (%s)" % (
                    shipped_rows, fp.mib,
                    ceiling / (1024.0 * 1024.0), backend),
                family="cost_batch_bwd", bytes=fp.total_bytes,
                ceiling=ceiling))
    for fam in FAMILIES:
        if fam.startswith("cost_batch"):
            cfg = KernelConfig(Mp=8, B=2, F=NORTH_STAR["F"],
                               tile=shipped_tile or 128)
        else:
            cfg = KernelConfig(Mp=NORTH_STAR["Mp"], F=NORTH_STAR["F"],
                               tile=shipped_tile or 128)
        try:
            for problem in model.coverage_problems(fam, cfg):
                violations.append(_violation("grid-coverage", problem,
                                             family=fam))
        except ModelExtractionError as exc:
            violations.append(_violation(
                "model-extraction",
                "%s: %s" % (fam, exc), family=fam))
    return violations


def _check_table(model: km.KernelModel, table_path: str,
                 backend: str) -> List[Dict[str, Any]]:
    if not os.path.exists(table_path):
        return [_violation(
            "table-stale",
            "%s missing — generate it with tools/kernel_vmem_table.py"
            % table_path)]
    try:
        with open(table_path, "r") as fh:
            banked = json.load(fh)
    except (OSError, ValueError) as exc:
        return [_violation(
            "table-stale", "%s unreadable: %s" % (table_path, exc))]
    current = model.build_table(backend)
    if banked != current:
        drifted = sorted(
            k for k in set(banked) | set(current)
            if banked.get(k) != current.get(k))
        return [_violation(
            "table-stale",
            "%s does not match the model (drifted keys: %s) — "
            "regenerate with tools/kernel_vmem_table.py" % (
                table_path, ", ".join(drifted)),
            drifted=drifted)]
    return []


def _check_lint(kernel_path: Optional[str]) -> List[Dict[str, Any]]:
    from sagecal_tpu.analysis.engine import analyze_paths
    from sagecal_tpu.analysis.rules.jl013 import CotangentCompleteness
    from sagecal_tpu.analysis.rules.jl014 import PrecisionFlow
    from sagecal_tpu.analysis.rules.jl015 import BlockSpecHazard
    if kernel_path is None:
        paths = [os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))]  # the whole package
    else:
        # mutation-sandbox mode: the kernel under test plus the bf16
        # ingestion context (solvers/sage.py) JL014 taints from
        paths = [kernel_path]
        sage = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "solvers", "sage.py")
        if os.path.exists(sage):
            paths.append(sage)
    findings, _stats, _graph = analyze_paths(
        paths, rules=[CotangentCompleteness(), PrecisionFlow(),
                      BlockSpecHazard()])
    out = []
    for f in findings:
        if f.report_only:
            continue
        out.append(_violation(
            f.rule, "%s:%d: %s" % (f.path, f.line, f.message),
            symbol=f.symbol))
    return out


def _check_crosscheck(model: km.KernelModel) -> List[Dict[str, Any]]:
    """Model HBM accounting vs jax compiled memory_analysis() on CPU
    lowerings of the forward impls (lazy jax import)."""
    import functools
    import jax
    import jax.numpy as jnp

    from sagecal_tpu.ops import rime_kernel

    np_dtype = {"f32": jnp.float32, "bf16": jnp.bfloat16,
                "i32": jnp.int32, "f64": jnp.float64}
    violations: List[Dict[str, Any]] = []
    for fam, cfg_kw, check_outputs in CROSSCHECK_CONFIGS:
        cfg = KernelConfig(**cfg_kw)
        tensors, kwargs = model._operands(fam, cfg)
        fn = getattr(rime_kernel, km.IMPLS[fam])
        args = [jax.ShapeDtypeStruct(t.shape, np_dtype[t.dtype])
                for t in tensors]
        compiled = jax.jit(
            functools.partial(fn, **kwargs)).lower(*args).compile()
        mem = compiled.memory_analysis()
        pairs = [
            ("operands", model.hbm_operand_bytes(fam, cfg),
             getattr(mem, "argument_size_in_bytes", None)),
        ]
        if check_outputs:
            pairs.append(
                ("outputs", model.hbm_output_bytes(fam, cfg),
                 getattr(mem, "output_size_in_bytes", None)))
        for what, predicted, measured in pairs:
            if measured is None:
                continue  # backend without memory_analysis fields
            rel = (abs(predicted - measured)
                   / max(1.0, float(measured)))
            if rel > CROSSCHECK_RTOL:
                violations.append(_violation(
                    "crosscheck",
                    "%s %s: model %d bytes vs compiled %d bytes "
                    "(rel %.4f > %.2f)" % (
                        fam, what, predicted, measured, rel,
                        CROSSCHECK_RTOL),
                    family=fam, predicted=predicted,
                    measured=int(measured)))
    return violations


def run_kernel_check(kernel_path: Optional[str] = None,
                     batched_path: Optional[str] = None,
                     table_path: Optional[str] = None,
                     backend: str = DEFAULT_BACKEND,
                     check_table: bool = True,
                     lint: bool = True,
                     crosscheck: bool = False) -> Dict[str, Any]:
    """Run every kernel contract; returns ``{"violations": [...],
    "summary": {...}}``.  Path overrides exist for the seeded-mutation
    tests; production callers use the defaults."""
    resolved_kernel = kernel_path or default_kernel_path()
    resolved_batched = batched_path or default_batched_path()
    resolved_table = table_path or default_table_path()
    violations: List[Dict[str, Any]] = []
    model: Optional[km.KernelModel] = None
    try:
        model = load_model(path=resolved_kernel)
    except (ModelExtractionError, OSError, SyntaxError) as exc:
        violations.append(_violation(
            "model-extraction",
            "cannot extract the VMEM model from %s: %s" % (
                resolved_kernel, exc)))
    if model is not None:
        try:
            violations.extend(_check_model_contracts(
                model, backend, resolved_batched))
        except ModelExtractionError as exc:
            violations.append(_violation(
                "model-extraction", str(exc)))
        if check_table:
            violations.extend(_check_table(
                model, resolved_table, backend))
        if crosscheck:
            violations.extend(_check_crosscheck(model))
    if lint:
        violations.extend(_check_lint(kernel_path))
    summary: Dict[str, Any] = {
        "backend": backend,
        "kernel": resolved_kernel,
        "violations": len(violations),
        "kinds": sorted({v["kind"] for v in violations}),
    }
    if model is not None:
        summary["full_cluster_tile"] = {
            "shipped": int(model.consts.get("FULL_CLUSTER_TILE", 0)),
            "derived": model.derived_full_cluster_tile(backend),
        }
        summary["batch_rows_max"] = {
            "shipped": shipped_batch_rows_max(resolved_batched),
            "f32": model.batch_rows_max(None, "f32", backend),
            "bf16": model.batch_rows_max(None, "bf16", backend),
        }
    return {"violations": violations, "summary": summary}


def render_text(result: Dict[str, Any]) -> str:
    lines: List[str] = []
    s = result["summary"]
    lines.append("kernelcheck: backend=%s kernel=%s" % (
        s["backend"], s["kernel"]))
    if "full_cluster_tile" in s:
        lines.append(
            "  FULL_CLUSTER_TILE shipped=%(shipped)d derived=%(derived)d"
            % s["full_cluster_tile"])
    if "batch_rows_max" in s:
        lines.append(
            "  batch rows shipped=%(shipped)s model f32=%(f32)d "
            "bf16=%(bf16)d" % s["batch_rows_max"])
    if not result["violations"]:
        lines.append("  OK — all kernel contracts hold")
    for v in result["violations"]:
        lines.append("  VIOLATION [%s] %s" % (v["kind"], v["message"]))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="kernelcheck",
        description="Static VMEM-budget and kernel-contract checker")
    parser.add_argument("--json", action="store_true",
                        help="emit machine-readable JSON")
    parser.add_argument("--backend", default=DEFAULT_BACKEND,
                        choices=sorted(CEILINGS),
                        help="ceiling table entry to prove against")
    parser.add_argument("--kernel", default=None,
                        help="kernel source override (mutation tests)")
    parser.add_argument("--batched", default=None,
                        help="batched-solver source override")
    parser.add_argument("--table", default=None,
                        help="VMEM table artifact path")
    parser.add_argument("--no-table-check", action="store_true",
                        help="skip the table staleness gate")
    parser.add_argument("--crosscheck", action="store_true",
                        help="also cross-check HBM accounting against "
                             "a compiled memory_analysis() (needs jax)")
    args = parser.parse_args(argv)
    try:
        result = run_kernel_check(
            kernel_path=args.kernel, batched_path=args.batched,
            table_path=args.table, backend=args.backend,
            check_table=not args.no_table_check,
            crosscheck=args.crosscheck)
    except Exception as exc:  # internal error, not a violation
        print("kernelcheck: internal error: %s" % exc, file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        print(render_text(result))
    return 1 if result["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
