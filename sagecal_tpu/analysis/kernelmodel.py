"""Symbolic VMEM-footprint model of the fused Pallas RIME grids.

The fused kernels in ``sagecal_tpu/ops/rime_kernel.py`` live or die by
a 16 MB scoped-VMEM ceiling that today is encoded only in hand-tuned
constants (``FULL_CLUSTER_TILE``, ``solvers/batched.py``'s
``_BATCH_ROWS_MAX``) and a comment block of round-5 hardware findings.
This module turns that comment into a checkable model: it parses the
REAL kernel source with the stdlib AST (no jax import — the model must
run in lint/CI context), symbolically executes the ``*_impl`` grid
builders to recover every ``pl.BlockSpec`` block shape, index map,
memory space and operand dtype, counts the kernel bodies' scoped
scratch planes, and prices the per-grid-step VMEM residency of any
``(tile, Mp, B, nc, coh_dtype)`` configuration.

Footprint decomposition (per grid step)::

    total = sum(block_bytes x buffering)          # BlockSpec operands
          + onehot_planes x NPAD x T x 4          # _onehots scratch
          + lane_planes x B x T x 4               # batch (B, T) planes
          + factor x census x rows x T x 4        # (rows, T) scratch

``buffering`` is 2 for streamed operands (index_map depends on the
grid parameter — Mosaic double-buffers the HBM copy) and 1 for
grid-invariant / revisited blocks.  ``census`` counts the kernel
body's live (rows, T) f32 planes, extracted from the helper functions
with loop-multiplier-aware AST counting so a source edit (dropping an
accumulator, adding a plane) moves the model.  ``factor`` is a
per-direction calibration ratio fitted as ``max(1, observed/raw)``
over the round-5 hardware anchors recorded in the kernel source's
VMEM comment — the model is exact on block arithmetic and
conservatively calibrated on Mosaic's scratch accounting.

Derived contracts:

- ``derived_full_cluster_tile()`` — the largest sweep tile whose
  forward AND backward footprints fit the backend ceiling at the
  north-star cluster count; must equal ``FULL_CLUSTER_TILE``.
- ``batch_rows_max(tile, coh_dtype)`` — the proven-envelope row bound
  for the batched objective: the largest ``rows = B*Mp`` (multiple of
  8) whose calibrated batched-backward footprint stays within the
  footprint of the hardware-proven (rows=104, tile=128, f32) point
  (never above the ceiling).  The f32 bound at tile 128 reproduces
  today's ``_BATCH_ROWS_MAX = 104`` exactly by construction; bf16
  coherencies legitimately admit more rows.
- ``build_table()`` — the ``KERNEL_VMEM_TABLE.json`` artifact that
  ``solvers.batched.choose_batched_path`` and future autotuners read
  instead of hardcoded constants.

Everything here is deterministic: same source bytes -> same table.
"""

from __future__ import annotations

import ast
import hashlib
import math
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

MODEL_VERSION = "1"

MIB = 1024 * 1024

#: Per-backend scoped-VMEM ceiling in bytes — the seam a GPU lowering
#: (ROADMAP item 5) extends with shared-memory budgets.
CEILINGS: Dict[str, int] = {"tpu-v5e": 16 * MIB}
DEFAULT_BACKEND = "tpu-v5e"

#: North-star problem size (ROADMAP: full cluster count, two channels).
NORTH_STAR: Dict[str, int] = {"Mp": 104, "F": 2}

SWEEP_TILES: Tuple[int, ...] = (64, 128, 256, 512)

FAMILIES: Tuple[str, ...] = (
    "predict_fwd", "predict_bwd", "cost_fwd", "cost_bwd",
    "cost_batch_fwd", "cost_batch_bwd",
)
#: Families whose bounds define FULL_CLUSTER_TILE (the solo
#: differentiated paths; the batched grid has its own rows bound).
DIFFERENTIATED_FAMILIES: Tuple[str, ...] = (
    "predict_fwd", "predict_bwd", "cost_fwd", "cost_bwd",
)

#: Round-5 v5e hardware measurements recorded in rime_kernel.py's VMEM
#: comment block.  ``observed_bytes`` is Mosaic's reported scoped-vmem
#: request for the grid; ``fits`` whether it compiled under the 16 MB
#: ceiling.  These anchor the per-direction calibration factors.
HARDWARE_ANCHORS: Tuple[Dict[str, Any], ...] = (
    {"family": "predict_fwd", "Mp": 104, "F": 2, "tile": 512,
     "observed_bytes": int(20.9 * MIB), "fits": False},
    {"family": "predict_fwd", "Mp": 104, "F": 2, "tile": 256,
     "observed_bytes": int(10.5 * MIB), "fits": True},
    {"family": "predict_bwd", "Mp": 104, "F": 2, "tile": 256,
     "observed_bytes": int(19.7 * MIB), "fits": False},
)

#: The hardware-proven batched-backward operating point (PR-14 bench:
#: B=13 lanes of Mp=8 at tile 128, f32 coherencies).  The batched row
#: bound is an ENVELOPE around this point: configurations are admitted
#: only while their calibrated footprint stays within the proven
#: point's footprint (a pure 16 MB ceiling would admit ~152 rows that
#: no hardware run has ever validated).
PROVEN_BATCH_ENVELOPE: Dict[str, Any] = {
    "rows": 104, "tile": 128, "coh_dtype": "f32",
}

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "i32": 4, "f64": 8,
                "float32": 4, "bfloat16": 2, "int32": 4, "float64": 8}
_DTYPE_CANON = {"float32": "f32", "bfloat16": "bf16", "int32": "i32",
                "float64": "f64"}

#: kernel function -> (family, hybrid-capable)
KERNEL_FAMILY: Dict[str, str] = {
    "_fwd_kernel": "predict_fwd",
    "_fwd_kernel_hybrid": "predict_fwd",
    "_bwd_kernel": "predict_bwd",
    "_bwd_kernel_hybrid": "predict_bwd",
    "_obj_fwd_kernel": "cost_fwd",
    "_obj_fwd_kernel_hybrid": "cost_fwd",
    "_obj_bwd_kernel": "cost_bwd",
    "_obj_bwd_kernel_hybrid": "cost_bwd",
    "_obj_fwd_kernel_batch": "cost_batch_fwd",
    "_obj_bwd_kernel_batch": "cost_batch_bwd",
}

#: family -> impl grid-builder function name
IMPLS: Dict[str, str] = {
    "predict_fwd": "_fused_predict_fwd_impl",
    "predict_bwd": "_fused_predict_bwd_impl",
    "cost_fwd": "_fused_cost_fwd_impl",
    "cost_bwd": "_fused_cost_bwd_impl",
    "cost_batch_fwd": "_fused_cost_batch_fwd_impl",
    "cost_batch_bwd": "_fused_cost_batch_bwd_impl",
}


class ModelExtractionError(Exception):
    """The kernel source no longer matches the model's structural
    assumptions (a helper disappeared, a shape contract failed, an
    impl builder uses an unsupported construct).  Surfaced by the
    checker as a ``model-extraction`` violation — the model must be
    updated WITH the kernel, never silently skipped."""


# --------------------------------------------------------------- values


@dataclass(frozen=True)
class Tensor:
    """A symbolic array: shape is concrete ints, dtype a short name."""
    name: str
    shape: Tuple[int, ...]
    dtype: str

    @property
    def nbytes(self) -> int:
        return _prod(self.shape) * _DTYPE_BYTES[self.dtype]


class _Opaque:
    """Value the interpreter cannot (and need not) reason about."""

    __slots__ = ("why",)

    def __init__(self, why: str = "") -> None:
        self.why = why

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<opaque {self.why}>"


class _Dotted(str):
    """A dotted external name (``jax.numpy.float32``) as a value."""


@dataclass
class FuncRef:
    name: str
    node: ast.FunctionDef


@dataclass
class PartialFn:
    ref: FuncRef
    kwargs: Dict[str, Any]


@dataclass
class LambdaVal:
    node: ast.Lambda
    env: Dict[str, Any]
    interp: "_Interp"

    def __call__(self, *vals: Any) -> Any:
        params = [a.arg for a in self.node.args.args]
        if len(vals) != len(params):
            raise ModelExtractionError(
                f"index_map lambda at line {self.node.lineno} takes "
                f"{len(params)} args, called with {len(vals)}")
        env = dict(self.env)
        env.update(zip(params, vals))
        return self.interp._eval(self.node.body, env)


@dataclass
class SpecInstance:
    """One evaluated ``pl.BlockSpec``."""
    block_shape: Tuple[int, ...]
    index_map: Optional[LambdaVal]
    memory_space: str
    line: int

    def streamed(self) -> bool:
        """Whether the block revisits a different operand window per
        grid step (Mosaic double-buffers these)."""
        if self.index_map is None:
            return True  # conservative
        return tuple(self.index_map(0)) != tuple(self.index_map(1))


@dataclass
class PallasCallObj:
    kernel: Any
    grid: Tuple[int, ...]
    in_specs: Any
    out_specs: Any
    out_shape: Any
    line: int


@dataclass
class GridRecord:
    """One recorded ``pl.pallas_call`` application."""
    kernel_name: str
    kernel_kwargs: Dict[str, Any]
    grid: Tuple[int, ...]
    in_specs: List[Tuple[SpecInstance, Tensor]]
    out_specs: List[Tuple[SpecInstance, Tensor]]
    line: int

    @property
    def family(self) -> str:
        return KERNEL_FAMILY[self.kernel_name]


def _prod(shape: Sequence[int]) -> int:
    out = 1
    for s in shape:
        out *= int(s)
    return out


def _dtype_name(v: Any) -> str:
    if isinstance(v, Tensor):
        return v.dtype
    s = str(v).rsplit(".", 1)[-1]
    return _DTYPE_CANON.get(s, s)


class _Ret(Exception):
    def __init__(self, value: Any) -> None:
        self.value = value


# ---------------------------------------------------------- interpreter


_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.Div: lambda a, b: a / b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Mod: lambda a, b: a % b,
    ast.Pow: lambda a, b: a ** b,
}

_CMPOPS = {
    ast.Eq: lambda a, b: a == b,
    ast.NotEq: lambda a, b: a != b,
    ast.Lt: lambda a, b: a < b,
    ast.LtE: lambda a, b: a <= b,
    ast.Gt: lambda a, b: a > b,
    ast.GtE: lambda a, b: a >= b,
    ast.Is: lambda a, b: a is b,
    ast.IsNot: lambda a, b: a is not b,
    ast.In: lambda a, b: a in b,
    ast.NotIn: lambda a, b: a not in b,
}


class _Interp:
    """Structured evaluator for the ``*_impl`` grid builders.

    Executes straight-line shape arithmetic, the ``nc == 1`` branch,
    the spec-helper calls, and ``pl.pallas_call`` applications under
    an environment of symbolic :class:`Tensor` operands, recording one
    :class:`GridRecord` per grid launched.  Anything outside that
    vocabulary raises :class:`ModelExtractionError` — by design: an
    impl builder the model cannot follow is a checker violation, not
    a silent gap."""

    def __init__(self, model: "KernelModel") -> None:
        self.m = model
        self.records: List[GridRecord] = []

    # -- entry

    def call_function(self, fref: FuncRef, pos: Sequence[Any],
                      kw: Dict[str, Any]) -> Any:
        env = self._bind(fref, list(pos), dict(kw))
        try:
            self._exec_block(fref.node.body, env)
        except _Ret as r:
            return r.value
        return None

    def _bind(self, fref: FuncRef, pos: List[Any],
              kw: Dict[str, Any]) -> Dict[str, Any]:
        a = fref.node.args
        names = [x.arg for x in a.args]
        if len(pos) > len(names):
            raise ModelExtractionError(
                f"{fref.name}: {len(pos)} positional args for "
                f"{len(names)} parameters")
        env: Dict[str, Any] = {}
        for n, v in zip(names, pos):
            env[n] = v
        for n in names:
            if n not in env and n in kw:
                env[n] = kw.pop(n)
        ndef = len(a.defaults)
        for i, d in enumerate(a.defaults):
            n = names[len(names) - ndef + i]
            if n not in env:
                env[n] = self._eval(d, dict(env))
        for ka, kd in zip(a.kwonlyargs, a.kw_defaults):
            n = ka.arg
            if n in kw:
                env[n] = kw.pop(n)
            elif kd is not None:
                env[n] = self._eval(kd, dict(env))
            else:
                raise ModelExtractionError(
                    f"{fref.name}: missing keyword-only arg {n!r}")
        missing = [n for n in names if n not in env]
        if missing or kw:
            raise ModelExtractionError(
                f"{fref.name}: missing={missing} unexpected={sorted(kw)}")
        return env

    # -- statements

    def _exec_block(self, stmts: Sequence[ast.stmt],
                    env: Dict[str, Any]) -> None:
        for s in stmts:
            if isinstance(s, ast.Return):
                raise _Ret(self._eval(s.value, env)
                           if s.value is not None else None)
            elif isinstance(s, ast.Assign):
                val = self._eval(s.value, env)
                for t in s.targets:
                    self._assign(t, val, env)
            elif isinstance(s, ast.AnnAssign):
                if s.value is not None:
                    self._assign(s.target, self._eval(s.value, env), env)
            elif isinstance(s, ast.Assert):
                ok = self._eval(s.test, env)
                if isinstance(ok, _Opaque):
                    continue
                if not ok:
                    detail = ""
                    if s.msg is not None:
                        try:
                            detail = f" [{self._eval(s.msg, env)!r}]"
                        except Exception:
                            pass
                    raise ModelExtractionError(
                        f"kernel shape contract failed at line {s.lineno}: "
                        f"assert {ast.unparse(s.test)}{detail}")
            elif isinstance(s, ast.If):
                t = self._eval(s.test, env)
                if isinstance(t, _Opaque):
                    raise ModelExtractionError(
                        f"opaque branch condition at line {s.lineno}: "
                        f"{ast.unparse(s.test)}")
                self._exec_block(s.body if t else s.orelse, env)
            elif isinstance(s, ast.Expr):
                self._eval(s.value, env)
            elif isinstance(s, ast.FunctionDef):
                env[s.name] = FuncRef(s.name, s)
            elif isinstance(s, ast.Pass):
                pass
            else:
                raise ModelExtractionError(
                    f"unsupported statement {type(s).__name__} at line "
                    f"{s.lineno}")

    def _assign(self, target: ast.expr, val: Any,
                env: Dict[str, Any]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            try:
                vals = list(val)
            except TypeError:
                raise ModelExtractionError(
                    f"cannot unpack {val!r} at line {target.lineno}")
            if len(vals) != len(target.elts):
                raise ModelExtractionError(
                    f"unpack arity mismatch at line {target.lineno}")
            for t, v in zip(target.elts, vals):
                self._assign(t, v, env)
        else:
            raise ModelExtractionError(
                f"unsupported assignment target at line {target.lineno}")

    # -- expressions

    def _eval(self, node: ast.expr, env: Dict[str, Any]) -> Any:
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            if node.id in self.m.functions:
                return FuncRef(node.id, self.m.functions[node.id])
            if node.id in self.m.consts:
                return self.m.consts[node.id]
            raise ModelExtractionError(
                f"unresolved name {node.id!r} at line {node.lineno}")
        if isinstance(node, ast.Tuple):
            return tuple(self._eval(e, env) for e in node.elts)
        if isinstance(node, ast.List):
            return [self._eval(e, env) for e in node.elts]
        if isinstance(node, ast.Attribute):
            return self._eval_attr(node, env)
        if isinstance(node, ast.BinOp):
            a = self._eval(node.left, env)
            b = self._eval(node.right, env)
            if isinstance(a, _Opaque) or isinstance(b, _Opaque):
                return _Opaque("binop")
            op = _BINOPS.get(type(node.op))
            if op is None:
                raise ModelExtractionError(
                    f"unsupported operator at line {node.lineno}")
            return op(a, b)
        if isinstance(node, ast.UnaryOp):
            v = self._eval(node.operand, env)
            if isinstance(v, _Opaque):
                return v
            if isinstance(node.op, ast.USub):
                return -v
            if isinstance(node.op, ast.Not):
                return not v
            raise ModelExtractionError(
                f"unsupported unary op at line {node.lineno}")
        if isinstance(node, ast.BoolOp):
            is_and = isinstance(node.op, ast.And)
            result: Any = True if is_and else False
            for v_node in node.values:
                v = self._eval(v_node, env)
                if isinstance(v, _Opaque):
                    return v
                result = v
                if is_and and not v:
                    return v
                if not is_and and v:
                    return v
            return result
        if isinstance(node, ast.Compare):
            left = self._eval(node.left, env)
            for op, comp in zip(node.ops, node.comparators):
                right = self._eval(comp, env)
                if isinstance(left, _Opaque) or isinstance(right, _Opaque):
                    return _Opaque("compare")
                fn = _CMPOPS.get(type(op))
                if fn is None:
                    raise ModelExtractionError(
                        f"unsupported comparison at line {node.lineno}")
                if not fn(left, right):
                    return False
                left = right
            return True
        if isinstance(node, ast.IfExp):
            t = self._eval(node.test, env)
            if isinstance(t, _Opaque):
                return _Opaque("ifexp")
            return self._eval(node.body if t else node.orelse, env)
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value, env)
            if isinstance(base, _Opaque):
                return base
            idx = self._eval(node.slice, env)
            if isinstance(idx, _Opaque):
                return _Opaque("subscript")
            try:
                return base[idx]
            except Exception:
                return _Opaque("subscript")
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.Lambda):
            return LambdaVal(node, dict(env), self)
        if isinstance(node, ast.JoinedStr):
            return "<fstring>"
        raise ModelExtractionError(
            f"unsupported expression {type(node).__name__} at line "
            f"{getattr(node, 'lineno', '?')}")

    def _eval_attr(self, node: ast.Attribute, env: Dict[str, Any]) -> Any:
        v = node.value
        if (isinstance(v, ast.Name) and v.id not in env
                and v.id not in self.m.functions
                and v.id not in self.m.consts):
            base: Any = _Dotted(self.m.aliases.get(v.id, v.id))
        else:
            base = self._eval(v, env)
        if isinstance(base, Tensor):
            if node.attr == "shape":
                return base.shape
            if node.attr == "dtype":
                return base.dtype
            raise ModelExtractionError(
                f"unsupported tensor attribute {node.attr!r} at line "
                f"{node.lineno}")
        if isinstance(base, _Dotted):
            return _Dotted(str(base) + "." + node.attr)
        if isinstance(base, _Opaque):
            return base
        raise ModelExtractionError(
            f"unsupported attribute base {base!r} at line {node.lineno}")

    def _eval_call(self, node: ast.Call, env: Dict[str, Any]) -> Any:
        fv = self._eval(node.func, env)
        pos: List[Any] = []
        for a in node.args:
            if isinstance(a, ast.Starred):
                v = self._eval(a.value, env)
                pos.extend(list(v))
            else:
                pos.append(self._eval(a, env))
        kw: Dict[str, Any] = {}
        for k in node.keywords:
            if k.arg is None:
                raise ModelExtractionError(
                    f"**kwargs call at line {node.lineno}")
            kw[k.arg] = self._eval(k.value, env)
        if isinstance(fv, PallasCallObj):
            return self._apply_pallas(fv, pos)
        if isinstance(fv, FuncRef):
            if fv.name == "_use_interpret":
                return False
            return self.call_function(fv, pos, kw)
        if isinstance(fv, PartialFn):
            merged = dict(fv.kwargs)
            merged.update(kw)
            return self.call_function(fv.ref, pos, merged)
        if isinstance(fv, LambdaVal):
            return fv(*pos)
        if isinstance(fv, _Dotted):
            leaf = str(fv).rsplit(".", 1)[-1]
            if leaf == "partial":
                f = pos[0]
                if not isinstance(f, FuncRef):
                    raise ModelExtractionError(
                        f"functools.partial of non-module function at "
                        f"line {node.lineno}")
                return PartialFn(f, dict(kw))
            if leaf == "BlockSpec":
                block = tuple(pos[0]) if pos else tuple(kw["block_shape"])
                idx = pos[1] if len(pos) > 1 else kw.get("index_map")
                ms = kw.get("memory_space")
                ms_leaf = (str(ms).rsplit(".", 1)[-1]
                           if isinstance(ms, _Dotted) else
                           ("" if ms is None else str(ms)))
                if not isinstance(idx, (LambdaVal, type(None))):
                    raise ModelExtractionError(
                        f"non-lambda index_map at line {node.lineno}")
                return SpecInstance(block, idx, ms_leaf, node.lineno)
            if leaf == "ShapeDtypeStruct":
                return Tensor("out", tuple(pos[0]), _dtype_name(pos[1]))
            if leaf == "pallas_call":
                grid = kw.get("grid") or ()
                return PallasCallObj(
                    pos[0], tuple(grid), kw.get("in_specs"),
                    kw.get("out_specs"), kw.get("out_shape"), node.lineno)
            return _Opaque(str(fv))
        if isinstance(fv, _Opaque):
            return fv
        raise ModelExtractionError(
            f"cannot call value {fv!r} at line {node.lineno}")

    def _apply_pallas(self, pc: PallasCallObj,
                      operands: Sequence[Any]) -> Any:
        in_specs = list(pc.in_specs or [])
        if len(in_specs) != len(operands):
            raise ModelExtractionError(
                f"pallas_call at line {pc.line}: {len(in_specs)} in_specs "
                f"for {len(operands)} operands")
        for op in operands:
            if not isinstance(op, Tensor):
                raise ModelExtractionError(
                    f"pallas_call at line {pc.line}: non-tensor operand "
                    f"{op!r}")
        multi_out = isinstance(pc.out_shape, list)
        outs = list(pc.out_shape) if multi_out else [pc.out_shape]
        out_specs = (list(pc.out_specs) if isinstance(pc.out_specs, list)
                     else [pc.out_specs])
        if len(outs) != len(out_specs):
            raise ModelExtractionError(
                f"pallas_call at line {pc.line}: out_specs/out_shape "
                f"arity mismatch")
        kernel = pc.kernel
        if isinstance(kernel, PartialFn):
            kname, kkw = kernel.ref.name, dict(kernel.kwargs)
        elif isinstance(kernel, FuncRef):
            kname, kkw = kernel.name, {}
        else:
            raise ModelExtractionError(
                f"pallas_call at line {pc.line}: unsupported kernel "
                f"binding {kernel!r}")
        self.records.append(GridRecord(
            kernel_name=kname, kernel_kwargs=kkw, grid=pc.grid,
            in_specs=list(zip(in_specs, operands)),
            out_specs=list(zip(out_specs, outs)), line=pc.line))
        return list(outs) if multi_out else outs[0]


# -------------------------------------------------- census extraction


def _range_extent(iter_node: ast.expr) -> Optional[int]:
    if (isinstance(iter_node, ast.Call)
            and isinstance(iter_node.func, ast.Name)
            and iter_node.func.id == "range"
            and len(iter_node.args) == 1
            and isinstance(iter_node.args[0], ast.Constant)
            and isinstance(iter_node.args[0].value, int)):
        return iter_node.args[0].value
    return None


def _weighted_count(root: ast.AST, hit) -> int:
    """Count nodes satisfying ``hit``, multiplying through ``for``
    loops and comprehensions over literal ``range(k)`` — a plane built
    inside ``for k in range(4)`` is 4 live planes."""
    total = 0

    def visit(n: ast.AST, mult: int) -> None:
        nonlocal total
        if hit(n):
            total += mult
        if isinstance(n, ast.For):
            ext = _range_extent(n.iter) or 1
            visit(n.iter, mult)
            for c in n.body:
                visit(c, mult * ext)
            for c in n.orelse:
                visit(c, mult)
            return
        if isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            ext = 1
            for g in n.generators:
                ext *= _range_extent(g.iter) or 1
                visit(g.iter, mult)
            visit(n.elt, mult * ext)
            return
        if isinstance(n, ast.DictComp):
            ext = 1
            for g in n.generators:
                ext *= _range_extent(g.iter) or 1
                visit(g.iter, mult)
            visit(n.key, mult * ext)
            visit(n.value, mult * ext)
            return
        for c in ast.iter_child_nodes(n):
            visit(c, mult)

    visit(root, 1)
    return total


def _calls_to(name: str):
    return lambda n: (isinstance(n, ast.Call)
                      and isinstance(n.func, ast.Name)
                      and n.func.id == name)


def _astype_calls(n: ast.AST) -> bool:
    return (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "astype")


def _stores_to(names) -> Any:
    names = set(names)
    return lambda n: (isinstance(n, ast.Subscript)
                      and isinstance(n.ctx, ast.Store)
                      and isinstance(n.value, ast.Name)
                      and n.value.id in names)


def _zeros_calls(n: ast.AST) -> bool:
    return (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "zeros")


# ------------------------------------------------------------- config


@dataclass(frozen=True)
class KernelConfig:
    """One point in the kernel configuration space.

    ``rowsp`` defaults to one tile (R=1) — the per-grid-step footprint
    does not depend on it; pass a multiple of ``tile`` to exercise
    grid-coverage checks over R > 1 steps."""
    Mp: int
    F: int
    tile: int
    nc: int = 1
    B: int = 1
    coh_dtype: str = "f32"
    rowsp: Optional[int] = None
    robust: bool = True

    @property
    def resolved_rowsp(self) -> int:
        return self.rowsp if self.rowsp is not None else self.tile


@dataclass
class Footprint:
    """Per-grid-step VMEM residency breakdown, in bytes."""
    family: str
    config: KernelConfig
    census: int
    rows: int
    block_bytes: int
    onehot_bytes: int
    lane_bytes: int
    scratch_raw_bytes: int
    factor: float
    total_bytes: int
    record: GridRecord = field(repr=False, default=None)

    @property
    def mib(self) -> float:
        return self.total_bytes / MIB


# -------------------------------------------------------------- model


class KernelModel:
    """The symbolic VMEM model extracted from one kernel source."""

    #: helper functions the census extraction requires; their absence
    #: means the kernel was restructured and the model must follow.
    _REQUIRED = ("_expand_gains", "_load_coh_planes", "_cjqh", "_jp_a",
                 "_bwd_accumulate", "_g_from_residual_batch", "_onehots",
                 "_sel_dot")

    def __init__(self, source: str, path: str = "<source>") -> None:
        self.source = source
        self.path = path
        self.sha256 = hashlib.sha256(source.encode("utf-8")).hexdigest()
        try:
            self.tree = ast.parse(source)
        except SyntaxError as e:
            raise ModelExtractionError(f"cannot parse {path}: {e}")
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.consts: Dict[str, Any] = {}
        self.aliases: Dict[str, str] = {}
        for node in self.tree.body:
            if isinstance(node, ast.FunctionDef):
                self.functions[node.name] = node
            elif isinstance(node, ast.Assign):
                if (len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Constant)):
                    self.consts[node.targets[0].id] = node.value.value
            elif isinstance(node, ast.Import):
                for al in node.names:
                    self.aliases[al.asname or al.name.split(".")[0]] = (
                        al.name if al.asname else al.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for al in node.names:
                    self.aliases[al.asname or al.name] = (
                        f"{node.module}.{al.name}" if node.module
                        else al.name)
        missing = [f for f in self._REQUIRED if f not in self.functions]
        if missing:
            raise ModelExtractionError(
                f"kernel helpers missing from {path}: {missing} — the "
                "VMEM model no longer matches the kernel structure")
        self.counts = self._extract_counts()
        self.expand_calls = {
            k: _weighted_count(self.functions[k], _calls_to("_expand_gains"))
            for k in KERNEL_FAMILY if k in self.functions
        }
        self._factors: Optional[Dict[str, float]] = None

    # -- extraction

    def _extract_counts(self) -> Dict[str, int]:
        fns = self.functions
        # _sel_dot planes per _expand_gains call: the nc == 1 branch
        # (solo path); fall back to the whole body if restructured.
        eg = fns["_expand_gains"]
        sel_scope: ast.AST = eg
        for n in ast.walk(eg):
            if isinstance(n, ast.If):
                try:
                    if ast.unparse(n.test).replace(" ", "") == "nc==1":
                        sel_scope = ast.Module(body=n.body,
                                               type_ignores=[])
                        break
                except Exception:
                    pass
        counts = {
            "sel_planes": _weighted_count(sel_scope, _calls_to("_sel_dot")),
            "load_planes": _weighted_count(fns["_load_coh_planes"],
                                           _astype_calls),
            "cjqh_planes": _weighted_count(fns["_cjqh"],
                                           _stores_to(("a_re", "a_im"))),
            "jpa_planes": _weighted_count(fns["_jp_a"],
                                          _stores_to(("v_re", "v_im"))),
            "acc_zeros": _weighted_count(fns["_bwd_accumulate"],
                                         _zeros_calls),
            "da_planes": _weighted_count(fns["_bwd_accumulate"],
                                         _stores_to(("da_re", "da_im"))),
            "lane_bcast_planes": _weighted_count(
                fns["_g_from_residual_batch"], _calls_to("_lane_bcast")),
            "onehot_planes": _weighted_count(fns["_onehots"],
                                             _astype_calls),
        }
        return counts

    # -- symbolic execution

    def _operands(self, family: str,
                  cfg: KernelConfig) -> Tuple[List[Tensor], Dict[str, Any]]:
        npad = int(self.consts.get("NPAD", 128))
        rowsp = cfg.resolved_rowsp
        batch = family.startswith("cost_batch")
        mrows = (cfg.B * cfg.Mp) if batch else (cfg.Mp * cfg.nc)
        tab_re = Tensor("tab_re", (4, mrows, npad), "f32")
        tab_im = Tensor("tab_im", (4, mrows, npad), "f32")
        ant_p = Tensor("ant_p", (1, rowsp), "i32")
        ant_q = Tensor("ant_q", (1, rowsp), "i32")
        if batch:
            coh = Tensor("coh_ri", (cfg.B * cfg.Mp, cfg.F, 8, rowsp),
                         cfg.coh_dtype)
            vis = Tensor("vis_ri", (cfg.B, cfg.F, 8, rowsp), "f32")
            mask = Tensor("mask_p", (cfg.B, cfg.F, rowsp), "f32")
            nu = Tensor("nu_rows", (cfg.B, npad), "f32")
            pos = [tab_re, tab_im, coh, ant_p, ant_q, vis, mask, nu]
            kw: Dict[str, Any] = {"robust": cfg.robust, "tile": cfg.tile}
            return pos, kw
        coh = Tensor("coh_ri", (cfg.Mp, cfg.F, 8, rowsp), cfg.coh_dtype)
        kw = {"tile": cfg.tile}
        if cfg.nc > 1:
            kw["nc"] = cfg.nc
            kw["cmap"] = Tensor("cmap", (cfg.Mp, rowsp), "i32")
        if family == "predict_fwd":
            pos = [tab_re, tab_im, coh, ant_p, ant_q]
        elif family == "predict_bwd":
            g_ri = Tensor("g_ri", (cfg.F, 8, rowsp), "f32")
            pos = [tab_re, tab_im, coh, ant_p, ant_q, g_ri]
        else:  # cost_fwd / cost_bwd
            vis = Tensor("vis_ri", (cfg.F, 8, rowsp), "f32")
            mask = Tensor("mask_p", (cfg.F, rowsp), "f32")
            nu = Tensor("nu_arr", (1, 1), "f32")
            pos = [tab_re, tab_im, coh, ant_p, ant_q, vis, mask, nu]
            kw["robust"] = cfg.robust
        return pos, kw

    def grid_record(self, family: str, cfg: KernelConfig) -> GridRecord:
        """Symbolically execute one family's impl builder and return
        its recorded grid."""
        if family not in IMPLS:
            raise ModelExtractionError(f"unknown family {family!r}")
        impl = IMPLS[family]
        if impl not in self.functions:
            raise ModelExtractionError(
                f"impl builder {impl} missing from {self.path}")
        interp = _Interp(self)
        pos, kw = self._operands(family, cfg)
        interp.call_function(
            FuncRef(impl, self.functions[impl]), pos, kw)
        if len(interp.records) != 1:
            raise ModelExtractionError(
                f"{impl}: expected exactly one pallas_call, recorded "
                f"{len(interp.records)}")
        rec = interp.records[0]
        if rec.kernel_name not in KERNEL_FAMILY:
            raise ModelExtractionError(
                f"{impl}: unknown kernel {rec.kernel_name!r}")
        return rec

    # -- census / calibration

    def census(self, kernel_name: str, F: int, nc: int = 1) -> int:
        c = self.counts
        G = c["sel_planes"] * self.expand_calls.get(kernel_name, 2)
        L, C, V = c["load_planes"], c["cjqh_planes"], c["jpa_planes"]
        A, DA, LG = c["acc_zeros"], c["da_planes"], c["lane_bcast_planes"]
        fam = KERNEL_FAMILY[kernel_name]
        fwd = G + F * (L + C + V)
        bwd = G + 2 * A + F * (L + C + DA + V)
        n = {
            "predict_fwd": fwd,
            "predict_bwd": bwd,
            "cost_fwd": fwd,
            # the objective backward re-forms the model via _jp_a
            "cost_bwd": bwd + F * V,
            "cost_batch_fwd": fwd,
            # + lane-broadcast cotangent planes
            "cost_batch_bwd": bwd + F * (V + LG),
        }[fam]
        if nc > 1:
            # hybrid: nc chunk-selector masks + per-component reshaped
            # selection planes
            n += nc + c["sel_planes"]
        return n

    def factors(self) -> Dict[str, float]:
        """Per-direction calibration factors fitted over the hardware
        anchors: ``max(1, observed / raw)``, applied to the census
        scratch term only (block arithmetic is exact)."""
        if self._factors is None:
            f = {"fwd": 1.0, "bwd": 1.0}
            for a in HARDWARE_ANCHORS:
                cfg = KernelConfig(Mp=a["Mp"], F=a["F"], tile=a["tile"])
                fp = self.footprint(a["family"], cfg, calibrated=False)
                bucket = _factor_bucket(a["family"])
                f[bucket] = max(f[bucket],
                                a["observed_bytes"] / fp.total_bytes)
            self._factors = f
        return self._factors

    # -- footprint

    def footprint(self, family: str, cfg: KernelConfig,
                  calibrated: bool = True) -> Footprint:
        rec = self.grid_record(family, cfg)
        kk = rec.kernel_kwargs
        T = int(kk["T"])
        F = int(kk["F"])
        rows = int(kk["MP"]) * int(kk.get("B", 1))
        nc = int(kk.get("NC", 1))
        census = self.census(rec.kernel_name, F, nc)
        blocks = 0
        for spec, tensor in rec.in_specs + rec.out_specs:
            if spec.memory_space != "VMEM":
                continue
            buf = 2 if spec.streamed() else 1
            blocks += (_prod(spec.block_shape)
                       * _DTYPE_BYTES[tensor.dtype] * buf)
        npad = int(self.consts.get("NPAD", 128))
        onehot = self.counts["onehot_planes"] * npad * T * 4
        lane = 0
        if "B" in kk:
            # second-order (B, T) planes: per-freq residual/cotangent
            # components + mask, plus the running cost accumulator and
            # nu column
            lane = (F * 9 + 2) * int(kk["B"]) * T * 4
        raw = census * rows * T * 4
        fac = (self.factors()[_factor_bucket(family)]
               if calibrated else 1.0)
        # per-row ceiling keeps the total EXACTLY affine in rows, so
        # batch_rows_max can invert it without quantization slop
        total = (blocks + onehot + lane
                 + rows * int(math.ceil(census * T * 4 * fac)))
        return Footprint(
            family=family, config=cfg, census=census, rows=rows,
            block_bytes=blocks, onehot_bytes=onehot, lane_bytes=lane,
            scratch_raw_bytes=raw, factor=fac, total_bytes=total,
            record=rec)

    # -- HBM totals (cross-checked against jax memory_analysis on CPU)

    def hbm_operand_bytes(self, family: str, cfg: KernelConfig) -> int:
        rec = self.grid_record(family, cfg)
        return sum(t.nbytes for _, t in rec.in_specs)

    def hbm_output_bytes(self, family: str, cfg: KernelConfig) -> int:
        rec = self.grid_record(family, cfg)
        return sum(t.nbytes for _, t in rec.out_specs)

    # -- grid coverage

    def coverage_problems(self, family: str,
                          cfg: KernelConfig) -> List[str]:
        """Index-map/grid hazards checked numerically: block rank vs
        index rank, and whether the grid's index sequence tiles each
        operand axis exactly (const axes must carry the full extent;
        stepped axes must satisfy block * R == extent with indices
        0..R-1)."""
        if cfg.rowsp is None:
            cfg = KernelConfig(**{**cfg.__dict__, "rowsp": 4 * cfg.tile})
        rec = self.grid_record(family, cfg)
        if len(rec.grid) != 1:
            return [f"{family}: expected a 1-d grid, got {rec.grid}"]
        R = int(rec.grid[0])
        problems: List[str] = []
        for spec, tensor in rec.in_specs + rec.out_specs:
            where = (f"{family}: {tensor.name} BlockSpec at line "
                     f"{spec.line}")
            if spec.index_map is None:
                problems.append(f"{where}: missing index_map")
                continue
            idxs = [tuple(spec.index_map(r)) for r in range(R)]
            if len(idxs[0]) != len(spec.block_shape):
                problems.append(
                    f"{where}: index_map rank {len(idxs[0])} != block "
                    f"rank {len(spec.block_shape)}")
                continue
            if len(spec.block_shape) != len(tensor.shape):
                problems.append(
                    f"{where}: block rank {len(spec.block_shape)} != "
                    f"operand rank {len(tensor.shape)}")
                continue
            for ax in range(len(spec.block_shape)):
                vals = [ix[ax] for ix in idxs]
                blk = spec.block_shape[ax]
                ext = tensor.shape[ax]
                if all(v == vals[0] for v in vals):
                    if vals[0] != 0 or blk != ext:
                        problems.append(
                            f"{where}: axis {ax} constant index "
                            f"{vals[0]} with block {blk} does not cover "
                            f"extent {ext}")
                else:
                    if vals != list(range(R)) or blk * R != ext:
                        problems.append(
                            f"{where}: axis {ax} indices {vals} with "
                            f"block {blk} x grid {R} do not cover "
                            f"extent {ext}")
        return problems

    # -- derived contracts

    def feasible_tiles(self, backend: str = DEFAULT_BACKEND,
                       Mp: Optional[int] = None,
                       F: Optional[int] = None) -> Dict[str, Dict[int, dict]]:
        ceiling = CEILINGS[backend]
        Mp = NORTH_STAR["Mp"] if Mp is None else Mp
        F = NORTH_STAR["F"] if F is None else F
        out: Dict[str, Dict[int, dict]] = {}
        for fam in FAMILIES:
            row: Dict[int, dict] = {}
            for tile in SWEEP_TILES:
                if fam.startswith("cost_batch"):
                    cfg = KernelConfig(Mp=8, B=Mp // 8, F=F, tile=tile)
                else:
                    cfg = KernelConfig(Mp=Mp, F=F, tile=tile)
                fp = self.footprint(fam, cfg)
                row[tile] = {"bytes": fp.total_bytes,
                             "feasible": fp.total_bytes <= ceiling}
            out[fam] = row
        return out

    def derived_full_cluster_tile(self,
                                  backend: str = DEFAULT_BACKEND) -> int:
        ft = self.feasible_tiles(backend)
        best = 0
        for tile in SWEEP_TILES:
            if all(ft[f][tile]["feasible"]
                   for f in DIFFERENTIATED_FAMILIES):
                best = max(best, tile)
        return best

    def batch_rows_max(self, tile: Optional[int] = None,
                       coh_dtype: str = "f32",
                       backend: str = DEFAULT_BACKEND,
                       F: Optional[int] = None) -> int:
        """Proven-envelope row bound for the batched objective (module
        docstring).  The footprint is exactly affine in ``rows`` at a
        fixed tile/dtype, so the bound is recovered by evaluating two
        points and inverting — no quantization slop: the f32 bound at
        the envelope tile reproduces the proven 104 rows exactly, and
        bf16's halved coherency stream buys its extra rows at byte
        resolution."""
        if tile is None:
            tile = int(self.consts.get("FULL_CLUSTER_TILE", 128))
        F = NORTH_STAR["F"] if F is None else F
        env = PROVEN_BATCH_ENVELOPE
        e = self.footprint("cost_batch_bwd", KernelConfig(
            Mp=8, B=env["rows"] // 8, F=F, tile=env["tile"],
            coh_dtype=env["coh_dtype"])).total_bytes
        bound = min(e, CEILINGS[backend])
        f8 = self.footprint("cost_batch_bwd", KernelConfig(
            Mp=8, B=1, F=F, tile=tile, coh_dtype=coh_dtype)).total_bytes
        f16 = self.footprint("cost_batch_bwd", KernelConfig(
            Mp=8, B=2, F=F, tile=tile, coh_dtype=coh_dtype)).total_bytes
        per_row = (f16 - f8) // 8
        fixed = f8 - 8 * per_row
        if per_row <= 0 or bound <= fixed:
            return 0
        return int((bound - fixed) // per_row)

    # -- table artifact

    def build_table(self, backend: str = DEFAULT_BACKEND) -> dict:
        ft = self.feasible_tiles(backend)
        const_keys = ("NPAD", "DEF_TILE", "FULL_CLUSTER_TILE",
                      "MAX_GRID_ROWS")
        return {
            "version": 1,
            "model_version": MODEL_VERSION,
            "backend": backend,
            "ceiling_bytes": CEILINGS[backend],
            "north_star": dict(NORTH_STAR),
            "constants": {k: self.consts[k] for k in const_keys
                          if k in self.consts},
            "census_counts": dict(self.counts),
            "calibration": {k: round(v, 6)
                            for k, v in sorted(self.factors().items())},
            "anchors": [dict(a) for a in HARDWARE_ANCHORS],
            "proven_batch_envelope": dict(PROVEN_BATCH_ENVELOPE),
            "feasible_tiles": {
                fam: {str(t): ft[fam][t] for t in SWEEP_TILES}
                for fam in FAMILIES},
            "derived": {
                "full_cluster_tile":
                    self.derived_full_cluster_tile(backend)},
            "batch_rows_max": {
                dt: {str(t): self.batch_rows_max(tile=t, coh_dtype=dt,
                                                 backend=backend)
                     for t in SWEEP_TILES}
                for dt in ("f32", "bf16")},
            "fingerprint": {"rime_kernel_sha256": self.sha256,
                            "model_version": MODEL_VERSION},
        }


def _factor_bucket(family: str) -> str:
    return "bwd" if family.endswith("bwd") else "fwd"


def default_kernel_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ops", "rime_kernel.py")


def load_model(path: Optional[str] = None,
               source: Optional[str] = None) -> KernelModel:
    """Load the VMEM model from kernel source (defaults to the
    in-tree ``ops/rime_kernel.py``)."""
    if source is None:
        path = path or default_kernel_path()
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
    return KernelModel(source, path=path or "<source>")
