"""Shared Pallas-site discovery for the kernel-aware lint rules.

JL014 (precision flow) and JL015 (BlockSpec hazards) both need to know
which functions ARE Pallas kernel bodies and which array operands feed
them.  The repo's idiom (``ops/rime_kernel.py``) binds kernels and
operand tuples branch-locally::

    if nc == 1:
        kernel = functools.partial(_fwd_kernel, F=F, MP=Mp, T=tile)
        args = (ant_p, ant_q, tab_re, tab_im, coh_ri)
    else:
        kernel = functools.partial(_fwd_kernel_hybrid, ...)
        args = (ant_p, ant_q, cmap, tab_re, tab_im, coh_ri)
    return pl.pallas_call(kernel, ...)(*args)

so kernel/operand resolution must pair the ``kernel = ...`` and
``args = (...)`` assignments from the SAME statement block — a naive
cross-product would bind the solo kernel to the hybrid operand tuple
and shift every positional parameter by one.  Direct applications
(``pl.pallas_call(k, ...)(a, b, c)``) resolve exactly.

Pure stdlib ``ast`` — no jax import (lint/CI context).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from sagecal_tpu.analysis.callgraph import ModuleInfo, qual_of


def is_pallas_module(mi: ModuleInfo) -> bool:
    """Whether the module imports the Pallas API."""
    return any(target.startswith("jax.experimental.pallas")
               for target in mi.imports.values())


def module_functions(mi: ModuleInfo) -> Dict[str, ast.FunctionDef]:
    """Top-level function definitions by name."""
    if mi.tree is None:
        return {}
    return {n.name: n for n in mi.tree.body
            if isinstance(n, ast.FunctionDef)}


def positional_params(fnode: ast.FunctionDef) -> List[str]:
    """Positional parameter names (keyword-only statics excluded) —
    the names pallas_call operands bind to, in order."""
    return [a.arg for a in fnode.args.args]


def _is_pallas_call(node: ast.AST, mi: ModuleInfo) -> bool:
    if not isinstance(node, ast.Call):
        return False
    q = qual_of(node.func, mi.imports, mi.toplevel, mi.name) or ""
    return q.endswith(".pallas_call") or q == "pallas_call"


def _partial_kernel_name(expr: ast.expr, mi: ModuleInfo,
                         fns: Dict[str, ast.FunctionDef]) -> Optional[str]:
    """Kernel function named by ``functools.partial(fn, ...)`` or a
    direct module-function reference."""
    if isinstance(expr, ast.Name) and expr.id in fns:
        return expr.id
    if isinstance(expr, ast.Call):
        q = qual_of(expr.func, mi.imports, mi.toplevel, mi.name) or ""
        if q.endswith(".partial") and expr.args:
            inner = expr.args[0]
            if isinstance(inner, ast.Name) and inner.id in fns:
                return inner.id
    return None


def _blocks(fn_node: ast.FunctionDef) -> List[List[ast.stmt]]:
    """Every statement block in the function: the body plus each
    branch/loop body — the granularity at which kernel/args pairs are
    considered bound together."""
    out: List[List[ast.stmt]] = [fn_node.body]
    for n in ast.walk(fn_node):
        if isinstance(n, ast.If):
            out.append(n.body)
            if n.orelse:
                out.append(n.orelse)
        elif isinstance(n, (ast.For, ast.While)):
            out.append(n.body)
    return out


@dataclass
class KernelBinding:
    """One resolved (kernel function, positional operand exprs) pair."""
    kernel_name: str
    operand_exprs: List[ast.expr] = field(default_factory=list)


@dataclass
class PallasSite:
    """One ``pl.pallas_call`` occurrence in a module."""
    mi: ModuleInfo
    call: ast.Call                 # the pallas_call(...) expression
    apply_call: Optional[ast.Call]  # the outer (...)(operands) call
    bindings: List[KernelBinding] = field(default_factory=list)


def find_pallas_sites(mi: ModuleInfo) -> List[PallasSite]:
    """Discover every pallas_call in a module with its kernel/operand
    bindings resolved (block-paired, see module docstring)."""
    if mi.tree is None or not is_pallas_module(mi):
        return []
    fns = module_functions(mi)
    sites: List[PallasSite] = []
    # application: the Call whose func IS a pallas_call Call node
    applications: Dict[int, ast.Call] = {}
    for n in ast.walk(mi.tree):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Call)
                and _is_pallas_call(n.func, mi)):
            applications[id(n.func)] = n
    for fn in fns.values():
        blocks = _blocks(fn)
        # per-block name -> value-expr maps (last assignment wins)
        block_assigns: List[Dict[str, ast.expr]] = []
        for blk in blocks:
            m: Dict[str, ast.expr] = {}
            for s in blk:
                if (isinstance(s, ast.Assign) and len(s.targets) == 1
                        and isinstance(s.targets[0], ast.Name)):
                    m[s.targets[0].id] = s.value
            block_assigns.append(m)
        for n in ast.walk(fn):
            if not _is_pallas_call(n, mi):
                continue
            site = PallasSite(mi=mi, call=n,
                              apply_call=applications.get(id(n)))
            site.bindings = _resolve_bindings(
                n, site.apply_call, mi, fns, block_assigns)
            sites.append(site)
    return sites


def _resolve_bindings(call: ast.Call, apply_call: Optional[ast.Call],
                      mi: ModuleInfo, fns: Dict[str, ast.FunctionDef],
                      block_assigns: List[Dict[str, ast.expr]],
                      ) -> List[KernelBinding]:
    if not call.args:
        return []
    kexpr = call.args[0]
    # kernel candidates: block index -> kernel name (None = unconditional)
    kernel_cands: List[Tuple[Optional[int], str]] = []
    direct = _partial_kernel_name(kexpr, mi, fns)
    if direct is not None:
        kernel_cands.append((None, direct))
    elif isinstance(kexpr, ast.Name):
        for bi, assigns in enumerate(block_assigns):
            if kexpr.id in assigns:
                kname = _partial_kernel_name(assigns[kexpr.id], mi, fns)
                if kname is not None:
                    kernel_cands.append((bi, kname))
    # operand candidates
    op_cands: List[Tuple[Optional[int], List[ast.expr]]] = []
    if apply_call is not None:
        args = apply_call.args
        if len(args) == 1 and isinstance(args[0], ast.Starred):
            star = args[0].value
            if isinstance(star, ast.Name):
                for bi, assigns in enumerate(block_assigns):
                    v = assigns.get(star.id)
                    if isinstance(v, (ast.Tuple, ast.List)):
                        op_cands.append((bi, list(v.elts)))
        elif not any(isinstance(a, ast.Starred) for a in args):
            op_cands.append((None, list(args)))
    bindings: List[KernelBinding] = []
    if not op_cands:
        for _, kname in kernel_cands:
            bindings.append(KernelBinding(kname, []))
        return bindings
    for kbi, kname in kernel_cands:
        for obi, ops in op_cands:
            # block-paired: branch-local kernel only binds the SAME
            # branch's operand tuple
            if kbi is not None and obi is not None and kbi != obi:
                continue
            bindings.append(KernelBinding(kname, ops))
    return bindings


def kernel_names(sites: List[PallasSite]) -> Set[str]:
    return {b.kernel_name for s in sites for b in s.bindings}


def kernel_reachable(mi: ModuleInfo, roots: Set[str]) -> Set[str]:
    """Module-local functions reachable from the kernel bodies via
    direct calls (nested defs are visited as part of their enclosing
    top-level function's subtree)."""
    fns = module_functions(mi)
    seen: Set[str] = set()
    work = [r for r in roots if r in fns]
    while work:
        f = work.pop()
        if f in seen:
            continue
        seen.add(f)
        for n in ast.walk(fns[f]):
            if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id in fns and n.func.id not in seen):
                work.append(n.func.id)
    return seen
