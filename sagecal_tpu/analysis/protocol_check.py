"""Explicit-state model checker for the fleet coordination protocols.

This module drives the REAL protocol code — :class:`sagecal_tpu.fleet.
queue.LeaseQueue` (claim / renew / steal / release / complete) and the
real stream owner-lease gate :func:`sagecal_tpu.elastic.checkpoint.
check_owner_lease` — through every interleaving of 2–3 logical
workers, with fail-stop crash injection at every filesystem-operation
boundary and logical-clock ticks across lease-TTL expiries, asserting
the protocol invariants at every reachable state.

How interleavings are generated
-------------------------------

Each logical worker runs the unmodified ``LeaseQueue`` methods on a
shared :class:`~sagecal_tpu.analysis.fsmodel.SimFS` behind a per-worker
:class:`_GatedFS` that parks the worker thread at every fs-op boundary.
The controller then explores the choice tree

- ``("step", w)``  — let worker *w* execute exactly one fs op;
- ``("crash", w)`` — fail-stop worker *w* at its pending op (the op
  does not run; staged-but-unpublished state is lost, exactly the
  POSIX crash contract);
- ``("tick",)``    — advance the logical clock to the next lease
  expiry (the only instants at which anything becomes stealable).

by stateless re-execution DFS: a state is a choice prefix, replayed
from the initial state, and deduplicated by fingerprint (visible
files + clock + per-worker program position, beliefs and budgets), so
equivalent interleavings are explored once.

Invariants (checked at EVERY reachable state)
---------------------------------------------

- **no double claim** — at most one live worker believes it holds a
  live lease on a request (beliefs are recorded by the worker script
  at the same logical instant the queue call takes effect);
- **no resurrection** — a renew never succeeds at-or-after the expiry
  the holder believes (expired-is-stable is the property the whole
  steal path leans on);
- **coherence** — if the lease head on disk is live for worker X, no
  other live worker believes it holds that request;
- **no torn/wrong manifest** — every result manifest visible on disk
  at any state parses and equals the deterministic expected content
  (a zombie and a stealer may both write it — atomically, with
  identical bytes);
- **exactly-once completion / no lost item** — a done marker implies
  a valid manifest, and from every reachable state a fresh recovery
  worker (run on a clone of the filesystem, after all leases expire)
  drains the queue: no interleaving or crash can wedge a request
  un-claimably or lose one.

The stream owner-lease model additionally checks that **a live
foreign owner-lease is always refused** at adoption (driving the real
``check_owner_lease``), that a writer never republishes its chain
after its own lease expired (the self-fence), and that adoption
re-validates chain stability after the gate (the stale-read window).

Seeded mutations (``MUTATIONS``) re-introduce each protocol bug the
checker is meant to catch — steal by delete + recreate (the ABA
double-claim), renew without the expiry refusal, claim without
exclusivity, epoch publish with a torn window, non-atomic manifest
writes, adoption without the owner-lease gate, adoption without the
stale-read re-check, a writer without the self-fence — and
``tests/test_protocol.py`` pins that every one is caught.

Stdlib-only, deterministic, CPU-only; the default 2-worker exploration
is bounded well under a minute (see USER_MANUAL.md for the state-space
bounds and the op-granularity argument).
"""

from __future__ import annotations

import dataclasses
import posixpath
import queue as queuelib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from sagecal_tpu.analysis.fsmodel import SimClock, SimCrash, SimFS
from sagecal_tpu.fleet.queue import (
    LEASE_PREFIX,
    LeaseLost,
    LeaseQueue,
    WorkItem,
    _dump_json,
    _parse_json,
)

QUEUE_ROOT = "/q"
OUT_ROOT = "/out"


class CheckerError(RuntimeError):
    """Internal failure of the checker harness itself (never a
    protocol violation)."""


def manifest_path(rid: str) -> str:
    return f"{OUT_ROOT}/result-{rid}.json"


def expected_manifest(rid: str) -> str:
    """Per-request results are deterministic (request-id-derived RNG,
    independent vmapped lanes), so a zombie and a stealer write
    identical bytes; the model's manifest is its stand-in."""
    return _dump_json({"request_id": rid, "verdict": "ok",
                       "solutions": f"gains[{rid}]"})


# ---------------------------------------------------------------------------
# configuration / results
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class CheckConfig:
    """One queue-exploration scenario."""

    rids: Tuple[str, ...] = ("r1",)
    worker_ids: Tuple[str, ...] = ("wA", "wB")
    ttl_s: float = 10.0
    t0: float = 1000.0
    crash_budget: int = 1
    tick_budget: int = 2
    seed_expired_lease: bool = False   # dead foreign holder at epoch 0
    seed_torn_lease: bool = False      # unparsable garbage head
    torn_manifest: bool = False        # mutation: non-atomic write
    queue_cls: type = LeaseQueue       # mutations swap this
    max_states: int = 500_000
    deadline_s: float = 55.0
    stop_on_first: bool = True


@dataclasses.dataclass
class Violation:
    kind: str
    detail: str
    trace: Tuple[Tuple[Any, ...], ...]  # the choice prefix reaching it

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "detail": self.detail,
                "trace": [list(c) for c in self.trace]}


@dataclasses.dataclass
class Report:
    scenario: str
    violations: List[Violation]
    states: int
    replays: int
    elapsed_s: float
    complete: bool  # False when a state/time bound truncated the DFS

    @property
    def ok(self) -> bool:
        return not self.violations and self.complete

    def to_dict(self) -> Dict[str, Any]:
        return {"scenario": self.scenario, "ok": self.ok,
                "states": self.states, "replays": self.replays,
                "elapsed_s": round(self.elapsed_s, 3),
                "complete": self.complete,
                "violations": [v.to_dict() for v in self.violations]}


def _dedupe(violations: List[Violation]) -> List[Violation]:
    """One Violation per (kind, detail), keeping the shortest trace."""
    best: Dict[Tuple[str, str], Violation] = {}
    for v in violations:
        k = (v.kind, v.detail)
        if k not in best or len(v.trace) < len(best[k].trace):
            best[k] = v
    return list(best.values())


# ---------------------------------------------------------------------------
# the gated worker
# ---------------------------------------------------------------------------

class _Worker:
    """Controller-side state of one logical worker."""

    def __init__(self, wid: str):
        self.wid = wid
        self.parked_op: Optional[Tuple[str, str]] = None
        self.crashed = False
        self.finished = False
        self.failure: Optional[str] = None
        self.beliefs: Dict[str, float] = {}  # rid -> believed expiry
        self.script_violations: List[str] = []
        self.ops = 0  # fs ops executed (program-position surrogate)
        # clock at entry of the in-flight queue call: the call captured
        # its ``now`` there, so two states that differ only in it
        # behave differently and must NOT be deduplicated together
        self.call_clock = 0.0
        self.gate_go = threading.Event()
        self.gate_action = "step"
        self.thread: Optional[threading.Thread] = None
        self.fs = None  # the worker's _GatedFS, set by the script


class _GatedFS:
    """Per-worker view of the shared SimFS: parks the worker thread at
    every op boundary so the controller can schedule or crash it.
    ``makedirs`` (idempotent on the pre-made root — no visible
    transition) and ``unique_suffix`` (pure naming) are not scheduling
    points."""

    def __init__(self, sim: SimFS, worker: _Worker, ctl: "_Execution"):
        self._sim = sim
        self._w = worker
        self._ctl = ctl

    def _gate(self, op: str, detail: str) -> None:
        w = self._w
        w.parked_op = (op, detail)
        self._ctl.msgs.put(("parked", w.wid))
        w.gate_go.wait()
        w.gate_go.clear()
        w.parked_op = None
        if w.gate_action == "crash":
            raise SimCrash(w.wid)
        w.ops += 1

    def makedirs(self, path):
        return self._sim.makedirs(path)

    def unique_suffix(self):
        return self._sim.unique_suffix()

    def exists(self, path):
        self._gate("exists", path)
        return self._sim.exists(path)

    def listdir(self, path):
        self._gate("listdir", path)
        return self._sim.listdir(path)

    def read_text(self, path):
        self._gate("read_text", path)
        return self._sim.read_text(path)

    def open_excl(self, path):
        self._gate("open_excl", path)
        return self._sim.open_excl(path)

    def create(self, path):
        self._gate("create", path)
        return self._sim.create(path)

    def commit(self, fd, text):
        self._gate("commit", getattr(fd, "path", "?"))
        return self._sim.commit(fd, text)

    def publish_excl(self, path, text):
        self._gate("publish_excl", path)
        return self._sim.publish_excl(path, text)

    def write_atomic(self, path, text):
        self._gate("write_atomic", path)
        return self._sim.write_atomic(path, text)

    def unlink(self, path):
        self._gate("unlink", path)
        return self._sim.unlink(path)

    def unlink_matching(self, dirpath, prefix):
        self._gate("unlink_matching", f"{dirpath}/{prefix}*")
        return self._sim.unlink_matching(dirpath, prefix)


def _write_manifest(w: _Worker, rid: str, torn: bool) -> None:
    if torn:
        # mutation: create + write as two separately-visible steps — a
        # reader (or a crash) between them sees a torn manifest
        fd = w.fs.create(manifest_path(rid))
        w.fs.commit(fd, expected_manifest(rid))
    else:
        w.fs.write_atomic(manifest_path(rid), expected_manifest(rid))


def _script_main(w: _Worker, ctl: "_Execution", cfg: CheckConfig) -> None:
    """The worker script: the FleetWorker lifecycle distilled to its
    protocol-visible steps — claim, one mid-solve renew, write the
    result manifest, complete.  Beliefs are recorded at the same
    logical instant the queue call captures its ``now`` (no fs op in
    between, hence no scheduling point in between)."""
    try:
        fs = _GatedFS(ctl.sim, w, ctl)
        w.fs = fs
        q = cfg.queue_cls(QUEUE_ROOT, worker=w.wid, ttl_s=cfg.ttl_s,
                          fs=fs, clock=lambda: ctl.clock.t)
        for rid in cfg.rids:
            t_claim = w.call_clock = ctl.clock.t
            if not q.claim(rid):
                continue
            w.beliefs[rid] = t_claim + q.ttl_s
            try:
                w.call_clock = ctl.clock.t
                exp = q.renew(rid)
            except LeaseLost:
                w.beliefs.pop(rid, None)
                continue
            # renew computed its expiry as now + ttl, so exp - ttl is
            # the instant the renew took effect; succeeding at-or-past
            # the believed expiry means an expired lease was
            # resurrected underneath a stealer's validated observation
            if exp - q.ttl_s >= w.beliefs[rid]:
                w.script_violations.append(
                    f"renew of {rid} by {w.wid} succeeded at "
                    f"t={exp - q.ttl_s:g} at-or-past believed expiry "
                    f"{w.beliefs[rid]:g} (expired leases must be "
                    f"un-renewable)")
            w.beliefs[rid] = exp
            _write_manifest(w, rid, cfg.torn_manifest)
            w.call_clock = ctl.clock.t
            q.complete(rid, verdict="ok")
            w.beliefs.pop(rid, None)
        w.finished = True
        ctl.msgs.put(("done", w.wid))
    except SimCrash:
        w.crashed = True
        w.beliefs.clear()  # a dead process believes nothing
        ctl.msgs.put(("crashed", w.wid))
    except BaseException as e:  # reported as a finding, never lost
        w.failure = f"{type(e).__name__}: {e}"
        ctl.msgs.put(("failed", w.wid))


# ---------------------------------------------------------------------------
# one replayed execution
# ---------------------------------------------------------------------------

class _Execution:
    """Replay of one choice prefix from the initial state."""

    def __init__(self, cfg: CheckConfig,
                 choices: Tuple[Tuple[Any, ...], ...]):
        self.cfg = cfg
        self.sim = SimFS()
        self.clock = SimClock(cfg.t0)
        self.msgs: "queuelib.Queue" = queuelib.Queue()
        self.crash_left = cfg.crash_budget
        self.tick_left = cfg.tick_budget
        self._seed()
        self.workers: Dict[str, _Worker] = {}
        for wid in cfg.worker_ids:
            w = _Worker(wid)
            w.thread = threading.Thread(
                target=_script_main, args=(w, self, cfg), daemon=True)
            self.workers[wid] = w
        for w in self.workers.values():
            w.thread.start()
        self._settle(len(self.workers))
        for c in choices:
            self.apply(c)

    def _seed(self) -> None:
        cfg = self.cfg
        seeder = LeaseQueue(QUEUE_ROOT, worker="seeder",
                            ttl_s=cfg.ttl_s, fs=self.sim,
                            clock=lambda: self.clock.t)
        self.sim.makedirs(OUT_ROOT)
        for rid in cfg.rids:
            seeder.put(WorkItem(request_id=rid, tenant="t", request={}))
        rid0 = cfg.rids[0]
        if cfg.seed_expired_lease:
            # a dead foreign worker's lease, already past its TTL
            self.sim.publish_excl(seeder.lease_path(rid0, 0), _dump_json({
                "worker": "ghost", "request_id": rid0, "epoch": 0,
                "acquired_at": cfg.t0 - cfg.ttl_s - 5.0,
                "renewed_at": cfg.t0 - cfg.ttl_s - 5.0,
                "expires_at": cfg.t0 - 5.0}))
        elif cfg.seed_torn_lease:
            # unparsable garbage at the head (external corruption or
            # an older protocol's torn write): must be claimable
            self.sim.publish_excl(seeder.lease_path(rid0, 0), "")

    # -- controller <-> worker handshakes -----------------------------

    def _settle(self, n: int) -> None:
        for _ in range(n):
            self._recv()

    def _recv(self) -> Tuple[str, str]:
        try:
            return self.msgs.get(timeout=10.0)
        except queuelib.Empty:
            raise CheckerError("worker thread hung (no message in 10s)")

    def _expect_from(self, wid: str) -> None:
        kind, got = self._recv()
        if got != wid:
            raise CheckerError(
                f"message from {got!r} while stepping {wid!r}")

    # -- actions -------------------------------------------------------

    def _next_expiry(self) -> Optional[float]:
        """The earliest future lease-head expiry, or None.  Only head
        epochs matter: non-head epochs are immutable history."""
        heads: Dict[str, Tuple[int, str]] = {}
        for path, text in self.sim.files.items():
            name = posixpath.basename(path)
            if not (name.startswith(LEASE_PREFIX)
                    and name.endswith(".json")):
                continue
            stem = name[len(LEASE_PREFIX):-len(".json")]
            rid, _, e = stem.rpartition(".e")
            try:
                k = int(e)
            except ValueError:
                continue
            if rid not in heads or k > heads[rid][0]:
                heads[rid] = (k, text)
        cands = []
        for _, (_, text) in heads.items():
            doc = _parse_json(text)
            if doc is not None:
                exp = float(doc.get("expires_at", 0.0))
                if exp > self.clock.t:
                    cands.append(exp)
        return min(cands) if cands else None

    def enabled(self) -> List[Tuple[Any, ...]]:
        acts: List[Tuple[Any, ...]] = []
        parked = [wid for wid, w in self.workers.items()
                  if w.parked_op is not None]
        for wid in parked:
            acts.append(("step", wid))
        if self.crash_left > 0:
            for wid in parked:
                acts.append(("crash", wid))
        if self.tick_left > 0 and parked \
                and self._next_expiry() is not None:
            acts.append(("tick",))
        return acts

    def apply(self, act: Tuple[Any, ...]) -> None:
        kind = act[0]
        if kind == "step":
            w = self.workers[act[1]]
            w.gate_action = "step"
            w.gate_go.set()
            self._expect_from(act[1])
        elif kind == "crash":
            self.crash_left -= 1
            w = self.workers[act[1]]
            w.gate_action = "crash"
            w.gate_go.set()
            self._expect_from(act[1])
        elif kind == "tick":
            self.tick_left -= 1
            nxt = self._next_expiry()
            if nxt is not None:
                self.clock.advance_to(nxt)
        else:
            raise CheckerError(f"unknown action {act!r}")

    def teardown(self) -> None:
        """Crash every still-parked worker so its thread exits."""
        waiting = 0
        for w in self.workers.values():
            if w.parked_op is not None and w.thread.is_alive():
                w.gate_action = "crash"
                w.gate_go.set()
                waiting += 1
        for _ in range(waiting):
            self._recv()
        for w in self.workers.values():
            w.thread.join(timeout=5.0)

    # -- state identity ------------------------------------------------

    def fingerprint(self) -> Tuple:
        ws = []
        for wid in self.cfg.worker_ids:
            w = self.workers[wid]
            ws.append((wid, w.crashed, w.finished, w.failure,
                       w.parked_op, w.ops, w.call_clock,
                       tuple(sorted(w.beliefs.items())),
                       tuple(w.script_violations)))
        return (self.sim.fingerprint(), self.clock.t,
                self.crash_left, self.tick_left, tuple(ws))


# ---------------------------------------------------------------------------
# invariants
# ---------------------------------------------------------------------------

def _check_state(ex: _Execution) -> List[Tuple[str, str]]:
    """All invariant violations visible at the current state, as
    (kind, detail) pairs."""
    out: List[Tuple[str, str]] = []
    now = ex.clock.t
    live = {wid: w for wid, w in ex.workers.items() if not w.crashed}

    for w in ex.workers.values():
        for sv in w.script_violations:
            out.append(("renew-past-expiry", sv))
        if w.failure is not None:
            out.append(("worker-exception",
                        f"{w.wid} raised {w.failure}"))

    # no double claim: at most one live worker believes a live lease
    for rid in ex.cfg.rids:
        holders = [wid for wid, w in live.items()
                   if w.beliefs.get(rid, 0.0) > now]
        if len(holders) > 1:
            out.append(("double-claim",
                        f"{holders} all believe they hold {rid} at "
                        f"t={now:g}"))

    # coherence: a live believed holder must own the live on-disk head
    q = LeaseQueue(QUEUE_ROOT, worker="observer", ttl_s=ex.cfg.ttl_s,
                   fs=ex.sim.clone(), clock=lambda: now)
    for rid in ex.cfg.rids:
        doc = q.read_lease(rid)
        if doc is None or float(doc.get("expires_at", 0.0)) <= now:
            continue
        head_worker = doc.get("worker")
        for wid, w in live.items():
            if wid != head_worker and w.beliefs.get(rid, 0.0) > now:
                out.append((
                    "lease-clobbered",
                    f"head of {rid} is live for {head_worker!r} but "
                    f"{wid} also believes it holds it at t={now:g}"))

    # manifests: whenever visible, parsed and byte-identical to the
    # deterministic expected content (torn = violation)
    for path, text in ex.sim.files.items():
        name = posixpath.basename(path)
        if not (name.startswith("result-") and name.endswith(".json")):
            continue
        rid = name[len("result-"):-len(".json")]
        if text != expected_manifest(rid):
            out.append(("torn-manifest",
                        f"manifest {name} holds {text!r} (torn or "
                        f"non-deterministic write)"))

    # done => manifest exists (validity is covered just above)
    for rid in ex.cfg.rids:
        if ex.sim.files.get(q.done_path(rid)) is not None \
                and manifest_path(rid) not in ex.sim.files:
            out.append(("done-without-manifest",
                        f"{rid} has a done marker but no manifest"))
    return out


def _check_recovery(ex: _Execution) -> Optional[str]:
    """From this state, after every lease expires and every worker is
    gone, can a fresh worker drain the queue?  Runs on a CLONE of the
    filesystem (the real execution is not disturbed).  Catches
    livelock (an un-claimably wedged request) and lost items — and
    because it runs at every visited state, it subsumes the
    crash-everyone-then-recover schedules."""
    cfg = ex.cfg
    fs2 = ex.sim.clone()
    t = ex.clock.t
    for path, text in fs2.files.items():
        if posixpath.basename(path).startswith(LEASE_PREFIX):
            doc = _parse_json(text)
            if doc is not None:
                t = max(t, float(doc.get("expires_at", 0.0)))
    tbox = [t + 0.001]
    rq = cfg.queue_cls(QUEUE_ROOT, worker="recovery", ttl_s=cfg.ttl_s,
                       fs=fs2, clock=lambda: tbox[0])
    for _ in range(3 * len(cfg.rids) + 3):
        if rq.all_done():
            break
        progress = False
        for it in rq.items():
            rid = it.request_id
            if fs2.files.get(rq.done_path(rid)) is not None:
                continue
            if rq.claim(rid):
                fs2.write_atomic(manifest_path(rid),
                                 expected_manifest(rid))
                rq.complete(rid, verdict="ok")
                progress = True
        if not progress:
            tbox[0] += cfg.ttl_s + 0.001
    if not rq.all_done():
        return (f"recovery worker cannot drain the queue from this "
                f"state: {rq.stats()} (wedged request — livelock or "
                f"lost item)")
    for rid in cfg.rids:
        if fs2.files.get(manifest_path(rid)) != expected_manifest(rid):
            return f"after recovery, manifest for {rid} is missing/torn"
    return None


# ---------------------------------------------------------------------------
# the queue explorer
# ---------------------------------------------------------------------------

def explore(cfg: CheckConfig, scenario: str = "queue") -> Report:
    """Exhaustive (within budgets) re-execution DFS over the choice
    tree, deduplicated by state fingerprint."""
    t_start = time.monotonic()
    seen = set()
    stack: List[Tuple[Tuple[Any, ...], ...]] = [()]
    violations: List[Violation] = []
    states = replays = 0
    complete = True
    while stack:
        if time.monotonic() - t_start > cfg.deadline_s \
                or states >= cfg.max_states:
            complete = False
            break
        prefix = stack.pop()
        ex = _Execution(cfg, prefix)
        replays += 1
        try:
            fp = ex.fingerprint()
            if fp in seen:
                continue
            seen.add(fp)
            states += 1
            for kind, detail in _check_state(ex):
                violations.append(Violation(kind, detail, prefix))
            stall = _check_recovery(ex)
            if stall is not None:
                violations.append(
                    Violation("recovery-stall", stall, prefix))
            if violations and cfg.stop_on_first:
                break
            for act in sorted(ex.enabled(), reverse=True):
                stack.append(prefix + (act,))
        finally:
            ex.teardown()
    return Report(scenario=scenario, violations=_dedupe(violations),
                  states=states, replays=replays,
                  elapsed_s=time.monotonic() - t_start,
                  complete=complete)


# ---------------------------------------------------------------------------
# the stream owner-lease model
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class StreamConfig:
    """Owner-lease handoff between a writer (w1) checkpointing its
    stream chain and a candidate adopter (w2)."""

    # three windows, so a mid-chain RENEWING write exists between the
    # first checkpoint and the final releasing one — the stale-read
    # fork needs a renewal, not a release, to race the adopter's gate
    windows: int = 3
    ttl_s: float = 10.0
    t0: float = 1000.0
    tick_budget: int = 2
    crash_budget: int = 1
    adopt_checks_lease: bool = True    # False = skip the real gate
    adopt_confirms_chain: bool = True  # stale-read revalidation
    writer_fences: bool = True         # no writes past own expiry
    deadline_s: float = 30.0


def explore_stream(cfg: StreamConfig) -> Report:
    """Explicit-state DFS over the checkpoint-granular actions of one
    writer and one adopter, driving the real
    :func:`~sagecal_tpu.elastic.checkpoint.check_owner_lease` at every
    adoption attempt.  Checkpoint writes are atomic single transitions
    (the real manager writes tmp + fsync + replace), so this
    granularity is exact, not an approximation.

    Adoption is modelled in the three phases the resume path performs:
    read the newest checkpoint meta, run the owner-lease gate, then
    re-read the newest checkpoint and restart if the chain advanced in
    between — the stale-read window this exploration surfaced (a gate
    pass on a stale expired meta while the writer had already renewed
    would otherwise fork the chain)."""
    from sagecal_tpu.elastic.checkpoint import (
        ResumeRefused,
        check_owner_lease,
    )

    t_start = time.monotonic()
    violations: List[Violation] = []
    seen = set()
    adoptions = 0
    complete = True

    # state: (t, ckpts, (w1_next, w1_alive, w1_fenced),
    #         (w2_read, w2_checked, w2_adopted),
    #         tick_left, crash_left)
    # ckpts: sorted tuple of (index, owner, lease_expires, windows_done)
    init = (cfg.t0, (), (0, True, False), (None, False, False),
            cfg.tick_budget, cfg.crash_budget)
    stack: List[Tuple[Tuple, Tuple]] = [(init, ())]

    def newest(ckpts):
        return ckpts[-1] if ckpts else None

    while stack:
        if time.monotonic() - t_start > cfg.deadline_s:
            complete = False
            break
        state, trace = stack.pop()
        if state in seen:
            continue
        seen.add(state)
        (t, ckpts, (w1_next, w1_alive, w1_fenced),
         (w2_read, w2_checked, w2_adopted), tick_left,
         crash_left) = state

        succs: List[Tuple[Tuple[Any, ...], Tuple]] = []

        # -- writer: checkpoint the next window (implicit lease renew;
        #    the final window releases with lease 0.0)
        if w1_alive and not w1_fenced and w1_next < cfg.windows:
            prev = None
            for c in ckpts:
                if c[1] == "w1":
                    prev = c
            stalled = prev is not None and 0.0 < prev[2] <= t
            if cfg.writer_fences and stalled:
                # the self-fence: own lease expired while stalled —
                # stop republishing the chain (a successor may own it)
                succs.append((("w1_fence",),
                              (t, ckpts, (w1_next, True, True),
                               (w2_read, w2_checked, w2_adopted),
                               tick_left, crash_left)))
            else:
                if stalled:
                    violations.append(Violation(
                        "writer-resurrected-chain",
                        f"w1 republished its chain at t={t:g} after "
                        f"its own lease expired at {prev[2]:g}",
                        trace + (("w1_write", w1_next),)))
                final = w1_next == cfg.windows - 1
                lease = 0.0 if final else t + cfg.ttl_s
                nc = tuple(sorted(
                    [c for c in ckpts if c[0] != w1_next]
                    + [(w1_next, "w1", lease, w1_next + 1)]))
                succs.append((("w1_write", w1_next),
                              (t, nc, (w1_next + 1, True, w1_fenced),
                               (w2_read, w2_checked, w2_adopted),
                               tick_left, crash_left)))

        # -- writer crash
        if w1_alive and crash_left > 0:
            succs.append((("w1_crash",),
                          (t, ckpts, (w1_next, False, w1_fenced),
                           (w2_read, w2_checked, w2_adopted),
                           tick_left, crash_left - 1)))

        # -- adopter phase 1: read the newest checkpoint meta
        if not w2_adopted and ckpts and w2_read is None:
            succs.append((("w2_read",),
                          (t, ckpts, (w1_next, w1_alive, w1_fenced),
                           (newest(ckpts), False, False),
                           tick_left, crash_left)))

        # -- adopter phase 2: the owner-lease gate (REAL code)
        if not w2_adopted and w2_read is not None and not w2_checked:
            _, owner, expires, _ = w2_read
            if cfg.adopt_checks_lease:
                try:
                    check_owner_lease(
                        {"owner": owner, "lease_expires_at": expires},
                        "w2", now=t)
                    passed = True
                except ResumeRefused:
                    passed = False
            else:
                passed = True  # mutation: gate skipped entirely
            if passed:
                succs.append((("w2_gate_pass",),
                              (t, ckpts,
                               (w1_next, w1_alive, w1_fenced),
                               (w2_read, True, False),
                               tick_left, crash_left)))
            else:
                succs.append((("w2_gate_refused",),
                              (t, ckpts,
                               (w1_next, w1_alive, w1_fenced),
                               (None, False, False),
                               tick_left, crash_left)))

        # -- adopter phase 3: confirm chain stability, then adopt
        if not w2_adopted and w2_read is not None and w2_checked:
            cur = newest(ckpts)
            if cfg.adopt_confirms_chain and cur != w2_read:
                # chain advanced between gate and adoption: restart
                succs.append((("w2_restart",),
                              (t, ckpts,
                               (w1_next, w1_alive, w1_fenced),
                               (None, False, False),
                               tick_left, crash_left)))
            else:
                head = newest(ckpts)
                if head is not None and head[1] not in ("", "w2") \
                        and head[2] > t and w1_alive and not w1_fenced:
                    violations.append(Violation(
                        "adopted-live-foreign-lease",
                        f"w2 adopted the chain at t={t:g} while "
                        f"{head[1]}'s lease is live until "
                        f"{head[2]:g} and its holder can still write",
                        trace + (("w2_adopt",),)))
                adoptions += 1
                wd = w2_read[3]
                nc = tuple(sorted(
                    [c for c in ckpts if c[0] != wd]
                    + [(wd, "w2", t + cfg.ttl_s, wd + 1)]))
                succs.append((("w2_adopt",),
                              (t, nc, (w1_next, w1_alive, w1_fenced),
                               (None, False, True),
                               tick_left, crash_left)))

        # -- logical time: the next lease expiry AND a mid-TTL point.
        #    The mid-TTL target matters: a writer renewing between two
        #    expiries produces overlapping leases with distinct
        #    deadlines, which is exactly the shape of the stale-read
        #    fork; expiry-only ticking can never construct it.
        if tick_left > 0:
            targets = {t + cfg.ttl_s / 2.0}
            exps = [c[2] for c in ckpts if c[2] > t]
            if exps:
                targets.add(min(exps))
            for tgt in sorted(targets):
                succs.append((("tick", tgt),
                              (tgt, ckpts,
                               (w1_next, w1_alive, w1_fenced),
                               (w2_read, w2_checked, w2_adopted),
                               tick_left - 1, crash_left)))

        for act, ns in succs:
            stack.append((ns, trace + (act,)))

    if cfg.adopt_checks_lease and cfg.adopt_confirms_chain \
            and adoptions == 0:
        violations.append(Violation(
            "adoption-unreachable",
            "no explored schedule ever adopted the chain — the "
            "owner-lease gate is vacuously strict", ()))
    return Report(scenario="stream-owner-lease",
                  violations=_dedupe(violations), states=len(seen),
                  replays=len(seen),
                  elapsed_s=time.monotonic() - t_start,
                  complete=complete)


# ---------------------------------------------------------------------------
# seeded mutations: each re-introduces one protocol bug
# ---------------------------------------------------------------------------

class _MutantStealByDelete(LeaseQueue):
    """Mutation: steal by unlinking the dead lease and re-creating the
    SAME name (the pre-epoch-chain protocol).  Two stealers that both
    read the dead lease race the unlink: the slower one deletes the
    winner's freshly created LIVE lease (unlink acts on the name, not
    on the content that was validated) and claims on top — the ABA
    double claim."""

    def claim(self, rid, now=None):
        now = self._now(now)
        if self.fs.exists(self.done_path(rid)):
            return False
        epoch, doc = self._lease_head(rid)
        if self._live(doc, now):
            return False
        if epoch >= 0:
            try:
                self.fs.unlink(self.lease_path(rid, epoch))
            except OSError:
                pass
        try:
            self.fs.publish_excl(
                self.lease_path(rid, max(epoch, 0)), _dump_json({
                    "worker": self.worker, "request_id": rid,
                    "epoch": max(epoch, 0), "acquired_at": now,
                    "renewed_at": now, "expires_at": now + self.ttl_s}))
        except (FileExistsError, OSError):
            return False
        return True


class _MutantRenewPastTTL(LeaseQueue):
    """Mutation: renew without the expiry refusal — an expired lease
    can be resurrected by its old holder, so "this head is expired" is
    no longer a stable observation."""

    def renew(self, rid, now=None):
        now = self._now(now)
        epoch, doc = self._lease_head(rid)
        if doc is None or doc.get("worker") != self.worker:
            raise LeaseLost(f"lease on {rid} lost")
        doc = dict(doc, renewed_at=now, expires_at=now + self.ttl_s)
        if not self._advance(rid, epoch, doc):
            raise LeaseLost(f"lease on {rid} lost")
        return doc["expires_at"]


class _MutantClaimNoExcl(LeaseQueue):
    """Mutation: advance the chain with a plain truncating create
    instead of an exclusive publish — every racer "wins", so two
    workers both believe they claimed."""

    def _advance(self, rid, epoch, doc):
        fd = self.fs.create(self.lease_path(rid, epoch + 1))
        self.fs.commit(fd, _dump_json(dict(doc, epoch=epoch + 1)))
        return True


class _MutantTornPublish(LeaseQueue):
    """Mutation: advance the chain with ``O_CREAT|O_EXCL`` followed by
    a separate content write.  The head is visible-but-empty between
    the two ops; a peer that reads the torn head treats the lease as
    dead and advances over it while its creator is alive mid-write —
    double claim.  This is why the shipped protocol publishes epoch
    files via the atomic hard-link publish instead."""

    def _advance(self, rid, epoch, doc):
        try:
            fd = self.fs.open_excl(self.lease_path(rid, epoch + 1))
        except (FileExistsError, OSError):
            return False
        self.fs.commit(fd, _dump_json(dict(doc, epoch=epoch + 1)))
        return True


def _mut_steal_by_delete(**kw) -> Report:
    cfg = CheckConfig(queue_cls=_MutantStealByDelete,
                      seed_expired_lease=True, crash_budget=0,
                      tick_budget=0, **kw)
    return explore(cfg, scenario="mutation:steal-by-delete")


def _mut_renew_past_ttl(**kw) -> Report:
    cfg = CheckConfig(queue_cls=_MutantRenewPastTTL,
                      worker_ids=("wA",), crash_budget=0,
                      tick_budget=2, **kw)
    return explore(cfg, scenario="mutation:renew-past-ttl")


def _mut_claim_no_excl(**kw) -> Report:
    cfg = CheckConfig(queue_cls=_MutantClaimNoExcl, crash_budget=0,
                      tick_budget=0, **kw)
    return explore(cfg, scenario="mutation:claim-no-excl")


def _mut_torn_publish(**kw) -> Report:
    cfg = CheckConfig(queue_cls=_MutantTornPublish, crash_budget=0,
                      tick_budget=0, **kw)
    return explore(cfg, scenario="mutation:torn-publish")


def _mut_torn_manifest(**kw) -> Report:
    cfg = CheckConfig(torn_manifest=True, worker_ids=("wA",),
                      crash_budget=1, tick_budget=0, **kw)
    return explore(cfg, scenario="mutation:torn-manifest")


def _mut_adopt_without_check(**kw) -> Report:
    return explore_stream(StreamConfig(adopt_checks_lease=False, **kw))


def _mut_adopt_stale_read(**kw) -> Report:
    return explore_stream(StreamConfig(adopt_confirms_chain=False,
                                       **kw))


def _mut_writer_no_fence(**kw) -> Report:
    return explore_stream(StreamConfig(writer_fences=False, **kw))


#: name -> runner; each re-introduces one protocol bug the checker
#: must catch (pinned by tests/test_protocol.py)
MUTATIONS: Dict[str, Callable[..., Report]] = {
    "steal-by-delete": _mut_steal_by_delete,
    "renew-past-ttl": _mut_renew_past_ttl,
    "claim-no-excl": _mut_claim_no_excl,
    "torn-publish": _mut_torn_publish,
    "torn-manifest": _mut_torn_manifest,
    "adopt-without-owner-check": _mut_adopt_without_check,
    "adopt-stale-read": _mut_adopt_stale_read,
    "writer-no-fence": _mut_writer_no_fence,
}


def run_mutation(name: str, **kw) -> Report:
    if name not in MUTATIONS:
        raise KeyError(f"unknown mutation {name!r} "
                       f"(have {sorted(MUTATIONS)})")
    return MUTATIONS[name](**kw)


# ---------------------------------------------------------------------------
# the default check (diag protocol / CI)
# ---------------------------------------------------------------------------

def default_scenarios(workers: int = 2, crash_budget: int = 1,
                      tick_budget: int = 2
                      ) -> List[Tuple[str, CheckConfig]]:
    wids = tuple(f"w{chr(ord('A') + i)}" for i in range(workers))
    base = dict(worker_ids=wids, crash_budget=crash_budget,
                tick_budget=tick_budget)
    return [
        ("fresh-item", CheckConfig(**base)),
        ("expired-foreign-lease",
         CheckConfig(seed_expired_lease=True, **base)),
        ("garbage-lease-head",
         CheckConfig(seed_torn_lease=True, **base)),
    ]


def run_protocol_check(workers: int = 2, crash_budget: int = 1,
                       tick_budget: int = 2, deadline_s: float = 55.0,
                       log=print) -> Dict[str, Any]:
    """The full default suite: every queue scenario exhaustively, plus
    the stream owner-lease model.  ``ok`` is True iff every scenario
    completed within budget with zero violations."""
    t0 = time.monotonic()
    reports: List[Report] = []
    for name, cfg in default_scenarios(workers, crash_budget,
                                       tick_budget):
        cfg.deadline_s = max(deadline_s - (time.monotonic() - t0), 5.0)
        rep = explore(cfg, scenario=name)
        reports.append(rep)
        log(f"protocol: {name}: {rep.states} states, "
            f"{rep.replays} replays, {rep.elapsed_s:.1f}s, "
            f"{'OK' if rep.ok else 'VIOLATED' if rep.violations else 'TRUNCATED'}")
    srep = explore_stream(StreamConfig())
    reports.append(srep)
    log(f"protocol: {srep.scenario}: {srep.states} states, "
        f"{srep.elapsed_s:.1f}s, {'OK' if srep.ok else 'VIOLATED'}")
    return {
        "ok": all(r.ok for r in reports),
        "workers": workers,
        "states": sum(r.states for r in reports),
        "replays": sum(r.replays for r in reports),
        "elapsed_s": round(time.monotonic() - t0, 3),
        "scenarios": [r.to_dict() for r in reports],
    }
