"""Rule registry: one module per rule, discovered statically.

Adding a rule = add a module here, list its class in ``all_rules``,
give it fixtures in ``tests/fixtures/jaxlint/`` and cases in
``tests/test_analysis.py``.
"""

from __future__ import annotations

from typing import List, Type

from sagecal_tpu.analysis.engine import Rule
from sagecal_tpu.analysis.rules.jl001 import TracedControlFlow
from sagecal_tpu.analysis.rules.jl002 import HostSync
from sagecal_tpu.analysis.rules.jl003 import RecompileHazard
from sagecal_tpu.analysis.rules.jl004 import DtypePolicy
from sagecal_tpu.analysis.rules.jl005 import DataDependentShape
from sagecal_tpu.analysis.rules.jl006 import StrayCollective
from sagecal_tpu.analysis.rules.jl007 import UndonatedCarry
from sagecal_tpu.analysis.rules.jl008 import NonAtomicProtocolWrite
from sagecal_tpu.analysis.rules.jl009 import UnguardedPickleLoad
from sagecal_tpu.analysis.rules.jl010 import RawClockInLeaseLogic
from sagecal_tpu.analysis.rules.jl011 import UseAfterDonation
from sagecal_tpu.analysis.rules.jl012 import MixedDtypeComparison
from sagecal_tpu.analysis.rules.jl013 import CotangentCompleteness
from sagecal_tpu.analysis.rules.jl014 import PrecisionFlow
from sagecal_tpu.analysis.rules.jl015 import BlockSpecHazard
from sagecal_tpu.analysis.rules.jl016 import BufferedJsonlAppend
from sagecal_tpu.analysis.rules.jl900 import DeadImport


def all_rules() -> List[Type[Rule]]:
    return [
        TracedControlFlow,
        HostSync,
        RecompileHazard,
        DtypePolicy,
        DataDependentShape,
        StrayCollective,
        UndonatedCarry,
        NonAtomicProtocolWrite,
        UnguardedPickleLoad,
        RawClockInLeaseLogic,
        UseAfterDonation,
        MixedDtypeComparison,
        CotangentCompleteness,
        PrecisionFlow,
        BlockSpecHazard,
        BufferedJsonlAppend,
        DeadImport,
    ]
