"""JL001: Python control flow on traced values inside jit-reachable code.

``if``/``while``/``assert`` with a test that calls into ``jax.numpy`` /
``jax.lax`` (or reads a local assigned from such a call) forces a trace
-time concretization error at best, a silent host sync at worst.  The
fix is ``jnp.where`` / ``lax.cond`` / ``lax.while_loop``.

Precision: only *jnp-tainted* tests fire.  ``if collect_trace:`` on a
static bool, ``if key is None``, and dtype comparisons are all legal
trace-time Python and stay silent.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sagecal_tpu.analysis.engine import (
    Finding,
    Rule,
    contains_jnp_call,
    tainted_locals,
)


def _identity_test(test: ast.AST) -> bool:
    """``x is None`` / ``x is not None`` — object identity is legal
    trace-time Python even when ``x`` may hold a tracer."""
    if isinstance(test, ast.Compare):
        return all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in test.ops)
    if isinstance(test, ast.BoolOp):
        return all(_identity_test(v) for v in test.values)
    return False


class TracedControlFlow(Rule):
    id = "JL001"
    title = ("Python if/while/assert on a traced value inside "
             "jit-reachable code")

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            taint_cache = {}
            for node in ast.walk(mi.tree):
                if not isinstance(node, (ast.If, ast.While, ast.Assert)):
                    continue
                fi = graph.stmt_reachable(mi, node)
                if fi is None:
                    continue
                if fi.qualname not in taint_cache:
                    taint_cache[fi.qualname] = tainted_locals(fi.node, mi)
                tainted = taint_cache[fi.qualname]
                test = node.test
                if _identity_test(test):
                    continue
                if not contains_jnp_call(test, mi, tainted):
                    continue
                kind = {ast.If: "if", ast.While: "while",
                        ast.Assert: "assert"}[type(node)]
                yield self.finding(
                    mi, node,
                    f"Python `{kind}` on a traced value "
                    f"(use jnp.where / lax.cond / lax.while_loop)",
                    symbol=fi.qualname,
                )
