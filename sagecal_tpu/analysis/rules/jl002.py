"""JL002: host-sync calls reachable from jitted code.

``.item()`` / ``.tolist()`` / ``.block_until_ready()`` / ``np.asarray``
/ ``jax.device_get`` inside a jit-reachable function either fail at
trace time or (worse, via callbacks) silently round-trip device->host.

Builtin casts (``float()``/``int()``/``bool()``/``complex()``) are only
flagged when the argument is jnp-tainted: ``float(fdelta)`` on a Python
closure scalar (sage.py's coherency block) is legal and common, while
``float(jnp.sum(r))`` inside jit is a concretization error.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sagecal_tpu.analysis.engine import (
    Finding,
    Rule,
    contains_jnp_call,
    tainted_locals,
)
from sagecal_tpu.analysis.callgraph import qual_of

_SYNC_ATTRS = ("item", "tolist", "block_until_ready")
_SYNC_QUALS = ("jax.device_get",)
# flagged only when the argument is jnp-tainted: np.array([...python
# floats...]) is a legal trace-time constant, np.asarray(traced) syncs
_TAINTED_ONLY_QUALS = ("numpy.asarray", "numpy.array")
_CAST_BUILTINS = ("float", "int", "bool", "complex")


class HostSync(Rule):
    id = "JL002"
    title = "host-sync call reachable from jitted code"

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            taint_cache = {}
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                fi = graph.stmt_reachable(mi, node)
                if fi is None:
                    continue
                msg = self._classify(node, mi, fi, taint_cache)
                if msg:
                    yield self.finding(mi, node, msg, symbol=fi.qualname)

    def _classify(self, call, mi, fi, taint_cache):
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_ATTRS:
            # skip list.item()-style on plain dicts: only flag when the
            # receiver is a Name/Attribute/Call (array-like receiver is
            # undecidable statically; .item/.block_until_ready are
            # array-API names so the prior is strong)
            return (f"`.{func.attr}()` forces a device->host sync "
                    f"inside jit-reachable code")
        q = qual_of(func, mi.imports, mi.toplevel, mi.name)
        if q in _SYNC_QUALS:
            return (f"`{q}` materializes a device array on host "
                    f"inside jit-reachable code")
        if q in _TAINTED_ONLY_QUALS and call.args:
            if fi.qualname not in taint_cache:
                taint_cache[fi.qualname] = tainted_locals(fi.node, mi)
            if contains_jnp_call(call.args[0], mi,
                                 taint_cache[fi.qualname]):
                return (f"`{q}` on a traced value forces a "
                        f"device->host sync inside jit-reachable code")
            return None
        if (isinstance(func, ast.Name) and func.id in _CAST_BUILTINS
                and func.id not in mi.imports and call.args):
            if fi.qualname not in taint_cache:
                taint_cache[fi.qualname] = tainted_locals(fi.node, mi)
            if contains_jnp_call(call.args[0], mi,
                                 taint_cache[fi.qualname]):
                return (f"`{func.id}()` on a traced value concretizes "
                        f"inside jit-reachable code")
        return None
