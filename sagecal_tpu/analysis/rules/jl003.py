"""JL003: recompile hazards — branch-controlling parameters of a jit
root not declared static.

A jit-wrapped function whose parameter (bool-annotated or bool-default)
is used in a Python ``if``/ternary test must declare that parameter in
``static_argnames``/``static_argnums`` — otherwise every call traces it
as a 0-d array and the branch fails, or (when callers pass weak-typed
Python scalars) each distinct value recompiles.  Statics are merged
across every wrap site of the function (decorator and call-site forms,
``jax.jit`` and ``instrumented_jit`` alike), so declaring them on any
wrapper satisfies the rule.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from sagecal_tpu.analysis.engine import Finding, Rule


def _bool_like_params(node) -> Set[str]:
    """Parameter names annotated ``bool`` or defaulted to True/False."""
    args = node.args
    out: Set[str] = set()
    all_args = list(args.posonlyargs) + list(args.args) + list(
        args.kwonlyargs)
    for a in all_args:
        ann = a.annotation
        if isinstance(ann, ast.Name) and ann.id == "bool":
            out.add(a.arg)
        elif (isinstance(ann, ast.Constant)
              and ann.value in ("bool", "Bool")):
            out.add(a.arg)
    pos = list(args.posonlyargs) + list(args.args)
    for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
        if isinstance(d, ast.Constant) and isinstance(d.value, bool):
            out.add(a.arg)
    for a, d in zip(args.kwonlyargs, args.kw_defaults):
        if d is not None and isinstance(d, ast.Constant) \
                and isinstance(d.value, bool):
            out.add(a.arg)
    return out


def _positions(node) -> dict:
    args = node.args
    return {a.arg: i for i, a in enumerate(
        list(args.posonlyargs) + list(args.args))}


class RecompileHazard(Rule):
    id = "JL003"
    title = ("jit parameter drives a Python branch but is not in "
             "static_argnames/static_argnums")

    def check(self, graph) -> Iterator[Finding]:
        for fi in graph.functions.values():
            if not fi.jit_root:
                continue
            mi = graph.modules.get(fi.module)
            if mi is None or mi.tree is None:
                continue
            candidates = _bool_like_params(fi.node)
            if not candidates:
                continue
            positions = _positions(fi.node)
            declared = set(fi.static_argnames)
            declared |= {name for name, pos in positions.items()
                         if pos in fi.static_argnums}
            used = self._branch_params(fi.node)
            for name in sorted((candidates & used) - declared):
                yield self.finding(
                    mi, fi.node,
                    f"jit parameter `{name}` drives a Python branch but "
                    f"is not declared static (add it to static_argnames "
                    f"at the jit wrap site)",
                    symbol=fi.qualname,
                )

    @staticmethod
    def _branch_params(node) -> Set[str]:
        """Names read inside if/ternary/while tests or boolean ops."""
        used: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, (ast.If, ast.IfExp, ast.While)):
                tests = [n.test]
            elif isinstance(n, ast.BoolOp):
                tests = n.values
            else:
                continue
            for t in tests:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name) and isinstance(
                            sub.ctx, ast.Load):
                        used.add(sub.id)
        return used
