"""JL004: 64-bit dtype promotions inside the precision-policy layers.

The TPU port's policy (utils/precision.py) is float32/complex64 in the
compute layers — ``ops/``, ``solvers/``, ``parallel/``.  An
*unconditional* ``jnp.float64`` / ``jnp.complex128`` reference there
either silently downgrades (x64 disabled) or doubles HBM traffic and
kills MXU throughput (x64 enabled).

Precision: only unconditional ``jax.numpy`` 64-bit dtypes fire.  The
repo's deliberate x64-aware idiom —

    ctype = jnp.complex64 if u.dtype == jnp.float32 else jnp.complex128

— selects the dtype *conditionally* (inside an ``IfExp`` or an
``if``-statement) and stays silent, as do host-side ``np.float64``
precomputations.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sagecal_tpu.analysis.engine import Finding, Rule, path_segments
from sagecal_tpu.analysis.callgraph import qual_of

_POLICY_SEGMENTS = {"ops", "solvers", "parallel"}
_WIDE = {
    "jax.numpy.float64", "jax.numpy.complex128", "jax.numpy.int64",
    "jax.numpy.uint64",
}


def _under_conditional(node: ast.AST) -> bool:
    cur = getattr(node, "_jaxlint_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.IfExp, ast.If)):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Module)):
            return False
        cur = getattr(cur, "_jaxlint_parent", None)
    return False


class DtypePolicy(Rule):
    id = "JL004"
    title = ("unconditional 64-bit jnp dtype inside the "
             "float32/complex64 policy layers (ops/solvers/parallel)")

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            if not (_POLICY_SEGMENTS & path_segments(mi.path)):
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                q = qual_of(node, mi.imports, mi.toplevel, mi.name)
                if q not in _WIDE:
                    continue
                if _under_conditional(node):
                    continue
                fi = mi.enclosing_function(node)
                yield self.finding(
                    mi, node,
                    f"unconditional `{q.replace('jax.numpy', 'jnp')}` "
                    f"breaks the float32/complex64 policy (select the "
                    f"wide dtype conditionally on the input dtype, or "
                    f"keep it out of the compute layers)",
                    symbol=fi.qualname if fi else "",
                )
