"""JL005: data-dependent output shapes inside jit-reachable code.

``jnp.nonzero`` / ``jnp.unique`` / boolean-mask indexing produce shapes
that depend on array *values* — untraceable under jit without a static
``size=`` escape hatch.  The fix is ``jnp.where`` with fill values, a
fixed-size mask-and-weight formulation (how robust.py keeps the
whole-tile residual resident), or ``size=``/``fill_value=``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sagecal_tpu.analysis.engine import Finding, Rule
from sagecal_tpu.analysis.callgraph import qual_of

_DDS_FUNCS = {
    "jax.numpy.nonzero", "jax.numpy.flatnonzero", "jax.numpy.argwhere",
    "jax.numpy.unique", "jax.numpy.compress", "jax.numpy.extract",
}


class DataDependentShape(Rule):
    id = "JL005"
    title = "data-dependent output shape inside jit-reachable code"

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            for node in ast.walk(mi.tree):
                msg = self._classify(node, mi)
                if msg is None:
                    continue
                fi = graph.stmt_reachable(mi, node)
                if fi is None:
                    continue
                yield self.finding(mi, node, msg, symbol=fi.qualname)

    def _classify(self, node, mi):
        if isinstance(node, ast.Call):
            q = qual_of(node.func, mi.imports, mi.toplevel, mi.name)
            if q in _DDS_FUNCS:
                if any(kw.arg == "size" for kw in node.keywords):
                    return None  # static size= escape hatch
                short = q.replace("jax.numpy", "jnp")
                return (f"`{short}` has a data-dependent output shape "
                        f"under jit (pass size=/fill_value=, or use a "
                        f"fixed-size mask formulation)")
            if q == "jax.numpy.where" and len(node.args) == 1 \
                    and not node.keywords:
                return ("one-argument `jnp.where` has a data-dependent "
                        "output shape under jit (use the three-argument "
                        "form or pass size=)")
        elif isinstance(node, ast.Subscript):
            # x[mask] / x[y > 0]: boolean-mask indexing
            sl = node.slice
            if isinstance(sl, ast.Compare):
                return ("boolean-mask indexing has a data-dependent "
                        "output shape under jit (use jnp.where with a "
                        "fill value instead)")
        return None
