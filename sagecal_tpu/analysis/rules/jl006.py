"""JL006: collectives outside the parallel layer.

Per the "Unwrapping ADMM" layering, the consensus loop is
communication-only: ``jax.lax.psum`` and friends belong in
``parallel/`` (and the shard_map boundary in ``solvers/sharded.py``).
A collective anywhere else couples compute kernels to a mesh axis —
unrunnable single-device, untestable in isolation.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator

from sagecal_tpu.analysis.engine import Finding, Rule, path_segments
from sagecal_tpu.analysis.callgraph import qual_of

_COLLECTIVES = {
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.ppermute", "jax.lax.pshuffle", "jax.lax.all_gather",
    "jax.lax.all_to_all", "jax.lax.axis_index", "jax.lax.psum_scatter",
}
_ALLOWED_SEGMENT = "parallel"
_ALLOWED_BASENAMES = {"sharded.py"}


class StrayCollective(Rule):
    id = "JL006"
    title = ("jax.lax collective outside parallel/ and "
             "solvers/sharded.py")

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            if _ALLOWED_SEGMENT in path_segments(mi.path):
                continue
            if os.path.basename(mi.path) in _ALLOWED_BASENAMES:
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                q = qual_of(node.func, mi.imports, mi.toplevel, mi.name)
                if q not in _COLLECTIVES:
                    continue
                fi = mi.enclosing_function(node)
                short = q.rsplit(".", 1)[-1]
                yield self.finding(
                    mi, node,
                    f"collective `lax.{short}` outside the parallel "
                    f"layer (move it to parallel/ or "
                    f"solvers/sharded.py; compute kernels must stay "
                    f"mesh-free)",
                    symbol=fi.qualname if fi else "",
                )
