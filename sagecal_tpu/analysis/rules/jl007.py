"""JL007: undonated large carry on a jit entry point (report-only).

The solver entry points thread large carry buffers — the parameter
vector ``p0``, the LBFGS ``memory`` pair, solver ``state`` — through
jit boundaries.  When the caller never reuses the input after the
call (the universal pattern for ``fit``-style entries that return the
updated carry), ``donate_argnums``/``donate_argnames`` lets XLA alias
the output into the input buffer and halves the HBM high-water mark
at the solver boundary.

This rule pins the convention: any jit root whose signature contains
a carry-named parameter that is neither static nor donated is
reported.  Report-only by default, because donation is *only* safe
when every caller treats the argument as consumed — entries whose
callers reuse the args tuple (the lm/os-lm micro-benchmark harnesses,
``bench.py`` timing loops) must stay undonated and live in the
baseline instead.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sagecal_tpu.analysis.engine import Finding, Rule

# parameter names that (by repo convention) carry solver state whose
# input buffer is dead after the call
_CARRY_NAMES = frozenset({"p0", "memory", "state", "carry"})


def _positional_params(node) -> list:
    a = node.args
    return list(getattr(a, "posonlyargs", ())) + list(a.args)


class UndonatedCarry(Rule):
    id = "JL007"
    title = "jit entry threads a large carry without donate_argnums"
    report_only = True

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            for fi in mi.functions.values():
                if not fi.jit_root:
                    continue
                if not isinstance(fi.node, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                    continue
                params = _positional_params(fi.node)
                for idx, p in enumerate(params):
                    name = p.arg
                    if name not in _CARRY_NAMES:
                        continue
                    if name in fi.static_argnames \
                            or idx in fi.static_argnums:
                        continue
                    if name in fi.donate_argnames \
                            or idx in fi.donate_argnums:
                        continue
                    yield self.finding(
                        mi, fi.node,
                        f"jit entry `{fi.name}` threads carry `{name}` "
                        f"(arg {idx}) without donate_argnums/"
                        f"donate_argnames — donate it if callers never "
                        f"reuse the input buffer, or baseline it if "
                        f"they do",
                        symbol=fi.qualname,
                    )
