"""JL008: non-atomic write to fleet protocol state.

The fleet correctness argument (verified exhaustively by
``sagecal_tpu/analysis/protocol_check.py``) rests on every piece of
shared protocol state — result manifests, queue/lease files,
checkpoints, published solutions — appearing *whole* in one atomic
step: either the hard-link exclusive publish (``RealFS.publish_excl``)
or the tmp + fsync + ``os.replace`` idiom (``RealFS.write_atomic``).
A plain ``open(path, "w")`` on such a path creates a visible-empty /
half-written window that a peer can misread — the exact bug family the
checker's ``torn-publish`` and ``torn-manifest`` mutations re-introduce
and catch.

This rule flags write-mode ``open`` calls in the fleet-era layers
(``fleet/``, ``serve/``, ``elastic/``) whose target path looks like
protocol state.  The path is judged by its *source text* (the call
argument, plus the one assignment that defined it when it is a local
name), so ``open(out_path, "w")`` after ``out_path = ...".solutions"``
is caught.  Staged tmp files (the atomic idiom's first half) are
exempt.  A deliberate non-atomic write — e.g. the stream solutions
append-chain, which must append across resumed runs and is consumed
only post-hoc — belongs in the committed baseline with its reason.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from sagecal_tpu.analysis.engine import Finding, Rule, path_segments

_SCOPE_SEGMENTS = {"fleet", "serve", "elastic"}

# substrings that mark a path expression as fleet protocol state
_STATE_TOKENS = (
    "manifest", "lease", "queue", "checkpoint", "ckpt",
    "solutions", "result", "done", "requests.json",
)


def _open_mode(call: ast.Call) -> Optional[str]:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _name_definitions(scope: ast.AST, name: str) -> Iterator[ast.AST]:
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    yield n.value
        elif isinstance(n, ast.AugAssign) and isinstance(
                n.target, ast.Name) and n.target.id == name:
            yield n.value


class NonAtomicProtocolWrite(Rule):
    id = "JL008"
    title = ("non-atomic write to fleet protocol state "
             "(manifest/queue/lease/checkpoint/solutions)")

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            if not (_SCOPE_SEGMENTS & path_segments(mi.path)):
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not (isinstance(node.func, ast.Name)
                        and node.func.id == "open"
                        and "open" not in mi.imports):
                    continue
                mode = _open_mode(node)
                if mode is None or not ({"w", "a", "x"} & set(mode)):
                    continue
                if not node.args:
                    continue
                path_src = ast.unparse(node.args[0]).lower()
                if "tmp" in path_src:
                    continue  # staging half of the atomic idiom
                srcs = [path_src]
                fi = mi.enclosing_function(node)
                scope = fi.node if fi is not None else mi.tree
                if isinstance(node.args[0], ast.Name):
                    srcs += [ast.unparse(d).lower() for d in
                             _name_definitions(scope, node.args[0].id)]
                hit = next((tok for tok in _STATE_TOKENS
                            if any(tok in s for s in srcs)), None)
                if hit is None:
                    continue
                yield self.finding(
                    mi, node,
                    f"non-atomic open(..., {mode!r}) of protocol state "
                    f"(path mentions `{hit}`) — stage a tmp file and "
                    f"os.replace it (RealFS.write_atomic), or "
                    f"publish_excl for exclusive claims; torn "
                    f"intermediate states are what the protocol "
                    f"checker's torn-manifest mutation exploits",
                    symbol=fi.qualname if fi else "",
                )
