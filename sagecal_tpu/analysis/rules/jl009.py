"""JL009: unguarded ``pickle.load`` of a shared artifact.

Pickled artifacts shared across fleet workers (the AOT executable
store) outlive any single process, so a loader will eventually meet
bytes produced by a different jaxlib/python/artifact-format vintage.
Unpickling those blind either deserializes garbage into the compile
cache or throws deep inside jax — both far from the real cause.

The repo's mandatory pattern is ``serve/aot_store.py``: a plain-text
JSON header line carrying a magic tag and the full version fields,
validated *before* ``pickle.load`` touches the stream, with any
mismatch treated as a cache miss.  This rule flags
``pickle.load``/``pickle.loads`` calls whose enclosing function shows
no sign of that gate (no magic/version check anywhere in the
function).  The detection is textual over the function body — crude,
but the point is to force new unpickling sites through a reviewed
header check rather than to prove the check correct (the protocol
checker and the aot_store tests do that).
"""

from __future__ import annotations

import ast
from typing import Iterator

from sagecal_tpu.analysis.engine import Finding, Rule
from sagecal_tpu.analysis.callgraph import qual_of

_PICKLE_LOADS = {"pickle.load", "pickle.loads",
                 "cPickle.load", "cPickle.loads"}
_GATE_TOKENS = ("magic", "version")


class UnguardedPickleLoad(Rule):
    id = "JL009"
    title = "pickle.load without a version-header gate"

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                q = qual_of(node.func, mi.imports, mi.toplevel, mi.name)
                if q not in _PICKLE_LOADS:
                    continue
                fi = mi.enclosing_function(node)
                scope = fi.node if fi is not None else mi.tree
                src = ast.unparse(scope).lower()
                if any(tok in src for tok in _GATE_TOKENS):
                    continue
                yield self.finding(
                    mi, node,
                    f"`{q}` without a magic/version header gate — "
                    f"validate a plain-text header (see "
                    f"serve/aot_store.py, the mandatory pattern) "
                    f"before unpickling, and treat any mismatch as a "
                    f"cache miss",
                    symbol=fi.qualname if fi else "",
                )
