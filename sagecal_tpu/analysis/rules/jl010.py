"""JL010: raw wall clock inside lease/deadline logic.

Lease expiry, steal-after-TTL, EDF deadlines and SLO burn are all
*time-threshold* predicates.  The protocol model checker can only
drive the real implementations through adversarial schedules because
every such predicate reads an injectable clock (``clock=time.time`` as
a constructor default, ``now=None`` parameters defaulting to the real
clock at the call site).  A raw ``time.time()`` buried inside the
logic re-anchors it to the wall clock, making TTL-boundary behavior
untestable — exactly where the checker found the renew-past-TTL bug.

This rule flags ``time.time()`` calls in the fleet-era layers
(``fleet/``, ``serve/``, ``elastic/``) whose enclosing function deals
in leases/deadlines (its source mentions lease, expire, ttl or
deadline).  The accepted injectable-default idiom
``now = time.time() if now is None else float(now)`` is exempt: the
call only fires when the caller declined to inject.  Latency
measurement (``tic = time.time()`` in solve paths) is out of scope —
it feeds reporting, not protocol predicates.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sagecal_tpu.analysis.engine import Finding, Rule, path_segments
from sagecal_tpu.analysis.callgraph import qual_of

_SCOPE_SEGMENTS = {"fleet", "serve", "elastic"}
_LEASE_TOKENS = ("lease", "expire", "ttl", "deadline")


def _is_injectable_default(node: ast.AST) -> bool:
    """True for the ``X if <param> is None else ...`` default idiom."""
    parent = getattr(node, "_jaxlint_parent", None)
    if not isinstance(parent, ast.IfExp) or parent.body is not node:
        return False
    test = parent.test
    return (isinstance(test, ast.Compare)
            and len(test.ops) == 1 and isinstance(test.ops[0], ast.Is)
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None)


class RawClockInLeaseLogic(Rule):
    id = "JL010"
    title = "raw time.time() in lease/deadline logic"

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            if not (_SCOPE_SEGMENTS & path_segments(mi.path)):
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                q = qual_of(node.func, mi.imports, mi.toplevel, mi.name)
                if q != "time.time":
                    continue
                if _is_injectable_default(node):
                    continue
                fi = mi.enclosing_function(node)
                scope = fi.node if fi is not None else mi.tree
                src = ast.unparse(scope).lower()
                if not any(tok in src for tok in _LEASE_TOKENS):
                    continue
                yield self.finding(
                    mi, node,
                    "raw time.time() inside lease/deadline logic — "
                    "read an injectable clock (constructor "
                    "`clock=time.time`, or a `now=None` parameter "
                    "defaulting at the boundary) so the protocol "
                    "checker can drive TTL boundaries",
                    symbol=fi.qualname if fi else "",
                )
