"""JL011: donated buffer read after the jit call that consumed it.

``donate_argnums``/``donate_argnames`` (the JL007 convention) hands the
input buffer to XLA: after the call returns, the donated array is
deleted and *any* host-side use of the old reference raises a
``RuntimeError: Array has been deleted`` — at best.  Under AOT
executables and async dispatch the failure can surface later and far
from the cause, so the repo treats post-donation use as a static
error, not a runtime one.

The rule finds call sites of known donating jit roots, takes every
donated argument that is a plain local name, and flags loads of that
name after the call — up to the point the name is rebound (the
``p0 = fit(p0, ...)`` consuming idiom rebinds on the call line itself
and is clean).  Calls through aliases the call graph cannot resolve
are out of scope; the point is to catch the easy-to-write, hard-to-
debug case of logging or re-solving with a consumed buffer.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from sagecal_tpu.analysis.engine import Finding, Rule
from sagecal_tpu.analysis.callgraph import qual_of


def _positional_params(node) -> List[str]:
    a = node.args
    return [p.arg for p in
            list(getattr(a, "posonlyargs", ())) + list(a.args)]


def _bound_names(stmt: ast.AST) -> List[str]:
    """Names (re)bound by an assignment or for statement, unpacking
    tuple/list/starred targets."""
    targets = list(getattr(stmt, "targets", ()))
    single = getattr(stmt, "target", None)
    if single is not None:
        targets.append(single)
    out: List[str] = []
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.append(n.id)
    return out


def _param_names(fnode) -> List[str]:
    a = fnode.args
    names = [p.arg for p in
             list(getattr(a, "posonlyargs", ())) + list(a.args)
             + list(a.kwonlyargs)]
    for va in (a.vararg, a.kwarg):
        if va is not None:
            names.append(va.arg)
    return names


def _shadowing_spans(scope: ast.AST, name: str):
    """Line spans of nested lambdas/defs that bind ``name`` as their
    own parameter: inside them, ``name`` is a fresh binding, not the
    donated buffer from the enclosing scope."""
    spans = []
    for n in ast.walk(scope):
        if n is scope:
            continue
        if isinstance(n, (ast.Lambda, ast.FunctionDef,
                          ast.AsyncFunctionDef)) \
                and name in _param_names(n):
            spans.append((n.lineno, getattr(n, "end_lineno", n.lineno)))
    return spans


def _donated_arg_exprs(call: ast.Call, callee) -> List[ast.AST]:
    """Caller-side expressions bound to the callee's donated params."""
    out: List[ast.AST] = []
    params = _positional_params(callee.node) if isinstance(
        callee.node, (ast.FunctionDef, ast.AsyncFunctionDef)) else []
    donated_idx = set(callee.donate_argnums)
    donated_idx |= {params.index(n) for n in callee.donate_argnames
                    if n in params}
    for idx in donated_idx:
        if idx < len(call.args):
            out.append(call.args[idx])
    for kw in call.keywords:
        if kw.arg in callee.donate_argnames:
            out.append(kw.value)
    return out


class UseAfterDonation(Rule):
    id = "JL011"
    title = "donated buffer used after the jit call"

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                q = qual_of(node.func, mi.imports, mi.toplevel, mi.name)
                if q is None:
                    continue
                fi = mi.enclosing_function(node)
                scope_q = fi.qualname if fi is not None else ""
                callee = graph._lookup(q, mi.name, scope_q)
                if callee is None or not callee.jit_root:
                    continue
                if not (callee.donate_argnums or callee.donate_argnames):
                    continue
                scope = fi.node if fi is not None else mi.tree
                end = getattr(node, "end_lineno", node.lineno)
                for arg in _donated_arg_exprs(node, callee):
                    if not isinstance(arg, ast.Name):
                        continue
                    name = arg.id
                    spans = _shadowing_spans(scope, name)
                    if any(lo <= node.lineno <= hi for lo, hi in spans):
                        # the donated name is a nested lambda/def's own
                        # parameter (a tracer under jit), not a buffer
                        # held by this scope
                        continue
                    rebinds = [n.lineno for n in ast.walk(scope)
                               if isinstance(n, (ast.Assign,
                                                 ast.AugAssign,
                                                 ast.AnnAssign,
                                                 ast.For))
                               and n.lineno >= node.lineno
                               and name in _bound_names(n)]
                    cut = min(rebinds) if rebinds else float("inf")
                    for use in ast.walk(scope):
                        if (isinstance(use, ast.Name)
                                and isinstance(use.ctx, ast.Load)
                                and use.id == name
                                and end < use.lineno < cut
                                and not any(lo <= use.lineno <= hi
                                            for lo, hi in spans)):
                            yield self.finding(
                                mi, use,
                                f"`{name}` was donated to jit root "
                                f"`{callee.name}` (line {node.lineno}) "
                                f"— its buffer is deleted after the "
                                f"call; use the returned value, or "
                                f"drop the donation if callers must "
                                f"reuse the input",
                                symbol=fi.qualname if fi else "",
                            )
                            break  # one finding per donated name/call
