"""JL012: mixed-dtype numeric comparison / tolerance-less closeness.

The bf16 coherency path (``coh_dtype="bf16"``) deliberately stores the
dominant HBM stream at half precision while accumulating in f32, and
the shadow auditor (obs/shadow.py) quantifies the resulting numerical
drift against a central tolerance policy.  Two code patterns silently
undermine that discipline inside the numerics layers:

- **mixed-dtype comparisons** — a predicate whose two sides reference
  different float families (bf16 vs f32/f64).  The comparison is legal
  (JAX upcasts), but the result encodes an implicit tolerance of one
  half-precision ULP that nobody chose.  Convergence checks and branch
  guards built this way change behavior when a caller flips
  ``coh_dtype``;
- **tolerance-less closeness checks** — ``allclose``/``isclose`` with
  no ``rtol``/``atol`` leans on library defaults (``rtol=1e-5``) that
  were tuned for f64 and are *dtype-blind*: at bf16 resolution (~3
  decimal digits) the default rtol is below one ULP, so the check is
  effectively exact equality; at f64 it is far looser than the solver
  tolerances.  Every closeness check in the numerics layers should
  state the tolerance it means, ideally sourced from the same policy
  table the shadow auditor gates on (``shadow.DRIFT_TOLERANCES``).

Report-only: both patterns have legitimate instances (e.g. a guard
that *intends* "equal at storage precision").  Deliberate cases are
recorded in ``jaxlint_baseline.json`` with a ``why``, or carry a
``# jaxlint: disable=JL012 — reason`` pragma at the line.

Scope: ``ops/`` and ``solvers/`` — the layers where a silent implicit
tolerance corrupts science, not plumbing/reporting code.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Set

from sagecal_tpu.analysis.engine import Finding, Rule, path_segments
from sagecal_tpu.analysis.callgraph import qual_of

_SCOPE_SEGMENTS = {"ops", "solvers"}

# dtype tokens -> float family; underscores count as token boundaries
# so `coh_bf16` and `x_f32` carry dtype intent while `crc32` does not
_FAMILY_RE = re.compile(
    r"(?<![A-Za-z0-9])(bfloat16|bf16|float32|float64|f32|f64)"
    r"(?![A-Za-z0-9])")
_FAMILY = {"bfloat16": "bf16", "bf16": "bf16",
           "float32": "f32", "f32": "f32",
           "float64": "f64", "f64": "f64"}

_CLOSE_NAMES = ("allclose", "isclose")
_TOL_KWARGS = {"rtol", "atol", "rel_tol", "abs_tol", "tol", "tolerance"}


def _families(node: ast.AST) -> Set[str]:
    """Float families referenced anywhere in an expression subtree."""
    try:
        text = ast.unparse(node).lower()
    except Exception:  # pragma: no cover - malformed subtree
        return set()
    return {_FAMILY[m] for m in _FAMILY_RE.findall(text)}


class MixedDtypeComparison(Rule):
    id = "JL012"
    title = "mixed-dtype comparison / tolerance-less closeness check"
    report_only = True

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            if not (_SCOPE_SEGMENTS & path_segments(mi.path)):
                continue
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.Call):
                    f = self._check_closeness(mi, node)
                    if f is not None:
                        yield f
                elif isinstance(node, ast.Compare):
                    f = self._check_mixed(mi, node)
                    if f is not None:
                        yield f

    def _check_closeness(self, mi, node: ast.Call):
        q = qual_of(node.func, mi.imports, mi.toplevel, mi.name) or ""
        leaf = q.rsplit(".", 1)[-1]
        if leaf not in _CLOSE_NAMES:
            return None
        if any(kw.arg in _TOL_KWARGS for kw in node.keywords
               if kw.arg is not None):
            return None
        if len(node.args) >= 3:  # positional rtol
            return None
        fi = mi.enclosing_function(node)
        return self.finding(
            mi, node,
            f"`{leaf}` without explicit rtol/atol in the numerics "
            "layers — library defaults are dtype-blind (below one ULP "
            "at bf16, looser than solver tolerances at f64); state "
            "the tolerance this check means",
            symbol=fi.qualname if fi else "",
        )

    def _check_mixed(self, mi, node: ast.Compare):
        left_fams = _families(node.left)
        if not left_fams:
            return None
        for comparator in node.comparators:
            right_fams = _families(comparator)
            if not right_fams or right_fams == left_fams:
                continue
            # string-literal dtype dispatch (`cfg.coh_dtype == "bf16"`)
            # is configuration, not numerics: exempt compares whose
            # every comparator is a bare string constant
            if all(isinstance(c, ast.Constant) and isinstance(c.value, str)
                   for c in node.comparators):
                return None
            fi = mi.enclosing_function(node)
            return self.finding(
                mi, node,
                "comparison mixes float families "
                f"({'/'.join(sorted(left_fams))} vs "
                f"{'/'.join(sorted(right_fams))}) — the upcast encodes "
                "an implicit half-precision tolerance nobody chose; "
                "cast both sides or compare at a stated tolerance",
                symbol=fi.qualname if fi else "",
            )
        return None
