"""JL013: custom_vjp cotangent completeness.

A ``jax.custom_vjp`` backward that silently returns ``None`` for a
differentiable primal argument manufactures a zero gradient: JAX treats
the slot as a symbolic zero, autodiff "succeeds", and the optimizer
quietly never moves that parameter.  This is exactly the failure class
the fused coherency path guards against at *runtime* with the
``FUSED_COHERENCY_COTANGENT`` capability refusal — this rule makes the
contract a commit-time proof instead of a hardware-day surprise.

A ``None`` cotangent slot is accepted only through one of three
explicit routes:

1. **refusal** — the backward unconditionally raises (no ``return``
   path), so the missing cotangent can never silently flow
   (``sky_constant``'s ``FusedSkyGradientError`` pattern);
2. **stop-gradient guard** — EVERY in-module call site of the
   custom_vjp primal passes that argument through
   ``jax.lax.stop_gradient`` (directly, or via a local that is
   assigned from ``stop_gradient``/a ``dynamic_slice`` of such a
   local), so no cotangent for the slot is ever requested.  At least
   one call site must exist — an uncalled primal with a ``None`` slot
   is still a trap for the first caller;
3. **capability declaration** — the module declares, at top level,
   ``<FLAG> = False`` together with ``<FLAG>_ARGS = ("argname", ...)``
   naming the argument.  This is the machine-checkable form of the
   existing ``FUSED_COHERENCY_COTANGENT`` contract: the flag documents
   the missing cotangent, callers can introspect it, and flipping the
   flag to ``True`` without implementing the cotangent becomes a lint
   violation ("capability promises a cotangent").

The rule also checks backward-return arity against the primal's
differentiable argument count (positional parameters minus
``nondiff_argnums``) — an off-by-one there mis-aligns every cotangent
after the gap.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from sagecal_tpu.analysis.engine import Finding, Rule
from sagecal_tpu.analysis.callgraph import ModuleInfo, qual_of


def _qual(node: ast.AST, mi: ModuleInfo) -> str:
    if not isinstance(node, (ast.Name, ast.Attribute)):
        return ""
    return qual_of(node, mi.imports, mi.toplevel, mi.name) or ""


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Literal tuple/list of ints (or a single int), else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


class _Primal:
    """One custom_vjp-wrapped primal discovered in a module."""

    def __init__(self, name: str, fdef: ast.FunctionDef,
                 nondiff: Tuple[int, ...]):
        self.name = name
        self.fdef = fdef
        self.nondiff = set(nondiff)
        params = [a.arg for a in fdef.args.args]
        self.diff_params: List[str] = [
            p for i, p in enumerate(params) if i not in self.nondiff]
        # primal positional index of each differentiable param
        self.diff_pos: List[int] = [
            i for i in range(len(params)) if i not in self.nondiff]


def _nondiff_from_decorator(dec: ast.expr, mi: ModuleInfo,
                            ) -> Optional[Tuple[int, ...]]:
    """() for bare ``@jax.custom_vjp``; the literal tuple for
    ``@functools.partial(jax.custom_vjp, nondiff_argnums=...)``;
    None when the decorator is not a custom_vjp form."""
    if _qual(dec, mi).endswith("jax.custom_vjp"):
        return ()
    if isinstance(dec, ast.Call):
        q = _qual(dec.func, mi)
        if q.endswith("jax.custom_vjp"):
            for kw in dec.keywords:
                if kw.arg == "nondiff_argnums":
                    return _int_tuple(kw.value) or ()
            return ()
        if q.endswith(".partial") and dec.args:
            if _qual(dec.args[0], mi).endswith("jax.custom_vjp"):
                for kw in dec.keywords:
                    if kw.arg == "nondiff_argnums":
                        return _int_tuple(kw.value) or ()
                return ()
    return None


def _collect_fdefs(tree: ast.Module) -> Dict[str, List[ast.FunctionDef]]:
    out: Dict[str, List[ast.FunctionDef]] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef):
            out.setdefault(n.name, []).append(n)
    return out


def _collect_primals(mi: ModuleInfo,
                     fdefs: Dict[str, List[ast.FunctionDef]],
                     ) -> Dict[str, _Primal]:
    primals: Dict[str, _Primal] = {}
    for cands in fdefs.values():
        for fdef in cands:
            for dec in fdef.decorator_list:
                nd = _nondiff_from_decorator(dec, mi)
                if nd is not None:
                    primals[fdef.name] = _Primal(fdef.name, fdef, nd)
    # assignment form: X = jax.custom_vjp(f, nondiff_argnums=...)
    for n in ast.walk(mi.tree):
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Call)):
            continue
        if not _qual(n.value.func, mi).endswith("jax.custom_vjp"):
            continue
        if not (n.value.args and isinstance(n.value.args[0], ast.Name)):
            continue
        inner = fdefs.get(n.value.args[0].id)
        if not inner:
            continue
        nd: Tuple[int, ...] = ()
        for kw in n.value.keywords:
            if kw.arg == "nondiff_argnums":
                nd = _int_tuple(kw.value) or ()
        primals[n.targets[0].id] = _Primal(
            n.targets[0].id, inner[0], nd)
    return primals


def _capabilities(mi: ModuleInfo) -> Dict[str, List[Tuple[str, bool]]]:
    """argname -> [(FLAG, value)] from paired module-level
    ``FLAG = bool`` / ``FLAG_ARGS = ("argname", ...)`` declarations."""
    flags: Dict[str, bool] = {}
    flag_args: Dict[str, Tuple[str, ...]] = {}
    for n in mi.tree.body:
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)):
            continue
        name = n.targets[0].id
        if (isinstance(n.value, ast.Constant)
                and isinstance(n.value.value, bool)):
            flags[name] = n.value.value
        elif name.endswith("_ARGS"):
            vals = _int_like_str_tuple(n.value)
            if vals is not None:
                flag_args[name[:-len("_ARGS")]] = vals
    caps: Dict[str, List[Tuple[str, bool]]] = {}
    for flag, args in flag_args.items():
        if flag not in flags:
            continue
        for a in args:
            caps.setdefault(a, []).append((flag, flags[flag]))
    return caps


def _int_like_str_tuple(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    out = []
    for e in node.elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.append(e.value)
    return tuple(out)


def _body_walk_no_nested(fdef: ast.FunctionDef) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs."""
    stack: List[ast.AST] = list(fdef.body)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _always_raises(fdef: ast.FunctionDef) -> bool:
    has_raise = False
    for n in _body_walk_no_nested(fdef):
        if isinstance(n, ast.Return):
            return False
        if isinstance(n, ast.Raise):
            has_raise = True
    return has_raise


def _return_elts(fdef: ast.FunctionDef) -> Optional[List[ast.expr]]:
    for n in _body_walk_no_nested(fdef):
        if isinstance(n, ast.Return) and n.value is not None:
            if isinstance(n.value, ast.Tuple):
                return list(n.value.elts)
            return [n.value]
    return None


def _is_stop_gradient(expr: ast.AST, mi: ModuleInfo) -> bool:
    return (isinstance(expr, ast.Call)
            and _qual(expr.func, mi).endswith("stop_gradient"))


def _guarded_locals(fn: ast.FunctionDef, mi: ModuleInfo) -> Set[str]:
    """Fixpoint of locals holding stop-gradient-guarded values:
    assigned from ``stop_gradient(...)`` or from a ``dynamic_slice``
    of an already-guarded local (the chunked wrappers' slicing idiom)."""
    guarded: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)):
                continue
            tgt = n.targets[0].id
            if tgt in guarded:
                continue
            v = n.value
            if _is_stop_gradient(v, mi):
                guarded.add(tgt)
                changed = True
            elif (isinstance(v, ast.Call)
                  and "dynamic_slice" in _qual(v.func, mi)
                  and v.args and isinstance(v.args[0], ast.Name)
                  and v.args[0].id in guarded):
                guarded.add(tgt)
                changed = True
    return guarded


class CotangentCompleteness(Rule):
    id = "JL013"
    title = "custom_vjp backward drops a primal cotangent"
    report_only = False

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            yield from self._check_module(mi)

    def _check_module(self, mi: ModuleInfo) -> Iterator[Finding]:
        fdefs = _collect_fdefs(mi.tree)
        primals = _collect_primals(mi, fdefs)
        if not primals:
            return
        caps = _capabilities(mi)
        # enclosing TOP-LEVEL function of every call node, for the
        # stop-gradient guard scan
        guard_cache: Dict[int, Set[str]] = {}

        def guards_for(fn: ast.FunctionDef) -> Set[str]:
            if id(fn) not in guard_cache:
                guard_cache[id(fn)] = _guarded_locals(fn, mi)
            return guard_cache[id(fn)]

        top_fns = [n for n in mi.tree.body
                   if isinstance(n, ast.FunctionDef)]

        for n in ast.walk(mi.tree):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "defvjp"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id in primals):
                continue
            primal = primals[n.func.value.id]
            if len(n.args) < 2 or not isinstance(n.args[1], ast.Name):
                continue
            bwd_cands = fdefs.get(n.args[1].id)
            if not bwd_cands:
                continue
            bwd = bwd_cands[0]
            yield from self._check_bwd(mi, primal, bwd, caps,
                                       top_fns, guards_for)

    def _check_bwd(self, mi, primal, bwd, caps, top_fns, guards_for,
                   ) -> Iterator[Finding]:
        if _always_raises(bwd):
            return  # refusal route: no cotangent can silently flow
        elts = _return_elts(bwd)
        if elts is None:
            return
        if len(elts) != len(primal.diff_params):
            yield self.finding(
                mi, bwd,
                "backward `%s` returns %d cotangents for %d "
                "differentiable primal args of `%s`" % (
                    bwd.name, len(elts), len(primal.diff_params),
                    primal.name),
                symbol=primal.name)
            return
        for param, pos, elt in zip(primal.diff_params, primal.diff_pos,
                                   elts):
            if not (isinstance(elt, ast.Constant) and elt.value is None):
                continue
            route = self._none_slot_route(
                mi, primal, param, pos, caps, top_fns, guards_for)
            if route == "ok":
                continue
            if route == "promised":
                yield self.finding(
                    mi, bwd,
                    "capability flag promises a `%s` cotangent but "
                    "backward `%s` of `%s` returns None for it" % (
                        param, bwd.name, primal.name),
                    symbol=primal.name)
            else:
                yield self.finding(
                    mi, bwd,
                    "backward `%s` returns None for differentiable "
                    "primal arg `%s` of `%s` — produce a cotangent, "
                    "stop_gradient-guard every call site, or declare "
                    "a capability flag (<FLAG> = False plus "
                    "<FLAG>_ARGS naming the arg)" % (
                        bwd.name, param, primal.name),
                    symbol=primal.name)

    def _none_slot_route(self, mi, primal, param, pos, caps, top_fns,
                         guards_for) -> str:
        for _flag, value in caps.get(param, []):
            if value is False:
                return "ok"
        if any(value is True for _f, value in caps.get(param, [])):
            return "promised"
        # stop-gradient route: every in-module call site guards the arg
        sites: List[Tuple[ast.Call, ast.FunctionDef]] = []
        for fn in top_fns:
            if fn is primal.fdef:
                continue
            for c in ast.walk(fn):
                if (isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Name)
                        and c.func.id == primal.name):
                    sites.append((c, fn))
        if not sites:
            return "violation"
        for call, fn in sites:
            arg = self._arg_at(call, pos, param)
            if arg is None:
                return "violation"
            if _is_stop_gradient(arg, mi):
                continue
            if (isinstance(arg, ast.Name)
                    and arg.id in guards_for(fn)):
                continue
            return "violation"
        return "ok"

    @staticmethod
    def _arg_at(call: ast.Call, pos: int, param: str,
                ) -> Optional[ast.expr]:
        if pos < len(call.args):
            a = call.args[pos]
            return None if isinstance(a, ast.Starred) else a
        for kw in call.keywords:
            if kw.arg == param:
                return kw.value
        return None
