"""JL014: precision flow in Pallas kernel bodies.

The bf16 coherency knob (``coh_dtype="bf16"``) halves the dominant HBM
stream but the numerics contract says *arithmetic stays f32*: every
bf16-stored operand must be upcast at the point of load, and every
matmul in a kernel body must pin its accumulator dtype.  Two silent
ways to break that contract:

- **missing upcast** — a kernel reads a bf16-ingested operand ref
  (``ref[i, :]``) and feeds it straight into arithmetic.  The MXU will
  happily accumulate at reduced precision and nothing fails — the
  solver just converges somewhere slightly wrong.  The repo idiom is
  ``_load_coh_planes``'s ``ref[...].astype(jnp.float32)`` at every
  load site;
- **unpinned matmul** — ``jnp.dot``/``jnp.matmul``/``lax.dot_general``
  without ``preferred_element_type``.  On TPU the default accumulator
  follows the operand dtype, so a bf16 operand silently flips the MXU
  into bf16 accumulation.  The repo idiom is ``_sel_dot``'s explicit
  ``preferred_element_type=jnp.float32``.

Taint is traced package-wide: any name assigned from
``.astype(jnp.bfloat16)`` anywhere in the package (the solver-side
ingestion point, e.g. ``coh_ri`` in ``solvers/sage.py``) marks the
kernel positional parameter it is passed to via ``pallas_call``, and
propagates through module-local helper calls by position.  The matmul
check covers every function reachable from a kernel body.

Scope: modules that contain a ``pallas_call`` (currently
``ops/rime_kernel.py``).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set, Tuple

from sagecal_tpu.analysis.engine import Finding, Rule
from sagecal_tpu.analysis.callgraph import ModuleInfo, qual_of
from sagecal_tpu.analysis.pallas import (
    find_pallas_sites, kernel_names, kernel_reachable,
    module_functions, positional_params)

_DOT_LEAVES = ("dot", "matmul", "dot_general")


def _qual(node: ast.AST, mi: ModuleInfo) -> str:
    if not isinstance(node, (ast.Name, ast.Attribute)):
        return ""
    return qual_of(node, mi.imports, mi.toplevel, mi.name) or ""


def _is_bf16_astype(expr: ast.AST, mi: ModuleInfo) -> bool:
    """Any ``X.astype(<bfloat16>)`` call within the expression."""
    for n in ast.walk(expr):
        if (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "astype" and n.args
                and _qual(n.args[0], mi).endswith("bfloat16")):
            return True
    return False


def bf16_tainted_names(graph) -> Set[str]:
    """Names assigned from ``.astype(jnp.bfloat16)`` anywhere in the
    analyzed set — the bf16 ingestion points."""
    out: Set[str] = set()
    for mi in graph.modules.values():
        if mi.tree is None:
            continue
        for n in ast.walk(mi.tree):
            if not isinstance(n, ast.Assign):
                continue
            if not _is_bf16_astype(n.value, mi):
                continue
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class PrecisionFlow(Rule):
    id = "JL014"
    title = "bf16 operand read without upcast / unpinned matmul accumulator"
    report_only = False

    def check(self, graph) -> Iterator[Finding]:
        tainted = bf16_tainted_names(graph)
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            sites = find_pallas_sites(mi)
            if not sites:
                continue
            yield from self._check_module(mi, sites, tainted)

    def _check_module(self, mi: ModuleInfo, sites, tainted: Set[str],
                      ) -> Iterator[Finding]:
        fns = module_functions(mi)
        # seed (kernel, param) taint from pallas operand bindings
        work: List[Tuple[str, str]] = []
        seen: Set[Tuple[str, str]] = set()
        for site in sites:
            for b in site.bindings:
                fn = fns.get(b.kernel_name)
                if fn is None:
                    continue
                params = positional_params(fn)
                for i, expr in enumerate(b.operand_exprs):
                    if i >= len(params):
                        break
                    if (isinstance(expr, ast.Name)
                            and expr.id in tainted):
                        key = (b.kernel_name, params[i])
                        if key not in seen:
                            seen.add(key)
                            work.append(key)
        # propagate through module-local helper calls by position
        idx = 0
        while idx < len(work):
            fname, pname = work[idx]
            idx += 1
            fn = fns.get(fname)
            if fn is None:
                continue
            for n in ast.walk(fn):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Name)
                        and n.func.id in fns):
                    continue
                callee = fns[n.func.id]
                cparams = positional_params(callee)
                for j, a in enumerate(n.args):
                    if (isinstance(a, ast.Name) and a.id == pname
                            and j < len(cparams)):
                        key = (n.func.id, cparams[j])
                        if key not in seen:
                            seen.add(key)
                            work.append(key)
        # (a) every Load subscript of a tainted ref must be upcast
        for fname, pname in seen:
            fn = fns.get(fname)
            if fn is None:
                continue
            yield from self._check_upcasts(mi, fn, pname)
        # (b) every matmul reachable from a kernel body pins its
        # accumulator
        reach = kernel_reachable(mi, kernel_names(sites))
        for fname in sorted(reach):
            yield from self._check_dots(mi, fns[fname])

    def _check_upcasts(self, mi: ModuleInfo, fn: ast.FunctionDef,
                       pname: str) -> Iterator[Finding]:
        wrapped: Set[int] = set()
        for n in ast.walk(fn):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "astype" and n.args
                    and _qual(n.args[0], mi).endswith("float32")):
                wrapped.add(id(n.func.value))
        for n in ast.walk(fn):
            if (isinstance(n, ast.Subscript)
                    and isinstance(n.ctx, ast.Load)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == pname
                    and id(n) not in wrapped):
                yield self.finding(
                    mi, n,
                    "bf16-ingested operand `%s` read in `%s` without "
                    "`.astype(jnp.float32)` — the bf16 knob halves "
                    "HBM traffic, not arithmetic precision; upcast "
                    "at the load" % (pname, fn.name),
                    symbol=fn.name)

    def _check_dots(self, mi: ModuleInfo, fn: ast.FunctionDef,
                    ) -> Iterator[Finding]:
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            q = _qual(n.func, mi)
            leaf = q.rsplit(".", 1)[-1] if q else ""
            if leaf not in _DOT_LEAVES or not (
                    q.startswith("jax.") or q.startswith("jnp.")):
                continue
            if any(kw.arg == "preferred_element_type"
                   for kw in n.keywords):
                continue
            yield self.finding(
                mi, n,
                "`%s` in kernel scope `%s` without "
                "preferred_element_type — a bf16 operand silently "
                "flips MXU accumulation to bf16; pin f32" % (
                    leaf, fn.name),
                symbol=fn.name)
