"""JL015: BlockSpec/grid hazards.

Pallas BlockSpec mistakes fail late and badly: an ``index_map`` whose
return rank disagrees with the block-shape rank is a Mosaic lowering
error on hardware (invisible on CPU interpret mode), and an operand
without an explicit ``memory_space`` gets backend-dependent default
placement — the repo's VMEM budget model (analysis/kernelmodel.py)
can only account for operands whose placement is declared.  Both are
statically decidable from the call expression, so this rule proves
them at commit time:

- **rank mismatch** — ``pl.BlockSpec((1, T), lambda r: (0, 0, r))``:
  a literal block-shape tuple whose length differs from the number of
  indices the ``index_map`` lambda returns;
- **missing memory_space** — a ``BlockSpec`` without an explicit
  ``memory_space=`` keyword.  The repo idiom pins every operand
  (``pltpu.TPUMemorySpace.ANY``/VMEM/SMEM) so the footprint model and
  the code agree on residency.

The *numeric* grid hazards (a grid axis that does not cover the padded
row extent, block x steps != extent) need shape arithmetic, which the
symbolic interpreter in ``analysis/kernelmodel.py`` performs — those
are reported by ``diag kernelcheck`` as ``grid-coverage`` violations
rather than by this AST-local rule.

Scope: modules importing ``jax.experimental.pallas`` (or ``.tpu``).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from sagecal_tpu.analysis.engine import Finding, Rule
from sagecal_tpu.analysis.callgraph import ModuleInfo, qual_of
from sagecal_tpu.analysis.pallas import is_pallas_module


def _qual(node: ast.AST, mi: ModuleInfo) -> str:
    if not isinstance(node, (ast.Name, ast.Attribute)):
        return ""
    return qual_of(node, mi.imports, mi.toplevel, mi.name) or ""


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class BlockSpecHazard(Rule):
    id = "JL015"
    title = "BlockSpec rank mismatch / unspecified memory space"
    report_only = False

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None or not is_pallas_module(mi):
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not _qual(node.func, mi).endswith(".BlockSpec"):
                    continue
                yield from self._check_spec(mi, node)

    def _check_spec(self, mi: ModuleInfo, node: ast.Call,
                    ) -> Iterator[Finding]:
        fi = mi.enclosing_function(node)
        sym = fi.qualname if fi else ""
        block = node.args[0] if node.args else _kwarg(node, "block_shape")
        index_map = (node.args[1] if len(node.args) > 1
                     else _kwarg(node, "index_map"))
        if (isinstance(block, ast.Tuple)
                and isinstance(index_map, ast.Lambda)):
            brank = len(block.elts)
            body = index_map.body
            irank = len(body.elts) if isinstance(body, ast.Tuple) else 1
            if brank != irank:
                yield self.finding(
                    mi, node,
                    "index_map returns %d indices for a rank-%d "
                    "block shape — Mosaic rejects this at lowering, "
                    "on hardware only" % (irank, brank),
                    symbol=sym)
        if _kwarg(node, "memory_space") is None:
            yield self.finding(
                mi, node,
                "BlockSpec without explicit memory_space — default "
                "placement is backend-dependent and invisible to the "
                "VMEM budget model; declare VMEM/SMEM/ANY",
                symbol=sym)
