"""JL016: JSONL appended via buffered file.write instead of the
registered O_APPEND single-write emitter.

Every JSONL record family the fleet emits is registered in the schema
ledger (``sagecal_tpu/obs/ledger.py``) with a writer identity, and the
audit trail's torn-record guarantee (``diag audit`` treats a torn line
as a *violation*, not noise) rests on each line reaching the file in
exactly one ``os.write`` on an ``O_APPEND`` descriptor — POSIX makes
that single write atomic with respect to concurrent appenders, so a
crash or a second writer can never interleave half-lines.

A buffered ``fh.write(json.dumps(rec) + "\\n")`` on an ordinary file
object silently breaks that argument twice: the userspace buffer may
flush mid-line (torn records under crash), and two processes appending
through separate buffered handles can interleave chunks (torn records
under concurrency).  Such lines would surface as ``torn`` in the audit
and — worse — implicate the emitters that *are* correct.

This rule flags single-argument ``<obj>.write(expr)`` calls in the
telemetry-bearing layers (``fleet/``, ``serve/``, ``obs/``) whose
argument both serializes JSON (a ``dumps`` call in the subtree) and
carries a newline constant — the JSONL-append signature.  Exempt:

- the registered emitter idiom itself (``os.write(fd, line)`` — two
  positional arguments, receiver ``os``);
- tmp-staged whole-document writes, where the enclosing function
  publishes via ``os.replace``/``os.link`` (atomic-rename idiom — the
  write target is never the live file);
- paths whose source text mentions ``tmp`` (the staging half).

Fix by routing through the family's registered emitter (EventLog /
Tracer / TimelineSampler / ShadowAuditor) or by opening with
``os.open(path, O_APPEND | ...)`` and emitting the line in one
``os.write``.  A deliberate buffered append (single-process, post-hoc
consumer) belongs in the baseline with a ``why`` or a
``# jaxlint: disable=JL016 — reason`` pragma.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sagecal_tpu.analysis.engine import Finding, Rule, path_segments

_SCOPE_SEGMENTS = {"fleet", "serve", "obs"}

_PUBLISH_ATTRS = {"replace", "link", "rename"}


def _has_dumps_call(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr == "dumps":
                return True
            if isinstance(f, ast.Name) and f.id == "dumps":
                return True
    return False


def _has_newline_const(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Constant):
            v = n.value
            if isinstance(v, str) and "\n" in v:
                return True
            if isinstance(v, bytes) and b"\n" in v:
                return True
    return False


def _publishes_atomically(scope: ast.AST) -> bool:
    """True when the scope links/renames a staged file into place —
    the buffered write then targets a tmp file, not the live record."""
    for n in ast.walk(scope):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _PUBLISH_ATTRS \
                and isinstance(n.func.value, ast.Name) \
                and n.func.value.id == "os":
            return True
    return False


class BufferedJsonlAppend(Rule):
    id = "JL016"
    title = ("JSONL appended via buffered file.write instead of the "
             "registered O_APPEND single-write emitter")

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            if not (_SCOPE_SEGMENTS & path_segments(mi.path)):
                continue
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not (isinstance(f, ast.Attribute)
                        and f.attr == "write"):
                    continue
                # os.write(fd, line) IS the registered emitter idiom
                if isinstance(f.value, ast.Name) and f.value.id == "os":
                    continue
                if len(node.args) != 1 or node.keywords:
                    continue
                arg = node.args[0]
                if not (_has_dumps_call(arg) and _has_newline_const(arg)):
                    continue
                recv_src = ast.unparse(f.value).lower()
                if "tmp" in recv_src:
                    continue  # staging half of the atomic idiom
                fi = mi.enclosing_function(node)
                scope = fi.node if fi is not None else mi.tree
                if fi is not None and _publishes_atomically(scope):
                    continue
                yield self.finding(
                    mi, node,
                    "JSONL line appended through a buffered file "
                    "handle — userspace buffering can flush mid-line "
                    "and concurrent appenders interleave, producing "
                    "torn records the fleet audit treats as "
                    "violations; emit via the family's registered "
                    "writer or one os.write on an O_APPEND fd",
                    symbol=fi.qualname if fi else "",
                )
