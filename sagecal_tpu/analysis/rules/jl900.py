"""JL900 (report-only): unused imports.

An auxiliary hygiene sweep, never gated: imports bound in a module but
never referenced.  ``# noqa`` on the import line (the repo's existing
convention for ``__init__`` re-exports), membership in ``__all__``, and
``__future__``/side-effect-only imports are all honored.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from sagecal_tpu.analysis.engine import Finding, Rule


def _exported_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    if isinstance(node.value, (ast.List, ast.Tuple)):
                        out |= {e.value for e in node.value.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)}
    return out


class DeadImport(Rule):
    id = "JL900"
    title = "unused import"
    report_only = True

    def check(self, graph) -> Iterator[Finding]:
        for mi in graph.modules.values():
            if mi.tree is None:
                continue
            exported = _exported_names(mi.tree)
            used: Set[str] = set()
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.Name) and not isinstance(
                        node.ctx, ast.Store):
                    used.add(node.id)
                elif isinstance(node, ast.Attribute):
                    # head of a dotted chain counts as a use of the
                    # binding; string annotations stay conservative
                    head = node
                    while isinstance(head, ast.Attribute):
                        head = head.value
                    if isinstance(head, ast.Name):
                        used.add(head.id)
                elif isinstance(node, ast.Constant) and isinstance(
                        node.value, str):
                    # forward-ref annotations / doctests: any word match
                    # keeps the import (conservative by design)
                    used |= set(_words(node.value))
            for node in ast.walk(mi.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        bound = a.asname or a.name.split(".")[0]
                        yield from self._flag(mi, node, a, bound,
                                              used, exported)
                elif isinstance(node, ast.ImportFrom):
                    if node.module == "__future__":
                        continue
                    for a in node.names:
                        if a.name == "*":
                            continue
                        bound = a.asname or a.name
                        yield from self._flag(mi, node, a, bound,
                                              used, exported)

    def _flag(self, mi, node, alias, bound, used, exported):
        if bound in used or bound in exported or bound.startswith("_"):
            return
        # multi-line from-import lists carry noqa per alias line
        spot = alias if getattr(alias, "lineno", None) else node
        for lineno in {node.lineno, spot.lineno}:
            if lineno <= len(mi.lines) and "noqa" in mi.lines[lineno - 1]:
                return
        yield self.finding(mi, spot, f"unused import `{bound}`",
                           symbol=bound)


_WORD_CACHE = {}


def _words(s: str) -> Set[str]:
    if len(s) > 4096:
        s = s[:4096]
    if s not in _WORD_CACHE:
        import re

        if len(_WORD_CACHE) >= 2048:
            _WORD_CACHE.clear()
        _WORD_CACHE[s] = set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", s))
    return _WORD_CACHE[s]
