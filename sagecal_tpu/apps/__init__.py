"""Application layer: calibration mode drivers + CLI (the role of
``/root/reference/src/MS``)."""
