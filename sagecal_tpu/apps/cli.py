"""``sagecal-tpu`` command line: the reference ``sagecal`` flag surface
(``/root/reference/src/MS/main.cpp:43-264``) on the TPU framework.

Mode dispatch mirrors main.cpp:295-307: ``-N``>0 with ``-A``>0 and
``-w``>1 -> minibatch-consensus; ``-N``>0 -> minibatch; else fullbatch.
The input is a vis.h5 dataset (convert an MS with
``python -m sagecal_tpu.apps.cli convert <ms> <h5>`` where casacore is
available).  ``sagecal-tpu diag ...`` exposes the observability tooling
(run manifests, JSONL event-log summaries, Prometheus export, the
``perf`` attribution table, and the ``gate`` bench-regression check).
"""

from __future__ import annotations

import argparse
import sys

from sagecal_tpu.apps.config import RunConfig


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu",
        description="Direction-dependent radio interferometric calibration "
        "on TPU (SAGECal capability set).",
    )
    ap.add_argument("-d", "--dataset", required=False, default="",
                    help="input vis.h5 dataset (ref: -d MS)")
    ap.add_argument("-s", "--sky", default="", help="sky model file (LSM)")
    ap.add_argument("-c", "--clusters", default="",
                    help="cluster file (defaults to <sky>.cluster)")
    ap.add_argument("-p", "--solutions", default="solutions.txt",
                    help="output solutions file")
    ap.add_argument("-q", "--init-solutions", default=None,
                    help="initial solutions (warm start)")
    ap.add_argument("-I", "--in-column", default="vis",
                    help="input dataset column: vis/corrected/model/... "
                    "(ref -I DATA/CORRECTED_DATA)")
    ap.add_argument("--out-column", default="corrected",
                    help="output dataset column for residuals "
                    "(ref -O OutField; -O is taken by spatial cadence)")
    ap.add_argument("-F", "--sky-format", type=int, default=-1,
                    choices=(-1, 0, 1),
                    help="sky model format: 0 LSM, 1 three-term spectra, "
                    "-1 auto-detect (ref -F)")
    ap.add_argument("-t", "--tilesz", type=int, default=120)
    ap.add_argument("-e", "--max-emiter", type=int, default=3)
    ap.add_argument("-g", "--max-iter", type=int, default=2)
    ap.add_argument("-l", "--max-lbfgs", type=int, default=10)
    ap.add_argument("-m", "--lbfgs-m", type=int, default=7)
    ap.add_argument("-j", "--solver-mode", type=int, default=3,
                    help="0..6 per Dirac.h SM_* modes")
    ap.add_argument("-x", "--min-uvcut", type=float, default=0.0)
    ap.add_argument("-y", "--max-uvcut", type=float, default=1e20)
    ap.add_argument("-L", "--nulow", type=float, default=2.0)
    ap.add_argument("-H", "--nuhigh", type=float, default=30.0)
    ap.add_argument("-R", "--no-randomize", action="store_true")
    ap.add_argument("-W", "--whiten", action="store_true")
    ap.add_argument("-B", "--beam", type=int, default=0,
                    help="beam model: 0 none, 1 array, 2 array+element, "
                    "3 element, 4/5/6 same per-channel (ref DOBEAM codes)")
    ap.add_argument("--element-coeffs", default=None,
                    help="element-beam coefficient table file "
                    "(default: built-in synthetic dipole)")
    ap.add_argument("-b", "--per-channel", action="store_true",
                    help="re-fit each channel after the averaged solve "
                    "(ref -b doChan)")
    ap.add_argument("-G", "--rho-file", default=None,
                    help="per-cluster ADMM rho file (read_arho_fromfile "
                    "format: cluster_id hybrid rho)")
    ap.add_argument("-K", "--skip-tiles", type=int, default=0,
                    help="skip this many solution tiles (partial rerun)")
    ap.add_argument("-T", "--max-tiles", type=int, default=0,
                    help="process at most this many tiles (0 = all)")
    ap.add_argument("-a", "--simulate", type=int, default=0,
                    help="1: model only, 2: add, 3: subtract")
    ap.add_argument("-z", "--ignore-clusters", default=None)
    ap.add_argument("-k", "--ccid", type=int, default=None,
                    help="cluster id whose inverse corrects the residual "
                    "(ref -k)")
    ap.add_argument("-E", "--gpu-predict", type=int, default=0,
                    help="accepted for drop-in compatibility (ref -E GPU "
                    "predict toggle); the whole compute path is the "
                    "accelerator here")
    ap.add_argument("-o", "--correction-rho", type=float, default=1e-9,
                    help="robust rho added to the MMSE matrix inversion "
                    "when correcting residuals by a cluster's solution "
                    "(ref -o, main.cpp:80)")
    ap.add_argument("-J", "--phase-only", type=int, default=0,
                    help="if >0, phase-only correction (ref -J)")
    ap.add_argument("--phase-only-correction", action="store_true",
                    help="alias for -J 1")
    ap.add_argument("-n", "--threads", type=int, default=0,
                    help="accepted for drop-in compatibility (ref -n "
                    "worker threads); parallelism is managed by XLA")
    ap.add_argument("-N", "--epochs", type=int, default=0)
    ap.add_argument("-M", "--minibatches", type=int, default=1)
    ap.add_argument("-w", "--bands", type=int, default=1)
    ap.add_argument("-A", "--admm-iters", type=int, default=0)
    ap.add_argument("-P", "--npoly", type=int, default=2)
    ap.add_argument("-Q", "--poly-type", type=int, default=2)
    ap.add_argument("-r", "--admm-rho", type=float, default=5.0)
    ap.add_argument("--consensus-zstep", choices=("grouped", "reduced"),
                    default="grouped",
                    help="consensus Z-step collective layout: 'reduced' "
                    "moves only basis-sized Gram terms per round "
                    "(transpose reduction) instead of the full "
                    "replicated psum; bit-close (<=1e-6) to 'grouped'")
    ap.add_argument("--consensus-cluster-groups", type=int, default=1,
                    help=">1 decomposes each ADMM x-step below band "
                    "granularity into this many cluster factor-node "
                    "groups (fine-grained consensus; rounds get "
                    "cheaper, the rotation covers all groups)")
    ap.add_argument("--consensus-staleness", type=int, default=0,
                    help=">0 bounded-staleness consensus rounds: bands "
                    "may contribute Gram terms up to K rounds stale "
                    "(rho-discounted); 0 = synchronous (bit-identical "
                    "to the default loop)")
    ap.add_argument("--consensus-staleness-discount", type=float,
                    default=1.0,
                    help="per-round rho discount applied to stale "
                    "consensus contributions (1.0 = undamped)")
    ap.add_argument("-C", "--adaptive-rho", type=int, default=0,
                    help="if >0, adaptive (Barzilai-Borwein) update of "
                    "the ADMM regularization (ref -C aadmm, default off "
                    "as in the reference)")
    ap.add_argument("--fused", action="store_true",
                    help="route the joint-LBFGS cost through the fused "
                         "Pallas RIME kernel (f32 runs only)")
    ap.add_argument("--coh-dtype", choices=("f32", "bf16"), default="f32",
                    help="coherency-stack storage dtype on the fused "
                         "path: bf16 halves the dominant HBM stream "
                         "(f32 accumulation, ~3 significant digits of "
                         "coherency precision); quality-watchdog events "
                         "record the active dtype.  Requires --fused "
                         "--f32")
    ap.add_argument("--f32", action="store_true",
                    help="solve in float32 (TPU-native precision)")
    ap.add_argument("-V", "--verbose", action="store_true")
    # distributed (sagecal-mpi) surface: -f pattern selects the mesh
    # driver (MPI/main.cpp:336; master MS discovery :60-224)
    ap.add_argument("-f", "--band-pattern", default=None,
                    help="glob of per-band vis.h5 datasets -> distributed "
                    "consensus-ADMM over the device mesh (ref sagecal-mpi "
                    "-f 'pattern')")
    ap.add_argument("--multihost", action="store_true",
                    help="call jax.distributed.initialize() for multi-host "
                    "meshes (DCN)")
    ap.add_argument("-U", "--global-residual", type=int, default=0,
                    help="if >0, compute final residuals from the GLOBAL "
                    "consensus solution B_f Z instead of the per-band "
                    "solutions (ref -U use_global_solution, "
                    "sagecal_slave.cpp:861-979)")
    ap.add_argument("-X", "--spatialreg", default=None,
                    metavar="lam,mu,n0,fista_maxiter,cadence",
                    help="enable spatial regularization with these "
                    "parameters (ref -X; overrides the individual "
                    "--spatial-* flags)")
    ap.add_argument("--spatial-n0", type=int, default=0,
                    help=">0 enables spatial regularization of Z with a "
                    "basis of this order (the -X n0 component)")
    ap.add_argument("--spatial-beta", type=float, default=0.01,
                    help="shapelet basis scale; <=0 uses the master's "
                    "auto scale 4*sqrt(l_max^2/M)")
    ap.add_argument("--spatial-mu", type=float, default=1e-3)
    ap.add_argument("-O", "--spatial-cadence", type=int, default=2,
                    help="run the spatial FISTA update every this many "
                    "ADMM iterations (ref admm_cadence)")
    ap.add_argument("--spatial-basis", choices=("shapelet", "sharmonic"),
                    default="shapelet",
                    help="spatial basis: shapelet(l,m) or spherical-"
                    "harmonic(r,theta) modes (ref spatialreg_basis)")
    ap.add_argument("--spatial-diffuse-id", type=int, default=None,
                    help="cluster id of the all-shapelet diffuse cluster "
                    "to constrain/re-predict from the spatial model "
                    "(ref sp_diffuse_id)")
    ap.add_argument("--spatial-gamma", type=float, default=0.1,
                    help="diffuse-constraint coupling (ref sp_gamma)")
    ap.add_argument("--spatial-lam", type=float, default=1e-3,
                    help="diffuse-constraint L2 (ref sh_lambda)")
    ap.add_argument("--mdl", action="store_true",
                    help="score consensus polynomial orders by AIC/MDL "
                    "each tile (ref master -M, mdl.c)")
    ap.add_argument("-u", "--federated-alpha", type=float, default=5.0,
                    help="federated Z~Zavg coupling strength for the "
                    "-f + -N stochastic mode (ref alpha, "
                    "find_prod_inverse_full_fed)")
    ap.add_argument("-i", "--influence", action="store_true",
                    help="write influence-function diagnostics instead of "
                    "residuals (ref -i)")
    ap.add_argument("--abort-on-divergence", action="store_true",
                    help="terminate (with a structured run_aborted event) "
                    "when the quality watchdog reports a diverged solve; "
                    "default is report-only")
    # elastic execution (sagecal_tpu/elastic/)
    ap.add_argument("--resume", action="store_true",
                    help="resume from the newest valid checkpoint in the "
                    "checkpoint directory (refused, exit 5, when the run "
                    "configuration or data fingerprint mismatches)")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help=">0 writes an atomic solver-state checkpoint "
                    "every this many tile (or minibatch) boundaries; "
                    "--resume implies 1 when unset")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="checkpoint directory (default: "
                    "<solutions>.ckpt)")
    # hardware-truth observability (obs/devprof.py)
    ap.add_argument("--device-profile", default=None, metavar="DIR",
                    help="capture a device-profiler trace of this run "
                    "into DIR for `diag roofline` (same as "
                    "SAGECAL_DEVICE_PROFILE=DIR)")
    return ap


def config_from_args(args) -> RunConfig:
    return RunConfig(
        dataset=args.dataset,
        sky_model=args.sky,
        cluster_file=args.clusters or (args.sky + ".cluster"),
        out_solutions=args.solutions,
        init_solutions=args.init_solutions,
        tilesz=args.tilesz,
        max_emiter=args.max_emiter,
        max_iter=args.max_iter,
        max_lbfgs=args.max_lbfgs,
        lbfgs_m=args.lbfgs_m,
        solver_mode=args.solver_mode,
        nulow=args.nulow,
        nuhigh=args.nuhigh,
        randomize=not args.no_randomize,
        min_uvcut=args.min_uvcut,
        max_uvcut=args.max_uvcut,
        whiten=args.whiten,
        beam_mode=args.beam,
        element_coeffs=args.element_coeffs,
        per_channel=args.per_channel,
        rho_file=args.rho_file,
        skip_tiles=args.skip_tiles,
        max_tiles=args.max_tiles,
        simulation_mode=args.simulate,
        ignore_clusters_file=args.ignore_clusters,
        ccid=args.ccid,
        correction_rho=args.correction_rho,
        phase_only_correction=(args.phase_only_correction
                               or args.phase_only > 0),
        epochs=args.epochs,
        minibatches=args.minibatches,
        in_column=args.in_column,
        out_column=args.out_column,
        sky_format=args.sky_format,
        bands=args.bands,
        admm_iters=args.admm_iters,
        npoly=args.npoly,
        poly_type=args.poly_type,
        admm_rho=args.admm_rho,
        consensus_zstep=args.consensus_zstep,
        consensus_cluster_groups=args.consensus_cluster_groups,
        consensus_staleness=args.consensus_staleness,
        consensus_staleness_discount=args.consensus_staleness_discount,
        use_f64=not args.f32,
        verbose=args.verbose,
        influence=args.influence,
        use_fused_predict=args.fused,
        coh_dtype=args.coh_dtype,
        abort_on_divergence=args.abort_on_divergence,
        resume=args.resume,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
    )


def _warn_dropped_fused(args, log=print):
    if args.fused and not args.f32:
        log("warning: --fused requires --f32 (the Pallas kernel computes "
            "in float32); the fused path is DISABLED for this f64 run")
    if getattr(args, "coh_dtype", "f32") == "bf16" and not (
            args.fused and args.f32):
        log("warning: --coh-dtype bf16 only applies to the fused f32 "
            "path (--fused --f32); coherencies stay at the run precision")


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "diag":
        # observability diagnostics: manifests, event-log summaries,
        # Prometheus export, perf attribution, regression gate
        # (obs/diag.py)
        from sagecal_tpu.obs.diag import main as diag_main

        return diag_main(argv[1:])
    if argv and argv[0] == "serve":
        # multi-tenant batch calibration service (sagecal_tpu/serve/):
        # bucketed vmapped solves over a JSON request manifest; owns
        # its own flag surface and exit-code mapping (apps/serve.py)
        from sagecal_tpu.apps.serve import main as serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "fleet":
        # coordinator + N workers over a shared filesystem work queue
        # with atomic leases and a cross-worker AOT executable store;
        # owns its own flag surface and exit codes (apps/fleet.py)
        from sagecal_tpu.apps.fleet import main as fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "load":
        # synthetic-tenant load harness vs a live fleet: seeded
        # open-loop arrivals, live timeline, capacity report
        # (apps/load.py / fleet/loadgen.py / obs/capacity.py)
        from sagecal_tpu.apps.load import main as load_main

        return load_main(argv[1:])
    if argv and argv[0] == "stream":
        # sliding-window streaming calibration with the elastic
        # warm-start chain (apps/stream.py)
        from sagecal_tpu.apps.stream import main as stream_main

        return stream_main(argv[1:])
    if argv and argv[0] == "widefield":
        # wide-field calibration via the tree-clustered hierarchical
        # sky predict (sagecal_tpu/sky/); owns its own flag surface
        # and exit codes (apps/widefield.py)
        from sagecal_tpu.apps.widefield import main as widefield_main

        return widefield_main(argv[1:])
    if argv and argv[0] == "refine":
        # differentiable sky-model refinement (sagecal_tpu/refine/):
        # outer LBFGS over sky parameters around the inner gain solve;
        # owns its own flag surface and exit codes (apps/refine.py)
        from sagecal_tpu.apps.refine import main as refine_main

        return refine_main(argv[1:])
    if argv and argv[0] == "spatial":
        # spatial regularization as a standalone workload: per-band
        # solves -> consensus polynomial + AIC/MDL -> FISTA fit
        # (apps/spatial.py)
        from sagecal_tpu.apps.spatial import main as spatial_main

        return spatial_main(argv[1:])
    if argv and argv[0] == "convert":
        # convert <ms> <h5> [spw] — multi-SPW MSs convert one window
        # per .h5 band file (the reference expects pre-split MSs)
        from sagecal_tpu.io.dataset import ms_to_h5

        ms_to_h5(argv[1], argv[2],
                 spw=int(argv[3]) if len(argv) > 3 else 0)
        return 0
    args = build_parser().parse_args(argv)
    _warn_dropped_fused(args)
    cfg = config_from_args(args)
    from sagecal_tpu.elastic import ResumeRefused
    from sagecal_tpu.obs.contracts import ContractViolation
    from sagecal_tpu.obs.quality import DivergenceAbort

    # --device-profile DIR (or SAGECAL_DEVICE_PROFILE): capture a
    # device-profiler trace of the whole dispatch for `diag roofline`;
    # the CM stops the capture on ANY exit path, so even an aborted
    # run leaves a parseable trace
    from sagecal_tpu.obs.devprof import device_profile

    try:
        with device_profile(args.device_profile):
            return _dispatch(args, cfg)
    except DivergenceAbort as e:
        # --abort-on-divergence: the run already emitted its structured
        # run_aborted event; exit distinctly from argparse's 2
        print(f"sagecal-tpu: {e}", file=sys.stderr)
        return 3
    except ContractViolation as e:
        # SAGECAL_CHECKIFY=1: a NaN/div/index contract tripped inside a
        # jitted solver; the contract_violation event is already in the
        # JSONL log (apps drain it before re-raising)
        print(f"sagecal-tpu: {e}", file=sys.stderr)
        return 4
    except ResumeRefused as e:
        # --resume against a checkpoint whose config/data fingerprint
        # mismatches (or whose solution files are inconsistent): refuse
        # rather than silently corrupt; the resume_refused event is
        # already in the JSONL log
        print(f"sagecal-tpu: {e}", file=sys.stderr)
        return 5


def _dispatch(args, cfg) -> int:
    # mode dispatch (main.cpp:295-307; -f selects the sagecal-mpi
    # equivalent, MPI/main.cpp:336)
    if args.band_pattern and cfg.epochs > 0:
        # sagecal-mpi -N > 0: federated stochastic mode
        # (MPI/main.cpp:353-366 dispatch)
        from sagecal_tpu.apps.federated import run_federated

        cfg.dataset = args.band_pattern
        run_federated(
            cfg,
            nadmm=max(cfg.admm_iters, 2),
            epochs=cfg.epochs,
            minibatches=max(cfg.minibatches, 1),
            alpha=args.federated_alpha,
        )
    elif args.band_pattern:
        from sagecal_tpu.apps.distributed import run_distributed

        cfg.dataset = args.band_pattern
        sp_n0 = args.spatial_n0
        sp_mu = args.spatial_mu
        sp_lam = args.spatial_lam
        sp_iters, sp_cadence = 30, args.spatial_cadence
        if args.spatialreg:
            # -X lam,mu,n0,fista_maxiter,cadence (MPI/main.cpp:102)
            parts = args.spatialreg.split(",")
            if len(parts) != 5:
                ap = build_parser()
                ap.error(
                    f"-X expects 5 comma-separated values "
                    f"lam,mu,n0,fista_maxiter,cadence, got {args.spatialreg!r}"
                )
            lam_s, mu_s, n0_s, it_s, cad_s = parts
            sp_lam, sp_mu = float(lam_s), float(mu_s)
            sp_n0, sp_iters, sp_cadence = int(n0_s), int(it_s), int(cad_s)
        run_distributed(
            cfg, multihost=args.multihost,
            nadmm=max(cfg.admm_iters, 2),
            spatial_n0=sp_n0,
            spatial_beta=args.spatial_beta,
            spatial_mu=sp_mu,
            spatial_cadence=sp_cadence,
            spatial_fista_maxiter=sp_iters,
            spatial_basis=args.spatial_basis,
            spatial_diffuse_id=args.spatial_diffuse_id,
            spatial_gamma=args.spatial_gamma,
            spatial_lam=sp_lam,
            mdl=args.mdl,
            global_residual=bool(args.global_residual),
            adaptive_rho=args.adaptive_rho > 0,
        )
    elif cfg.epochs > 0:
        from sagecal_tpu.apps.minibatch import run_minibatch

        run_minibatch(cfg)
    else:
        from sagecal_tpu.apps.fullbatch import run_fullbatch

        run_fullbatch(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
