"""Run configuration: the framework's typed replacement for the
reference's ``namespace Data`` mutable option globals
(``/root/reference/src/MS/data.h:140-211``, defaults data.cpp:60-130).
Field names follow the reference's single-letter flags (see cli.py)."""

from __future__ import annotations

import dataclasses
from typing import Optional

from sagecal_tpu.solvers.sage import SM_OSLM_OSRLM_RLBFGS


@dataclasses.dataclass
class RunConfig:
    # data / sky
    dataset: str = ""  # -d
    sky_model: str = ""  # -s
    cluster_file: str = ""  # -F is format in ref; here explicit path
    out_solutions: str = "solutions.txt"  # -p
    init_solutions: Optional[str] = None  # -q warm start
    tilesz: int = 120  # -t
    # solver (defaults per user_manual.rst:32-58 / data.cpp)
    max_emiter: int = 3  # -e
    max_iter: int = 2  # -g
    max_lbfgs: int = 10  # -l
    lbfgs_m: int = 7  # -m
    solver_mode: int = SM_OSLM_OSRLM_RLBFGS  # -j
    nulow: float = 2.0
    nuhigh: float = 30.0
    randomize: bool = True  # -R
    min_uvcut: float = 0.0  # -x
    max_uvcut: float = 1e20  # -y
    whiten: bool = False  # -W
    # simulation (-a) / correction (-E)
    simulation_mode: int = 0  # 0 calibrate; 1/2/3 = SIMUL_ONLY/ADD/SUB
    ignore_clusters_file: Optional[str] = None  # -z
    ccid: Optional[int] = None  # -E cluster id to correct residuals by
    correction_rho: float = 1e-9
    phase_only_correction: bool = False
    # stochastic modes
    epochs: int = 0  # -N  (>0 selects minibatch mode)
    minibatches: int = 1  # -M
    in_column: str = "vis"  # -I input column (data.h DataField)
    out_column: str = "corrected"  # --out-column (ref -O OutField)
    sky_format: int = -1  # -F: -1 auto, 0 LSM, 1 three-term spectra
    bands: int = 1  # -w mini-bands
    admm_iters: int = 0  # -A (>0 with bands>1 selects consensus)
    npoly: int = 2  # -P
    poly_type: int = 2  # -Q (POLY_* in parallel.consensus)
    admm_rho: float = 5.0  # -r
    # consensus-layer scaling knobs (parallel/consensus.ConsensusConfig
    # on the mesh path; parallel/async_consensus on the host minibatch
    # loop — see USER_MANUAL "Scaling ADMM"):
    # zstep "reduced" = transpose-reduced Z-step (basis-sized Gram
    # collectives instead of full-solution psums, arXiv:1504.02147)
    consensus_zstep: str = "grouped"
    # >1 splits each x-step below band granularity into this many
    # cluster factor-node groups (arXiv:1603.02526)
    consensus_cluster_groups: int = 1
    # >0 allows bands to contribute Gram terms up to this many rounds
    # stale (rho-discounted by consensus_staleness_discount per round);
    # 0 = fully synchronous rounds
    consensus_staleness: int = 0
    consensus_staleness_discount: float = 1.0
    # beam (-B: 0 none, 1 array, 2 array+element, 3 element, 4/5/6 the
    # same per-channel/wideband — main.cpp DOBEAM_* codes)
    beam_mode: int = 0
    element_coeffs: Optional[str] = None  # element-coefficient table file
    # per-channel re-fit after the averaged solve (-b, doChan;
    # fullbatch_mode.cpp:453-499)
    per_channel: bool = False
    # joint-LBFGS cost through the fused Pallas RIME kernel (f32 only)
    use_fused_predict: bool = False
    # coherency-stack storage dtype on the fused path: "f32" (default)
    # or "bf16" (halved HBM stream, f32 accumulation — ~3 significant
    # digits of coherency precision; the quality watchdog validates the
    # solves it produces and its events carry the active coh_dtype)
    coh_dtype: str = "f32"
    # per-cluster ADMM rho / spatial alpha file (-G, read_arho_fromfile)
    rho_file: Optional[str] = None
    # partial reruns: skip first K tiles, process at most T tiles
    # (-K/-T, MPI/main.cpp:133-139)
    skip_tiles: int = 0
    max_tiles: int = 0  # 0 = no limit
    # divergence guard (fullbatch_mode.cpp:250,618-632)
    res_ratio: float = 5.0
    # quality watchdog escalation: report-only by default; True makes a
    # diverged solve (non-finite gains/chi^2, residual-ratio blowup,
    # ADMM consensus runaway) terminate the run with a structured
    # run_aborted event (obs/quality.py DivergenceAbort)
    abort_on_divergence: bool = False
    # influence-function diagnostics in place of residuals (-i,
    # diagnostics.c / fullbatch_mode.cpp:526-534)
    influence: bool = False
    # elastic execution (sagecal_tpu/elastic/): checkpoint_every > 0
    # writes an atomic solver-state checkpoint every that many tile
    # boundaries; resume restarts from the newest valid checkpoint
    # (deriving the effective skip count, truncating any torn trailing
    # solution interval, warm-starting the gains).  checkpoint_dir
    # defaults to "<out_solutions>.ckpt".
    resume: bool = False
    checkpoint_every: int = 0
    checkpoint_dir: Optional[str] = None
    # precision
    use_f64: bool = True
    verbose: bool = False  # -V


@dataclasses.dataclass
class RefineConfig:
    """``sagecal-tpu refine``: differentiable sky-model refinement
    (sagecal_tpu/refine/).  An outer LBFGS over the free sky parameters
    wraps the inner gain solve; gradients flow through the inner fixed
    point (implicit function theorem by default, truncated unrolling as
    the fallback).  XLA predict path only — the fused kernel has no
    coherency cotangent (see refine.objective.require_xla_predict)."""

    dataset: str = ""  # vis.h5 (one tile); empty with synthetic>0
    sky_model: str = ""
    cluster_file: str = ""
    out_prefix: str = "refine-out"  # <prefix>.json / .npz / .trace.jsonl
    tilesz: int = 2
    # which parameters are free: "c:s" entries (cluster:source index),
    # comma-separated; modes entries are "c:m" (cluster:flat mode idx)
    free_flux: str = "0:0"
    free_spec: str = ""
    free_pos: str = ""
    free_modes: str = ""
    # outer loop
    outer_iters: int = 10
    lbfgs_m: int = 7
    gradient: str = "implicit"  # or "unrolled"
    tol: float = 0.0
    # inner solve / adjoint
    inner_iters: int = 12
    cg_iters: int = 32
    damping: float = 1e-6
    adjoint_cg_iters: int = 64
    adjoint_matvec: str = "hvp"  # or "jtj" (Gauss-Newton)
    ridge: float = 1e-2  # inner gain prior (degeneracy breaker)
    # synthetic mode (smoke/bench/tests): simulate a make_sky fixture,
    # perturb one flux by this factor, refine it back
    synthetic: int = 0  # >0: nstations of the synthetic sky
    perturb: float = 1.15
    noise_sigma: float = 0.0
    seed: int = 3
    # elastic (outer-state checkpoints at outer-iteration boundaries)
    resume: bool = False
    checkpoint_every: int = 0
    checkpoint_dir: Optional[str] = None
    use_f64: bool = True
    verbose: bool = False


@dataclasses.dataclass
class SpatialConfig:
    """``sagecal-tpu spatial``: spatial regularization as a first-class
    workload — per-band calibration solves -> consensus polynomial ->
    FISTA elastic-net fit of Z onto the spatial basis
    (parallel/spatial.py) + AIC/MDL consensus-order scan."""

    band_pattern: str = ""  # glob of per-band vis.h5; empty = synthetic
    sky_model: str = ""
    cluster_file: str = ""
    out_prefix: str = "spatial-out"  # <prefix>.json / .npz
    tilesz: int = 2
    # per-band solver (RunConfig semantics)
    max_emiter: int = 3
    max_iter: int = 2
    max_lbfgs: int = 10
    lbfgs_m: int = 7
    solver_mode: int = SM_OSLM_OSRLM_RLBFGS
    # consensus + spatial
    admm_rho: float = 5.0
    npoly: int = 2
    poly_type: int = 2
    spatial_n0: int = 2
    spatial_beta: float = 0.0  # <=0: master's auto scale
    spatial_basis: str = "shapelet"
    spatial_mu: float = 1e-3
    fista_maxiter: int = 60
    mdl_kmax: int = 0  # 0: max(npoly, 2)
    # synthetic mode: make_multiband_skies bands
    synthetic: int = 0  # >0: number of synthetic bands
    nstations: int = 7
    noise_sigma: float = 0.0
    seed: int = 5
    # elastic (checkpoint after each solved band)
    resume: bool = False
    checkpoint_every: int = 0
    checkpoint_dir: Optional[str] = None
    use_f64: bool = True
    verbose: bool = False


@dataclasses.dataclass
class ServeConfig:
    """``sagecal-tpu serve``: the multi-tenant calibration service
    (sagecal_tpu/serve/).  Solver fields are SERVICE-WIDE defaults; a
    request manifest entry may override any of the per-request knobs
    (serve/request.py SOLVER_KNOBS)."""

    requests: str = ""          # request manifest (JSON) path
    out_dir: str = "serve-out"  # solutions + result manifests
    batch: int = 8              # lanes per bucketed batch solve
    # solver defaults (same semantics as RunConfig)
    max_emiter: int = 3
    max_iter: int = 2
    max_lbfgs: int = 10
    lbfgs_m: int = 7
    solver_mode: int = SM_OSLM_OSRLM_RLBFGS
    nulow: float = 2.0
    nuhigh: float = 30.0
    randomize: bool = True
    res_ratio: float = 5.0
    abort_on_divergence: bool = False
    # elastic: per-tenant checkpoint namespaces under
    # <checkpoint_dir or out_dir/serve.ckpt>/tenants/<tenant>
    resume: bool = False
    checkpoint_every: int = 0
    checkpoint_dir: Optional[str] = None
    use_f64: bool = True
    # route the solves' joint-LBFGS phase through the fused Pallas RIME
    # kernels — batched (one grid per batch) when the bucket passes the
    # capability checks of solvers/batched.choose_batched_path, vmapped
    # solo kernels or the XLA predict otherwise.  f32 only: combined
    # with use_f64=True the fused request is ignored (fullbatch
    # precedent) and the dispatch stays on the XLA path.
    use_fused_predict: bool = False
    # coherency-stack dtype on the fused paths ("f32" | "bf16"; see
    # RunConfig.coh_dtype)
    coh_dtype: str = "f32"
    verbose: bool = False
    # per-tenant SLO specs (obs/slo.py): path to a slo.json; empty
    # falls back to any "slos" key inside the request manifest
    slo: str = ""
    # cross-worker AOT executable artifact store directory
    # (serve/aot_store.py); empty = in-process cache only
    aot_store: str = ""
    # cap on concurrently open TilePrefetcher streams (one per
    # (tenant, dataset, tilesz, column)); 0 = unbounded (legacy).
    # Above the cap the least-recently-used stream is closed (reader
    # threads reaped) and transparently reopened from its remaining
    # tiles on next touch; serve_prefetch_evictions_total counts it.
    max_streams: int = 0
    # shadow-solve differential auditing (obs/shadow.py): re-solve this
    # fraction of requests on the reference path (XLA, f32 coherencies,
    # single lane) AFTER each result manifest is written, and append a
    # drift record to <out_dir>/drift.jsonl.  Sampling is a pure
    # function of (shadow_seed, request_id); 0 disables auditing
    # entirely — provably byte-identical output to a build without the
    # feature (tests/test_drift.py)
    shadow_rate: float = 0.0
    shadow_seed: int = 0
    # per-process wall-clock budget for shadow re-solves; once spent,
    # further sampled requests are skipped and COUNTED (diag drift
    # reports the skip count, so a starved budget can't look clean)
    shadow_budget_s: float = 120.0
    # escalate a tolerance-policy breach (obs/shadow.DRIFT_TOLERANCES)
    # from report-only to a run abort (exit 3), raised only after the
    # whole run's manifests + drift ledger are on disk
    abort_on_drift: bool = False


@dataclasses.dataclass
class FleetConfig:
    """``sagecal-tpu fleet``: coordinator + N worker processes sharing
    a filesystem work queue with atomic lease files (sagecal_tpu/fleet/).
    Workers claim requests by bucket affinity, leases expire so a
    killed worker's requests requeue, and admission control consumes
    obs/slo.py burn rates (shed-or-degrade on overload)."""

    requests: str = ""          # request manifest (JSON) path
    out_dir: str = "fleet-out"  # solutions + result manifests
    queue_dir: str = ""         # shared queue; default <out_dir>/queue
    aot_store: str = ""         # shared AOT artifacts;
    #                             default <out_dir>/aot-store
    workers: int = 2            # worker processes the coordinator spawns
    role: str = "coordinator"   # "coordinator" | "worker"
    worker_id: str = ""         # set by the coordinator for workers
    batch: int = 4              # lanes per bucketed batch solve
    # lease protocol: claims expire after ttl; holders renew at
    # renew_s (0 = ttl/3); an expired lease may be stolen by any worker
    lease_ttl_s: float = 30.0
    lease_renew_s: float = 0.0
    poll_s: float = 0.2         # queue poll period when idle
    max_idle_s: float = 10.0    # worker exits after this long idle
    # placement: requests with nstations >= large_stations (and >1
    # local device) solve via solvers/sharded.sharded_joint_fit instead
    # of riding a vmapped batch lane; 0 disables the large path
    large_stations: int = 0
    # admission control on SLO burn (obs/slo.py): what to do when a
    # tenant's shed_burn threshold trips — "shed" refuses the request
    # (manifest verdict "shed", no solve), "degrade" solves with
    # reduced iteration budgets (quality watchdog still verdicts the
    # result), "off" restores PR 11 report-only behavior
    overload_policy: str = "degrade"
    degrade_emiter: int = 1
    degrade_lbfgs: int = 4
    # solver defaults (ServeConfig semantics; per-request overrides win)
    max_emiter: int = 3
    max_iter: int = 2
    max_lbfgs: int = 10
    lbfgs_m: int = 7
    solver_mode: int = SM_OSLM_OSRLM_RLBFGS
    nulow: float = 2.0
    nuhigh: float = 30.0
    randomize: bool = True
    res_ratio: float = 5.0
    abort_on_divergence: bool = False
    resume: bool = False
    checkpoint_every: int = 0
    checkpoint_dir: Optional[str] = None
    use_f64: bool = True
    # fused-kernel routing for the workers' batch solves (ServeConfig
    # semantics: batched fused kernel when capability checks pass,
    # ignored under use_f64)
    use_fused_predict: bool = False
    coh_dtype: str = "f32"
    verbose: bool = False
    slo: str = ""
    max_streams: int = 8
    # live observability (obs/timeline.py): the coordinator appends one
    # timeline.jsonl row per watch poll and feeds the report-only
    # autoscale recommender (obs/capacity.py) — pure observation unless
    # elastic_workers is set
    timeline: bool = True
    # bounded respawn of CRASHED workers (nonzero exit with work left):
    # per-slot replacement budget; clean exits never respawn
    max_respawns: int = 2
    # opt-in: act on the recommender (spawn/retire one worker per
    # recommendation change, clamped to [min_workers, max_workers];
    # retire = SIGTERM -> the worker's existing lease-release path).
    # Off (default) the recommender provably changes no solve output.
    elastic_workers: bool = False
    min_workers: int = 1
    max_workers: int = 0        # 0 = max(workers, min_workers)
    # open-loop submission (the load harness): arrivals keep landing
    # AFTER workers start, so "every item submitted so far is done" is
    # not an exit signal — workers hold on until max_idle_s or SIGTERM
    open_loop: bool = False
    # shadow-solve differential auditing (ServeConfig semantics): each
    # worker audits its own claimed requests against the XLA/f32
    # reference, appending to the SHARED <out_dir>/drift.jsonl (the
    # O_APPEND single-write contract keeps concurrent workers from
    # interleaving); the budget is per worker
    shadow_rate: float = 0.0
    shadow_seed: int = 0
    shadow_budget_s: float = 120.0
    abort_on_drift: bool = False


@dataclasses.dataclass
class StreamConfig:
    """``sagecal-tpu stream``: streaming/online calibration.  The
    dataset is consumed as a time stream; each sliding window of
    ``window`` time samples (advanced by ``hop``) is solved with a
    warm start from the previous window's gains via the elastic
    warm-start chain, minimizing latency-to-first-solution."""

    dataset: str = ""           # vis.h5 consumed as a time stream
    sky_model: str = ""
    cluster_file: str = ""
    out_dir: str = "stream-out"
    window: int = 2             # time samples per sliding window
    hop: int = 1                # samples the window advances per solve
    max_windows: int = 0        # 0 = run to the end of the stream
    warm_start: bool = True     # p0 <- previous window's solution
    # iteration budget for warm-started windows (the chain means a
    # near-converged start; full budgets only for the cold window 0)
    warm_emiter: int = 1
    warm_lbfgs: int = 0         # 0 = inherit max_lbfgs
    in_column: str = "vis"
    # solver (RunConfig semantics)
    max_emiter: int = 3
    max_iter: int = 2
    max_lbfgs: int = 10
    lbfgs_m: int = 7
    solver_mode: int = SM_OSLM_OSRLM_RLBFGS
    nulow: float = 2.0
    nuhigh: float = 30.0
    randomize: bool = True
    res_ratio: float = 5.0
    # elastic: lease-aware stream checkpoints — the checkpoint carries
    # an owner lease so a second stream process refuses to adopt a
    # LIVE peer's chain and only resumes one whose lease expired
    resume: bool = False
    checkpoint_every: int = 1
    checkpoint_dir: Optional[str] = None
    lease_ttl_s: float = 30.0
    use_f64: bool = True
    verbose: bool = False
    # synthetic mode (tests/bench): simulate a make_sky fixture stream
    synthetic: int = 0          # >0: nstations of the synthetic array
    ntime: int = 6
    nchan: int = 2
    noise_sigma: float = 0.0
    seed: int = 7


@dataclasses.dataclass
class WidefieldConfig:
    """``sagecal-tpu widefield``: 10k+-source wide-field calibration
    through the hierarchical sky predict (sagecal_tpu/sky/).  A
    synthetic compact-array/all-sky observation is generated with
    ``data.simsky.make_sky(wide_field=True)``, the full source list is
    collapsed into ``nclusters`` tree-partitioned effective calibration
    directions, and each tile's cluster coherencies come from
    ``predict_coherencies_hier`` (a-posteriori-verified by the quality
    watchdog) before the standard packed SAGE solve."""

    out_dir: str = "widefield-out"
    # synthetic wide-field sky (data/simsky.py wide_field branch)
    nstations: int = 24
    ntiles: int = 4             # solve tiles (total obs = ntiles*tilesz)
    tilesz: int = 2             # time samples per solve tile
    nchan: int = 1
    nsources: int = 2000        # total point sources across the field
    nblobs: int = 12            # spatial blobs the sky generator draws
    fov: float = 1.1            # field diameter, direction cosines
    cluster_scale: float = 0.004
    freq0: float = 30e6         # low-frequency all-sky regime
    extent_m: float = 80.0      # compact-array station layout radius
    gain_amp: float = 0.1
    noise_sigma: float = 0.0
    seed: int = 11
    # hierarchical predict knobs (sky/predict.py)
    nclusters: int = 4          # tree-collapsed effective directions
    order: int = 8              # multipole/Taylor truncation order p
    theta: float = 1.5          # well-separation phase budget (rad)
    leaf_size: int = 32
    tile_rows: int = 128
    source_chunk: int = 32
    exact: bool = False         # route through the exact predict instead
    # a-posteriori verification (sky.predict.sampled_error_estimate ->
    # obs.quality.check_hier_predict): rows sampled per tile; the
    # verdict degrades when the sampled error exceeds max_rel_err
    # (<= 0 uses the a-priori bound of (order, theta))
    hier_nsample: int = 32
    hier_max_rel_err: float = 1e-3
    # solver (RunConfig semantics)
    max_emiter: int = 3
    max_iter: int = 2
    max_lbfgs: int = 10
    lbfgs_m: int = 7
    solver_mode: int = SM_OSLM_OSRLM_RLBFGS
    nulow: float = 2.0
    nuhigh: float = 30.0
    randomize: bool = True
    res_ratio: float = 5.0
    abort_on_divergence: bool = False
    # elastic (checkpoint at tile boundaries; bit-exact resume)
    resume: bool = False
    checkpoint_every: int = 0
    checkpoint_dir: Optional[str] = None
    use_f64: bool = True
    verbose: bool = False
