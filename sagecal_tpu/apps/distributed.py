"""Distributed multi-band calibration driver: the ``sagecal-mpi`` binary.

Redesign of the MPI master/slave application pair
(``/root/reference/src/MPI/sagecal_master.cpp:41-1316`` /
``sagecal_slave.cpp``): one SPMD program over a ``('freq',)`` device
mesh replaces the rank-0 master + per-MS slaves.  The per-timeslot tile
loop (master :694-), metadata consistency checks (:238-287), fratio
scaling of rho (:709-723), the consensus-ADMM iteration
(:func:`sagecal_tpu.parallel.mesh.make_admm_mesh_fn`), the global-Z
solution file (:499-533, :1165-1175), per-band solution files and
residual write-back (slave :959-979) all live here; the MPI tag
protocol (proto.h) has no equivalent because the z-step psum and the
manifold-average all_gather are compiled collectives.

Multi-host: pass ``multihost=True`` to call
``jax.distributed.initialize()`` before touching devices — the same
mesh code then spans hosts over DCN (each host feeds its local bands).
"""

from __future__ import annotations

import glob
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sagecal_tpu.apps.config import RunConfig
from sagecal_tpu.core.types import (
    identity_jones,
    jones_to_params,
    mat_of_flat,
    params_to_jones,
)
from sagecal_tpu.io import solutions as solio
from sagecal_tpu.io.dataset import TilePrefetcher, VisDataset
from sagecal_tpu.io.skymodel import load_sky, read_cluster_rho
from sagecal_tpu.ops.residual import calculate_residuals
from sagecal_tpu.parallel import consensus
from sagecal_tpu.parallel.mesh import (
    SpatialConfig,
    make_admm_mesh_fn,
    stack_for_mesh,
)
from sagecal_tpu.solvers.lm import LMConfig
from sagecal_tpu.solvers.sage import build_cluster_data


def write_global_z_header(fh, freq0_hz, npoly, nstations, nclusters, neff):
    """Global-Z solution file header (sagecal_master.cpp:515-517)."""
    fh.write("# solution file (Z) created by SAGECal\n")
    fh.write("# reference_freq(MHz) polynomial_order stations clusters "
             "effective_clusters\n")
    fh.write(f"{freq0_hz * 1e-6:.6f} {npoly} {nstations} {nclusters} {neff}\n")


def append_global_z(fh, Z, nstations, npoly, nchunk_max, flush: bool = True):
    """One timeslot's Z rows (sagecal_master.cpp:1165-1175): row p of
    N*8*Npoly values, effective-cluster columns in REVERSE order.

    Z: (M, Npoly, nchunk_max*8N) real.

    Crash-safety contract mirrors :func:`sagecal_tpu.io.solutions.
    append_solutions`: the whole timeslot is one buffered write + flush,
    so a kill between timeslots never leaves a torn interval —
    :func:`sagecal_tpu.io.solutions.validate_global_z` truncates the
    rare mid-write tear on resume."""
    M = Z.shape[0]
    n8 = 8 * nstations
    # effective cluster (m, c) -> (Npoly*8N,) with p = poly*8N + i
    Zb = np.asarray(Z).reshape(M, npoly, nchunk_max, n8)
    cols = [
        Zb[m, :, c, :].reshape(-1)
        for m in range(M) for c in range(nchunk_max)
    ]
    cols = cols[::-1]  # reverse effective-cluster ordering
    rows = npoly * n8
    buf = "".join(
        f"{p} " + " ".join(f"{col[p]:e}" for col in cols) + "\n"
        for p in range(rows)
    )
    fh.write(buf)
    if flush:
        fh.flush()


def _check_band_consistency(metas, log):
    """The master's metadata validation (sagecal_master.cpp:238-287):
    all bands must agree on N / nbase / timeslot count."""
    n0, nb0, nt0 = metas[0].nstations, metas[0].nbase, metas[0].ntime
    for i, m in enumerate(metas[1:], 1):
        if (m.nstations, m.nbase) != (n0, nb0):
            raise ValueError(
                f"band {i}: station/baseline layout mismatch "
                f"({m.nstations},{m.nbase}) != ({n0},{nb0})"
            )
        if m.ntime != nt0:
            log(f"warning: band {i} has {m.ntime} timeslots != {nt0}; "
                f"using the minimum")
    return min(m.ntime for m in metas)


def _emit_admm_attribution(tracer, elog, log, t0, admm_seconds,
                           admm_start_unix, fratios, nf, nadmm, nslots,
                           plain_emiter, max_emiter, cluster_groups=1):
    """Host-side straggler attribution for one tile's mesh ADMM window.

    The whole nadmm loop is ONE jitted shard_map dispatch, so per-band /
    per-round wall time is not observable from the host; instead the
    measured dispatch->block window is distributed over per-band work
    weights (unflagged-row fractions — the same fratio that scales rho)
    and the static per-round work model
    (:func:`sagecal_tpu.parallel.admm.round_work_weights`) as SYNTHETIC
    child spans that sum exactly to the window.  Straggler gauges
    (slowest/median ratio, skew) + a ``straggler_detected`` event fire
    on the attributed seconds."""
    from sagecal_tpu.obs.registry import get_registry
    from sagecal_tpu.obs.trace import band_attribution, straggler_stats
    from sagecal_tpu.parallel.admm import round_work_weights

    weights = [float(f) for f in fratios[:nf]]
    band_secs = band_attribution(admm_seconds, weights)
    stats = straggler_stats(band_secs)
    if tracer.enabled:
        admm_id = tracer.add_span(
            "admm", admm_seconds, start_unix=admm_start_unix,
            kind="admm", tile=t0, nadmm=nadmm, nf=nf)
        # per-round weights track each round's ACTIVE slot's unflagged
        # rows (slot_rows) — a flag-skewed band's rounds bill more of
        # the measured window instead of papering over the straggler
        rsecs = band_attribution(
            admm_seconds,
            round_work_weights(nadmm, nslots, plain_emiter, max_emiter,
                               slot_rows=weights,
                               cluster_groups=cluster_groups))
        r_start = admm_start_unix
        for r, s in enumerate(rsecs):
            tracer.add_span("admm.round", s, parent_id=admm_id,
                            start_unix=r_start, round=r, tile=t0,
                            synthetic=True, attribution="round-work-model")
            r_start += s
        for b, s in enumerate(band_secs):
            tracer.add_span("admm.band", s, parent_id=admm_id,
                            start_unix=admm_start_unix, band=b, tile=t0,
                            lane=f"band{b}", synthetic=True,
                            attribution="unflagged-rows")
    reg = get_registry()
    for b, s in enumerate(band_secs):
        reg.gauge_set("admm_band_seconds", s,
                      help="attributed per-band seconds of the last "
                           "mesh ADMM window", band=str(b))
    reg.gauge_set("admm_straggler_ratio", stats["ratio"],
                  help="slowest/median attributed band seconds of the "
                       "last mesh ADMM window")
    reg.gauge_set("admm_band_skew", stats["skew"],
                  help="(max-mean)/mean attributed band seconds")
    if stats["detected"]:
        if elog is not None:
            elog.emit("straggler_detected", tile=t0, band=stats["argmax"],
                      ratio=stats["ratio"], skew=stats["skew"],
                      band_seconds=band_secs,
                      threshold=stats["threshold"])
        log(f"tile {t0}: straggler band {stats['argmax']} "
            f"({stats['ratio']:.2f}x median attributed work)")
    return band_secs, stats


def run_distributed(
    cfg: RunConfig,
    datasets: Optional[Sequence[str]] = None,
    log=print,
    multihost: bool = False,
    nadmm: Optional[int] = None,
    spatial_n0: int = 0,
    spatial_beta: float = 0.01,
    spatial_mu: float = 1e-3,
    spatial_alpha: float = 0.0,
    spatial_cadence: int = 2,
    spatial_basis: str = "shapelet",
    spatial_diffuse_id: Optional[int] = None,
    spatial_gamma: float = 0.0,
    spatial_lam: float = 0.0,
    spatial_fista_maxiter: int = 30,
    mdl: bool = False,
    global_residual: bool = False,
    adaptive_rho: bool = True,
):
    """Calibrate a multi-band observation on the device mesh.

    ``datasets``: explicit band file list, or None to expand
    ``cfg.dataset`` as a glob (the reference's ``-f 'pattern'``,
    sagecal_master.cpp:60-224 MS discovery).  Returns per-tile lists of
    (dual_res, primal_res) traces.

    ``spatial_n0 > 0`` switches on spatial regularization inside the
    ADMM loop (the master's -U path); ``spatial_basis`` selects
    shapelet or spherical-harmonic modes (master:359-397);
    ``spatial_beta <= 0`` uses the master's auto scale.

    ``spatial_diffuse_id``: cluster id whose (all-shapelet) coherencies
    are re-predicted from the diffuse-constrained spatial model — the
    find_initial_spatial / Zspat_diff / Psi chain (master:649-926,
    slave:670-698) with ``spatial_gamma``/``spatial_lam`` as
    (sp_gamma, sh_lambda).  The refresh runs between tiles (the
    reference refreshes every admm_cadence iterations inside the loop;
    we keep the whole Nadmm loop in one jit program and apply the
    refreshed coherencies to the next tile).

    ``mdl=True`` scores consensus polynomial orders 1..Npoly by
    AIC/MDL on each tile's rho-scaled solutions and logs the winner
    (the master's -M path, sagecal_master.cpp:991-993).
    """
    from sagecal_tpu.obs.perf import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    if multihost:
        jax.distributed.initialize()
    if datasets is None:
        datasets = sorted(glob.glob(cfg.dataset))
    if not datasets:
        raise ValueError(f"no band datasets match {cfg.dataset!r}")
    nadmm = nadmm if nadmm is not None else max(cfg.admm_iters, 2)
    dtype = np.float64 if cfg.use_f64 else np.float32

    handles: List[VisDataset] = [VisDataset(p, "r+") for p in datasets]
    open_files: List = []
    try:
        return _run_distributed_inner(
            cfg, datasets, handles, open_files, log, nadmm, dtype,
            spatial_n0, spatial_beta, spatial_mu, spatial_alpha,
            spatial_cadence, spatial_basis, spatial_diffuse_id,
            spatial_gamma, spatial_lam, mdl, spatial_fista_maxiter,
            global_residual, adaptive_rho,
        )
    finally:
        for fh in open_files:
            try:
                fh.close()
            except Exception:
                pass
        for h in handles:
            try:
                h.close()
            except Exception:
                pass


def _run_distributed_inner(
    cfg, datasets, handles, open_files, log, nadmm, dtype,
    spatial_n0, spatial_beta, spatial_mu, spatial_alpha, spatial_cadence,
    spatial_basis="shapelet", spatial_diffuse_id=None, spatial_gamma=0.0,
    spatial_lam=0.0, mdl=False, spatial_fista_maxiter=30,
    global_residual=False, adaptive_rho=True,
):
    metas = [h.meta for h in handles]
    ntime = _check_band_consistency(metas, log)
    meta0 = metas[0]
    N = meta0.nstations
    freqs = np.asarray([m.freq0 for m in metas])
    freq0 = float(np.mean(freqs))

    clusters, cdefs, shapelets = load_sky(
        cfg.sky_model, cfg.cluster_file, meta0.ra0, meta0.dec0, dtype=dtype,
        three_term_spectra=None if cfg.sky_format < 0 else bool(cfg.sky_format),
    )
    M = len(clusters)
    nchunks = [cd.nchunk for cd in cdefs]
    nchunk_max = max(nchunks)
    n8 = 8 * N

    # per-cluster rho (and spatial alpha) from the -G file when given
    if cfg.rho_file:
        rho_m, alpha_m = read_cluster_rho(
            cfg.rho_file, cdefs, spatialreg=True
        )
    else:
        rho_m = np.full((M,), cfg.admm_rho)
        alpha_m = np.full((M,), spatial_alpha)

    # pad band count to a mesh multiple with zero-weight bands
    devs = jax.devices()
    Nf = len(datasets)
    ndev = min(len(devs), Nf)
    Nf_pad = -(-Nf // ndev) * ndev
    mesh = Mesh(np.array(devs[:ndev]), ("freq",))
    log(f"distributed: {Nf} bands on {ndev} devices"
        + (f" (padded to {Nf_pad})" if Nf_pad != Nf else ""))

    B = consensus.setup_polynomials(freqs, freq0, cfg.npoly, cfg.poly_type)
    B_pad = np.concatenate(
        [B, np.tile(B[-1:], (Nf_pad - Nf, 1))], axis=0
    ) if Nf_pad != Nf else B

    spatial = None
    diffuse_idx = None
    diffuse_beta = None
    if spatial_n0 > 0:
        from sagecal_tpu.parallel.spatial import (
            basis_blocks, find_initial_spatial, phikk_matrix,
            spatial_basis_modes,
        )

        # flux-weighted cluster centroids (the master's spatial-basis
        # setup computes these from the sky model, :293-423)
        def _centroid(c):
            w = np.maximum(np.abs(np.asarray(c.sI0)), 1e-12)
            return (
                float(np.average(np.asarray(c.ll), weights=w)),
                float(np.average(np.asarray(c.mm), weights=w)),
            )

        cent = [_centroid(c) for c in clusters]
        lls = np.asarray([x[0] for x in cent])
        mms = np.asarray([x[1] for x in cent])
        # effective clusters repeat their centroid per hybrid chunk
        lle = np.repeat(lls, nchunk_max)
        mme = np.repeat(mms, nchunk_max)
        sp_modes, beta_used = spatial_basis_modes(
            lle, mme, spatial_n0,
            None if spatial_beta <= 0 else spatial_beta, spatial_basis,
        )
        diffuse_beta = beta_used if beta_used > 0 else spatial_beta
        log(f"spatial basis {spatial_basis} n0={spatial_n0} "
            f"beta={beta_used:.4g}")
        Phi = basis_blocks(sp_modes)
        Z_diff0 = None
        if spatial_diffuse_id is not None:
            if spatial_basis != "shapelet":
                raise ValueError(
                    "the diffuse constraint re-predicts coherencies "
                    "through SHAPELET products (diffuse_predict.c); use "
                    "--spatial-basis shapelet with --spatial-diffuse-id"
                )
            # diffuse target: cluster id -> index; must be all-shapelet
            ids = [cd.cluster_id for cd in cdefs]
            if spatial_diffuse_id not in ids:
                raise ValueError(
                    f"diffuse cluster id {spatial_diffuse_id} not in "
                    f"cluster file (ids {ids})"
                )
            diffuse_idx = ids.index(spatial_diffuse_id)
            Z_diff0 = find_initial_spatial(B, sp_modes, N)
        spatial = SpatialConfig(
            Phi=Phi, Phikk=phikk_matrix(Phi, lam=1e-6),
            alpha=jnp.asarray(
                np.where(alpha_m > 0, alpha_m, cfg.admm_rho), dtype
            ),
            mu=spatial_mu, cadence=spatial_cadence,
            fista_maxiter=spatial_fista_maxiter,
            Z_diff0=Z_diff0, gamma=spatial_gamma, lam_diff=spatial_lam,
        )

    # telemetry: per-band ADMM residual + rho traces ride along as extra
    # mesh outputs when SAGECAL_TELEMETRY=1, and each tile's consensus
    # run lands in the JSONL event log as one admm_round event
    from sagecal_tpu.obs import RunManifest, default_event_log, telemetry_enabled

    # per-band trajectories also feed the consensus watchdog, so an
    # abort-enabled run collects them even with telemetry off
    collect = telemetry_enabled() or cfg.abort_on_divergence

    def _build_mesh_fn(band_weights=None):
        # consensus-layer scaling knobs (parallel/consensus.
        # ConsensusConfig): transpose-reduced z-step, fine-grained
        # cluster factor groups, in-mesh staleness weighting
        ccfg = consensus.ConsensusConfig(
            zstep=cfg.consensus_zstep,
            cluster_groups=max(cfg.consensus_cluster_groups, 1),
            staleness=(cfg.consensus_staleness
                       if cfg.consensus_staleness > 0 else None),
            staleness_discount=cfg.consensus_staleness_discount,
        )
        if band_weights is not None:
            import dataclasses as _dc

            from sagecal_tpu.parallel.admm import factor_schedule

            slot_s, group_s = factor_schedule(
                nadmm, Nf_pad // ndev,
                cluster_groups=max(cfg.consensus_cluster_groups, 1),
                band_weights=band_weights, ndev=ndev,
            )
            ccfg = _dc.replace(ccfg, slot_schedule=slot_s,
                               group_schedule=group_s)
        return make_admm_mesh_fn(
            mesh, nadmm=nadmm, max_emiter=cfg.max_emiter,
            plain_emiter=max(cfg.max_emiter, 2),
            lm_config=LMConfig(itmax=cfg.max_iter),
            bb_rho=adaptive_rho, solver_mode=cfg.solver_mode,
            spatial=spatial,
            collect_trace=collect,
            consensus_cfg=ccfg,
        )

    # fine-grained rounds rebalance their slot schedule on per-band
    # unflagged-row counts, which are only known once the first tile's
    # masks are on device — defer the build to the first tile then;
    # everything else builds the program up front as before
    _want_rebalance = (
        cfg.consensus_cluster_groups > 1 and Nf_pad // ndev >= 1
        and cfg.consensus_staleness <= 0
        and cfg.consensus_staleness_discount == 1.0
    )
    fn = None if _want_rebalance else _build_mesh_fn()
    manifest = RunManifest.collect(
        app="distributed", bands=Nf, nadmm=nadmm,
        solver_mode=cfg.solver_mode, n_clusters=M, n_stations=N,
        adaptive_rho=adaptive_rho,
    )
    elog = default_event_log(manifest=manifest)
    # crash forensics + tracing (obs/flight.py, obs/trace.py): the
    # excepthook/SIGTERM handlers flush the event log with run_aborted,
    # the flight recorder heartbeats for the watch scripts, and the
    # tracer correlates spans with the manifest's run_id
    from sagecal_tpu.obs.flight import (
        close_flight_recorder,
        get_flight_recorder,
        install_crash_handlers,
        note_activity,
        register_event_log,
        unregister_event_log,
    )
    from sagecal_tpu.obs.trace import close_tracer, configure_tracer, get_tracer

    install_crash_handlers()
    if elog is not None:
        register_event_log(elog)
    get_flight_recorder(run_id=manifest.run_id)
    configure_tracer(run_id=manifest.run_id)
    tracer = get_tracer()

    # elastic execution (sagecal_tpu/elastic/): per-tile checkpoints of
    # the full cross-tile carry (p_bands warm start, diffuse Zspat
    # carry, residual traces) make a SIGTERM'd run resumable bit-exactly
    # — the mesh ADMM has no RNG, so the carry IS the whole state
    ckmgr = None
    resume_state = None
    resume_done = 0
    if cfg.resume or cfg.checkpoint_every > 0:
        import os as _os

        from sagecal_tpu.elastic import (
            CheckpointManager,
            ResumeRefused,
            config_fingerprint,
        )

        fingerprint = config_fingerprint(
            app="distributed",
            datasets=[_os.path.abspath(p) for p in datasets],
            sky_model=_os.path.abspath(cfg.sky_model),
            cluster_file=_os.path.abspath(cfg.cluster_file),
            nstations=N, ntime=ntime, nbands=Nf,
            freqs=[float(f) for f in freqs],
            nadmm=nadmm, tilesz=cfg.tilesz, solver_mode=cfg.solver_mode,
            max_emiter=cfg.max_emiter, max_iter=cfg.max_iter,
            npoly=cfg.npoly, poly_type=cfg.poly_type,
            admm_rho=cfg.admm_rho, use_f64=cfg.use_f64,
            in_column=cfg.in_column, skip_tiles=cfg.skip_tiles,
            max_tiles=cfg.max_tiles, spatial_n0=spatial_n0,
            adaptive_rho=adaptive_rho,
            consensus_zstep=cfg.consensus_zstep,
            consensus_cluster_groups=cfg.consensus_cluster_groups,
            consensus_staleness=cfg.consensus_staleness,
            consensus_staleness_discount=cfg.consensus_staleness_discount,
        )
        ckmgr = CheckpointManager(
            cfg.checkpoint_dir or f"{cfg.out_solutions}.ckpt",
            fingerprint, "distributed",
            every=max(cfg.checkpoint_every, 1), elog=elog, log=log,
        )
        if cfg.resume:
            found = ckmgr.resume()
            if found is not None:
                rmeta, resume_state, rpath = found
                resume_done = int(rmeta["tiles_done"])
                # re-open the solution files append-consistently: drop
                # any torn trailing rows AND any complete intervals past
                # the checkpoint (the recomputed tile appends once)
                for path, validate in (
                    [(cfg.out_solutions, solio.validate_global_z)]
                    + [(f"{cfg.out_solutions}.band{i}",
                        solio.validate_solutions)
                       for i in range(Nf)]
                ):
                    if not _os.path.exists(path):
                        raise ResumeRefused(
                            f"checkpoint {rpath} expects solution file "
                            f"{path}, which does not exist")
                    v = validate(path, truncate=True,
                                 max_intervals=resume_done)
                    if v["n_intervals"] < resume_done:
                        raise ResumeRefused(
                            f"{path} holds {v['n_intervals']} intervals "
                            f"but checkpoint {rpath} expects "
                            f"{resume_done}")

    # solution files: global Z + per-band J (slave :959-979 analog);
    # every handle is registered with the caller's finally-block
    zfh = open(cfg.out_solutions, "a" if resume_done else "w")
    open_files.append(zfh)
    if not resume_done:
        write_global_z_header(zfh, freq0, cfg.npoly, N, M, M * nchunk_max)
    band_fhs = []
    for i, path in enumerate(datasets):
        fh = open(f"{cfg.out_solutions}.band{i}",
                  "a" if resume_done else "w")
        open_files.append(fh)
        if not resume_done:
            solio.write_header(
                fh, metas[i].freq0, metas[i].deltaf,
                metas[i].deltat * cfg.tilesz / 60.0, N, M, M * nchunk_max,
            )
        band_fhs.append(fh)

    eye = jones_to_params(identity_jones(
        N, np.complex128 if cfg.use_f64 else np.complex64))
    p_bands = jnp.broadcast_to(
        eye, (Nf_pad, M, nchunk_max, n8)
    ).astype(dtype)

    traces = []
    zdiff_carry = None
    if resume_state is not None:
        # warm-start from the checkpointed carry; restore the completed
        # tiles' residual traces so the return value covers the whole run
        p_bands = jnp.asarray(resume_state["p_bands"], dtype)
        traces = [
            (np.asarray(d), np.asarray(p))
            for d, p in zip(resume_state["traces_dual"],
                            resume_state["traces_primal"])
        ]
        if "zdiff" in resume_state:
            zdiff_carry = jnp.asarray(resume_state["zdiff"], dtype)
    tile_starts = list(range(0, ntime, cfg.tilesz))
    pairs = [(i, t0) for i, t0 in enumerate(tile_starts)
             if i >= cfg.skip_tiles]
    if cfg.max_tiles:
        pairs = pairs[: cfg.max_tiles]
    pairs = pairs[resume_done:]
    # Per-band background prefetch of the FULL-SIZE tiles (the final
    # clamped partial tile loads directly): each band's next tile reads
    # while the mesh ADMM solves the current one (TilePrefetcher,
    # io/dataset.py — the fullbatch loop's loadData-overlap role).
    spec = [dict(average_channels=True, min_uvcut=cfg.min_uvcut,
                 max_uvcut=cfg.max_uvcut, dtype=dtype,
                 column=cfg.in_column)]
    full_t0s = [t0 for _, t0 in pairs
                if min(cfg.tilesz, ntime - t0) == cfg.tilesz]
    prefetchers = [
        TilePrefetcher(path, full_t0s, spec, cfg.tilesz, depth=1)
        for path in datasets
    ]
    from sagecal_tpu.obs.perf import TransferAudit, emit_perf_events
    from sagecal_tpu.utils.profiling import PhaseTimer, trace

    timer = PhaseTimer()
    # manual enter so the existing try/finally below owns the exits
    # (exception-safe: a crash still flushes a loadable XLA trace)
    trace_cm = trace()
    if trace_cm.__enter__():
        log("profiling: XLA trace enabled")
    audit = TransferAudit()
    audit.__enter__()

    def _prepare_tile(t0, zdiff):
        """Load + precompute one tile's per-band arrays.  All device
        work here is ASYNC-dispatched jit (JAX returns before compute
        finishes), so calling this between dispatching tile t's solve
        and blocking on its outputs overlaps the coherency precompute
        with the device solve — the role of the reference's per-tile
        threaded precalculate_coherencies (fullbatch_mode.cpp:371-388)
        without a host thread pool.  ``zdiff`` may be a LAZY device
        array from the in-flight solve (the diffuse chain stays on
        device, no sync)."""
        datas, cdatas, fratios = [], [], []
        # clamp the tile to the COMMON timeslot range so bands with more
        # timeslots than ntime_min still produce equal row counts on the
        # final partial tile (stack_for_mesh needs identical shapes)
        eff_tilesz = min(cfg.tilesz, ntime - t0)
        for bi, h in enumerate(handles):
            if eff_tilesz == cfg.tilesz:
                t0_chk, (d,) = next(pf_iters[bi])
                if t0_chk != t0:
                    raise RuntimeError(
                        f"band {bi} prefetch order mismatch: "
                        f"{t0_chk} != {t0}"
                    )
            else:
                # same kwargs as the prefetch spec so the two load
                # paths can never drift apart
                d = h.load_tile(t0, eff_tilesz, **spec[0])
            # static pytree fields must match across the stacked bands
            # (the per-channel ``freqs`` array carries each band's true
            # frequency; freq0/deltaf statics only matter pre-stack)
            d = d.replace(freq0=freq0, deltaf=meta0.deltaf)
            datas.append(d)
            cdata_b = build_cluster_data(d, clusters, nchunks,
                                         shapelets=shapelets)
            if diffuse_idx is not None and zdiff is not None:
                # re-predict the diffuse cluster from the previous
                # tile's diffuse-constrained spatial model
                # (slave:670-698; between-tiles by design, see
                # run_distributed docstring)
                from sagecal_tpu.ops.diffuse import (
                    recalculate_diffuse_coherencies,
                )
                from sagecal_tpu.parallel.spatial import bz_spatial

                Zb = bz_spatial(zdiff, B_pad[bi], N)
                cdata_b = recalculate_diffuse_coherencies(
                    d, cdata_b, diffuse_idx, clusters[diffuse_idx],
                    shapelets, Zb, spatial_n0, diffuse_beta,
                )
            cdatas.append(cdata_b)
            # LAZY unflagged fraction: a host float() here would block
            # behind the in-flight tile-t solve on an in-order device
            # stream, serializing 'prepare' after the solve; the sync
            # happens at the NEXT dispatch when the queue is free
            fratios.append(jnp.mean(d.mask))
        # zero-weight padding bands: replicate band 0 with mask 0
        for _ in range(Nf_pad - Nf):
            dpad = datas[0].replace(mask=jnp.zeros_like(datas[0].mask))
            datas.append(dpad)
            cdatas.append(cdatas[0])
            fratios.append(jnp.zeros(()))
        return datas, cdatas, fratios

    pf_iters = []

    def _ckpt_update(pi):
        """End-of-tile checkpoint: everything the loop carries across
        tiles, materialized to host numpy so a later signal-time flush
        never touches the device."""
        if ckmgr is None:
            return
        arrs = {
            "p_bands": np.asarray(p_bands),
            "traces_dual": np.asarray([d for d, _ in traces]),
            "traces_primal": np.asarray([p for _, p in traces]),
        }
        if zdiff_carry is not None:
            arrs["zdiff"] = np.asarray(zdiff_carry)
        ckmgr.update(resume_done + pi, arrs,
                     tiles_done=resume_done + pi + 1,
                     run_id=manifest.run_id)

    # root span for the whole run; manual enter so the existing
    # try/finally owns the exit (tile + phase spans nest under it)
    run_span = tracer.span("distributed", kind="run", bands=Nf, ndev=ndev,
                           nadmm=nadmm)
    run_span.__enter__()
    try:
      pf_iters = [iter(pf.__enter__()) for pf in prefetchers]
      prepared = None
      if pairs:
        with timer.phase("prepare"):
            prepared = _prepare_tile(pairs[0][1], zdiff_carry)
      for pi, (tile_no, t0) in enumerate(pairs):
        tic = time.time()
        tile_span = tracer.span("tile", kind="tile", tile=t0)
        tile_span.__enter__()
        datas, cdatas, fratios_lazy = prepared
        # sync the lazy per-band unflagged fractions NOW (the previous
        # tile's solve has been consumed, the queue is free)
        fratios = [float(np.asarray(f)) for f in fratios_lazy]
        # rho scaled by each band's unflagged fraction (master :709-723)
        rho = jnp.asarray(
            np.asarray(fratios)[:, None] * rho_m[None, :], dtype
        )
        if fn is None:
            # first tile: build the rebalanced fine-grained program on
            # this tile's unflagged-row fractions (padded bands get
            # zero weight -> their slots stop billing rounds)
            bw = np.zeros((Nf_pad,))
            bw[:Nf] = np.asarray(fratios[:Nf])
            fn = _build_mesh_fn(band_weights=bw)
        admm_start_unix = time.time()
        t_dispatch = time.perf_counter()
        with timer.phase("dispatch"):
            out = fn(
                stack_for_mesh(datas), stack_for_mesh(cdatas),
                p_bands, rho, jnp.asarray(B_pad, dtype),
            )
        p_bands = out.p  # warm start the next tile (reference keeps p)
        if diffuse_idx is not None:
            zdiff_carry = out.Zspat_diff  # lazy device array, no sync
        # overlap: prepare tile t+1 (I/O + coherency dispatch) while
        # the mesh solves tile t on device
        if pi + 1 < len(pairs):
            with timer.phase("prepare"):
                prepared = _prepare_tile(pairs[pi + 1][1], zdiff_carry)
        # close the ADMM device window AFTER the overlap work: this is
        # the first sync on tile t's outputs, so dispatch->here is the
        # tile's measured mesh-ADMM wall-time, attributed to synthetic
        # per-band / per-round child spans + straggler gauges
        with timer.phase("solve-wait"):
            out = jax.block_until_ready(out)
        admm_seconds = time.perf_counter() - t_dispatch
        band_secs, straggler = _emit_admm_attribution(
            tracer, elog, log, t0, admm_seconds, admm_start_unix,
            fratios, Nf, nadmm, Nf_pad // ndev,
            max(cfg.max_emiter, 2), cfg.max_emiter,
            cluster_groups=max(cfg.consensus_cluster_groups, 1))
        note_activity("tile", name=f"tile{t0}", seconds=admm_seconds)
        if mdl:
            # AIC/MDL consensus-order scan on this tile's rho-scaled
            # solutions (the master's -M path at admm==0,
            # sagecal_master.cpp:986-993)
            from sagecal_tpu.parallel.spatial import (
                minimum_description_length,
            )

            w = np.asarray(fratios[:Nf])
            Jst = (
                np.asarray(out.p[:Nf], np.float64).reshape(Nf, M, -1)
                * w[:, None, None] * np.asarray(rho_m)[None, :, None]
            )
            aic, mdl_s, k_aic, k_mdl = minimum_description_length(
                Jst, rho_m, freqs, freq0, weight=w,
                Kstart=1, Kfinish=max(cfg.npoly, 2),
            )
            log(f"tile {t0} MDL: best order AIC={k_aic} MDL={k_mdl} "
                f"(aic {np.array2string(aic, precision=2)}, "
                f"mdl {np.array2string(mdl_s, precision=2)})")
        with timer.phase("solve-wait+write"):
          append_global_z(zfh, out.Z, N, cfg.npoly, nchunk_max)
          zfh.flush()
          for i in range(Nf):
            jsol = np.asarray(params_to_jones(out.p[i])).reshape(
                M * nchunk_max, N, 2, 2
            )
            solio.append_solutions(band_fhs[i], jsol)
            # -U: residuals from the GLOBAL consensus solution B_f Z
            # instead of the per-band J (sagecal_slave.cpp:861-979
            # use_global_solution path)
            p_res = out.p[i]
            if global_residual:
                p_res = consensus.bz_for_freq(
                    out.Z, jnp.asarray(B_pad[i], dtype)
                ).reshape(M, nchunk_max, n8)
            res = calculate_residuals(
                datas[i], cdatas[i], p_res,
            )
            handles[i].write_tile(
                t0, np.asarray(mat_of_flat(res)), column=cfg.out_column
            )
        traces.append(
            (np.asarray(out.dual_res), np.asarray(out.primal_res))
        )
        _ckpt_update(pi)
        if elog is not None:
            # one event per tile = one consensus run of nadmm rounds;
            # band-resolved residuals + the rho trajectory when the mesh
            # fn was built with collect_trace
            extra = {}
            if out.primal_res_band is not None:
                extra["primal_res_band"] = np.asarray(out.primal_res_band)
                extra["dual_res_band"] = np.asarray(out.dual_res_band)
                extra["rho_trace"] = np.asarray(out.rho_trace)
            elog.emit(
                "admm_round", tile=t0, nadmm=nadmm,
                primal_res=np.asarray(out.primal_res),
                dual_res=np.asarray(out.dual_res),
                seconds=time.time() - tic,
                admm_seconds=admm_seconds, band_seconds=band_secs,
                straggler_ratio=straggler["ratio"],
                phase_seconds=timer.tile_timings(), **extra,
            )
        if out.primal_res_band is not None:
            # consensus watchdog: per-band residual trajectories ->
            # ratio/trend/diverged (parallel.consensus.consensus_health
            # via obs.quality.assess_consensus)
            from sagecal_tpu.obs.quality import (
                abort_if_diverged, assess_consensus,
            )

            verdict, reasons, health = assess_consensus(
                np.asarray(out.primal_res_band),
                np.asarray(out.dual_res_band),
            )
            if elog is not None:
                elog.emit("consensus_health", tile=t0, verdict=verdict,
                          reasons=reasons, ratio=health["ratio"],
                          trend=health["trend"])
                if verdict == "diverged":
                    elog.emit("solver_diverged", reasons=reasons,
                              tile=t0, app="distributed")
            if verdict != "ok":
                log(f"tile {t0}: consensus watchdog {verdict} "
                    f"({', '.join(reasons)})")
            if cfg.abort_on_divergence:
                abort_if_diverged(elog, verdict, reasons, tile=t0,
                                  app="distributed")
        log(
            f"tile {t0}: dual {float(out.dual_res[-1]):.3e} primal "
            f"{float(out.primal_res[-1]):.3e} ({time.time()-tic:.1f}s) "
            f"[{timer.tile_summary()}]"
        )
        tile_span.__exit__(None, None, None)
      log(f"phases: {timer.run_summary()}")
      if ckmgr is not None:
          ckmgr.flush()
          ckmgr.close()
      audit.__exit__(None, None, None)
      if elog is not None:
          from sagecal_tpu.obs.contracts import emit_contract_events

          emit_perf_events(elog)
          audit.emit(elog)
          emit_contract_events(elog)
          elog.emit("run_done", n_tiles=len(traces),
                    phase_totals=dict(timer.totals))
          elog.close()
          unregister_event_log(elog)
      # end-of-run spatial-model amplitude plot (the master's PPM
      # output, sagecal_master.cpp:1198 / pngoutput.c) from the final
      # tile's Zspat — shapelet basis only (the plot evaluates the
      # image-plane shapelet series)
      if (spatial_n0 > 0 and spatial_basis == "shapelet" and pairs
              and out.Zspat is not None):
          from sagecal_tpu.utils.ppm import plot_spatial_model

          ppm_path = f"{cfg.out_solutions}.spatial.ppm"
          plot_spatial_model(
              np.asarray(out.Zspat), cfg.npoly, N, spatial_n0,
              beta=diffuse_beta or spatial_beta, path=ppm_path,
          )
          log(f"spatial model plot -> {ppm_path}")
    finally:
        # reap every band's prefetch thread even on a mid-loop failure;
        # the audit exit is idempotent (already closed on the happy
        # path above) and the trace CM only stops a trace it started
        for pf in prefetchers:
            pf.__exit__(None, None, None)
        audit.__exit__(None, None, None)
        trace_cm.__exit__(None, None, None)
        run_span.__exit__(None, None, None)
        # writes the Chrome trace (trace.json) alongside the span JSONL
        close_tracer()

    # success path only: a raise above must leave the recorder (ring)
    # alive for the excepthook's forensic dump
    close_flight_recorder()
    return traces
