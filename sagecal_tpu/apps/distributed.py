"""Distributed multi-band calibration driver: the ``sagecal-mpi`` binary.

Redesign of the MPI master/slave application pair
(``/root/reference/src/MPI/sagecal_master.cpp:41-1316`` /
``sagecal_slave.cpp``): one SPMD program over a ``('freq',)`` device
mesh replaces the rank-0 master + per-MS slaves.  The per-timeslot tile
loop (master :694-), metadata consistency checks (:238-287), fratio
scaling of rho (:709-723), the consensus-ADMM iteration
(:func:`sagecal_tpu.parallel.mesh.make_admm_mesh_fn`), the global-Z
solution file (:499-533, :1165-1175), per-band solution files and
residual write-back (slave :959-979) all live here; the MPI tag
protocol (proto.h) has no equivalent because the z-step psum and the
manifold-average all_gather are compiled collectives.

Multi-host: pass ``multihost=True`` to call
``jax.distributed.initialize()`` before touching devices — the same
mesh code then spans hosts over DCN (each host feeds its local bands).
"""

from __future__ import annotations

import glob
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sagecal_tpu.apps.config import RunConfig
from sagecal_tpu.core.types import (
    identity_jones,
    jones_to_params,
    mat_of_flat,
    params_to_jones,
)
from sagecal_tpu.io import solutions as solio
from sagecal_tpu.io.dataset import TilePrefetcher, VisDataset
from sagecal_tpu.io.skymodel import load_sky, read_cluster_rho
from sagecal_tpu.ops.residual import calculate_residuals
from sagecal_tpu.parallel import consensus
from sagecal_tpu.parallel.mesh import (
    SpatialConfig,
    make_admm_mesh_fn,
    stack_for_mesh,
)
from sagecal_tpu.solvers.lm import LMConfig
from sagecal_tpu.solvers.sage import build_cluster_data


def write_global_z_header(fh, freq0_hz, npoly, nstations, nclusters, neff):
    """Global-Z solution file header (sagecal_master.cpp:515-517)."""
    fh.write("# solution file (Z) created by SAGECal\n")
    fh.write("# reference_freq(MHz) polynomial_order stations clusters "
             "effective_clusters\n")
    fh.write(f"{freq0_hz * 1e-6:.6f} {npoly} {nstations} {nclusters} {neff}\n")


def append_global_z(fh, Z, nstations, npoly, nchunk_max):
    """One timeslot's Z rows (sagecal_master.cpp:1165-1175): row p of
    N*8*Npoly values, effective-cluster columns in REVERSE order.

    Z: (M, Npoly, nchunk_max*8N) real.
    """
    M = Z.shape[0]
    n8 = 8 * nstations
    # effective cluster (m, c) -> (Npoly*8N,) with p = poly*8N + i
    Zb = np.asarray(Z).reshape(M, npoly, nchunk_max, n8)
    cols = [
        Zb[m, :, c, :].reshape(-1)
        for m in range(M) for c in range(nchunk_max)
    ]
    cols = cols[::-1]  # reverse effective-cluster ordering
    rows = npoly * n8
    for p in range(rows):
        vals = " ".join(f"{col[p]:e}" for col in cols)
        fh.write(f"{p} {vals}\n")


def _check_band_consistency(metas, log):
    """The master's metadata validation (sagecal_master.cpp:238-287):
    all bands must agree on N / nbase / timeslot count."""
    n0, nb0, nt0 = metas[0].nstations, metas[0].nbase, metas[0].ntime
    for i, m in enumerate(metas[1:], 1):
        if (m.nstations, m.nbase) != (n0, nb0):
            raise ValueError(
                f"band {i}: station/baseline layout mismatch "
                f"({m.nstations},{m.nbase}) != ({n0},{nb0})"
            )
        if m.ntime != nt0:
            log(f"warning: band {i} has {m.ntime} timeslots != {nt0}; "
                f"using the minimum")
    return min(m.ntime for m in metas)


def run_distributed(
    cfg: RunConfig,
    datasets: Optional[Sequence[str]] = None,
    log=print,
    multihost: bool = False,
    nadmm: Optional[int] = None,
    spatial_n0: int = 0,
    spatial_beta: float = 0.01,
    spatial_mu: float = 1e-3,
    spatial_alpha: float = 0.0,
    spatial_cadence: int = 2,
):
    """Calibrate a multi-band observation on the device mesh.

    ``datasets``: explicit band file list, or None to expand
    ``cfg.dataset`` as a glob (the reference's ``-f 'pattern'``,
    sagecal_master.cpp:60-224 MS discovery).  Returns per-tile lists of
    (dual_res, primal_res) traces.

    ``spatial_n0 > 0`` switches on spatial regularization inside the
    ADMM loop (shapelet basis of order n0, the master's -U path).
    """
    if multihost:
        jax.distributed.initialize()
    if datasets is None:
        datasets = sorted(glob.glob(cfg.dataset))
    if not datasets:
        raise ValueError(f"no band datasets match {cfg.dataset!r}")
    nadmm = nadmm if nadmm is not None else max(cfg.admm_iters, 2)
    dtype = np.float64 if cfg.use_f64 else np.float32

    handles: List[VisDataset] = [VisDataset(p, "r+") for p in datasets]
    open_files: List = []
    try:
        return _run_distributed_inner(
            cfg, datasets, handles, open_files, log, nadmm, dtype,
            spatial_n0, spatial_beta, spatial_mu, spatial_alpha,
            spatial_cadence,
        )
    finally:
        for fh in open_files:
            try:
                fh.close()
            except Exception:
                pass
        for h in handles:
            try:
                h.close()
            except Exception:
                pass


def _run_distributed_inner(
    cfg, datasets, handles, open_files, log, nadmm, dtype,
    spatial_n0, spatial_beta, spatial_mu, spatial_alpha, spatial_cadence,
):
    metas = [h.meta for h in handles]
    ntime = _check_band_consistency(metas, log)
    meta0 = metas[0]
    N = meta0.nstations
    freqs = np.asarray([m.freq0 for m in metas])
    freq0 = float(np.mean(freqs))

    clusters, cdefs = load_sky(
        cfg.sky_model, cfg.cluster_file, meta0.ra0, meta0.dec0, dtype=dtype
    )
    M = len(clusters)
    nchunks = [cd.nchunk for cd in cdefs]
    nchunk_max = max(nchunks)
    n8 = 8 * N

    # per-cluster rho (and spatial alpha) from the -G file when given
    if cfg.rho_file:
        rho_m, alpha_m = read_cluster_rho(
            cfg.rho_file, cdefs, spatialreg=True
        )
    else:
        rho_m = np.full((M,), cfg.admm_rho)
        alpha_m = np.full((M,), spatial_alpha)

    # pad band count to a mesh multiple with zero-weight bands
    devs = jax.devices()
    Nf = len(datasets)
    ndev = min(len(devs), Nf)
    Nf_pad = -(-Nf // ndev) * ndev
    mesh = Mesh(np.array(devs[:ndev]), ("freq",))
    log(f"distributed: {Nf} bands on {ndev} devices"
        + (f" (padded to {Nf_pad})" if Nf_pad != Nf else ""))

    B = consensus.setup_polynomials(freqs, freq0, cfg.npoly, cfg.poly_type)
    B_pad = np.concatenate(
        [B, np.tile(B[-1:], (Nf_pad - Nf, 1))], axis=0
    ) if Nf_pad != Nf else B

    spatial = None
    if spatial_n0 > 0:
        from sagecal_tpu.parallel.spatial import build_spatial_basis, phikk_matrix

        # flux-weighted cluster centroids (the master's spatial-basis
        # setup computes these from the sky model, :293-423)
        def _centroid(c):
            w = np.maximum(np.abs(np.asarray(c.sI0)), 1e-12)
            return (
                float(np.average(np.asarray(c.ll), weights=w)),
                float(np.average(np.asarray(c.mm), weights=w)),
            )

        cent = [_centroid(c) for c in clusters]
        lls = np.asarray([x[0] for x in cent])
        mms = np.asarray([x[1] for x in cent])
        # effective clusters repeat their centroid per hybrid chunk
        lle = np.repeat(lls, nchunk_max)
        mme = np.repeat(mms, nchunk_max)
        Phi = build_spatial_basis(lle, mme, n0=spatial_n0, beta=spatial_beta)
        spatial = SpatialConfig(
            Phi=Phi, Phikk=phikk_matrix(Phi, lam=1e-6),
            alpha=jnp.asarray(
                np.where(alpha_m > 0, alpha_m, cfg.admm_rho), dtype
            ),
            mu=spatial_mu, cadence=spatial_cadence,
        )

    fn = make_admm_mesh_fn(
        mesh, nadmm=nadmm, max_emiter=cfg.max_emiter,
        plain_emiter=max(cfg.max_emiter, 2),
        lm_config=LMConfig(itmax=cfg.max_iter),
        bb_rho=True, solver_mode=cfg.solver_mode,
        spatial=spatial,
    )

    # solution files: global Z + per-band J (slave :959-979 analog);
    # every handle is registered with the caller's finally-block
    zfh = open(cfg.out_solutions, "w")
    open_files.append(zfh)
    write_global_z_header(zfh, freq0, cfg.npoly, N, M, M * nchunk_max)
    band_fhs = []
    for i, path in enumerate(datasets):
        fh = open(f"{cfg.out_solutions}.band{i}", "w")
        open_files.append(fh)
        solio.write_header(
            fh, metas[i].freq0, metas[i].deltaf,
            metas[i].deltat * cfg.tilesz / 60.0, N, M, M * nchunk_max,
        )
        band_fhs.append(fh)

    eye = jones_to_params(identity_jones(
        N, np.complex128 if cfg.use_f64 else np.complex64))
    p_bands = jnp.broadcast_to(
        eye, (Nf_pad, M, nchunk_max, n8)
    ).astype(dtype)

    traces = []
    tile_starts = list(range(0, ntime, cfg.tilesz))
    pairs = [(i, t0) for i, t0 in enumerate(tile_starts)
             if i >= cfg.skip_tiles]
    if cfg.max_tiles:
        pairs = pairs[: cfg.max_tiles]
    # Per-band background prefetch of the FULL-SIZE tiles (the final
    # clamped partial tile loads directly): each band's next tile reads
    # while the mesh ADMM solves the current one (TilePrefetcher,
    # io/dataset.py — the fullbatch loop's loadData-overlap role).
    spec = [dict(average_channels=True, min_uvcut=cfg.min_uvcut,
                 max_uvcut=cfg.max_uvcut, dtype=dtype)]
    full_t0s = [t0 for _, t0 in pairs
                if min(cfg.tilesz, ntime - t0) == cfg.tilesz]
    prefetchers = [
        TilePrefetcher(path, full_t0s, spec, cfg.tilesz, depth=1)
        for path in datasets
    ]
    pf_iters = []
    try:
      pf_iters = [iter(pf.__enter__()) for pf in prefetchers]
      for tile_no, t0 in pairs:
        tic = time.time()
        datas, cdatas, fratios = [], [], []
        # clamp the tile to the COMMON timeslot range so bands with more
        # timeslots than ntime_min still produce equal row counts on the
        # final partial tile (stack_for_mesh needs identical shapes)
        eff_tilesz = min(cfg.tilesz, ntime - t0)
        for bi, h in enumerate(handles):
            if eff_tilesz == cfg.tilesz:
                t0_chk, (d,) = next(pf_iters[bi])
                if t0_chk != t0:
                    raise RuntimeError(
                        f"band {bi} prefetch order mismatch: "
                        f"{t0_chk} != {t0}"
                    )
            else:
                # same kwargs as the prefetch spec so the two load
                # paths can never drift apart
                d = h.load_tile(t0, eff_tilesz, **spec[0])
            # static pytree fields must match across the stacked bands
            # (the per-channel ``freqs`` array carries each band's true
            # frequency; freq0/deltaf statics only matter pre-stack)
            d = d.replace(freq0=freq0, deltaf=meta0.deltaf)
            datas.append(d)
            cdatas.append(build_cluster_data(d, clusters, nchunks))
            fratios.append(float(jnp.mean(d.mask)))
        # zero-weight padding bands: replicate band 0 with mask 0
        for _ in range(Nf_pad - Nf):
            dpad = datas[0].replace(mask=jnp.zeros_like(datas[0].mask))
            datas.append(dpad)
            cdatas.append(cdatas[0])
            fratios.append(0.0)
        # rho scaled by each band's unflagged fraction (master :709-723)
        rho = jnp.asarray(
            np.asarray(fratios)[:, None] * rho_m[None, :], dtype
        )
        out = fn(
            stack_for_mesh(datas), stack_for_mesh(cdatas),
            p_bands, rho, jnp.asarray(B_pad, dtype),
        )
        p_bands = out.p  # warm start the next tile (reference keeps p)
        append_global_z(zfh, out.Z, N, cfg.npoly, nchunk_max)
        zfh.flush()
        for i in range(Nf):
            jsol = np.asarray(params_to_jones(out.p[i])).reshape(
                M * nchunk_max, N, 2, 2
            )
            solio.append_solutions(band_fhs[i], jsol)
            res = calculate_residuals(
                datas[i], cdatas[i], out.p[i],
            )
            handles[i].write_tile(
                t0, np.asarray(mat_of_flat(res)), column="corrected"
            )
        traces.append(
            (np.asarray(out.dual_res), np.asarray(out.primal_res))
        )
        log(
            f"tile {t0}: dual {float(out.dual_res[-1]):.3e} primal "
            f"{float(out.primal_res[-1]):.3e} ({time.time()-tic:.1f}s)"
        )
    finally:
        # reap every band's prefetch thread even on a mid-loop failure
        for pf in prefetchers:
            pf.__exit__(None, None, None)

    return traces
