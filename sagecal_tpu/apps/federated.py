"""Federated stochastic calibration driver — the ``sagecal-mpi -N``
mode end-to-end.

Redesign of the stochastic MPI pair
(``/root/reference/src/MPI/sagecal_stochastic_master.cpp`` /
``sagecal_stochastic_slave.cpp``): per solution tile, ``nadmm``
federated rounds each running ``epochs x minibatches`` consensus
minibatch-LBFGS passes over the tile's timeslots with PERSISTENT
curvature memory per band (slave:637-638, 671-855), a per-band local
z-step tied to the federated average with the alpha constraint, and a
manifold-averaging round-trip at the reference's cadence (after each
epoch block; master:347, slave:856-868).  Bands map to the mesh's
``freq`` axis — the MPI star becomes an ``all_gather`` + replicated
manifold math.

Reset protocol (CTRL_RESET, slave:1044-1066 / stochastic_master.cpp:360):
after each federated round, any band whose data cost is non-finite or
grew by more than ``reset_ratio`` over its tile-start cost resets its
solutions, duals, and LBFGS memory (``lbfgs_persist_reset``) and
rejoins from identity; when a majority of bands reset in one round the
driver logs the master's "Most slaves did not converge" warning.
"""

from __future__ import annotations

import glob
import time
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from sagecal_tpu.apps.config import RunConfig
from sagecal_tpu.core.types import identity_jones, jones_to_params, params_to_jones
from sagecal_tpu.io import solutions as solio
from sagecal_tpu.io.dataset import VisDataset
from sagecal_tpu.io.skymodel import load_sky
from sagecal_tpu.parallel import consensus
from sagecal_tpu.parallel.federated import (
    FederatedState,
    init_federated_state,
    make_fed_avg_fn,
    make_federated_minibatch_fn,
)
from sagecal_tpu.solvers.sage import build_cluster_data


def _reset_band(state: FederatedState, band: int, p_init) -> FederatedState:
    """CTRL_RESET analog for one band: fresh p/Y/Z/Zbar/X and LBFGS
    memory (slave:1044-1060, lbfgs_persist_reset Dirac.h:133-136)."""
    z0 = jnp.zeros_like(state.Z[band])
    mem_b = jax.tree_util.tree_map(lambda x: jnp.zeros_like(x[band]),
                                   state.mem)
    return FederatedState(
        p=state.p.at[band].set(p_init),
        Y=state.Y.at[band].set(jnp.zeros_like(state.Y[band])),
        Z=state.Z.at[band].set(z0),
        Zbar=state.Zbar.at[band].set(z0),
        X=state.X.at[band].set(z0),
        mem=jax.tree_util.tree_map(
            lambda full, zb: full.at[band].set(zb), state.mem, mem_b
        ),
    )


def run_federated(
    cfg: RunConfig,
    datasets: Optional[Sequence[str]] = None,
    log=print,
    nadmm: int = 4,
    epochs: int = 2,
    minibatches: int = 2,
    alpha: float = 5.0,
    robust_nu: Optional[float] = None,
    reset_ratio: float = 5.0,
):
    """Run the federated stochastic mode over per-band datasets.

    Per tile of ``cfg.tilesz`` timeslots: nadmm federated rounds, each
    epochs x minibatches minibatch passes (time_per_minibatch =
    ceil(tilesz/minibatches), slave:138), then the Z -> Zavg manifold
    round-trip.  Returns per-tile lists of (dual_res trace, resets).
    """
    from sagecal_tpu.obs.perf import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    if datasets is None:
        datasets = sorted(glob.glob(cfg.dataset))
    if not datasets:
        raise ValueError(f"no band datasets match {cfg.dataset!r}")
    dtype = np.float64 if cfg.use_f64 else np.float32

    handles: List[VisDataset] = [VisDataset(p, "r") for p in datasets]
    open_files: List = []
    try:
        return _run_inner(cfg, datasets, handles, open_files, log, nadmm,
                          epochs, minibatches, alpha, robust_nu,
                          reset_ratio, dtype)
    finally:
        for fh in open_files:
            try:
                fh.close()
            except Exception:
                pass
        for h in handles:
            h.close()


def _run_inner(cfg, datasets, handles, open_files, log, nadmm, epochs,
               minibatches, alpha, robust_nu, reset_ratio, dtype):
    metas = [h.meta for h in handles]
    meta0 = metas[0]
    N = meta0.nstations
    Nf = len(datasets)
    ntime = min(m.ntime for m in metas)
    freqs = np.asarray([m.freq0 for m in metas])
    freq0 = float(np.mean(freqs))

    # telemetry + crash forensics (the federated driver joins the same
    # event-log / span / heartbeat lifecycle as the other apps)
    from sagecal_tpu.obs import RunManifest, default_event_log
    from sagecal_tpu.obs.flight import (
        close_flight_recorder,
        get_flight_recorder,
        install_crash_handlers,
        note_activity,
        register_event_log,
        unregister_event_log,
    )
    from sagecal_tpu.obs.trace import close_tracer, configure_tracer, get_tracer

    manifest = RunManifest.collect(
        app="federated", bands=Nf, nadmm=nadmm, epochs=epochs,
        minibatches=minibatches, solver_mode=cfg.solver_mode,
        n_stations=N,
    )
    elog = default_event_log(manifest=manifest)
    install_crash_handlers()
    if elog is not None:
        register_event_log(elog)
    get_flight_recorder(run_id=manifest.run_id)
    configure_tracer(run_id=manifest.run_id)
    tracer = get_tracer()

    clusters, cdefs, shapelets = load_sky(
        cfg.sky_model, cfg.cluster_file, meta0.ra0, meta0.dec0, dtype=dtype,
        three_term_spectra=None if cfg.sky_format < 0 else bool(cfg.sky_format),
    )
    M = len(clusters)
    nchunks = [cd.nchunk for cd in cdefs]
    nchunk_max = max(nchunks)
    n8 = 8 * N

    devs = np.array(jax.devices()[:Nf])
    if len(devs) < Nf:
        raise ValueError(f"{Nf} bands need {Nf} devices, have {len(devs)}")
    mesh = Mesh(devs, ("freq",))
    B = consensus.setup_polynomials(freqs, freq0, cfg.npoly, cfg.poly_type)
    B = jnp.asarray(B, dtype)
    rho = jnp.full((Nf, M), cfg.admm_rho, dtype)

    step_fn = make_federated_minibatch_fn(
        mesh, itmax=cfg.max_lbfgs or 8, lbfgs_m=cfg.lbfgs_m or 7,
        alpha=alpha, robust_nu=robust_nu,
    )
    avg_fn = make_fed_avg_fn(mesh, alpha=alpha)

    eye = jones_to_params(identity_jones(
        N, np.complex128 if cfg.use_f64 else np.complex64))
    p_init = jnp.broadcast_to(eye, (M, nchunk_max, n8)).astype(dtype)

    # elastic execution (sagecal_tpu/elastic/): the whole FederatedState
    # pytree (p/Y/Z/Zbar/X + LBFGS memory) is the only cross-tile carry,
    # so per-tile checkpoints of its flattened leaves make a restart
    # resume exactly where the killed run stopped
    ckmgr = None
    resume_state = None
    resume_done = 0  # completed tiles
    if cfg.resume or cfg.checkpoint_every > 0:
        import os as _os

        from sagecal_tpu.elastic import (
            CheckpointManager,
            ResumeRefused,
            config_fingerprint,
        )

        fingerprint = config_fingerprint(
            app="federated",
            datasets=[_os.path.abspath(p) for p in datasets],
            sky_model=_os.path.abspath(cfg.sky_model),
            cluster_file=_os.path.abspath(cfg.cluster_file),
            nstations=N, ntime=ntime, nbands=Nf,
            freqs=[float(f) for f in freqs],
            nadmm=nadmm, epochs=epochs, minibatches=minibatches,
            tilesz=cfg.tilesz, npoly=cfg.npoly, poly_type=cfg.poly_type,
            admm_rho=cfg.admm_rho, alpha=alpha, robust_nu=robust_nu,
            reset_ratio=reset_ratio, max_lbfgs=cfg.max_lbfgs,
            lbfgs_m=cfg.lbfgs_m, use_f64=cfg.use_f64,
            in_column=cfg.in_column,
        )
        ckmgr = CheckpointManager(
            cfg.checkpoint_dir or f"{cfg.out_solutions}.ckpt",
            fingerprint, "federated", every=max(cfg.checkpoint_every, 1),
            elog=elog, log=log,
        )
        if cfg.resume:
            found = ckmgr.resume()
            if found is not None:
                rmeta, resume_state, rpath = found
                resume_done = int(rmeta["tiles_done"])
                for i in range(Nf):
                    path = f"{cfg.out_solutions}.band{i}"
                    if not _os.path.exists(path):
                        raise ResumeRefused(
                            f"checkpoint {rpath} expects solution file "
                            f"{path}, which does not exist")
                    v = solio.validate_solutions(
                        path, truncate=True, max_intervals=resume_done)
                    if v["n_intervals"] < resume_done:
                        raise ResumeRefused(
                            f"{path} holds {v['n_intervals']} intervals "
                            f"but checkpoint {rpath} expects "
                            f"{resume_done}")

    # per-band solution files
    band_fhs = []
    for i, path in enumerate(datasets):
        fh = open(f"{cfg.out_solutions}.band{i}",
                  "a" if resume_done else "w")
        open_files.append(fh)
        if not resume_done:
            solio.write_header(
                fh, metas[i].freq0, metas[i].deltaf,
                metas[i].deltat * cfg.tilesz / 60.0, N, M, M * nchunk_max,
            )
        band_fhs.append(fh)

    tmb = -(-cfg.tilesz // minibatches)  # time per minibatch (slave:138)
    results = []
    state = init_federated_state(Nf, M, nchunk_max, n8, cfg.npoly,
                                 cfg.lbfgs_m or 7, dtype)
    if resume_state is not None:
        from sagecal_tpu.elastic import unflatten_state

        # the freshly-initialized state is the unflatten template (same
        # treedef); restore the carried pytree + per-tile results
        state = unflatten_state("state", resume_state, state)
        rr = resume_state["results_resets"]
        results = [
            (np.asarray(resume_state[f"results_dres.{i}"]), int(rr[i]))
            for i in range(len(rr))
        ]
    spec = dict(average_channels=True, min_uvcut=cfg.min_uvcut,
                max_uvcut=cfg.max_uvcut, dtype=dtype,
                column=cfg.in_column)

    from sagecal_tpu.parallel.mesh import stack_for_mesh

    def _ckpt_update(ti):
        """End-of-tile checkpoint: the FederatedState leaves plus the
        per-tile (dual-res trace, resets) results, host-materialized so
        a signal-time flush never touches the device."""
        if ckmgr is None:
            return
        from sagecal_tpu.elastic import flatten_state

        arrs = dict(flatten_state("state", state))
        arrs["results_resets"] = np.asarray(
            [r for _, r in results], np.int64)
        for i, (d, _) in enumerate(results):
            arrs[f"results_dres.{i}"] = np.asarray(d)
        ckmgr.update(resume_done + ti, arrs,
                     tiles_done=resume_done + ti + 1,
                     run_id=manifest.run_id)

    run_span = tracer.span("federated", kind="run", bands=Nf,
                           nadmm=nadmm, epochs=epochs)
    run_span.__enter__()
    tile_starts = list(range(0, ntime, cfg.tilesz))[resume_done:]
    for ti, t0 in enumerate(tile_starts):
        tic = time.time()
        tile_span = tracer.span("tile", kind="tile", tile=t0)
        tile_span.__enter__()
        eff = min(cfg.tilesz, ntime - t0)
        # minibatch time-slices of this tile; per-band loads + cdata
        slices = [(t0 + s, min(tmb, t0 + eff - (t0 + s)))
                  for s in range(0, eff, tmb)]
        mb_data = []
        for (s0, slen) in slices:
            ds, cs = [], []
            for h in handles:
                d = h.load_tile(s0, slen, **spec)
                d = d.replace(freq0=freq0, deltaf=meta0.deltaf)
                ds.append(d)
                cs.append(build_cluster_data(d, clusters, nchunks,
                                             shapelets=shapelets))
            mb_data.append((stack_for_mesh(ds), stack_for_mesh(cs)))

        dres_trace: List[float] = []
        resets_total = 0
        cost0 = None
        # bounded-staleness coupling (--consensus-staleness K): the
        # manifold-averaging consensus step runs every K+1 rounds, so a
        # band's local trajectory may drift up to K rounds from the
        # federated average before being pulled back — the federated
        # analog of the minibatch loop's stale Gram terms.  K=0 (the
        # default) averages every round, unchanged.
        avg_every = max(int(cfg.consensus_staleness), 0) + 1
        if avg_every > 1 and elog is not None and ti == 0:
            elog.emit("async_schedule", staleness=avg_every - 1,
                      avg_every=avg_every, nadmm=nadmm)
        for admm in range(nadmm):
            # real per-round span: the np.asarray(cost) below syncs the
            # round's device work, so the measured window is honest
            round_span = tracer.span("fed.round", kind="admm_round",
                                     round=admm, tile=t0)
            round_span.__enter__()
            for ep in range(epochs):
                for mb, (dst, cst) in enumerate(mb_data):
                    state, dres, cost = step_fn(dst, cst, state, rho, B)
                    dres_trace.append(float(dres))
            if (admm + 1) % avg_every == 0 or admm == nadmm - 1:
                # always average on the last round so the written
                # solutions reflect a coupled state
                state = avg_fn(state)
            cost_np = np.asarray(cost)
            if cost0 is None:
                cost0 = np.where(np.isfinite(cost_np), cost_np, np.inf)
            else:
                # re-base the divergence baseline for bands that were
                # reset (their from-identity restart cost would
                # otherwise trip the ratio against the old converged
                # cost0 every round, resetting them forever)
                rebase = np.isinf(cost0) & np.isfinite(cost_np)
                cost0 = np.where(rebase, cost_np, cost0)
            # CTRL_RESET analog (slave:1044-1066, res_ratio)
            bad = ~np.isfinite(cost_np) | (cost_np > reset_ratio * cost0)
            for b in np.nonzero(bad)[0]:
                log(f"tile {t0} round {admm}: band {b} diverged "
                    f"(cost {cost_np[b]:.3e}) - reset")
                if elog is not None:
                    elog.emit("band_reset", tile=t0, round=admm,
                              band=int(b), cost=float(cost_np[b]))
                state = _reset_band(state, int(b), p_init)
                cost0[b] = np.inf  # re-base on the next finite cost
                resets_total += 1
            if bad.sum() * 2 > Nf:
                # stochastic_master.cpp:360
                log(f"tile {t0} round {admm}: Most bands did not "
                    f"converge ({int(bad.sum())}/{Nf} reset)")
            round_span.__exit__(None, None, None)
            if elog is not None:
                elog.emit("fed_round", tile=t0, round=admm,
                          dual_res=dres_trace[-1] if dres_trace else None,
                          resets=int(bad.sum()))
        for i in range(Nf):
            jsol = np.asarray(params_to_jones(state.p[i])).reshape(
                M * nchunk_max, N, 2, 2
            )
            solio.append_solutions(band_fhs[i], jsol)
            band_fhs[i].flush()
        note_activity("tile", name=f"tile{t0}", seconds=time.time() - tic)
        tile_span.__exit__(None, None, None)
        if elog is not None:
            elog.emit("tile_done", tile=t0, resets=resets_total,
                      dual_res=dres_trace[-1] if dres_trace else None,
                      seconds=time.time() - tic)
        log(f"tile {t0}: dual {dres_trace[-1]:.3e} "
            f"resets {resets_total} ({time.time() - tic:.1f}s)")
        results.append((np.asarray(dres_trace), resets_total))
        _ckpt_update(ti)
    if ckmgr is not None:
        ckmgr.flush()
        ckmgr.close()
    run_span.__exit__(None, None, None)
    close_tracer()
    if elog is not None:
        elog.emit("run_done", n_tiles=len(results))
        elog.close()
        unregister_event_log(elog)
    # success path only: leaves the final "closed" heartbeat; a crash
    # keeps the recorder alive for the excepthook's dump
    close_flight_recorder()
    return results
