"""``sagecal-tpu fleet``: multi-worker serve mesh over a shared
filesystem work queue (sagecal_tpu/fleet/).

Two roles share one entry point:

- ``--role coordinator`` (default) seeds the queue from the request
  manifest, spawns ``--workers`` worker subprocesses, watches the
  lease files, and prints the merged fleet summary;
- ``--role worker`` (normally spawned BY the coordinator, but valid
  standalone — point any number of hosts at the same queue directory)
  runs the claim-solve-complete loop.

Workers share compiled executables through the cross-worker AOT
artifact store: only the first worker to touch a bucket compiles.

Exit codes: 0 queue fully drained; 4 requests left undrained.
"""

from __future__ import annotations

import argparse
import os
import sys

from sagecal_tpu.apps.config import FleetConfig


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu fleet",
        description="Coordinator + N workers draining a shared "
        "filesystem work queue with atomic lease files.")
    ap.add_argument("--requests", default="",
                    help="request manifest (JSON; serve/request.py)")
    ap.add_argument("--out-dir", default="fleet-out")
    ap.add_argument("--queue-dir", default="",
                    help="shared queue directory "
                    "(default <out-dir>/queue)")
    ap.add_argument("--aot-store", default="",
                    help="shared AOT artifact store "
                    "(default <out-dir>/aot-store)")
    ap.add_argument("--workers", type=int, default=2,
                    help="worker subprocesses the coordinator spawns")
    ap.add_argument("--role", choices=("coordinator", "worker"),
                    default="coordinator")
    ap.add_argument("--worker-id", default="",
                    help="stable worker identity (worker role)")
    ap.add_argument("--batch", type=int, default=4,
                    help="max requests claimed (and vmapped) per cycle")
    ap.add_argument("--lease-ttl", type=float, default=30.0,
                    help="lease expiry; a killed worker's claims "
                    "requeue after this many seconds")
    ap.add_argument("--poll", type=float, default=0.2,
                    help="idle queue poll period (s)")
    ap.add_argument("--max-idle", type=float, default=10.0,
                    help="worker exits after this long with nothing "
                    "claimable")
    ap.add_argument("--large-stations", type=int, default=0,
                    help="requests with >= this many stations are "
                    "placed on sharded_joint_fit across all local "
                    "devices (0 = always use batch lanes)")
    ap.add_argument("--overload-policy",
                    choices=("shed", "degrade", "off"),
                    default="degrade",
                    help="admission action while a tenant's SLO "
                    "shed_burn threshold is tripped")
    ap.add_argument("--degrade-emiter", type=int, default=1)
    ap.add_argument("--degrade-lbfgs", type=int, default=4)
    ap.add_argument("--max-streams", type=int, default=8,
                    help="cap on concurrently open prefetch streams "
                    "per worker (LRU-evicted above)")
    ap.add_argument("--synthetic", type=int, default=0, metavar="N",
                    help="ignore --requests and seed N synthetic "
                    "requests (coordinator role)")
    ap.add_argument("--tenants", type=int, default=2,
                    help="tenant count for --synthetic")
    ap.add_argument("-e", "--max-emiter", type=int, default=3)
    ap.add_argument("-g", "--max-iter", type=int, default=2)
    ap.add_argument("-l", "--max-lbfgs", type=int, default=10)
    ap.add_argument("-m", "--lbfgs-m", type=int, default=7)
    ap.add_argument("-j", "--solver-mode", type=int, default=3)
    ap.add_argument("-L", "--nulow", type=float, default=2.0)
    ap.add_argument("-H", "--nuhigh", type=float, default=30.0)
    ap.add_argument("-R", "--no-randomize", action="store_true")
    ap.add_argument("--f32", action="store_true",
                    help="solve in float32 (TPU-native precision)")
    ap.add_argument("--fused", action="store_true",
                    help="route workers' batch solves through the fused "
                    "Pallas kernels — one batched grid per bucket when "
                    "the capability checks pass.  Requires --f32; "
                    "ignored under f64")
    ap.add_argument("--coh-dtype", choices=("f32", "bf16"), default="f32",
                    help="coherency-stack storage dtype on the fused "
                    "paths (bf16 halves the dominant HBM stream, f32 "
                    "accumulation)")
    ap.add_argument("--slo", default="",
                    help="per-tenant SLO specs (slo.json); also drives "
                    "admission control deadlines; falls back to a "
                    "'slos' key in the request manifest")
    ap.add_argument("--shadow-rate", type=float, default=0.0,
                    help="fraction of requests each worker shadow "
                    "re-solves on the XLA/f32 reference path after "
                    "their manifests land, appending drift records to "
                    "the shared <out-dir>/drift.jsonl (obs/shadow.py)")
    ap.add_argument("--shadow-budget-s", type=float, default=120.0,
                    help="per-worker wall-clock budget for shadow "
                    "re-solves; sampled requests past it are skipped "
                    "and counted")
    ap.add_argument("--shadow-seed", type=int, default=0,
                    help="sampler seed: same seed -> same sampled "
                    "request ids fleet-wide, whichever worker claims")
    ap.add_argument("--abort-on-drift", action="store_true",
                    help="workers escalate a drift-tolerance breach "
                    "from report-only to an abort")
    ap.add_argument("-V", "--verbose", action="store_true")
    ap.add_argument("--no-timeline", action="store_true",
                    help="disable the coordinator's live timeline "
                    "sampler (obs/timeline.py timeline.jsonl) and the "
                    "report-only autoscale recommender")
    ap.add_argument("--max-respawns", type=int, default=2,
                    help="per-worker budget for respawning CRASHED "
                    "workers (nonzero exit with work left); clean "
                    "exits never respawn")
    ap.add_argument("--elastic-workers", action="store_true",
                    help="act on the autoscale recommender: spawn/"
                    "retire one worker per recommendation change, "
                    "clamped to [--min-workers, --max-workers].  "
                    "Retire = SIGTERM -> the worker's existing "
                    "lease-release path.  Off: report-only")
    ap.add_argument("--min-workers", type=int, default=1)
    ap.add_argument("--max-workers", type=int, default=0,
                    help="elastic ceiling (0 = max(--workers, "
                    "--min-workers))")
    ap.add_argument("--open-loop", action="store_true",
                    help="arrivals keep landing after workers start "
                    "(load harness): workers ignore the all-done exit "
                    "and hold on until --max-idle or SIGTERM")
    ap.add_argument("--profile-worker", default="", metavar="WID",
                    help="coordinator: arm worker WID for a one-cycle "
                    "device-profile capture by dropping the devprof "
                    "flag file in the shared out-dir — the targeted "
                    "worker of a LIVE fleet profiles its next claimed "
                    "cycle, no restart (obs/devprof.py; the retired "
                    "flag's .done file records the trace path)")
    ap.add_argument("--profile-dir", default="",
                    help="capture directory for --profile-worker "
                    "(default <out-dir>/devprof_<WID>)")
    return ap


def config_from_args(args) -> FleetConfig:
    return FleetConfig(
        requests=args.requests, out_dir=args.out_dir,
        queue_dir=args.queue_dir, aot_store=args.aot_store,
        workers=args.workers, role=args.role,
        worker_id=args.worker_id, batch=args.batch,
        lease_ttl_s=args.lease_ttl, poll_s=args.poll,
        max_idle_s=args.max_idle,
        large_stations=args.large_stations,
        overload_policy=args.overload_policy,
        degrade_emiter=args.degrade_emiter,
        degrade_lbfgs=args.degrade_lbfgs,
        max_streams=args.max_streams,
        max_emiter=args.max_emiter, max_iter=args.max_iter,
        max_lbfgs=args.max_lbfgs, lbfgs_m=args.lbfgs_m,
        solver_mode=args.solver_mode, nulow=args.nulow,
        nuhigh=args.nuhigh, randomize=not args.no_randomize,
        use_f64=not args.f32, use_fused_predict=args.fused,
        coh_dtype=args.coh_dtype, verbose=args.verbose, slo=args.slo,
        timeline=not args.no_timeline,
        max_respawns=args.max_respawns,
        elastic_workers=args.elastic_workers,
        min_workers=args.min_workers, max_workers=args.max_workers,
        open_loop=args.open_loop, shadow_rate=args.shadow_rate,
        shadow_budget_s=args.shadow_budget_s,
        shadow_seed=args.shadow_seed,
        abort_on_drift=args.abort_on_drift)


def _obs_setup(cfg, role: str):
    """RunManifest + event log + crash handlers + tracer, mirroring
    the serve app."""
    from sagecal_tpu.obs import RunManifest, default_event_log
    from sagecal_tpu.obs.flight import (
        get_flight_recorder, install_crash_handlers, register_event_log,
    )
    from sagecal_tpu.obs.trace import configure_tracer

    manifest = RunManifest.collect(
        kernel_path="xla", app="fleet", role=role,
        out_dir=cfg.out_dir)
    # fleet/load runs default the event log INTO the out-dir (rather
    # than the CWD) so every record family of one run lands in one
    # auditable directory; SAGECAL_EVENT_LOG still overrides, and the
    # spawned workers inherit the same resolution via --out-dir
    path = None
    if not os.environ.get("SAGECAL_EVENT_LOG") and cfg.out_dir:
        path = os.path.join(cfg.out_dir, "sagecal_events.jsonl")
    elog = default_event_log(manifest=manifest, path=path)
    install_crash_handlers()
    if elog is not None:
        register_event_log(elog)
    get_flight_recorder(run_id=manifest.run_id)
    configure_tracer(run_id=manifest.run_id)
    return elog


def _obs_teardown(elog) -> None:
    from sagecal_tpu.obs.flight import (
        close_flight_recorder, unregister_event_log,
    )
    from sagecal_tpu.obs.perf import emit_perf_events
    from sagecal_tpu.obs.trace import close_tracer

    close_tracer()
    if elog is not None:
        emit_perf_events(elog)
        elog.close()
        unregister_event_log(elog)
    close_flight_recorder()


def run_worker(cfg: FleetConfig, log=print):
    """One worker's whole life: the host pipeline runs under a CPU
    default device, batches cross to the accelerator (serve split)."""
    import jax

    from sagecal_tpu.fleet.worker import FleetWorker
    from sagecal_tpu.obs.perf import enable_persistent_compilation_cache
    from sagecal_tpu.utils.platform import cpu_device

    enable_persistent_compilation_cache()
    try:
        accel = jax.devices()[0]
    except RuntimeError:
        accel = None
    if accel is not None and accel.platform == "cpu":
        accel = None
    elog = _obs_setup(cfg, "worker")
    try:
        with jax.default_device(cpu_device()):
            return FleetWorker(cfg, log=log, device=accel).run(elog=elog)
    finally:
        _obs_teardown(elog)


def run_coordinator(cfg: FleetConfig, requests=None, log=print):
    from sagecal_tpu.fleet.coordinator import FleetCoordinator
    from sagecal_tpu.serve.request import load_requests

    if requests is None:
        requests = load_requests(cfg.requests)
    elog = _obs_setup(cfg, "coordinator")
    try:
        return FleetCoordinator(cfg, log=log).run(requests, elog=elog)
    finally:
        _obs_teardown(elog)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if args.profile_worker:
        # drop the arm flag BEFORE any work starts, so a worker spawned
        # by this very coordinator (or one already alive on the shared
        # dir) sees it on its next claim
        from sagecal_tpu.obs.devprof import arm_fleet_profile

        path = arm_fleet_profile(cfg.out_dir, args.profile_worker,
                                 args.profile_dir or None)
        print(f"fleet: armed device profile for worker "
              f"{args.profile_worker} ({path})")
    if cfg.role == "worker":
        if not (cfg.queue_dir or cfg.out_dir):
            build_parser().error("--queue-dir (or --out-dir) required")
        run_worker(cfg)
        return 0
    requests = None
    if args.synthetic > 0:
        from sagecal_tpu.serve.request import load_requests
        from sagecal_tpu.serve.synthetic import make_synthetic_workload

        path = make_synthetic_workload(cfg.out_dir, args.synthetic,
                                       n_tenants=args.tenants)
        cfg.requests = path
        requests = load_requests(path)
    elif not cfg.requests:
        build_parser().error("--requests (or --synthetic N) is required")
    summary = run_coordinator(cfg, requests=requests)
    return 0 if summary.get("drained") else 4


if __name__ == "__main__":
    sys.exit(main())
