"""Fullbatch calibration driver: the ``sagecal`` main path.

Redesign of ``run_fullbatch_calibration``
(``/root/reference/src/MS/fullbatch_mode.cpp:38-656``): per-tile loop of
load -> precalculate coherencies -> SAGE solve -> write solutions ->
residuals -> divergence guard.  The pthread/GPU pipeline orchestration
of the reference dissolves into jitted solver calls; the host side only
streams tiles and files.
"""

from __future__ import annotations

import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from sagecal_tpu.apps.config import RunConfig
from sagecal_tpu.core.types import (
    identity_jones,
    jones_to_params,
    mat_of_flat,
    params_to_jones,
)
from sagecal_tpu.io import solutions as solio
from sagecal_tpu.io.dataset import TilePrefetcher, VisDataset
from sagecal_tpu.io.skymodel import load_sky
from sagecal_tpu.ops.residual import calculate_residuals, simulate_visibilities
from sagecal_tpu.solvers.robust import whiten_uv_weights
from sagecal_tpu.solvers.sage import (
    SageConfig,
    build_cluster_data,
    build_cluster_data_withbeam,
    solve_tile,
)


def _load_ignore_list(path: Optional[str], cdefs) -> list:
    if not path:
        return []
    with open(path) as f:
        ids = {int(tok) for line in f for tok in line.split()
               if not line.strip().startswith("#") and tok.strip()}
    return [i for i, cd in enumerate(cdefs) if cd.cluster_id in ids]


def _resolve_ccid(ccid: Optional[int], cdefs) -> Optional[int]:
    """Reference cluster id (-E) -> cluster array index
    (residual.c:953-960)."""
    if ccid is None:
        return None
    for i, cd in enumerate(cdefs):
        if cd.cluster_id == ccid:
            return i
    return None


_REF_BEAM_MODES = {
    # reference -B codes (Dirac_common.h:120-140) -> (internal mode, wideband)
    0: (0, False), 1: (1, False), 2: (3, False), 3: (2, False),
    4: (1, True), 5: (3, True), 6: (2, True),
}


def _beam_setup(cfg: RunConfig, ds: VisDataset):
    """Resolve -B: returns (geom, pointing, coeff, mode, wideband) or
    None when beams are off (the doBeam dispatch of
    fullbatch_mode.cpp:371-388)."""
    if not cfg.beam_mode:
        return None
    from sagecal_tpu.ops.beam import (
        DOBEAM_ARRAY, ElementCoeffs, synthetic_dipole_coeffs,
    )

    mode, wideband = _REF_BEAM_MODES[cfg.beam_mode]
    bp = ds.load_beam()
    if bp is None:
        raise ValueError(
            f"beam mode {cfg.beam_mode} requested but dataset "
            f"{cfg.dataset} has no /beam group (station geometry)"
        )
    geom, pointing = bp
    coeff = None
    if mode != DOBEAM_ARRAY:
        if cfg.element_coeffs:
            # 'lba'/'hba'/'alo' (or a table npz) -> real coefficient
            # tables interpolated to the observing frequency; plain npz
            # -> the single-frequency loadable format
            try:
                coeff = ElementCoeffs.from_table(
                    cfg.element_coeffs, ds.meta.freq0
                )
            except (KeyError, FileNotFoundError):
                coeff = ElementCoeffs.load(cfg.element_coeffs)
        else:
            coeff = synthetic_dipole_coeffs()
    return geom, pointing, coeff, mode, wideband


def run_fullbatch(cfg: RunConfig, log=print):
    """Calibrate (or simulate) every tile of the dataset.  Returns the
    per-tile (res_0, res_1) list.

    Device split: every host stage — IO, coherency precompute,
    residuals, bookkeeping (some of it complex math the axon runtime
    cannot transfer) — runs under a CPU default device; each tile's
    SAGE solve crosses to the accelerator as ONE packed-real jit
    dispatch (solvers/sage.py solve_tile), mirroring the reference's
    CPU-pipeline + GPU-solver split (fullbatch_mode.cpp:371-464)."""
    import jax

    from sagecal_tpu.obs.perf import enable_persistent_compilation_cache
    from sagecal_tpu.utils.platform import cpu_device

    # SAGECAL_COMPILE_CACHE (or JAX_COMPILATION_CACHE_DIR): a restarted
    # run deserializes yesterday's XLA executables instead of recompiling
    enable_persistent_compilation_cache()

    try:
        accel = jax.devices()[0]
    except RuntimeError:
        # accelerator plugin failed to initialize — cpu_device() below
        # forces the CPU platform and the whole run stays host-side
        accel = None
    if accel is not None and accel.platform == "cpu":
        accel = None
    with jax.default_device(cpu_device()):
        return _run_fullbatch_host(cfg, log, accel)


def _run_fullbatch_host(cfg: RunConfig, log, accel):
    dtype = np.float64 if cfg.use_f64 else np.float32
    cdtype = np.complex128 if cfg.use_f64 else np.complex64
    ds = VisDataset(cfg.dataset, "r+")
    meta = ds.meta
    clusters, cdefs, shapelets = load_sky(
        cfg.sky_model, cfg.cluster_file, meta.ra0, meta.dec0, dtype=dtype,
        three_term_spectra=None if cfg.sky_format < 0 else bool(cfg.sky_format),
    )
    M = len(clusters)
    nchunks = [cd.nchunk for cd in cdefs]
    nchunk_max = max(nchunks)
    N = meta.nstations
    ignore_idx = _load_ignore_list(cfg.ignore_clusters_file, cdefs)
    ccid_index = _resolve_ccid(cfg.ccid, cdefs)
    beam = _beam_setup(cfg, ds)

    # initial solutions: identity or warm start (-q),
    # fullbatch_mode.cpp:206-237; simulation mode advances through the
    # file's solution intervals per tile (fullbatch_mode.cpp:562)
    jones_intervals = None
    if cfg.init_solutions:
        _, jones_intervals = solio.read_solutions(cfg.init_solutions)
        p = jnp.asarray(
            jones_to_params(jnp.asarray(jones_intervals[0], cdtype)).reshape(
                M, nchunk_max, 8 * N
            )
        )
    else:
        eye = jones_to_params(identity_jones(N, cdtype))
        p = jnp.broadcast_to(eye, (M, nchunk_max, 8 * N)).astype(dtype)
    pinit = p

    # telemetry (obs/): per-iteration solver traces ride along as extra
    # jitted outputs when SAGECAL_TELEMETRY=1; the JSONL event log gets
    # the manifest now and per-tile events in the loop below
    from sagecal_tpu.obs import RunManifest, default_event_log, telemetry_enabled
    from sagecal_tpu.obs.records import sage_convergence_records

    scfg = SageConfig(
        max_emiter=cfg.max_emiter, max_iter=cfg.max_iter,
        max_lbfgs=cfg.max_lbfgs, lbfgs_m=cfg.lbfgs_m,
        solver_mode=cfg.solver_mode,
        nulow=cfg.nulow, nuhigh=cfg.nuhigh, randomize=cfg.randomize,
        use_fused_predict=cfg.use_fused_predict and not cfg.use_f64,
        # bf16 coherency storage only exists on the fused f32 path; the
        # quality watchdog below validates the solves it produces
        coh_dtype=(cfg.coh_dtype
                   if cfg.use_fused_predict and not cfg.use_f64 else "f32"),
        collect_telemetry=telemetry_enabled(),
        # quality side outputs feed the watchdog: needed whenever
        # telemetry records them OR the run must be able to abort
        collect_quality=telemetry_enabled() or cfg.abort_on_divergence,
    )
    manifest = RunManifest.collect(
        kernel_path="fused" if scfg.use_fused_predict else "xla",
        app="fullbatch", dataset=cfg.dataset, solver_mode=cfg.solver_mode,
        tilesz=cfg.tilesz, n_clusters=M, n_stations=N,
        simulation_mode=cfg.simulation_mode, coh_dtype=scfg.coh_dtype,
    )
    elog = default_event_log(manifest=manifest)
    # crash forensics + tracing: excepthook/SIGTERM flush the event log
    # (run_aborted + flight-dump path), the flight recorder heartbeats
    # for the watch scripts, spans correlate on the manifest run_id
    from sagecal_tpu.obs.flight import (
        close_flight_recorder,
        get_flight_recorder,
        install_crash_handlers,
        note_activity,
        register_event_log,
        unregister_event_log,
    )
    from sagecal_tpu.obs.trace import close_tracer, configure_tracer, get_tracer

    install_crash_handlers()
    if elog is not None:
        register_event_log(elog)
    get_flight_recorder(run_id=manifest.run_id)
    configure_tracer(run_id=manifest.run_id)
    tracer = get_tracer()

    # elastic execution (sagecal_tpu/elastic/): checkpoint at tile
    # boundaries, resume from the newest valid checkpoint.  The RNG key
    # chain is explicit so a resumed tile sees the exact key the
    # uninterrupted run would have used.
    import jax

    rng_key = jax.random.PRNGKey(0)
    ckmgr = None
    resume_done = 0  # pairs completed (and intervals on disk) at resume
    results = []
    if cfg.simulation_mode == 0 and (cfg.resume or cfg.checkpoint_every > 0):
        from sagecal_tpu.elastic.checkpoint import (
            CheckpointManager, ResumeRefused, config_fingerprint,
        )
        import os as _os

        fingerprint = config_fingerprint(
            app="fullbatch", dataset=_os.path.abspath(cfg.dataset),
            sky_model=_os.path.abspath(cfg.sky_model),
            cluster_file=_os.path.abspath(cfg.cluster_file),
            nstations=N, ntime=meta.ntime, nchan=meta.nchan,
            freq0=meta.freq0, n_clusters=M, nchunk_max=nchunk_max,
            tilesz=cfg.tilesz, solver_mode=cfg.solver_mode,
            max_emiter=cfg.max_emiter, max_iter=cfg.max_iter,
            max_lbfgs=cfg.max_lbfgs, lbfgs_m=cfg.lbfgs_m,
            nulow=cfg.nulow, nuhigh=cfg.nuhigh, randomize=cfg.randomize,
            use_f64=cfg.use_f64, whiten=cfg.whiten,
            in_column=cfg.in_column, skip_tiles=cfg.skip_tiles,
            max_tiles=cfg.max_tiles, init_solutions=cfg.init_solutions,
        )
        ckmgr = CheckpointManager(
            cfg.checkpoint_dir or f"{cfg.out_solutions}.ckpt",
            fingerprint, "fullbatch",
            every=max(cfg.checkpoint_every, 1), elog=elog, log=log)
        if cfg.resume:
            found = ckmgr.resume()
            if found is not None:
                rmeta, rarr, rpath = found
                resume_done = int(rmeta["tiles_done"])
                p = jnp.asarray(rarr["p"])
                rng_key = jnp.asarray(rarr["rng_key"])
                results = [tuple(map(float, r))
                           for r in rarr.get("results",
                                             np.zeros((0, 2)))]
                v = None
                if _os.path.exists(cfg.out_solutions):
                    v = solio.validate_solutions(
                        cfg.out_solutions, truncate=True,
                        max_intervals=resume_done)
                if v is None or v["n_intervals"] < resume_done:
                    raise ResumeRefused(
                        f"checkpoint {rpath} records {resume_done} "
                        f"completed tiles but {cfg.out_solutions} holds "
                        f"{0 if v is None else v['n_intervals']} intact "
                        f"intervals; solution file and checkpoint "
                        f"disagree")
                log(f"resume: {resume_done} tiles from {rpath}"
                    + (" (torn interval truncated)"
                       if v["truncated"] else ""))

    sol_fh = None
    if cfg.simulation_mode == 0:
        if resume_done:
            # append-consistent re-open: the file was validated (and
            # any torn/post-checkpoint interval truncated) above
            sol_fh = open(cfg.out_solutions, "a")
        else:
            sol_fh = open(cfg.out_solutions, "w")
            solio.write_header(
                sol_fh, meta.freq0, meta.deltaf,
                meta.deltat * cfg.tilesz / 60.0,
                N, M, M * nchunk_max,
            )

    def _cdata(dat, t0, fdelta=None):
        """Cluster coherencies, beam-aware when -B is on
        (fullbatch_mode.cpp:371-388 dispatch)."""
        if beam is None:
            return build_cluster_data(dat, clusters, nchunks, fdelta=fdelta,
                                      shapelets=shapelets)
        geom, pointing, coeff, mode, wideband = beam
        # ALO (lunar) element: no terrestrial J2000 precession
        # (fullbatch_mode.cpp:335 beam.elType!=ELEM_ALO gate)
        is_alo = (cfg.element_coeffs or "").lower() == "alo"
        return build_cluster_data_withbeam(
            dat, clusters, nchunks, geom, pointing, coeff, mode,
            ds.time_jd(t0, dat.tilesz), meta.ra0, meta.dec0,
            fdelta=fdelta, wideband=wideband, shapelets=shapelets,
            precess=not is_alo,
        )

    # first-class profiling (SURVEY section 5): per-phase wall-clock
    # always on; SAGECAL_PROFILE_DIR additionally captures an XLA trace
    # and SAGECAL_TRANSFER_AUDIT=1 logs implicit host<->device transfers
    from sagecal_tpu.obs.contracts import (
        ContractViolation,
        emit_contract_events,
    )
    from sagecal_tpu.obs.perf import (
        TransferAudit,
        dump_memory_profile,
        emit_perf_events,
    )
    from sagecal_tpu.utils.profiling import PhaseTimer, trace

    timer = PhaseTimer()
    # entered by hand (not `with`) so the existing try/finally below can
    # own the exits without reindenting the whole tile loop; the finally
    # guarantees a crashed run still flushes a loadable trace
    trace_cm = trace()
    trace_dir = trace_cm.__enter__()
    if trace_dir:
        log(f"profiling: XLA trace -> {trace_dir}")
    audit = TransferAudit()
    audit.__enter__()

    # -K/-T partial reruns (MPI/main.cpp:133-139) resolved up front so
    # the prefetcher reads exactly the tiles the loop will consume;
    # resume additionally drops the pairs the checkpointed run already
    # completed (their intervals are on disk)
    pairs = [
        (i, t0) for i, t0 in enumerate(ds.tiles(cfg.tilesz))
        if i >= cfg.skip_tiles
    ]
    if cfg.max_tiles:
        pairs = pairs[: cfg.max_tiles]
    pairs = pairs[resume_done:]
    load_kw = dict(min_uvcut=cfg.min_uvcut, max_uvcut=cfg.max_uvcut,
                   dtype=dtype, column=cfg.in_column)
    specs = [dict(average_channels=False, **load_kw)]
    if not cfg.simulation_mode:
        specs.append(dict(average_channels=True, **load_kw))
    # Background-thread tile prefetch (io/dataset.py TilePrefetcher):
    # the next tile's HDF5 read + packing overlaps this tile's solve —
    # the reference's loadData-around-the-pipeline role.  The "load"
    # profiling phase therefore measures the prefetch STALL, not the
    # raw read.
    prefetch_cm = TilePrefetcher(cfg.dataset, [t0 for _, t0 in pairs],
                                 specs, cfg.tilesz, depth=1)
    # root span of the run; manual enter — the try/finally owns the exit
    run_span = tracer.span("fullbatch", kind="run", tiles=len(pairs))
    run_span.__enter__()
    try:
      prefetch = iter(prefetch_cm.__enter__())

      def _prepare(t0):
          """Load + coherency precompute for one tile.  All device
          work here is ASYNC jit dispatch, so calling this right after
          dispatching the previous tile's solve overlaps the coherency
          precompute with the device solve (the same software pipeline
          as the distributed driver; the reference's threaded per-tile
          precompute role, fullbatch_mode.cpp:371-388).  Coherencies
          depend only on u/v/w/freqs, so whitening (vis/mask-only) can
          be applied later without invalidating them."""
          t0_chk, tiles = next(prefetch)
          if t0_chk != t0:
              raise RuntimeError(
                  f"prefetch order mismatch: got tile {t0_chk}, "
                  f"expected {t0}"
              )
          full_ = tiles[0]
          data_ = None if cfg.simulation_mode else tiles[1]
          cdata_full_ = _cdata(
              full_, t0, fdelta=meta.deltaf / max(meta.nchan, 1)
          )
          cdata_ = None if cfg.simulation_mode else _cdata(data_, t0)
          return full_, data_, cdata_full_, cdata_

      def _ckpt_update(pi):
          """End-of-tile checkpoint: the tile's solution interval and
          residuals are durable, so (p, rng chain, results) at this
          boundary is a complete resume point."""
          if ckmgr is None:
              return
          ckmgr.update(
              resume_done + pi,
              {"p": np.asarray(p), "rng_key": np.asarray(rng_key),
               "results": np.asarray(results, np.float64).reshape(-1, 2)},
              tiles_done=resume_done + pi + 1, run_id=manifest.run_id,
          )

      prepared = None
      if pairs:
          with timer.phase("load+coh"):
              prepared = _prepare(pairs[0][1])
      for pi, (tile_no, t0) in enumerate(pairs):
        tic = time.time()
        tile_span = tracer.span("tile", kind="tile", tile=t0)
        tile_span.__enter__()
        full, data, cdata_full, cdata = prepared

        if cfg.simulation_mode:
            # predict / add / subtract (fullbatch_mode.cpp:536-591);
            # corrupt with the tile's own solution interval
            psim = None
            if jones_intervals is not None:
                ti = min(tile_no, jones_intervals.shape[0] - 1)
                psim = jnp.asarray(
                    jones_to_params(
                        jnp.asarray(jones_intervals[ti], cdtype)
                    ).reshape(M, nchunk_max, 8 * N)
                )
            out_vis = simulate_visibilities(
                full, cdata_full, psim, mode=cfg.simulation_mode,
                ignore_clusters=ignore_idx, ccid_index=ccid_index,
                rho=cfg.correction_rho, phase_only=cfg.phase_only_correction,
            )
            if pi + 1 < len(pairs):
                with timer.phase("load+coh"):
                    prepared = _prepare(pairs[pi + 1][1])
            ds.write_tile(t0, np.asarray(mat_of_flat(out_vis)), column="model")
            if elog is not None:
                elog.emit("tile_simulated", tile=t0,
                          seconds=time.time() - tic,
                          phase_seconds=timer.tile_timings())
            log(f"tile {t0}: simulated ({time.time()-tic:.1f}s)")
            tile_span.__exit__(None, None, None)
            continue

        if cfg.whiten:
            wts = jnp.sqrt(whiten_uv_weights(data.u, data.v, meta.freq0))
            data = data.replace(vis=data.vis * wts[None, None, :],
                                mask=data.mask * (wts[None, :] > 0))
        with timer.phase("solve"):
            # packed-real boundary: the whole SAGE/EM solve is one jit
            # dispatch to the default device — complex never crosses, so
            # this runs on the axon TPU as-is (solvers/sage.py
            # sagefit_packed)
            out = solve_tile(data, cdata, p, scfg, key=rng_key,
                             device=accel)  # async dispatch
        # overlap: next tile's load + coherency dispatch runs while the
        # device solves this tile
        if pi + 1 < len(pairs):
            with timer.phase("load+coh"):
                prepared = _prepare(pairs[pi + 1][1])
        with timer.phase("solve-wait"):
            res0, res1 = float(out.res_0), float(out.res_1)
        # divergence guard (fullbatch_mode.cpp:618-632)
        diverged = (
            not np.isfinite(res1) or res1 == 0.0 or res1 > cfg.res_ratio * res0
        )
        # out.p comes home as real numpy so all downstream eager math
        # (params_to_jones, residuals) stays on the CPU device
        p = pinit if diverged else jnp.asarray(np.asarray(out.p))
        # advance the tile RNG chain (the tile just solved used the
        # pre-advance key; a resumed run restores this chain from the
        # checkpoint, so resume == uninterrupted bit-for-bit)
        rng_key = jax.random.fold_in(rng_key, tile_no)
        if diverged:
            log(f"tile {t0}: diverged ({res0:.3e} -> {res1:.3e}), reset")

        # quality watchdog (obs/quality.py): chi^2 attribution + gain
        # health of this tile's solve -> solve_quality event + gauges,
        # escalating to quality_degraded / solver_diverged.  The
        # residual-ratio guard above joins the same verdict so
        # --abort-on-divergence covers both detectors.
        from sagecal_tpu.obs.quality import abort_if_diverged, check_and_emit

        q_verdict, q_reasons = "ok", []
        if out.quality is not None:
            # coh_dtype rides on every quality event so a degraded bf16
            # run is attributable to the precision knob at a glance
            q_verdict, q_reasons = check_and_emit(
                elog, out.quality, log=log, tile=t0, app="fullbatch",
                coh_dtype=scfg.coh_dtype,
            )
        if diverged:
            if q_verdict != "diverged" and elog is not None:
                elog.emit("solver_diverged",
                          reasons=[f"residual_ratio:{res0:.3e}->{res1:.3e}"],
                          tile=t0, app="fullbatch")
            q_verdict = "diverged"
            q_reasons = q_reasons + [
                f"residual_ratio:{res0:.3e}->{res1:.3e}"
            ]
        if cfg.abort_on_divergence:
            abort_if_diverged(elog, q_verdict, q_reasons,
                              tile=t0, app="fullbatch")

        # append solution columns (fullbatch_mode.cpp:595-605)
        jsol = np.asarray(params_to_jones(p)).reshape(M * nchunk_max, N, 2, 2)
        solio.append_solutions(sol_fh, jsol)

        if cfg.influence:
            # -i: influence function replaces the residuals
            # (fullbatch_mode.cpp:526-534 -> calculate_diagnostics_gpu)
            from sagecal_tpu.ops.diagnostics import influence_function

            infl = influence_function(full, cdata_full, p)  # host numpy
            # host-side flat -> (rows, F, 2, 2) (no device round trip)
            infl_mat = np.moveaxis(infl, -1, 0).reshape(
                infl.shape[-1], infl.shape[0], 2, 2
            )
            ds.write_tile(t0, infl_mat, column="influence")
            log(f"tile {t0}: influence diagnostics written "
                f"({time.time()-tic:.1f}s)")
            results.append((float(out.res_0), float(out.res_1)))
            _ckpt_update(pi)
            tile_span.__exit__(None, None, None)
            continue

        if cfg.per_channel and meta.nchan > 1:
            # -b: per-channel joint-LBFGS re-fit from the averaged
            # solution, residuals per channel with each channel's own
            # solution (fullbatch_mode.cpp:453-499 doChan path)
            from sagecal_tpu.solvers.batchmode import bfgsfit_minibatch

            res_np = np.empty(
                (full.vis.shape[-1], meta.nchan, 2, 2),
                np.complex128 if cfg.use_f64 else np.complex64,
            )
            for c in range(meta.nchan):
                dc = full.replace(
                    vis=full.vis[c:c + 1],
                    mask=full.mask[c:c + 1],
                    freqs=full.freqs[c:c + 1],
                )
                cc = cdata_full._replace(coh=cdata_full.coh[:, c:c + 1])
                p_c, _ = bfgsfit_minibatch(
                    dc, cc, p, itmax=cfg.max_lbfgs, lbfgs_m=cfg.lbfgs_m,
                )
                res_c = calculate_residuals(
                    dc, cc, p_c, ccid_index=ccid_index,
                    rho=cfg.correction_rho,
                    phase_only=cfg.phase_only_correction,
                )
                res_np[:, c] = np.asarray(mat_of_flat(res_c))[:, 0]
            res = res_np
        else:
            # residuals on the full-channel data, optional correction
            with timer.phase("residual"):
                res = np.asarray(mat_of_flat(calculate_residuals(
                    full, cdata_full, p, ccid_index=ccid_index,
                    rho=cfg.correction_rho,
                    phase_only=cfg.phase_only_correction,
                )))
        with timer.phase("write"):
            ds.write_tile(t0, np.asarray(res), column=cfg.out_column)
        # warm-start accounting: gains carry tile-to-tile (temporal
        # smoothness), so iterations-to-converge per tile is the
        # measured win; gauge + tile_done field feed `diag prom` and
        # the bench's warm_start_speedup
        warm_start = bool(pi > 0 or resume_done > 0
                          or cfg.init_solutions)
        iters_tile = None
        conv_recs = sage_convergence_records(out.telemetry)
        if conv_recs:
            iters_tile = int(sum(int(r.get("iterations", 0))
                                 for r in conv_recs))
            from sagecal_tpu.obs.registry import get_registry

            get_registry().gauge_set(
                "tile_iterations_to_converge", iters_tile,
                help="summed solver iterations of this tile's solve "
                     "(warm starts shrink it)", tile=str(t0),
                warm_start=str(int(warm_start)))
        if elog is not None:
            for rec in conv_recs:
                elog.emit("cluster_convergence", tile=t0, **rec)
            elog.emit(
                "tile_done", tile=t0, res0=res0, res1=res1,
                mean_nu=float(out.mean_nu), diverged=bool(diverged),
                seconds=time.time() - tic,
                warm_start=warm_start, iterations=iters_tile,
                phase_seconds=timer.tile_timings(),
            )
        log(
            f"tile {t0}: residual {res0:.6f} -> {res1:.6f} "
            f"nu {float(out.mean_nu):.1f} ({time.time()-tic:.1f}s) "
            f"[{timer.tile_summary()}]"
        )
        results.append((res0, res1))
        _ckpt_update(pi)
        note_activity("tile", name=f"tile{t0}", seconds=time.time() - tic)
        tile_span.__exit__(None, None, None)

    except ContractViolation as e:
        # SAGECAL_CHECKIFY contract tripped mid-solve: flush the
        # structured contract_violation event + a run_aborted marker
        # into the log before the CLI maps the exception to exit 4
        if elog is not None:
            emit_contract_events(elog)
            elog.emit("run_aborted", reason="contract_violation",
                      fn=e.fn_name, detail=e.detail)
            elog.close()
            elog = None
        raise
    finally:
        # always reap the worker thread + its read handle, even when the
        # solve/write raises mid-loop; same for the transfer audit (its
        # counts survive exit) and the XLA trace
        prefetch_cm.__exit__(None, None, None)
        audit.__exit__(None, None, None)
        trace_cm.__exit__(None, None, None)
        run_span.__exit__(None, None, None)
        close_tracer()  # writes trace.json alongside the span JSONL
    log(timer.run_summary())
    if elog is not None:
        emit_perf_events(elog)
        audit.emit(elog)
        # contract_unsupported markers (checkify skipped a wrapper) are
        # worth keeping even in clean runs
        emit_contract_events(elog)
        elog.emit("run_done", n_tiles=len(results),
                  phase_totals=dict(timer.totals))
        elog.close()
        unregister_event_log(elog)
    dump_memory_profile()
    if sol_fh:
        sol_fh.close()
    if ckmgr is not None:
        # persist the final boundary even with a sparse cadence, then
        # unhook from the crash handlers (run is complete)
        ckmgr.flush()
        ckmgr.close()
    ds.close()
    # success path only: leaves the final "closed" heartbeat; a crash
    # keeps the recorder alive for the excepthook's dump
    close_flight_recorder()
    return results
