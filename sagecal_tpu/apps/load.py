"""``sagecal-tpu load``: synthetic-tenant load harness vs a live fleet.

Builds a seeded tenant population + open-loop arrival schedule
(fleet/loadgen.py), spawns a real coordinator+worker fleet, submits
requests at their scheduled instants, then runs the capacity analysis
(obs/capacity.py) and writes ``load_report.json`` next to the result
manifests, ``timeline.jsonl`` and ``load_steps.json``.  Render with
``sagecal-tpu diag load <out-dir>``.

Exit codes: 0 queue fully drained; 4 requests left undrained.
"""

from __future__ import annotations

import argparse
import sys

from sagecal_tpu.apps.config import FleetConfig
from sagecal_tpu.fleet.loadgen import ARRIVAL_KINDS, LoadSpec


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu load",
        description="Seeded open-loop load generator driving a live "
        "coordinator+worker fleet; records offered-load ground truth, "
        "a live timeline, and the capacity report.")
    ap.add_argument("--out-dir", default="load-out")
    ap.add_argument("--queue-dir", default="",
                    help="shared queue directory "
                    "(default <out-dir>/queue)")
    ap.add_argument("--aot-store", default="",
                    help="shared AOT artifact store "
                    "(default <out-dir>/aot-store)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--arrival", choices=ARRIVAL_KINDS,
                    default="ramp",
                    help="open-loop arrival process")
    ap.add_argument("--rate", type=float, default=1.0,
                    help="mean arrivals/s (poisson; onoff ON phase)")
    ap.add_argument("--rate-off", type=float, default=0.0,
                    help="onoff OFF-phase rate")
    ap.add_argument("--mean-on", type=float, default=8.0,
                    help="onoff mean ON-phase length (s)")
    ap.add_argument("--mean-off", type=float, default=8.0,
                    help="onoff mean OFF-phase length (s)")
    ap.add_argument("--duration", type=float, default=30.0,
                    help="poisson/onoff run length (s)")
    ap.add_argument("--rates", default="0.25,0.75,2.0",
                    help="ramp: comma-separated offered rates "
                    "(arrivals/s), one load step each")
    ap.add_argument("--step", type=float, default=12.0,
                    help="ramp: seconds per load step")
    ap.add_argument("--tenants", type=int, default=2)
    ap.add_argument("--seed", type=int, default=23)
    ap.add_argument("--tilesz", type=int, default=2)
    ap.add_argument("--deadline", type=float, default=4.0,
                    help="base tenant SLO deadline (s); odd tenants "
                    "get 1.5x")
    ap.add_argument("--availability", type=float, default=0.9)
    ap.add_argument("--shed-burn", type=float, default=3.0,
                    help="short-window burn rate that trips admission "
                    "shedding")
    ap.add_argument("--warmup", type=float, default=0.0,
                    help="lead-in (s) between worker spawn and the "
                    "schedule clock, so worker startup lag is not "
                    "mislabeled as saturation of the first step")
    ap.add_argument("--drain-timeout", type=float, default=0.0,
                    help="give up waiting for the drain after this "
                    "many seconds (0 = wait for full drain)")
    ap.add_argument("--overload-policy",
                    choices=("shed", "degrade", "off"),
                    default="shed",
                    help="admission action under overload (load runs "
                    "default to shed so the shed-rate metric is "
                    "exercised)")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--lease-ttl", type=float, default=30.0)
    ap.add_argument("--poll", type=float, default=0.2)
    ap.add_argument("--max-idle", type=float, default=30.0,
                    help="worker idle exit (generous: an OFF phase "
                    "must not drain the fleet)")
    ap.add_argument("--max-respawns", type=int, default=2)
    ap.add_argument("--elastic-workers", action="store_true",
                    help="act on the autoscale recommender "
                    "(report-only otherwise)")
    ap.add_argument("--min-workers", type=int, default=1)
    ap.add_argument("--max-workers", type=int, default=0)
    ap.add_argument("--f32", action="store_true")
    ap.add_argument("-V", "--verbose", action="store_true")
    return ap


def config_from_args(args) -> FleetConfig:
    return FleetConfig(
        out_dir=args.out_dir, queue_dir=args.queue_dir,
        aot_store=args.aot_store, workers=args.workers,
        batch=args.batch, lease_ttl_s=args.lease_ttl,
        poll_s=args.poll, max_idle_s=args.max_idle,
        overload_policy=args.overload_policy,
        use_f64=not args.f32, verbose=args.verbose,
        max_respawns=args.max_respawns,
        elastic_workers=args.elastic_workers,
        min_workers=args.min_workers, max_workers=args.max_workers,
        open_loop=True)


def spec_from_args(args) -> LoadSpec:
    rates = tuple(float(r) for r in str(args.rates).split(",") if r)
    return LoadSpec(
        arrival=args.arrival, rate=args.rate, rate_off=args.rate_off,
        mean_on_s=args.mean_on, mean_off_s=args.mean_off,
        duration_s=args.duration, rates=rates, step_s=args.step,
        tenants=args.tenants, seed=args.seed, tilesz=args.tilesz,
        deadline_s=args.deadline, availability=args.availability,
        shed_burn=args.shed_burn,
        drain_timeout_s=args.drain_timeout, warmup_s=args.warmup)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    spec = spec_from_args(args)
    from sagecal_tpu.apps.fleet import _obs_setup, _obs_teardown
    from sagecal_tpu.fleet.loadgen import LoadRunner

    elog = _obs_setup(cfg, "loadgen")
    try:
        report = LoadRunner(cfg, spec).run(elog=elog)
    finally:
        _obs_teardown(elog)
    return 0 if report.get("drained") else 4


if __name__ == "__main__":
    sys.exit(main())
