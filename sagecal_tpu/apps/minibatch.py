"""Stochastic minibatch calibration (bandpass mode) + in-process
band-consensus ADMM.

Redesign of ``run_minibatch_calibration``
(``/root/reference/src/MS/minibatch_mode.cpp:47``) and
``run_minibatch_consensus_calibration`` (``minibatch_consensus_mode.cpp:47``):
channels split into ``bands`` mini-bands each with its own solution,
``epochs`` x ``minibatches`` passes over time with LBFGS curvature
memory persisting across batches, and (consensus mode) ADMM coupling of
the per-band solutions through frequency polynomials — the single-node
rehearsal of the distributed mesh mode, with bands in place of MPI
workers (minibatch_consensus_mode.cpp:359-363,455-606).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.apps.config import RunConfig
from sagecal_tpu.core.types import identity_jones, jones_to_params, params_to_jones
from sagecal_tpu.io import solutions as solio
from sagecal_tpu.io.dataset import VisDataset
from sagecal_tpu.io.skymodel import load_sky
from sagecal_tpu.ops.residual import calculate_residuals
from sagecal_tpu.parallel import consensus
from sagecal_tpu.solvers.batchmode import (
    bfgsfit_minibatch,
    bfgsfit_minibatch_consensus,
)
from sagecal_tpu.solvers.sage import build_cluster_data


def _band_slices(nchan: int, bands: int):
    """Channel ranges per mini-band (minibatch_mode.cpp:355 logic:
    near-equal splits)."""
    edges = np.linspace(0, nchan, bands + 1).astype(int)
    return [(int(edges[i]), int(edges[i + 1])) for i in range(bands)]


def _band_visdata(full, c0, c1):
    """Restrict a multichannel VisData to channels [c0, c1) — the flat
    layout's channel axis is leading."""
    return full.replace(
        vis=full.vis[c0:c1],
        mask=full.mask[c0:c1],
        freqs=full.freqs[c0:c1],
    )


def run_minibatch(cfg: RunConfig, log=print):
    """Epochs x minibatches over time, one solution per mini-band.
    Returns per-band final (res_0, res_1).

    Thin exception-safe shell: the XLA trace (``SAGECAL_PROFILE_DIR``)
    and the transfer audit (``SAGECAL_TRANSFER_AUDIT=1``) are opened
    here so a crash mid-epoch still flushes a loadable trace and
    restores stderr."""
    from sagecal_tpu.obs.perf import (
        TransferAudit,
        enable_persistent_compilation_cache,
    )
    from sagecal_tpu.utils.profiling import trace

    enable_persistent_compilation_cache()
    audit = TransferAudit()
    with trace(), audit:
        return _run_minibatch(cfg, log, audit)


def _run_minibatch(cfg: RunConfig, log, audit):
    dtype = np.float64 if cfg.use_f64 else np.float32
    cdtype = np.complex128 if cfg.use_f64 else np.complex64
    ds = VisDataset(cfg.dataset, "r+")
    meta = ds.meta
    clusters, cdefs, shapelets = load_sky(
        cfg.sky_model, cfg.cluster_file, meta.ra0, meta.dec0, dtype=dtype,
        three_term_spectra=None if cfg.sky_format < 0 else bool(cfg.sky_format),
    )
    M = len(clusters)
    nchunks = [cd.nchunk for cd in cdefs]
    nchunk_max = max(nchunks)
    N = meta.nstations
    bands = _band_slices(meta.nchan, cfg.bands)
    consensus_mode = cfg.admm_iters > 0 and cfg.bands > 1
    # bounded-staleness consensus (--consensus-staleness K): bands
    # refresh their Gram contributions on deterministic work-weighted
    # periods instead of every round; K=0 keeps periods of all-ones and
    # the unified round engine below reproduces the synchronous
    # trajectory bit-for-bit (tests/test_async_consensus.py)
    K_stale = max(int(cfg.consensus_staleness), 0)
    sdisc = float(cfg.consensus_staleness_discount)
    async_mode = consensus_mode and (K_stale > 0 or sdisc != 1.0)

    eye = jones_to_params(identity_jones(N, cdtype))
    p_bands = [
        jnp.broadcast_to(eye, (M, nchunk_max, 8 * N)).astype(dtype)
        for _ in bands
    ]
    mem_bands = [None] * len(bands)

    # consensus setup over band center frequencies
    # (minibatch_consensus_mode.cpp:359-363)
    if consensus_mode:
        bfreqs = np.asarray(
            [np.mean(meta.freqs[c0:c1]) for c0, c1 in bands]
        )
        B = consensus.setup_polynomials(
            bfreqs, meta.freq0, cfg.npoly, cfg.poly_type
        )
        if cfg.rho_file:
            # -G per-cluster regularization (read_arho_fromfile)
            from sagecal_tpu.io.skymodel import read_cluster_rho

            rho_m, _ = read_cluster_rho(cfg.rho_file, cdefs)
            rho = jnp.broadcast_to(
                jnp.asarray(rho_m, dtype), (len(bands), M)
            )
        else:
            rho = jnp.full((len(bands), M), cfg.admm_rho, dtype)
        Bii = consensus.find_prod_inverse_full(
            jnp.asarray(B, dtype), rho
        )
        K = nchunk_max * 8 * N
        Z = jnp.zeros((M, cfg.npoly, K), dtype)
        Y_bands = [jnp.zeros_like(p_bands[0]) for _ in bands]
        # the async state: per-band stored Gram terms + ages + the
        # global round counter (persists ACROSS minibatches so the
        # refresh schedule is one deterministic sequence; checkpointed
        # whole, so --resume replays it exactly)
        from sagecal_tpu.parallel.async_consensus import (
            StalenessLedger, band_active, refresh_periods,
        )

        ledger = StalenessLedger(len(bands), (M, cfg.npoly, K), dtype)

    # minibatch time ranges
    ntime = meta.ntime
    nb = max(cfg.minibatches, 1)
    tedges = np.linspace(0, ntime, nb + 1).astype(int)

    robust_nu = None
    from sagecal_tpu.solvers.sage import _ROBUST_MODES

    if cfg.solver_mode in _ROBUST_MODES:
        robust_nu = 0.5 * (cfg.nulow + cfg.nuhigh)

    # telemetry: per-minibatch progress + (consensus mode) per-ADMM-round
    # band primal residuals land in the JSONL event log
    from sagecal_tpu.obs import RunManifest, default_event_log

    manifest = RunManifest.collect(
        app="minibatch", bands=len(bands), epochs=cfg.epochs,
        minibatches=nb, consensus=consensus_mode,
        solver_mode=cfg.solver_mode, n_clusters=M, n_stations=N,
    )
    elog = default_event_log(manifest=manifest)
    # crash forensics + tracing (same lifecycle as the other apps):
    # excepthook/SIGTERM flush the event log, the flight recorder
    # heartbeats, spans correlate on the manifest run_id
    from sagecal_tpu.obs.flight import (
        close_flight_recorder,
        get_flight_recorder,
        install_crash_handlers,
        note_activity,
        register_event_log,
        unregister_event_log,
    )
    from sagecal_tpu.obs.trace import (
        close_tracer,
        configure_tracer,
        get_tracer,
        straggler_stats,
    )

    install_crash_handlers()
    if elog is not None:
        register_event_log(elog)
    get_flight_recorder(run_id=manifest.run_id)
    configure_tracer(run_id=manifest.run_id)
    tracer = get_tracer()

    # elastic execution (sagecal_tpu/elastic/): checkpoints at
    # (epoch, minibatch) boundaries carry p_bands (+ consensus Z and the
    # Y duals) AND each band's LBFGS curvature memory (``mem{bi}.*``
    # flattened-pytree entries), so a resumed run is bit-for-bit
    # identical to an uninterrupted one — the same elastic contract as
    # the fullbatch and distributed drivers (tests/test_elastic.py).
    # Checkpoints from builds that predate the memory entries still
    # resume (the memory rebuilds over the next few batches; convergent
    # but not bit-exact).
    ckmgr = None
    resume_done = 0  # completed (epoch, minibatch) steps
    if cfg.resume or cfg.checkpoint_every > 0:
        import os as _os

        from sagecal_tpu.elastic import CheckpointManager, config_fingerprint

        fingerprint = config_fingerprint(
            app="minibatch",
            dataset=_os.path.abspath(cfg.dataset),
            sky_model=_os.path.abspath(cfg.sky_model),
            cluster_file=_os.path.abspath(cfg.cluster_file),
            nstations=N, ntime=ntime, nchan=meta.nchan,
            bands=cfg.bands, epochs=cfg.epochs, minibatches=nb,
            admm_iters=cfg.admm_iters, npoly=cfg.npoly,
            poly_type=cfg.poly_type, admm_rho=cfg.admm_rho,
            consensus_staleness=cfg.consensus_staleness,
            consensus_staleness_discount=cfg.consensus_staleness_discount,
            solver_mode=cfg.solver_mode, max_lbfgs=cfg.max_lbfgs,
            lbfgs_m=cfg.lbfgs_m, nulow=cfg.nulow, nuhigh=cfg.nuhigh,
            use_f64=cfg.use_f64, in_column=cfg.in_column,
        )
        ckmgr = CheckpointManager(
            cfg.checkpoint_dir or f"{cfg.out_solutions}.ckpt",
            fingerprint, "minibatch", every=max(cfg.checkpoint_every, 1),
            elog=elog, log=log,
        )
        if cfg.resume:
            found = ckmgr.resume()
            if found is not None:
                rmeta, rarrs, _rpath = found
                resume_done = int(rmeta["steps_done"])
                p_bands = [jnp.asarray(a, dtype)
                           for a in rarrs["p_bands"]]
                if consensus_mode:
                    Z = jnp.asarray(rarrs["Z"], dtype)
                    Y_bands = [jnp.asarray(a, dtype)
                               for a in rarrs["Y_bands"]]
                    if StalenessLedger.present(rarrs):
                        # async runs: the staleness ledger (stored Gram
                        # terms + ages + round counter) is part of the
                        # trajectory — restore it so the refresh
                        # schedule continues where the killed run was
                        ledger = StalenessLedger.from_arrays(
                            rarrs, dtype=dtype)
                # LBFGS curvature memory (guarded per band: absent in
                # checkpoints from older builds, and a band that never
                # solved has none) — restoring it is what makes the
                # resumed trajectory bit-exact
                from sagecal_tpu.elastic.checkpoint import unflatten_state
                from sagecal_tpu.solvers.lbfgs import LBFGSMemory

                mem_template = LBFGSMemory.init(
                    M * nchunk_max * 8 * N, cfg.lbfgs_m, dtype)
                for bi in range(len(bands)):
                    if f"mem{bi}.0" in rarrs:
                        mem_bands[bi] = unflatten_state(
                            f"mem{bi}", rarrs, mem_template)

    def solve_band(bi, data_band, cdata_band):
        p1, mem1 = bfgsfit_minibatch(
            data_band, cdata_band, p_bands[bi],
            memory=mem_bands[bi], itmax=cfg.max_lbfgs,
            lbfgs_m=cfg.lbfgs_m, robust_nu=robust_nu,
        )
        return p1, mem1

    run_span = tracer.span("minibatch", kind="run", bands=len(bands),
                           epochs=max(cfg.epochs, 1), minibatches=nb,
                           consensus=consensus_mode)
    run_span.__enter__()
    for epoch in range(max(cfg.epochs, 1)):
        for mb in range(nb):
            step = epoch * nb + mb
            if step < resume_done:
                continue  # completed before the checkpoint we resumed
            t0, t1 = int(tedges[mb]), int(tedges[mb + 1])
            if t1 <= t0:
                continue
            tic = time.time()
            mb_span = tracer.span("batch", kind="batch", epoch=epoch,
                                  minibatch=mb)
            mb_span.__enter__()
            full = ds.load_tile(t0, t1 - t0, average_channels=False,
                                min_uvcut=cfg.min_uvcut,
                                max_uvcut=cfg.max_uvcut, dtype=dtype,
                                column=cfg.in_column)
            fd = meta.deltaf / max(meta.nchan, 1)
            if not consensus_mode:
                for bi, (c0, c1) in enumerate(bands):
                    db = _band_visdata(full, c0, c1)
                    cb = build_cluster_data(db, clusters, nchunks, fdelta=fd,
                            shapelets=shapelets)
                    p_bands[bi], mem_bands[bi] = solve_band(bi, db, cb)
            else:
                # band ADMM within this minibatch
                # (minibatch_consensus_mode.cpp:455-606)
                dbs, cbs = [], []
                for (c0, c1) in bands:
                    db = _band_visdata(full, c0, c1)
                    dbs.append(db)
                    cbs.append(build_cluster_data(db, clusters, nchunks,
                                                  fdelta=fd,
                                                  shapelets=shapelets))
                # consensus watchdog bookkeeping: per-round per-band
                # primal residuals + global dual residual trajectories
                track = (cfg.verbose or elog is not None
                         or cfg.abort_on_divergence)
                pres_traj, dual_traj = [], []
                # unlike the mesh ADMM (one jitted program, synthetic
                # attribution) this per-band loop IS host-visible, so
                # band spans are REAL wall times; blocking per band only
                # when tracing is on keeps the traced timings honest and
                # the untraced path's dispatch pipelining untouched
                band_secs = [0.0] * len(bands)
                # deterministic refresh periods from this minibatch's
                # unflagged-row counts (the straggler signal itself):
                # heavy bands refresh less often under a staleness
                # bound, so a round stops tracking the slowest band;
                # K=0 -> all-ones periods -> the synchronous loop
                band_rows = [float(jnp.sum(db.mask)) for db in dbs]
                periods = refresh_periods(band_rows, K_stale)
                if async_mode and elog is not None:
                    elog.emit("async_schedule", epoch=epoch, minibatch=mb,
                              staleness=K_stale, discount=sdisc,
                              periods=[int(x) for x in periods],
                              band_rows=band_rows,
                              round_index=ledger.round_index)
                for admm in range(cfg.admm_iters):
                    Z_old = Z
                    active = band_active(ledger.round_index, periods)
                    # a band with no stored Gram term yet must solve
                    # (cold start / first visit) — starvation-free
                    active = active | (ledger.ages < 0)
                    round_span = tracer.span("admm.round",
                                             kind="admm_round", round=admm,
                                             epoch=epoch, minibatch=mb)
                    round_span.__enter__()
                    for bi in range(len(bands)):
                        if not active[bi]:
                            continue
                        BZ = consensus.bz_for_freq(
                            Z, jnp.asarray(B[bi], dtype)
                        ).reshape(M, nchunk_max, 8 * N)
                        t_band = time.perf_counter()
                        with tracer.span("admm.band", kind="band", band=bi,
                                         lane=f"band{bi}", round=admm):
                            p1, mem1 = bfgsfit_minibatch_consensus(
                                dbs[bi], cbs[bi], p_bands[bi], Y_bands[bi],
                                BZ, rho[bi], memory=mem_bands[bi],
                                itmax=cfg.max_lbfgs, lbfgs_m=cfg.lbfgs_m,
                                robust_nu=robust_nu,
                            )
                            if tracer.enabled:
                                p1 = jax.block_until_ready(p1)
                        if tracer.enabled:
                            band_secs[bi] += time.perf_counter() - t_band
                        p_bands[bi], mem_bands[bi] = p1, mem1
                        Yhat = Y_bands[bi] + rho[bi][:, None, None] * p1
                        ledger.record(bi, consensus.accumulate_z_term(
                            jnp.asarray(B[bi], dtype),
                            Yhat.reshape(M, -1),
                        ))
                    # Z solve over EVERY band's freshest stored term,
                    # rho-discounted by age (discount**age, dropped
                    # beyond the bound); all-fresh weights are exactly
                    # 1 so the synchronous case reuses the precomputed
                    # Bii and stays bit-identical to the classic loop
                    ages_eff = np.where(active, 0, ledger.ages)
                    w_z = np.where(ages_eff < 0, 0.0,
                                   sdisc ** np.maximum(ages_eff, 0))
                    if K_stale > 0:
                        w_z = np.where(ages_eff > K_stale, 0.0, w_z)
                    if not np.any(w_z > 0):
                        w_z = np.ones_like(w_z)
                    zacc = jnp.zeros((M, cfg.npoly, nchunk_max * 8 * N),
                                     dtype)
                    for bi in range(len(bands)):
                        if w_z[bi] == 0.0:
                            continue
                        term = jnp.asarray(ledger.zterms[bi], dtype)
                        if w_z[bi] != 1.0:
                            term = jnp.asarray(w_z[bi], dtype) * term
                        zacc = zacc + term
                    if np.all(w_z == 1.0):
                        Bii_r = Bii
                    else:
                        Bii_r = consensus.find_prod_inverse_full(
                            jnp.asarray(B, dtype),
                            jnp.asarray(w_z, dtype)[:, None] * rho,
                        )
                    Z = consensus.update_global_z(zacc, Bii_r)
                    for bi in range(len(bands)):
                        if not active[bi]:
                            # an idle band keeps its dual: it did not
                            # re-solve against this round's Z, so a
                            # dual ascent step here would double-count
                            # its stale contribution
                            continue
                        BZ1 = consensus.bz_for_freq(
                            Z, jnp.asarray(B[bi], dtype)
                        ).reshape(M, nchunk_max, 8 * N)
                        Y_bands[bi] = (
                            Y_bands[bi]
                            + rho[bi][:, None, None] * (p_bands[bi] - BZ1)
                        )
                    ledger.advance()
                    round_span.__exit__(None, None, None)
                    if track:
                        # per-band scaled primal residuals (the same
                        # normalization the mesh driver logs,
                        # consensus.admm_primal_residual)
                        pres_band = [
                            float(consensus.admm_primal_residual(
                                p_bands[bi].ravel(),
                                consensus.bz_for_freq(
                                    Z, jnp.asarray(B[bi], dtype)
                                ).ravel(),
                            ))
                            for bi in range(len(bands))
                        ]
                        dres = float(consensus.admm_dual_residual(Z, Z_old))
                        pres_traj.append(pres_band)
                        dual_traj.append(dres)
                        if elog is not None:
                            elog.emit(
                                "admm_round", epoch=epoch, minibatch=mb,
                                admm_iter=admm, primal_res=pres_band,
                                dual_res=dres,
                            )
                        if cfg.verbose:
                            log(f"  admm {admm}: primal "
                                f"{sum(pres_band):.4e} dual {dres:.4e}")
                if tracer.enabled and len(bands) > 1:
                    # straggler gauges on the MEASURED per-band seconds
                    # (same gauge names as the mesh driver's attributed
                    # ones, so dashboards join across modes)
                    from sagecal_tpu.obs.registry import get_registry

                    stats = straggler_stats(band_secs)
                    reg = get_registry()
                    for bi, s in enumerate(band_secs):
                        reg.gauge_set(
                            "admm_band_seconds", s,
                            help="measured per-band seconds of this "
                                 "minibatch's band ADMM", band=str(bi))
                    reg.gauge_set(
                        "admm_straggler_ratio", stats["ratio"],
                        help="slowest/median measured band seconds of "
                             "the band ADMM")
                    reg.gauge_set(
                        "admm_band_skew", stats["skew"],
                        help="(max-mean)/mean measured band seconds")
                    if stats["detected"]:
                        if elog is not None:
                            elog.emit("straggler_detected", epoch=epoch,
                                      minibatch=mb, band=stats["argmax"],
                                      ratio=stats["ratio"],
                                      skew=stats["skew"],
                                      band_seconds=band_secs,
                                      threshold=stats["threshold"])
                        log(f"epoch {epoch} minibatch {mb}: straggler "
                            f"band {stats['argmax']} "
                            f"({stats['ratio']:.2f}x median)")
                if pres_traj:
                    # ADMM watchdog: a band whose primal residual grows
                    # away from its trajectory minimum (or goes
                    # non-finite) marks this minibatch's consensus as
                    # diverged (obs/quality.assess_consensus)
                    from sagecal_tpu.obs.quality import (
                        abort_if_diverged, assess_consensus,
                    )

                    pr = np.asarray(pres_traj)
                    du = np.tile(np.asarray(dual_traj)[:, None],
                                 (1, pr.shape[1]))
                    verdict, reasons, health = assess_consensus(
                        pr, du,
                        ages=(np.maximum(ledger.ages, 0)
                              if async_mode else None),
                        staleness=(K_stale if async_mode else None),
                    )
                    if elog is not None:
                        elog.emit(
                            "consensus_health", epoch=epoch, minibatch=mb,
                            verdict=verdict, reasons=reasons,
                            ratio=health["ratio"], trend=health["trend"],
                        )
                        if verdict == "diverged":
                            elog.emit("solver_diverged", reasons=reasons,
                                      epoch=epoch, minibatch=mb,
                                      app="minibatch")
                    if verdict != "ok":
                        log(f"consensus watchdog: {verdict} "
                            f"({', '.join(reasons)})")
                    if cfg.abort_on_divergence:
                        abort_if_diverged(elog, verdict, reasons,
                                          epoch=epoch, minibatch=mb,
                                          app="minibatch")
            note_activity("minibatch", name=f"e{epoch}mb{mb}",
                          seconds=time.time() - tic)
            mb_span.__exit__(None, None, None)
            if elog is not None:
                elog.emit("minibatch_done", epoch=epoch, minibatch=mb,
                          t0=t0, t1=t1, seconds=time.time() - tic)
            if ckmgr is not None:
                from sagecal_tpu.elastic.checkpoint import flatten_state

                arrs = {"p_bands": np.stack(
                    [np.asarray(p) for p in p_bands])}
                if consensus_mode:
                    arrs["Z"] = np.asarray(Z)
                    arrs["Y_bands"] = np.stack(
                        [np.asarray(y) for y in Y_bands])
                    if async_mode:
                        # ages + stored Gram terms + round counter: the
                        # complete async trajectory state, so --resume
                        # replays the exact refresh schedule
                        arrs.update(ledger.to_arrays())
                for bi, mem in enumerate(mem_bands):
                    if mem is not None:
                        arrs.update(flatten_state(f"mem{bi}", mem))
                ckmgr.update(step, arrs, steps_done=step + 1,
                             run_id=manifest.run_id)
            log(f"epoch {epoch} minibatch {mb}: "
                f"({time.time()-tic:.1f}s)")

    if ckmgr is not None:
        ckmgr.flush()
        ckmgr.close()
    # final residuals per band (minibatch_mode.cpp final epoch), streamed
    # tile-by-tile with the same time edges as the training loop — the
    # reference streams per tile; loading the whole observation at once
    # would defeat the tile-streaming design for realistic sizes
    fd = meta.deltaf / max(meta.nchan, 1)
    acc = [[0.0, 0.0] for _ in bands]  # per band: [sum|vis|^2, sum|res|^2]
    for mb in range(nb):
        t0, t1 = int(tedges[mb]), int(tedges[mb + 1])
        if t1 <= t0:
            continue
        full = ds.load_tile(t0, t1 - t0, average_channels=False, dtype=dtype,
                            column=cfg.in_column)
        from sagecal_tpu.core.types import mat_of_flat

        res_all = np.array(np.asarray(mat_of_flat(full.vis)), copy=True)
        for bi, (c0, c1) in enumerate(bands):
            db = _band_visdata(full, c0, c1)
            cb = build_cluster_data(db, clusters, nchunks, fdelta=fd,
                            shapelets=shapelets)
            res = calculate_residuals(db, cb, p_bands[bi])
            res_all[:, c0:c1] = np.asarray(mat_of_flat(res))
            acc[bi][0] += float(jnp.sum(jnp.abs(db.vis) ** 2))
            acc[bi][1] += float(jnp.sum(jnp.abs(res) ** 2))
        ds.write_tile(t0, res_all, column=cfg.out_column)
    results = []
    for bi in range(len(bands)):
        r0, r1 = float(np.sqrt(acc[bi][0])), float(np.sqrt(acc[bi][1]))
        results.append((r0, r1))
        if elog is not None:
            elog.emit("band_residual", band=bi, res0=r0, res1=r1)
        log(f"band {bi}: residual {r0:.4f} -> {r1:.4f}")
    if elog is not None:
        from sagecal_tpu.obs.contracts import emit_contract_events
        from sagecal_tpu.obs.perf import emit_perf_events

        # close the audit now (idempotent; the shell's exit is then a
        # no-op) so its counts land in this run's event log
        audit.__exit__(None, None, None)
        emit_perf_events(elog)
        audit.emit(elog)
        emit_contract_events(elog)
        elog.emit("run_done", n_bands=len(bands))
        elog.close()
        unregister_event_log(elog)
    run_span.__exit__(None, None, None)
    close_tracer()

    # write per-band solutions
    with open(cfg.out_solutions, "w") as fh:
        solio.write_header(fh, meta.freq0, meta.deltaf, meta.deltat / 60.0,
                           N, M, M * nchunk_max)
        for pb in p_bands:
            jsol = np.asarray(params_to_jones(pb)).reshape(
                M * nchunk_max, N, 2, 2
            )
            solio.append_solutions(fh, jsol)
    ds.close()
    # success path only: leaves the final "closed" heartbeat; a crash
    # keeps the recorder alive for the excepthook's dump
    close_flight_recorder()
    return results
