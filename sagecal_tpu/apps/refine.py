"""``sagecal-tpu refine``: differentiable sky-model refinement.

Outer LBFGS over the free sky parameters (``--free-flux 0:0,1:2`` etc.)
around the inner gain solve, gradients through the inner fixed point
(``sagecal_tpu/refine/``).  Two input modes:

- dataset mode: one vis.h5 tile + sky/cluster files — refines the
  catalog values of the freed parameters against the data;
- ``--synthetic N``: an N-station simulated sky with known ground
  truth; one flux is perturbed by ``--perturb`` and refined back
  (the smoke/bench/test mode — the result JSON carries the true-flux
  relative error).

Elastic: ``--checkpoint-every K`` writes the full outer state (theta,
LBFGS curvature memory, warm-start gains) every K outer iterations;
``--resume`` continues bit-exactly from the newest checkpoint
(fingerprint-checked, exit 5 on mismatch).  Every outer iteration also
appends one JSON line to ``<out>.trace.jsonl`` and emits a
``refine_iter`` event.

XLA predict path only: requesting the fused kernel here fails loudly
at config time (refine.objective.require_xla_predict).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Tuple

import numpy as np

from sagecal_tpu.apps.config import RefineConfig


def parse_keys(text: str) -> List[Tuple[int, int]]:
    """'0:0,1:2' -> [(0, 0), (1, 2)] (cluster:index pairs)."""
    out = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        c, _, s = part.partition(":")
        out.append((int(c), int(s)))
    return out


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu refine",
        description="Differentiable sky-model refinement: outer LBFGS "
        "over sky parameters around the inner calibration solve.")
    ap.add_argument("-d", "--dataset", default="",
                    help="input vis.h5 dataset (one tile)")
    ap.add_argument("-s", "--sky", default="", help="sky model file")
    ap.add_argument("-c", "--clusters", default="",
                    help="cluster file (defaults to <sky>.cluster)")
    ap.add_argument("-o", "--out", default="refine-out",
                    help="output prefix (<out>.json/.npz/.trace.jsonl)")
    ap.add_argument("-t", "--tilesz", type=int, default=2)
    ap.add_argument("--free-flux", default="0:0",
                    help="free fluxes, 'cluster:source' comma list")
    ap.add_argument("--free-spec", default="",
                    help="free spectral indices, 'cluster:source' list")
    ap.add_argument("--free-pos", default="",
                    help="free (ll,mm) positions, 'cluster:source' list")
    ap.add_argument("--free-modes", default="",
                    help="free shapelet modes, 'cluster:flat_mode' list")
    ap.add_argument("--outer-iters", type=int, default=10)
    ap.add_argument("-m", "--lbfgs-m", type=int, default=7)
    ap.add_argument("--gradient", choices=("implicit", "unrolled"),
                    default="implicit",
                    help="gradient route through the inner solve: IFT "
                    "adjoint at the fixed point, or truncated unrolling")
    ap.add_argument("--tol", type=float, default=0.0,
                    help=">0 stops when the outer gradient norm drops "
                    "below it")
    ap.add_argument("--inner-iters", type=int, default=12)
    ap.add_argument("--cg-iters", type=int, default=32)
    ap.add_argument("--damping", type=float, default=1e-6)
    ap.add_argument("--adjoint-cg-iters", type=int, default=64)
    ap.add_argument("--adjoint-matvec", choices=("hvp", "jtj"),
                    default="hvp",
                    help="IFT adjoint Hessian: exact HVP or Gauss-Newton")
    ap.add_argument("--ridge", type=float, default=1e-2,
                    help="inner gain-prior strength (breaks the "
                    "flux/gain scale degeneracy)")
    ap.add_argument("--synthetic", type=int, default=0, metavar="N",
                    help="refine a perturbed N-station simulated sky "
                    "instead of a dataset")
    ap.add_argument("--perturb", type=float, default=1.15,
                    help="flux perturbation factor for --synthetic")
    ap.add_argument("--noise-sigma", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--fused", action="store_true",
                    help="rejected: refinement needs coherency "
                    "cotangents the fused kernel cannot produce")
    ap.add_argument("--f32", action="store_true")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--checkpoint-every", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("-V", "--verbose", action="store_true")
    return ap


def config_from_args(args) -> RefineConfig:
    return RefineConfig(
        dataset=args.dataset, sky_model=args.sky,
        cluster_file=args.clusters or (args.sky + ".cluster"
                                       if args.sky else ""),
        out_prefix=args.out, tilesz=args.tilesz,
        free_flux=args.free_flux, free_spec=args.free_spec,
        free_pos=args.free_pos, free_modes=args.free_modes,
        outer_iters=args.outer_iters, lbfgs_m=args.lbfgs_m,
        gradient=args.gradient, tol=args.tol,
        inner_iters=args.inner_iters, cg_iters=args.cg_iters,
        damping=args.damping, adjoint_cg_iters=args.adjoint_cg_iters,
        adjoint_matvec=args.adjoint_matvec, ridge=args.ridge,
        synthetic=args.synthetic, perturb=args.perturb,
        noise_sigma=args.noise_sigma, seed=args.seed,
        resume=args.resume, checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir, use_f64=not args.f32,
        verbose=args.verbose)


def _build_problem(cfg: RefineConfig, spec, log):
    """(RefineProblem, true_flux or None).  Synthetic mode simulates a
    known sky and perturbs one flux; dataset mode loads one tile plus
    the sky catalog."""
    from sagecal_tpu.refine import RefineProblem

    dtype = np.float64 if cfg.use_f64 else np.float32
    if cfg.synthetic > 0:
        from sagecal_tpu.data import make_sky, perturb_flux

        sky = make_sky(nstations=cfg.synthetic, tilesz=cfg.tilesz,
                       noise_sigma=cfg.noise_sigma, seed=cfg.seed,
                       shapelet_n0=2 if cfg.free_modes else 0,
                       spectral=bool(cfg.free_spec), dtype=dtype)
        c0, s0 = parse_keys(cfg.free_flux)[0] if cfg.free_flux else (0, 0)
        clusters = perturb_flux(sky, factor=cfg.perturb,
                                cluster=c0, source=s0)
        true_flux = float(sky.true_flux[c0][s0])
        log(f"synthetic sky: {cfg.synthetic} stations, flux "
            f"({c0},{s0}) perturbed x{cfg.perturb:.3f} "
            f"(true {true_flux:.4f})")
        problem = RefineProblem(
            data=sky.data, clusters=clusters,
            tables=sky.shapelet_tables, spec=spec, ridge=cfg.ridge)
        return problem, true_flux
    from sagecal_tpu.io.dataset import VisDataset
    from sagecal_tpu.io.skymodel import load_sky

    with VisDataset(cfg.dataset) as ds:
        meta = ds.meta
        data = ds.load_tile(0, cfg.tilesz, dtype=dtype)
    clusters, _, shapelets = load_sky(
        cfg.sky_model, cfg.cluster_file, meta.ra0, meta.dec0, dtype=dtype)
    tables = ([shapelets] * len(clusters)
              if shapelets is not None else None)
    problem = RefineProblem(data=data, clusters=clusters, tables=tables,
                            spec=spec, ridge=cfg.ridge)
    return problem, None


def run_refine_app(cfg: RefineConfig, log=print) -> dict:
    """Run one refinement to completion; returns the result summary."""
    from sagecal_tpu.elastic import (
        CheckpointManager,
        config_fingerprint,
        flatten_state,
        unflatten_state,
    )
    from sagecal_tpu.obs import RunManifest, default_event_log
    from sagecal_tpu.refine import SkySpec, require_xla_predict, run_refine
    from sagecal_tpu.solvers.lbfgs import LBFGSMemory

    require_xla_predict(False)
    spec = SkySpec(flux=parse_keys(cfg.free_flux),
                   spec=parse_keys(cfg.free_spec),
                   pos=parse_keys(cfg.free_pos),
                   modes=parse_keys(cfg.free_modes))
    problem, true_flux = _build_problem(cfg, spec, log)
    theta0 = spec.theta0(problem.clusters, problem.tables)

    manifest = RunManifest.collect(
        kernel_path="xla", app="refine", nparams=spec.nparams,
        gradient=cfg.gradient, outer_iters=cfg.outer_iters,
        out_prefix=cfg.out_prefix)
    elog = default_event_log(manifest=manifest)
    fingerprint = config_fingerprint(
        app="refine", dataset=cfg.dataset, sky=cfg.sky_model,
        clusters=cfg.cluster_file, synthetic=cfg.synthetic,
        seed=cfg.seed, perturb=cfg.perturb, tilesz=cfg.tilesz,
        spec=repr(spec), gradient=cfg.gradient,
        inner_iters=cfg.inner_iters, cg_iters=cfg.cg_iters,
        ridge=cfg.ridge, use_f64=cfg.use_f64)
    ckpt_dir = cfg.checkpoint_dir or f"{cfg.out_prefix}.ckpt"
    every = cfg.checkpoint_every or (1 if cfg.resume else 0)
    manager = None
    if every > 0 or cfg.resume:
        manager = CheckpointManager(ckpt_dir, fingerprint, app="refine",
                                    every=max(every, 1), elog=elog,
                                    log=log if cfg.verbose else None)

    start_iter = 0
    p_start = None
    memory = None
    theta_resume = None
    if cfg.resume and manager is not None:
        found = manager.resume()
        if found is not None:
            meta, arrays, path = found
            start_iter = int(meta["tile_index"]) + 1
            theta_resume = arrays["theta"]
            p_start = arrays["p_warm"]
            template = LBFGSMemory.init(
                int(theta0.shape[0]), cfg.lbfgs_m, theta0.dtype)
            memory = unflatten_state("mem", arrays, template)
            log(f"resumed at outer iteration {start_iter} from {path}")

    trace_path = f"{cfg.out_prefix}.trace.jsonl"
    out_dir = os.path.dirname(os.path.abspath(cfg.out_prefix))
    os.makedirs(out_dir, exist_ok=True)
    trace_fh = open(trace_path, "a" if start_iter > 0 else "w")

    def on_iteration(it, theta, mem, p_warm, entry):
        if true_flux is not None:
            entry["flux_err"] = abs(
                float(theta[0]) - true_flux) / abs(true_flux)
        trace_fh.write(json.dumps(entry) + "\n")
        trace_fh.flush()
        if elog is not None:
            elog.emit("refine_iter", **{k: v for k, v in entry.items()
                                        if k != "theta"})
        if manager is not None:
            manager.update(it, {"theta": theta, "p_warm": p_warm,
                                **flatten_state("mem", mem)})
        if cfg.verbose:
            log(f"outer {it}: cost {entry['cost']:.6e} "
                f"gradnorm {entry['gradnorm']:.3e}")

    t0 = time.perf_counter()
    try:
        res = run_refine(
            problem, theta0=theta_resume, outer_iters=cfg.outer_iters,
            lbfgs_m=cfg.lbfgs_m, gradient=cfg.gradient,
            inner_iters=cfg.inner_iters, cg_iters=cfg.cg_iters,
            damping=cfg.damping,
            adjoint_cg_iters=cfg.adjoint_cg_iters,
            adjoint_matvec=cfg.adjoint_matvec, tol=cfg.tol,
            p_start=p_start, memory=memory, start_iter=start_iter,
            on_iteration=on_iteration)
    finally:
        trace_fh.close()
        if manager is not None:
            manager.flush()
            manager.close()
    wall = time.perf_counter() - t0

    summary = {
        "app": "refine",
        "nparams": spec.nparams,
        "gradient": cfg.gradient,
        "outer_iters": res.iterations,
        "cost": res.cost,
        "gradnorm": res.gradnorm,
        "theta": np.asarray(res.theta).tolist(),
        "wall_s": wall,
        "outer_iters_per_sec": res.iterations / max(wall, 1e-9),
    }
    if true_flux is not None:
        summary["true_flux"] = true_flux
        summary["flux_err"] = abs(
            float(res.theta[0]) - true_flux) / abs(true_flux)
    with open(f"{cfg.out_prefix}.json", "w") as f:
        json.dump(summary, f, indent=2)
    np.savez(f"{cfg.out_prefix}.npz",
             theta=np.asarray(res.theta), p=np.asarray(res.p))
    if elog is not None:
        elog.emit("refine_done", **{k: v for k, v in summary.items()
                                    if k != "theta"})
        elog.close()
    msg = (f"refine: {res.iterations} outer iterations in {wall:.1f}s, "
           f"cost {res.cost:.4e}, gradnorm {res.gradnorm:.3e}")
    if true_flux is not None:
        msg += f", flux rel err {summary['flux_err']:.2e}"
    log(msg)
    return summary


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    args = build_parser().parse_args(argv)
    if args.fused:
        from sagecal_tpu.refine import require_xla_predict

        try:
            require_xla_predict(True)
        except ValueError as e:
            print(f"sagecal-tpu refine: {e}", file=sys.stderr)
            return 2
    cfg = config_from_args(args)
    if cfg.synthetic <= 0 and not cfg.dataset:
        build_parser().error("--dataset (or --synthetic N) is required")
    if cfg.use_f64:
        import jax

        jax.config.update("jax_enable_x64", True)
    from sagecal_tpu.elastic import ResumeRefused

    try:
        run_refine_app(cfg)
    except ResumeRefused as e:
        print(f"sagecal-tpu refine: {e}", file=sys.stderr)
        return 5
    return 0


if __name__ == "__main__":
    sys.exit(main())
