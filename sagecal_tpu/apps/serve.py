"""``sagecal-tpu serve``: drain a multi-tenant request manifest through
the batch calibration service (sagecal_tpu/serve/).

Device split follows fullbatch: every host stage (request parsing,
HDF5 prefetch, coherency precompute, manifest writes) runs under a CPU
default device; each bucketed batch crosses to the accelerator as ONE
vmapped packed-real jit dispatch.

Exit codes: 0 success; 3 a request diverged under
``--abort-on-divergence``; 5 ``--resume`` refused (foreign checkpoint).
"""

from __future__ import annotations

import argparse
import sys

from sagecal_tpu.apps.config import ServeConfig


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu serve",
        description="Multi-tenant batch calibration service: bucketed "
        "vmapped solves over a JSON request manifest.")
    ap.add_argument("--requests", default="",
                    help="request manifest (JSON); see serve/request.py "
                    "for the schema")
    ap.add_argument("--out-dir", default="serve-out",
                    help="per-request solutions + result manifests")
    ap.add_argument("--batch", type=int, default=8,
                    help="lanes per bucketed batch solve (a bucket "
                    "dispatches when this many same-shape requests "
                    "accumulate; the ragged tail pads by replication)")
    ap.add_argument("--synthetic", type=int, default=0, metavar="N",
                    help="ignore --requests and serve N synthetic "
                    "requests (smoke/bench mode; datasets are simulated "
                    "under --out-dir)")
    ap.add_argument("--tenants", type=int, default=2,
                    help="tenant count for --synthetic")
    ap.add_argument("-e", "--max-emiter", type=int, default=3)
    ap.add_argument("-g", "--max-iter", type=int, default=2)
    ap.add_argument("-l", "--max-lbfgs", type=int, default=10)
    ap.add_argument("-m", "--lbfgs-m", type=int, default=7)
    ap.add_argument("-j", "--solver-mode", type=int, default=3)
    ap.add_argument("-L", "--nulow", type=float, default=2.0)
    ap.add_argument("-H", "--nuhigh", type=float, default=30.0)
    ap.add_argument("-R", "--no-randomize", action="store_true")
    ap.add_argument("--f32", action="store_true",
                    help="solve in float32 (TPU-native precision)")
    ap.add_argument("--fused", action="store_true",
                    help="route batch solves' joint-LBFGS through the "
                    "fused Pallas kernels — ONE batched grid per bucket "
                    "when the capability checks pass (solvers/batched."
                    "choose_batched_path), vmapped solo kernels or XLA "
                    "otherwise.  Requires --f32; ignored under f64")
    ap.add_argument("--coh-dtype", choices=("f32", "bf16"), default="f32",
                    help="coherency-stack storage dtype on the fused "
                    "paths (bf16 halves the dominant HBM stream, f32 "
                    "accumulation)")
    ap.add_argument("--abort-on-divergence", action="store_true")
    ap.add_argument("--shadow-rate", type=float, default=0.0,
                    help="fraction of requests to shadow re-solve on "
                    "the XLA/f32 reference path after their manifests "
                    "land, appending drift records to "
                    "<out-dir>/drift.jsonl (obs/shadow.py); 0 = off, "
                    "bit-identical to no feature")
    ap.add_argument("--shadow-budget-s", type=float, default=120.0,
                    help="wall-clock budget for shadow re-solves; "
                    "sampled requests past it are skipped + counted")
    ap.add_argument("--shadow-seed", type=int, default=0,
                    help="sampler seed: same seed -> same sampled "
                    "request ids, independent of scheduling")
    ap.add_argument("--abort-on-drift", action="store_true",
                    help="escalate a drift-tolerance breach "
                    "(obs/shadow.DRIFT_TOLERANCES) from report-only to "
                    "a run abort (exit 3) after the drain")
    ap.add_argument("--resume", action="store_true",
                    help="skip requests a previous (preempted) server "
                    "run already completed (per-tenant checkpoints)")
    ap.add_argument("--slo", default="",
                    help="per-tenant SLO specs (slo.json; obs/slo.py). "
                    "Report-only: burn-rate alerts + serve_slo_* gauges; "
                    "falls back to a 'slos' key in the request manifest")
    ap.add_argument("--checkpoint-every", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--aot-store", default="",
                    help="cross-worker AOT executable artifact store "
                    "directory (serve/aot_store.py); workers joining a "
                    "warm store compile nothing")
    ap.add_argument("--max-streams", type=int, default=0,
                    help="cap on concurrently open prefetch streams; "
                    "LRU-evicted above the cap (0 = unbounded)")
    ap.add_argument("-V", "--verbose", action="store_true")
    return ap


def config_from_args(args) -> ServeConfig:
    return ServeConfig(
        requests=args.requests, out_dir=args.out_dir, batch=args.batch,
        max_emiter=args.max_emiter, max_iter=args.max_iter,
        max_lbfgs=args.max_lbfgs, lbfgs_m=args.lbfgs_m,
        solver_mode=args.solver_mode, nulow=args.nulow,
        nuhigh=args.nuhigh, randomize=not args.no_randomize,
        abort_on_divergence=args.abort_on_divergence,
        resume=args.resume, checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir, use_f64=not args.f32,
        use_fused_predict=args.fused, coh_dtype=args.coh_dtype,
        verbose=args.verbose, slo=args.slo, aot_store=args.aot_store,
        max_streams=args.max_streams, shadow_rate=args.shadow_rate,
        shadow_budget_s=args.shadow_budget_s,
        shadow_seed=args.shadow_seed,
        abort_on_drift=args.abort_on_drift)


def run_serve(cfg: ServeConfig, requests=None, log=print):
    """Serve ``requests`` (or ``cfg.requests`` manifest) to completion;
    returns the service summary dict."""
    import jax

    from sagecal_tpu.obs.perf import enable_persistent_compilation_cache
    from sagecal_tpu.utils.platform import cpu_device

    enable_persistent_compilation_cache()
    try:
        accel = jax.devices()[0]
    except RuntimeError:
        accel = None
    if accel is not None and accel.platform == "cpu":
        accel = None
    with jax.default_device(cpu_device()):
        return _run_serve_host(cfg, requests, log, accel)


def _run_serve_host(cfg: ServeConfig, requests, log, accel):
    from sagecal_tpu.obs import RunManifest, default_event_log
    from sagecal_tpu.obs.flight import (
        close_flight_recorder,
        get_flight_recorder,
        install_crash_handlers,
        register_event_log,
        unregister_event_log,
    )
    from sagecal_tpu.obs.perf import emit_perf_events
    from sagecal_tpu.obs.trace import close_tracer, configure_tracer
    from sagecal_tpu.serve.request import load_requests
    from sagecal_tpu.serve.service import CalibrationService

    if requests is None:
        requests = load_requests(cfg.requests)
    # manifest stamps the CONFIGURED routing intent; the path each batch
    # actually executed is recorded per dispatch in the
    # ``serve_batch_dispatched`` events (kernel_path / kernel_path_reason)
    fused_intent = (getattr(cfg, "use_fused_predict", False)
                    and not cfg.use_f64)
    manifest = RunManifest.collect(
        kernel_path="fused" if fused_intent else "xla", app="serve",
        requests=len(requests),
        tenants=len({r.tenant for r in requests}), batch=cfg.batch,
        out_dir=cfg.out_dir)
    elog = default_event_log(manifest=manifest)
    install_crash_handlers()
    if elog is not None:
        register_event_log(elog)
    get_flight_recorder(run_id=manifest.run_id)
    # request-lifecycle tracing (SAGECAL_TRACE=1): run-level spans join
    # the event stream on run_id; each request writes its own trace
    configure_tracer(run_id=manifest.run_id)
    store = None
    if getattr(cfg, "aot_store", ""):
        from sagecal_tpu.serve.aot_store import AOTArtifactStore

        store = AOTArtifactStore(cfg.aot_store)
    service = CalibrationService(cfg, log=log, device=accel,
                                 aot_store=store)
    try:
        summary = service.run(requests, elog=elog)
    finally:
        close_tracer()
        if elog is not None:
            emit_perf_events(elog)
            elog.close()
            unregister_event_log(elog)
    log(f"served {summary['served']}/{summary['requests']} requests "
        f"({summary['skipped_resume']} resumed-skipped) in "
        f"{summary['wall_s']:.1f}s — "
        f"{summary['solves_per_sec']:.2f} solves/s, "
        f"p50 latency {summary['p50_latency_s']:.1f}s, "
        f"buckets {summary['buckets']}")
    close_flight_recorder()
    return summary


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    requests = None
    if args.synthetic > 0:
        from sagecal_tpu.serve.request import load_requests
        from sagecal_tpu.serve.synthetic import make_synthetic_workload

        path = make_synthetic_workload(cfg.out_dir, args.synthetic,
                                       n_tenants=args.tenants)
        cfg.requests = path
        requests = load_requests(path)
    elif not cfg.requests:
        build_parser().error("--requests (or --synthetic N) is required")

    from sagecal_tpu.elastic import ResumeRefused
    from sagecal_tpu.obs.quality import DivergenceAbort

    try:
        run_serve(cfg, requests=requests)
    except DivergenceAbort as e:
        print(f"sagecal-tpu serve: {e}", file=sys.stderr)
        return 3
    except ResumeRefused as e:
        print(f"sagecal-tpu serve: {e}", file=sys.stderr)
        return 5
    return 0


if __name__ == "__main__":
    sys.exit(main())
