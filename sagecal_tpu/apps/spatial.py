"""``sagecal-tpu spatial``: spatial regularization as a first-class
workload.

The distributed app runs the spatial FISTA update *inside* its ADMM
loop; this app runs the same ``parallel/spatial.py`` machinery as a
standalone end-to-end pipeline over consensus solutions:

1. solve each frequency band's calibration (``solvers.sage.sagefit``);
2. fit the consensus polynomial Z over bands
   (``parallel.consensus``) and scan AIC/MDL consensus orders
   (``minimum_description_length``, the master's -M path);
3. regress Z onto the spatial basis over cluster centroids by FISTA
   elastic-net (``update_spatialreg_fista``) and write both the raw and
   the spatially-constrained consensus models.

Input modes: ``-f`` glob of per-band vis.h5 datasets + sky/cluster
files, or ``--synthetic NBANDS`` (the make_multiband_skies fixture —
same sky and gains in every band, so the consensus is exactly
polynomial order 1 and MDL has a known oracle answer).

Elastic: a checkpoint after every solved band (``--checkpoint-every``)
makes a killed run resume bit-exactly — the already-solved band
solutions are restored from the checkpoint, the remaining bands solve
fresh, and the downstream consensus/FISTA stages are deterministic
functions of the band solutions.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

import numpy as np

from sagecal_tpu.apps.config import SpatialConfig


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu spatial",
        description="Spatial regularization of consensus solutions: "
        "per-band solves -> consensus polynomial + AIC/MDL scan -> "
        "FISTA elastic-net fit onto the spatial basis.")
    ap.add_argument("-f", "--band-pattern", default="",
                    help="glob of per-band vis.h5 datasets")
    ap.add_argument("-s", "--sky", default="", help="sky model file")
    ap.add_argument("-c", "--clusters", default="",
                    help="cluster file (defaults to <sky>.cluster)")
    ap.add_argument("-o", "--out", default="spatial-out",
                    help="output prefix (<out>.json/.npz)")
    ap.add_argument("-t", "--tilesz", type=int, default=2)
    ap.add_argument("-e", "--max-emiter", type=int, default=3)
    ap.add_argument("-g", "--max-iter", type=int, default=2)
    ap.add_argument("-l", "--max-lbfgs", type=int, default=10)
    ap.add_argument("-m", "--lbfgs-m", type=int, default=7)
    ap.add_argument("-j", "--solver-mode", type=int, default=3)
    ap.add_argument("-r", "--admm-rho", type=float, default=5.0)
    ap.add_argument("-P", "--npoly", type=int, default=2)
    ap.add_argument("-Q", "--poly-type", type=int, default=2)
    ap.add_argument("--spatial-n0", type=int, default=2,
                    help="spatial basis order (G = n0*n0 modes)")
    ap.add_argument("--spatial-beta", type=float, default=0.0,
                    help="shapelet basis scale; <=0 auto")
    ap.add_argument("--spatial-basis", choices=("shapelet", "sharmonic"),
                    default="shapelet")
    ap.add_argument("--spatial-mu", type=float, default=1e-3,
                    help="FISTA L1 strength")
    ap.add_argument("--fista-maxiter", type=int, default=60)
    ap.add_argument("--mdl-kmax", type=int, default=0,
                    help="max consensus order scanned (0: max(npoly,2))")
    ap.add_argument("--synthetic", type=int, default=0, metavar="NBANDS",
                    help="use a simulated multi-band sky instead of -f")
    ap.add_argument("--nstations", type=int, default=7,
                    help="stations for --synthetic")
    ap.add_argument("--noise-sigma", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=5)
    ap.add_argument("--f32", action="store_true")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--checkpoint-every", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("-V", "--verbose", action="store_true")
    return ap


def config_from_args(args) -> SpatialConfig:
    return SpatialConfig(
        band_pattern=args.band_pattern, sky_model=args.sky,
        cluster_file=args.clusters or (args.sky + ".cluster"
                                       if args.sky else ""),
        out_prefix=args.out, tilesz=args.tilesz,
        max_emiter=args.max_emiter, max_iter=args.max_iter,
        max_lbfgs=args.max_lbfgs, lbfgs_m=args.lbfgs_m,
        solver_mode=args.solver_mode, admm_rho=args.admm_rho,
        npoly=args.npoly, poly_type=args.poly_type,
        spatial_n0=args.spatial_n0, spatial_beta=args.spatial_beta,
        spatial_basis=args.spatial_basis, spatial_mu=args.spatial_mu,
        fista_maxiter=args.fista_maxiter, mdl_kmax=args.mdl_kmax,
        synthetic=args.synthetic, nstations=args.nstations,
        noise_sigma=args.noise_sigma, seed=args.seed,
        resume=args.resume, checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir, use_f64=not args.f32,
        verbose=args.verbose)


def _load_bands(cfg: SpatialConfig, log):
    """-> (datas [F], clusters, freqs (F,)).  Synthetic mode simulates
    the same sky in every band; dataset mode loads tile 0 of each file
    in the glob."""
    dtype = np.float64 if cfg.use_f64 else np.float32
    if cfg.synthetic > 0:
        from sagecal_tpu.data import make_multiband_skies

        skies = make_multiband_skies(
            nbands=cfg.synthetic, nstations=cfg.nstations,
            tilesz=cfg.tilesz, noise_sigma=cfg.noise_sigma,
            seed=cfg.seed, dtype=dtype)
        freqs = np.asarray([s.freq0 for s in skies])
        log(f"synthetic multi-band sky: {cfg.synthetic} bands, "
            f"{cfg.nstations} stations, {skies[0].nclusters} clusters")
        return [s.data for s in skies], skies[0].clusters, freqs
    from sagecal_tpu.io.dataset import VisDataset
    from sagecal_tpu.io.skymodel import load_sky

    paths = sorted(glob.glob(cfg.band_pattern))
    if not paths:
        raise FileNotFoundError(
            f"no datasets match band pattern {cfg.band_pattern!r}")
    datas, metas = [], []
    for p in paths:
        with VisDataset(p) as ds:
            metas.append(ds.meta)
            datas.append(ds.load_tile(0, cfg.tilesz, dtype=dtype))
    clusters, _, _ = load_sky(
        cfg.sky_model, cfg.cluster_file, metas[0].ra0, metas[0].dec0,
        dtype=dtype)
    freqs = np.asarray([m.freq0 for m in metas])
    log(f"{len(paths)} bands from {cfg.band_pattern!r}, "
        f"{len(clusters)} clusters")
    return datas, clusters, freqs


def _solve_bands(cfg: SpatialConfig, datas, clusters, manager, elog, log):
    """Per-band calibration solves -> (F, M, 8N) float64 solutions.
    Checkpointed per band; resume restores the solved prefix."""
    import jax
    import jax.numpy as jnp

    from sagecal_tpu.core.types import identity_jones, jones_to_params
    from sagecal_tpu.solvers.sage import (
        SageConfig,
        build_cluster_data,
        sagefit,
    )

    M = len(clusters)
    N = datas[0].nstations
    cdtype = np.complex128 if cfg.use_f64 else np.complex64
    solved = {}
    start_band = 0
    if cfg.resume and manager is not None:
        found = manager.resume()
        if found is not None:
            meta, arrays, path = found
            start_band = int(meta["tile_index"]) + 1
            for b in range(start_band):
                solved[b] = arrays[f"p.{b}"]
            log(f"resumed: bands 0..{start_band - 1} restored from {path}")

    scfg = SageConfig(
        max_emiter=cfg.max_emiter, max_iter=cfg.max_iter,
        max_lbfgs=cfg.max_lbfgs, lbfgs_m=cfg.lbfgs_m,
        solver_mode=cfg.solver_mode)
    eye = jones_to_params(identity_jones(N, cdtype))
    p0 = jnp.broadcast_to(eye, (M, 1, 8 * N)).astype(datas[0].u.dtype)
    for b in range(start_band, len(datas)):
        t0 = time.perf_counter()
        cdata = build_cluster_data(datas[b], clusters, [1] * M)
        res = sagefit(datas[b], cdata, p0, scfg, key=jax.random.PRNGKey(b))
        solved[b] = np.asarray(res.p, np.float64).reshape(M, -1)
        if elog is not None:
            elog.emit("band_solved", band=b,
                      res_0=float(res.res_0), res_1=float(res.res_1),
                      diverged=bool(res.diverged),
                      seconds=time.perf_counter() - t0)
        if cfg.verbose:
            log(f"band {b}: res {float(res.res_0):.4e} -> "
                f"{float(res.res_1):.4e}")
        if manager is not None:
            manager.update(b, {f"p.{i}": solved[i]
                               for i in sorted(solved)})
    return np.stack([solved[b] for b in range(len(datas))])


def run_spatial(cfg: SpatialConfig, log=print) -> dict:
    """Run the spatial pipeline to completion; returns the summary."""
    import jax.numpy as jnp

    from sagecal_tpu.elastic import CheckpointManager, config_fingerprint
    from sagecal_tpu.obs import RunManifest, default_event_log
    from sagecal_tpu.parallel import consensus
    from sagecal_tpu.parallel.mesh import (
        _z_of_zbar_blocks,
        _zbar_blocks_of_z,
    )
    from sagecal_tpu.parallel.spatial import (
        basis_blocks,
        minimum_description_length,
        phikk_matrix,
        spatial_basis_modes,
        spatial_model_apply,
        update_spatialreg_fista,
    )

    t_run = time.perf_counter()
    datas, clusters, freqs = _load_bands(cfg, log)
    F, M, N = len(datas), len(clusters), datas[0].nstations
    n8 = 8 * N
    freq0 = float(np.mean(freqs))
    rho = np.full((M,), cfg.admm_rho)

    manifest = RunManifest.collect(
        kernel_path="xla", app="spatial", bands=F, nclusters=M,
        npoly=cfg.npoly, spatial_n0=cfg.spatial_n0,
        spatial_basis=cfg.spatial_basis, out_prefix=cfg.out_prefix)
    elog = default_event_log(manifest=manifest)
    fingerprint = config_fingerprint(
        app="spatial", band_pattern=cfg.band_pattern,
        sky=cfg.sky_model, clusters=cfg.cluster_file,
        synthetic=cfg.synthetic, nstations=cfg.nstations,
        seed=cfg.seed, tilesz=cfg.tilesz, bands=F,
        solver_mode=cfg.solver_mode, max_emiter=cfg.max_emiter,
        max_iter=cfg.max_iter, use_f64=cfg.use_f64)
    ckpt_dir = cfg.checkpoint_dir or f"{cfg.out_prefix}.ckpt"
    every = cfg.checkpoint_every or (1 if cfg.resume else 0)
    manager = None
    if every > 0 or cfg.resume:
        manager = CheckpointManager(ckpt_dir, fingerprint, app="spatial",
                                    every=max(every, 1), elog=elog,
                                    log=log if cfg.verbose else None)

    try:
        J = _solve_bands(cfg, datas, clusters, manager, elog, log)
    finally:
        if manager is not None:
            manager.flush()
            manager.close()

    # rho-scaled solutions (the master's weight*rho*J blocks); synthetic
    # and single-tile datasets have no flagging, so band weights are 1
    w = np.ones((F,))
    Jst = J * w[:, None, None] * rho[None, :, None]

    # AIC/MDL consensus-order scan (the master's -M path)
    kmax = cfg.mdl_kmax or max(cfg.npoly, 2)
    aic, mdl, k_aic, k_mdl = minimum_description_length(
        Jst, rho, freqs, freq0, weight=w, polytype=cfg.poly_type,
        Kstart=1, Kfinish=kmax)
    log(f"MDL scan orders 1..{kmax}: best AIC={k_aic} MDL={k_mdl} "
        f"(aic {np.array2string(aic, precision=2)}, "
        f"mdl {np.array2string(mdl, precision=2)})")
    if elog is not None:
        elog.emit("mdl_selected", k_aic=int(k_aic), k_mdl=int(k_mdl),
                  aic=[float(x) for x in aic],
                  mdl=[float(x) for x in mdl], kmax=kmax)

    # consensus polynomial Z at the configured order
    ptype = (consensus.POLY_NORMALIZED if cfg.npoly == 1
             else cfg.poly_type)
    B = consensus.setup_polynomials(freqs, freq0, cfg.npoly, ptype)
    B = jnp.asarray(B, Jst.dtype)
    Bi = consensus.find_prod_inverse(B, jnp.asarray(w))
    inv_rho = 1.0 / rho
    z = jnp.einsum("fp,fmk->mpk", B, Jst) * inv_rho[:, None, None]
    Z = jnp.einsum("pq,mqk->mpk", Bi, z)  # (M, Npoly, 8N)

    # spatial basis over flux-weighted cluster centroids (the master's
    # basis setup; nchunk=1 so effective clusters == clusters)
    def _centroid(c):
        wgt = np.maximum(np.abs(np.asarray(c.sI0)), 1e-12)
        return (float(np.average(np.asarray(c.ll), weights=wgt)),
                float(np.average(np.asarray(c.mm), weights=wgt)))

    cent = [_centroid(c) for c in clusters]
    lls = np.asarray([x[0] for x in cent])
    mms = np.asarray([x[1] for x in cent])
    modes, beta_used = spatial_basis_modes(
        lls, mms, cfg.spatial_n0,
        None if cfg.spatial_beta <= 0 else cfg.spatial_beta,
        cfg.spatial_basis)
    log(f"spatial basis {cfg.spatial_basis} n0={cfg.spatial_n0} "
        f"beta={beta_used:.4g}")
    Phi = basis_blocks(modes)
    Phikk = phikk_matrix(Phi, lam=1e-6)

    # FISTA elastic-net regression of Zbar onto the basis (fista.c)
    t_fista = time.perf_counter()
    Zbar = _zbar_blocks_of_z(Z, M, cfg.npoly, 1, n8)  # (M, 2N*Npoly, 2)
    Zs = update_spatialreg_fista(
        Zbar, Phikk.astype(Zbar.dtype), Phi.astype(Zbar.dtype),
        cfg.spatial_mu, maxiter=cfg.fista_maxiter)
    Zbar_sp = spatial_model_apply(Zs, Phi.astype(Zs.dtype))
    Z_spatial = _z_of_zbar_blocks(Zbar_sp, M, cfg.npoly, 1, n8)
    fista_s = time.perf_counter() - t_fista
    fit_rel = float(jnp.linalg.norm((Zbar - Zbar_sp).ravel())
                    / jnp.maximum(jnp.linalg.norm(Zbar.ravel()), 1e-30))
    nnz = int(jnp.sum(jnp.abs(Zs) > 0))
    log(f"FISTA fit: rel residual {fit_rel:.4e}, {nnz}/{Zs.size} "
        f"nonzero coefficients in {fista_s:.2f}s")
    if elog is not None:
        elog.emit("spatial_fista", fit_rel=fit_rel, nnz=nnz,
                  maxiter=cfg.fista_maxiter, mu=cfg.spatial_mu,
                  beta=beta_used, seconds=fista_s)

    wall = time.perf_counter() - t_run
    summary = {
        "app": "spatial", "bands": F, "nclusters": M, "nstations": N,
        "npoly": cfg.npoly, "spatial_n0": cfg.spatial_n0,
        "spatial_basis": cfg.spatial_basis, "beta": beta_used,
        "k_aic": int(k_aic), "k_mdl": int(k_mdl),
        "aic": [float(x) for x in aic], "mdl": [float(x) for x in mdl],
        "fista_fit_rel": fit_rel, "fista_nnz": nnz,
        "wall_s": wall,
    }
    out_dir = os.path.dirname(os.path.abspath(cfg.out_prefix))
    os.makedirs(out_dir, exist_ok=True)
    with open(f"{cfg.out_prefix}.json", "w") as f:
        json.dump(summary, f, indent=2)
    np.savez(f"{cfg.out_prefix}.npz",
             J=J, Z=np.asarray(Z), Zs=np.asarray(Zs),
             Z_spatial=np.asarray(Z_spatial), aic=aic, mdl=mdl,
             freqs=freqs)
    if elog is not None:
        elog.emit("spatial_done",
                  **{k: v for k, v in summary.items()
                     if k not in ("aic", "mdl")})
        elog.close()
    log(f"spatial: {F} bands -> order-{cfg.npoly} consensus -> "
        f"{cfg.spatial_n0 ** 2}-mode {cfg.spatial_basis} fit in "
        f"{wall:.1f}s -> {cfg.out_prefix}.json/.npz")
    return summary


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if cfg.synthetic <= 0 and not cfg.band_pattern:
        build_parser().error("-f PATTERN (or --synthetic N) is required")
    if cfg.use_f64:
        import jax

        jax.config.update("jax_enable_x64", True)
    from sagecal_tpu.elastic import ResumeRefused

    try:
        run_spatial(cfg)
    except ResumeRefused as e:
        print(f"sagecal-tpu spatial: {e}", file=sys.stderr)
        return 5
    return 0


if __name__ == "__main__":
    sys.exit(main())
