"""``sagecal-tpu stream``: streaming/online calibration CLI.

Sliding-window solves over a time stream with the elastic warm-start
chain (sagecal_tpu/fleet/stream.py).  Exit codes: 0 success; 5 resume
refused (fingerprint mismatch or a live foreign owner lease on the
chain checkpoint — the standard elastic mapping).
"""

from __future__ import annotations

import argparse
import sys

from sagecal_tpu.apps.config import StreamConfig


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu stream",
        description="Sliding-window streaming calibration with "
        "warm-started windows (latency-to-first-solution workload).")
    ap.add_argument("-d", "--dataset", default="",
                    help="input vis.h5 consumed as a time stream")
    ap.add_argument("-s", "--sky", default="", help="sky model file")
    ap.add_argument("-c", "--clusters", default="",
                    help="cluster file (defaults to <sky>.cluster)")
    ap.add_argument("--out-dir", default="stream-out")
    ap.add_argument("-t", "--window", type=int, default=2,
                    help="time samples per sliding window")
    ap.add_argument("--hop", type=int, default=1,
                    help="samples the window advances per solve")
    ap.add_argument("--max-windows", type=int, default=0,
                    help="stop after this many windows (0 = stream end)")
    ap.add_argument("--cold", action="store_true",
                    help="disable the warm-start chain (every window "
                    "solves from identity with full budgets) — the "
                    "bench baseline the warm chain is gated against")
    ap.add_argument("--warm-emiter", type=int, default=1,
                    help="EM passes for warm-started windows")
    ap.add_argument("--warm-lbfgs", type=int, default=0,
                    help="LBFGS budget for warm windows (0 = inherit -l)")
    ap.add_argument("-I", "--in-column", default="vis")
    ap.add_argument("-e", "--max-emiter", type=int, default=3)
    ap.add_argument("-g", "--max-iter", type=int, default=2)
    ap.add_argument("-l", "--max-lbfgs", type=int, default=10)
    ap.add_argument("-m", "--lbfgs-m", type=int, default=7)
    ap.add_argument("-j", "--solver-mode", type=int, default=3)
    ap.add_argument("-L", "--nulow", type=float, default=2.0)
    ap.add_argument("-H", "--nuhigh", type=float, default=30.0)
    ap.add_argument("-R", "--no-randomize", action="store_true")
    ap.add_argument("--res-ratio", type=float, default=5.0,
                    help="divergence guard: res1 > ratio*res0 resets "
                    "the warm-start chain to identity")
    ap.add_argument("--resume", action="store_true",
                    help="adopt the newest chain checkpoint (refused "
                    "on fingerprint mismatch or a live owner lease)")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help=">0 checkpoints the chain every this many "
                    "windows; --resume implies 1 when unset")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="chain checkpoint directory "
                    "(default <out-dir>/stream.ckpt)")
    ap.add_argument("--lease-ttl", type=float, default=30.0,
                    help="owner-lease TTL stamped into chain "
                    "checkpoints; a second process adopts the chain "
                    "only after this long without a renewal")
    ap.add_argument("--f32", action="store_true",
                    help="solve in float32 (TPU-native precision)")
    ap.add_argument("--synthetic", type=int, default=0, metavar="N",
                    help="ignore -d/-s and simulate an N-station "
                    "stream fixture in the out dir")
    ap.add_argument("--ntime", type=int, default=6,
                    help="stream length for --synthetic")
    ap.add_argument("--nchan", type=int, default=2)
    ap.add_argument("--noise-sigma", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("-V", "--verbose", action="store_true")
    return ap


def config_from_args(args) -> StreamConfig:
    return StreamConfig(
        dataset=args.dataset, sky_model=args.sky,
        cluster_file=args.clusters or (args.sky + ".cluster"),
        out_dir=args.out_dir, window=args.window, hop=args.hop,
        max_windows=args.max_windows, warm_start=not args.cold,
        warm_emiter=args.warm_emiter, warm_lbfgs=args.warm_lbfgs,
        in_column=args.in_column,
        max_emiter=args.max_emiter, max_iter=args.max_iter,
        max_lbfgs=args.max_lbfgs, lbfgs_m=args.lbfgs_m,
        solver_mode=args.solver_mode, nulow=args.nulow,
        nuhigh=args.nuhigh, randomize=not args.no_randomize,
        res_ratio=args.res_ratio, resume=args.resume,
        checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir,
        lease_ttl_s=args.lease_ttl, use_f64=not args.f32,
        verbose=args.verbose, synthetic=args.synthetic,
        ntime=args.ntime, nchan=args.nchan,
        noise_sigma=args.noise_sigma, seed=args.seed)


def run_stream(cfg: StreamConfig, log=print):
    """Host pipeline under a CPU default device; each window's solve
    crosses to the accelerator as one jit dispatch (the serve split)."""
    import jax

    from sagecal_tpu.fleet.stream import StreamCalibrator
    from sagecal_tpu.obs import RunManifest, default_event_log
    from sagecal_tpu.obs.flight import (
        close_flight_recorder, get_flight_recorder,
        install_crash_handlers, register_event_log,
        unregister_event_log,
    )
    from sagecal_tpu.obs.perf import (
        emit_perf_events, enable_persistent_compilation_cache,
    )
    from sagecal_tpu.obs.trace import close_tracer, configure_tracer
    from sagecal_tpu.utils.platform import cpu_device

    enable_persistent_compilation_cache()
    try:
        accel = jax.devices()[0]
    except RuntimeError:
        accel = None
    if accel is not None and accel.platform == "cpu":
        accel = None
    manifest = RunManifest.collect(
        kernel_path="xla", app="stream", dataset=cfg.dataset,
        window=cfg.window, hop=cfg.hop, warm_start=cfg.warm_start,
        solver_mode=cfg.solver_mode)
    elog = default_event_log(manifest=manifest)
    install_crash_handlers()
    if elog is not None:
        register_event_log(elog)
    get_flight_recorder(run_id=manifest.run_id)
    configure_tracer(run_id=manifest.run_id)
    try:
        with jax.default_device(cpu_device()):
            return StreamCalibrator(cfg, log=log, device=accel).run(
                elog=elog)
    finally:
        close_tracer()
        if elog is not None:
            emit_perf_events(elog)
            elog.close()
            unregister_event_log(elog)
        close_flight_recorder()


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    if cfg.synthetic > 0:
        from sagecal_tpu.fleet.stream import make_synthetic_stream

        ds, sky, cluster = make_synthetic_stream(
            cfg.out_dir, nstations=cfg.synthetic, ntime=cfg.ntime,
            nchan=cfg.nchan, noise_sigma=cfg.noise_sigma,
            seed=cfg.seed)
        cfg.dataset, cfg.sky_model, cfg.cluster_file = ds, sky, cluster
    elif not (cfg.dataset and cfg.sky_model):
        build_parser().error(
            "-d and -s (or --synthetic N) are required")
    from sagecal_tpu.elastic import ResumeRefused

    try:
        run_stream(cfg)
    except ResumeRefused as e:
        print(f"sagecal-tpu stream: {e}", file=sys.stderr)
        return 5
    return 0


if __name__ == "__main__":
    sys.exit(main())
