"""``sagecal-tpu widefield``: wide-field calibration through the
hierarchical sky predict.

A synthetic compact-array/all-sky observation over ``nsources`` point
sources (``data.simsky.make_sky(wide_field=True)``) is calibrated tile
by tile: the full source list is collapsed into ``nclusters``
tree-partitioned effective directions (``sky.tree.partition_by_tree``),
each tile's per-cluster coherencies come from
``predict_coherencies_hier`` (or the exact predict under ``--exact``),
the sampled a-posteriori error is verified by the quality watchdog
(``obs.quality.check_hier_predict``), and the standard packed SAGE
solve runs warm-started from the previous tile.  Exit codes: 0
success; 3 divergence abort (``--abort-on-divergence``); 5 resume
refused (fingerprint mismatch).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

from sagecal_tpu.apps.config import WidefieldConfig


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="sagecal-tpu widefield",
        description="10k+-source wide-field calibration via the "
        "tree-clustered hierarchical sky predict.")
    ap.add_argument("--out-dir", default="widefield-out")
    ap.add_argument("-n", "--nstations", type=int, default=24)
    ap.add_argument("--ntiles", type=int, default=4)
    ap.add_argument("-t", "--tilesz", type=int, default=2)
    ap.add_argument("--nchan", type=int, default=1)
    ap.add_argument("-S", "--nsources", type=int, default=2000,
                    help="total point sources across the field")
    ap.add_argument("--nblobs", type=int, default=12,
                    help="spatial blobs the sky generator draws")
    ap.add_argument("--fov", type=float, default=1.1,
                    help="field diameter in direction cosines")
    ap.add_argument("--cluster-scale", type=float, default=0.004)
    ap.add_argument("--freq0", type=float, default=30e6)
    ap.add_argument("--extent-m", type=float, default=80.0,
                    help="station layout radius (compact-array regime)")
    ap.add_argument("--gain-amp", type=float, default=0.1)
    ap.add_argument("--noise-sigma", type=float, default=0.0)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("-k", "--nclusters", type=int, default=4,
                    help="tree-collapsed effective calibration "
                    "directions fed to the packed solver")
    ap.add_argument("-p", "--order", type=int, default=8,
                    help="multipole/Taylor truncation order")
    ap.add_argument("--theta", type=float, default=1.5,
                    help="well-separation phase budget (radians); "
                    "<= 0 forces the exact near-field path")
    ap.add_argument("--leaf-size", type=int, default=32)
    ap.add_argument("--tile-rows", type=int, default=128)
    ap.add_argument("--source-chunk", type=int, default=32)
    ap.add_argument("--exact", action="store_true",
                    help="use the exact predict for the cluster "
                    "coherencies (parity / baseline runs)")
    ap.add_argument("--hier-nsample", type=int, default=32,
                    help="baseline rows sampled per tile for the "
                    "a-posteriori error check (0 disables)")
    ap.add_argument("--hier-max-rel-err", type=float, default=1e-3,
                    help="watchdog threshold on the sampled error "
                    "(<= 0: the a-priori bound of (order, theta))")
    ap.add_argument("-e", "--max-emiter", type=int, default=3)
    ap.add_argument("-g", "--max-iter", type=int, default=2)
    ap.add_argument("-l", "--max-lbfgs", type=int, default=10)
    ap.add_argument("-m", "--lbfgs-m", type=int, default=7)
    ap.add_argument("-j", "--solver-mode", type=int, default=3)
    ap.add_argument("-L", "--nulow", type=float, default=2.0)
    ap.add_argument("-H", "--nuhigh", type=float, default=30.0)
    ap.add_argument("-R", "--no-randomize", action="store_true")
    ap.add_argument("--res-ratio", type=float, default=5.0)
    ap.add_argument("--abort-on-divergence", action="store_true")
    ap.add_argument("--resume", action="store_true",
                    help="adopt the newest checkpoint (refused on "
                    "fingerprint mismatch)")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help=">0 checkpoints every this many tiles; "
                    "--resume implies 1 when unset")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="default <out-dir>/widefield.ckpt")
    ap.add_argument("--f32", action="store_true")
    ap.add_argument("-V", "--verbose", action="store_true")
    return ap


def config_from_args(args) -> WidefieldConfig:
    return WidefieldConfig(
        out_dir=args.out_dir, nstations=args.nstations,
        ntiles=args.ntiles, tilesz=args.tilesz, nchan=args.nchan,
        nsources=args.nsources, nblobs=args.nblobs, fov=args.fov,
        cluster_scale=args.cluster_scale, freq0=args.freq0,
        extent_m=args.extent_m, gain_amp=args.gain_amp,
        noise_sigma=args.noise_sigma, seed=args.seed,
        nclusters=args.nclusters, order=args.order, theta=args.theta,
        leaf_size=args.leaf_size, tile_rows=args.tile_rows,
        source_chunk=args.source_chunk, exact=args.exact,
        hier_nsample=args.hier_nsample,
        hier_max_rel_err=args.hier_max_rel_err,
        max_emiter=args.max_emiter, max_iter=args.max_iter,
        max_lbfgs=args.max_lbfgs, lbfgs_m=args.lbfgs_m,
        solver_mode=args.solver_mode, nulow=args.nulow,
        nuhigh=args.nuhigh, randomize=not args.no_randomize,
        res_ratio=args.res_ratio,
        abort_on_divergence=args.abort_on_divergence,
        resume=args.resume, checkpoint_every=args.checkpoint_every,
        checkpoint_dir=args.checkpoint_dir, use_f64=not args.f32,
        verbose=args.verbose)


def _slice_tile(data, t: int, tilesz: int):
    """Tile ``t`` of a long observation: ``tilesz`` consecutive time
    samples with the time index rebased so chunk maps start at 0."""
    rpt = data.nbase * tilesz
    sl = slice(t * rpt, (t + 1) * rpt)
    return data.replace(
        u=data.u[sl], v=data.v[sl], w=data.w[sl],
        ant_p=data.ant_p[sl], ant_q=data.ant_q[sl],
        vis=data.vis[:, :, sl], mask=data.mask[:, sl],
        time_idx=data.time_idx[sl] - t * tilesz, tilesz=tilesz)


def _tile_coherencies(cfg: WidefieldConfig, data_t, eff_clusters):
    """Per-cluster (F, 4, rows) coherencies for one tile — hierarchical
    by default, exact under ``cfg.exact``."""
    import jax.numpy as jnp

    from sagecal_tpu.ops.rime import predict_coherencies
    from sagecal_tpu.sky.predict import predict_coherencies_hier

    cohs = []
    for src in eff_clusters:
        if cfg.exact or cfg.theta <= 0.0:
            coh = predict_coherencies(
                data_t.u, data_t.v, data_t.w, data_t.freqs, src,
                0.0, cfg.source_chunk,
                has_extended=False, has_shapelet=False)
        else:
            coh = predict_coherencies_hier(
                data_t.u, data_t.v, data_t.w, data_t.freqs, src,
                order=cfg.order, theta=cfg.theta,
                leaf_size=cfg.leaf_size, tile_rows=cfg.tile_rows,
                source_chunk=cfg.source_chunk)
        cohs.append(coh)
    return jnp.stack(cohs)


def run_widefield(cfg: WidefieldConfig, log=print) -> dict:
    """Host pipeline under a CPU default device; each tile's solve
    crosses to the accelerator as one jit dispatch (the serve split).
    Returns the summary dict also written to widefield.json."""
    import jax

    from sagecal_tpu.obs import RunManifest, default_event_log
    from sagecal_tpu.obs.flight import (
        close_flight_recorder, get_flight_recorder,
        install_crash_handlers, register_event_log,
        unregister_event_log,
    )
    from sagecal_tpu.obs.perf import (
        emit_perf_events, enable_persistent_compilation_cache,
    )
    from sagecal_tpu.obs.trace import close_tracer, configure_tracer
    from sagecal_tpu.utils.platform import cpu_device

    enable_persistent_compilation_cache()
    try:
        accel = jax.devices()[0]
    except RuntimeError:
        accel = None
    if accel is not None and accel.platform == "cpu":
        accel = None
    manifest = RunManifest.collect(
        kernel_path="xla", app="widefield", nsources=cfg.nsources,
        nclusters=cfg.nclusters, ntiles=cfg.ntiles, order=cfg.order,
        theta=cfg.theta, exact=cfg.exact)
    elog = default_event_log(manifest=manifest)
    install_crash_handlers()
    if elog is not None:
        register_event_log(elog)
    get_flight_recorder(run_id=manifest.run_id)
    configure_tracer(run_id=manifest.run_id)
    try:
        with jax.default_device(cpu_device()):
            return _run_tiles(cfg, elog, accel, log)
    finally:
        close_tracer()
        if elog is not None:
            emit_perf_events(elog)
            elog.close()
            unregister_event_log(elog)
        close_flight_recorder()


def _run_tiles(cfg: WidefieldConfig, elog, accel, log) -> dict:
    import jax
    import jax.numpy as jnp

    from sagecal_tpu.core.types import identity_jones, jones_to_params
    from sagecal_tpu.data.simsky import make_sky
    from sagecal_tpu.elastic import CheckpointManager, config_fingerprint
    from sagecal_tpu.obs.quality import (
        abort_if_diverged, check_and_emit, check_hier_predict,
    )
    from sagecal_tpu.sky.farfield import apriori_rel_bound
    from sagecal_tpu.sky.predict import (
        gather_sources, sampled_error_estimate,
    )
    from sagecal_tpu.sky.tree import build_source_tree, partition_by_tree
    from sagecal_tpu.solvers.sage import ClusterData, SageConfig, solve_tile

    t_run = time.perf_counter()
    os.makedirs(cfg.out_dir, exist_ok=True)
    dtype = np.float64 if cfg.use_f64 else np.float32

    # one long observation; tiles are consecutive time slices of it
    sky = make_sky(
        nstations=cfg.nstations, tilesz=cfg.ntiles * cfg.tilesz,
        nchan=cfg.nchan, nclusters=cfg.nblobs, freq0=cfg.freq0,
        gain_amp=cfg.gain_amp, noise_sigma=cfg.noise_sigma,
        seed=cfg.seed, dtype=dtype, wide_field=True,
        nsources=cfg.nsources, fov=cfg.fov,
        cluster_scale=cfg.cluster_scale, extent_m=cfg.extent_m)

    # hierarchical collapse: all sources -> nclusters effective
    # calibration directions via the shallowest tree level that can
    # support them (sky/tree.py partition_by_tree)
    merged = jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs), *sky.clusters)
    tree = build_source_tree(
        np.asarray(merged.ll, np.float64), np.asarray(merged.mm, np.float64),
        np.asarray(merged.nn, np.float64), leaf_size=cfg.leaf_size)
    groups = partition_by_tree(tree, cfg.nclusters)
    eff_clusters = [gather_sources(merged, g) for g in groups]
    M, N = len(eff_clusters), cfg.nstations
    bound = apriori_rel_bound(cfg.order, cfg.theta)
    tol = cfg.hier_max_rel_err if cfg.hier_max_rel_err > 0 else bound
    log(f"widefield: {cfg.nsources} sources in {cfg.nblobs} blobs -> "
        f"{M} effective clusters "
        f"({', '.join(str(len(g)) for g in groups)} sources); "
        f"predict={'exact' if cfg.exact else f'hier(p={cfg.order}, theta={cfg.theta})'}")

    fingerprint = config_fingerprint(
        app="widefield", nstations=cfg.nstations, ntiles=cfg.ntiles,
        tilesz=cfg.tilesz, nchan=cfg.nchan, nsources=cfg.nsources,
        nblobs=cfg.nblobs, nclusters=cfg.nclusters, fov=cfg.fov,
        freq0=cfg.freq0, extent_m=cfg.extent_m, seed=cfg.seed,
        order=cfg.order, theta=cfg.theta, exact=cfg.exact,
        solver_mode=cfg.solver_mode, max_emiter=cfg.max_emiter,
        max_iter=cfg.max_iter, max_lbfgs=cfg.max_lbfgs,
        use_f64=cfg.use_f64)
    ckpt_dir = cfg.checkpoint_dir or os.path.join(
        cfg.out_dir, "widefield.ckpt")
    every = cfg.checkpoint_every or (1 if cfg.resume else 0)
    manager = None
    if every > 0 or cfg.resume:
        manager = CheckpointManager(ckpt_dir, fingerprint, app="widefield",
                                    every=max(every, 1), elog=elog,
                                    log=log if cfg.verbose else None)

    cdtype = np.complex128 if cfg.use_f64 else np.complex64
    eye = jones_to_params(identity_jones(N, cdtype))
    pinit = jnp.broadcast_to(eye, (M, 1, 8 * N)).astype(sky.data.u.dtype)
    scfg = SageConfig(
        max_emiter=cfg.max_emiter, max_iter=cfg.max_iter,
        max_lbfgs=cfg.max_lbfgs, lbfgs_m=cfg.lbfgs_m,
        solver_mode=cfg.solver_mode, nulow=cfg.nulow,
        nuhigh=cfg.nuhigh, randomize=cfg.randomize)
    key0 = jax.random.PRNGKey(cfg.seed)

    gains: dict = {}
    tiles_meta: dict = {}
    p = pinit
    start_tile = 0
    if cfg.resume and manager is not None:
        found = manager.resume()
        if found is not None:
            meta, arrays, path = found
            start_tile = int(meta["tile_index"]) + 1
            for i in range(start_tile):
                gains[i] = arrays[f"g.{i}"]
            p = jnp.asarray(arrays["warm"])
            tiles_meta = {int(k): v for k, v in
                          json.loads(meta.get("tiles_json", "{}")).items()}
            log(f"resumed: tiles 0..{start_tile - 1} restored from {path}")

    max_rel_err = 0.0
    watchdog_ok = True
    # re-derive verification state from a resumed prefix so the summary
    # is identical to an uninterrupted run's
    for i in range(start_tile):
        tm = tiles_meta.get(i, {})
        if tm.get("rel_err") is not None:
            max_rel_err = max(max_rel_err, float(tm["rel_err"]))
        if tm.get("hier_verdict", "ok") != "ok":
            watchdog_ok = False

    try:
        for t in range(start_tile, cfg.ntiles):
            t0 = time.perf_counter()
            data_t = _slice_tile(sky.data, t, cfg.tilesz)
            coh = _tile_coherencies(cfg, data_t, eff_clusters)
            rows = int(data_t.u.shape[0])
            cdata = ClusterData(
                coh=coh,
                chunk_map=jnp.zeros((M, rows), jnp.int32),
                nchunk=jnp.ones((M,), jnp.int32))

            # a-posteriori verification of the hierarchical prediction:
            # exact predict on a sampled row subset of the largest
            # effective cluster vs the same rows of its hier coherency
            rel_err = None
            h_verdict = "ok"
            if not cfg.exact and cfg.hier_nsample > 0:
                est = sampled_error_estimate(
                    data_t.u, data_t.v, data_t.w, data_t.freqs,
                    eff_clusters[0], coh[0],
                    nsample=cfg.hier_nsample, seed=cfg.seed + t,
                    source_chunk=cfg.source_chunk)
                rel_err = float(est["rel_err"])
                max_rel_err = max(max_rel_err, rel_err)
                h_verdict, _ = check_hier_predict(
                    elog, rel_err, tol, log=log, tile=t, app="widefield",
                    order=cfg.order, theta=cfg.theta,
                    apriori_bound=bound, nsample=int(est["nsample"]))
                watchdog_ok = watchdog_ok and (h_verdict == "ok")

            res = solve_tile(data_t, cdata, p, scfg,
                             key=jax.random.fold_in(key0, t),
                             device=accel)
            res0, res1 = float(res.res_0), float(res.res_1)
            diverged = (not np.isfinite(res1) or res1 == 0.0
                        or res1 > cfg.res_ratio * res0)
            gains[t] = np.asarray(res.p, np.float64)
            # warm-start chain: the next tile starts from this solution
            # (identity reset on divergence, the fullbatch guard)
            p = pinit if diverged else jnp.asarray(gains[t]).astype(p.dtype)

            q_verdict, q_reasons = "ok", []
            if getattr(res, "quality", None) is not None:
                q_verdict, q_reasons = check_and_emit(
                    elog, res.quality, log=log, tile=t, app="widefield")
            if diverged:
                if q_verdict != "diverged" and elog is not None:
                    elog.emit(
                        "solver_diverged",
                        reasons=[f"residual_ratio:{res0:.3e}->{res1:.3e}"],
                        tile=t, app="widefield")
                q_verdict = "diverged"
                q_reasons = q_reasons + [
                    f"residual_ratio:{res0:.3e}->{res1:.3e}"]
            if cfg.abort_on_divergence:
                abort_if_diverged(elog, q_verdict, q_reasons,
                                  tile=t, app="widefield")

            tiles_meta[t] = {
                "res_0": res0, "res_1": res1, "rel_err": rel_err,
                "hier_verdict": h_verdict, "solve_verdict": q_verdict,
                "seconds": time.perf_counter() - t0}
            if elog is not None:
                elog.emit("widefield_tile", tile=t, **tiles_meta[t])
            if cfg.verbose:
                err_s = "n/a" if rel_err is None else f"{rel_err:.3e}"
                log(f"tile {t}: res {res0:.4e} -> {res1:.4e}, "
                    f"hier_err {err_s} ({tiles_meta[t]['seconds']:.1f}s)")
            if manager is not None:
                arrays = {f"g.{i}": gains[i] for i in sorted(gains)}
                arrays["warm"] = np.asarray(p)
                manager.update(
                    t, arrays,
                    tiles_json=json.dumps(
                        {str(k): v for k, v in tiles_meta.items()}))
    finally:
        if manager is not None:
            manager.flush()
            manager.close()

    stacked = np.stack([gains[t] for t in range(cfg.ntiles)])
    np.savez(os.path.join(cfg.out_dir, "solutions.npz"),
             gains=stacked,
             cluster_sizes=np.asarray([len(g) for g in groups]))
    summary = {
        "app": "widefield",
        "nsources": cfg.nsources,
        "nblobs": cfg.nblobs,
        "nclusters_eff": M,
        "cluster_sizes": [int(len(g)) for g in groups],
        "ntiles": cfg.ntiles,
        "exact": bool(cfg.exact),
        "order": cfg.order,
        "theta": cfg.theta,
        "apriori_bound": float(bound),
        "hier_max_rel_err": (None if cfg.exact or cfg.hier_nsample <= 0
                             else float(max_rel_err)),
        "hier_watchdog_ok": bool(watchdog_ok),
        "tiles": [tiles_meta[t] for t in range(cfg.ntiles)],
        "seconds": time.perf_counter() - t_run,
    }
    with open(os.path.join(cfg.out_dir, "widefield.json"), "w") as fh:
        json.dump(summary, fh, indent=1)
    log(f"widefield: {cfg.ntiles} tiles in {summary['seconds']:.1f}s, "
        f"max sampled rel err "
        f"{'n/a' if summary['hier_max_rel_err'] is None else f'{max_rel_err:.3e}'} "
        f"(tolerance {tol:.3e}), watchdog "
        f"{'ok' if watchdog_ok else 'DEGRADED'}")
    return summary


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    args = build_parser().parse_args(argv)
    cfg = config_from_args(args)
    import jax

    if cfg.use_f64:
        jax.config.update("jax_enable_x64", True)
    from sagecal_tpu.elastic import ResumeRefused
    from sagecal_tpu.obs.quality import DivergenceAbort

    try:
        run_widefield(cfg)
    except DivergenceAbort as e:
        print(f"sagecal-tpu widefield: {e}", file=sys.stderr)
        return 3
    except ResumeRefused as e:
        print(f"sagecal-tpu widefield: {e}", file=sys.stderr)
        return 5
    return 0


if __name__ == "__main__":
    sys.exit(main())
