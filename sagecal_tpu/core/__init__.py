from sagecal_tpu.core.types import (
    VisData,
    JonesSolution,
    params_to_jones,
    jones_to_params,
)
from sagecal_tpu.core.baselines import generate_baselines, tile_baselines

__all__ = [
    "VisData",
    "JonesSolution",
    "params_to_jones",
    "jones_to_params",
    "generate_baselines",
    "tile_baselines",
]
