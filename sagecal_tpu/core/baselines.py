"""Baseline enumeration and tiling.

Replaces the reference's ``generate_baselines`` / ``rearrange_*`` machinery
(``/root/reference/src/lib/Dirac/baseline_utils.c``): instead of building
pthread-partitioned C structs, we emit flat index arrays that serve as
gather indices inside jitted kernels — the XLA analog of the reference's
flattened GPU layouts ``ddcoh``/``ddbase``.
"""

from __future__ import annotations

import numpy as np


def generate_baselines(nstations: int) -> tuple[np.ndarray, np.ndarray]:
    """All cross-correlation pairs p < q; returns (ant_p, ant_q) int32 arrays
    of length N(N-1)/2 (ordering matches the reference's nested station loop,
    baseline_utils.c)."""
    p, q = np.triu_indices(nstations, k=1)
    return p.astype(np.int32), q.astype(np.int32)


def tile_baselines(
    nstations: int, tilesz: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Baseline index arrays for a whole tile of ``tilesz`` timeslots.

    Returns (ant_p, ant_q, time_idx), each of length nbase*tilesz, baseline
    varying fastest (the reference's IOData row order, src/MS/data.h:48-73).
    """
    p, q = generate_baselines(nstations)
    nbase = p.shape[0]
    ant_p = np.tile(p, tilesz)
    ant_q = np.tile(q, tilesz)
    time_idx = np.repeat(np.arange(tilesz, dtype=np.int32), nbase)
    return ant_p, ant_q, time_idx


def count_baselines(nstations: int) -> int:
    return nstations * (nstations - 1) // 2
