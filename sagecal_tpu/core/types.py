"""Core data model: visibilities, Jones parameter layout, flags.

Design notes
------------
The reference stores a visibility row as 8 doubles (XX,XY,YX,YY x re,im;
ordering documented at ``/root/reference/src/lib/Dirac/Dirac.h:1617-1618``)
and a station's Jones solution as 8 reals ``S0..S7`` with
``J = [S0+jS1, S4+jS5; S2+jS3, S6+jS7]`` (``/root/reference/README.md``
section 6).

**Canonical visibility layout — rows minor-most.**  Visibilities,
coherencies, models and residuals are complex arrays of shape
``(..., F, 4, rows)``: channel, then the four coherency components
``[XX, XY, YX, YY]`` (the 2x2 matrix row-major), with the long
``rows = nbase * tilesz`` axis LAST.  This is the TPU-native choice: XLA
tiles the two minor-most dims to (8, 128) lanes, so a trailing 2x2 matrix
axis would pad every visibility buffer 64x (measured: the 62-station/
100-cluster tile's 726 MB coherency stack became a 46.47 GB allocation),
while rows-minor layouts pad only the tail of the rows axis.  The RIME's
tiny 2x2 matrix products are expanded into explicit component arithmetic
(:func:`corrupt_flat`): elementwise VPU math vectorized along the rows
lane axis, which is both layout-friendly and faster than gathering
per-row 2x2 matrices (2x2 matmuls never reach the MXU anyway).  Jones
solutions stay ``(..., nstations, 2, 2)`` complex — they are small.  The
8-real S-ordering only exists at the text-file boundary
(:mod:`sagecal_tpu.io.solutions`) for byte-compatibility with the
reference's solution format.

Solver parameter vectors are *real* (shape ``(..., 8*N)``) like the
reference's ``p`` vectors (``/root/reference/src/lib/Dirac/lmfit.c``),
because LM / LBFGS line searches and trust regions are real-valued
optimizers.  :func:`params_to_jones` / :func:`jones_to_params` convert, and
their ordering matches the reference so solution files can be diffed
directly against ``sagecal`` output.

Everything is a pytree (``flax.struct``) so whole datasets can be passed
through ``jit`` / ``shard_map`` boundaries and sharded over a mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

# Speed of light (m/s), used to convert metre uvw to wavelengths (the
# reference scales u,v,w by 1/c once per tile, fullbatch_mode.cpp:320-322).
C0 = 299792458.0


@struct.dataclass
class VisData:
    """One tile (solution interval) of visibility data, flattened over time.

    ``rows = nbase * tilesz`` with baseline varying fastest inside each
    timeslot (same layout the reference's ``Data::IOData`` uses,
    ``/root/reference/src/MS/data.h:48-73``).

    Attributes:
      u, v, w:  (rows,) baseline coordinates in *seconds* (metres / c).
      ant_p, ant_q: (rows,) int32 station indices of each baseline.
      vis: (nchan, 4, rows) complex observed coherencies, components
        [XX, XY, YX, YY] on axis -2 (see module docstring for why rows
        is minor-most).
      mask: (nchan, rows) 1.0 = good, 0.0 = flagged. Multiplicative, so
        flagged rows contribute zero to every residual/gradient reduction
        (replaces the reference's preset_flags_and_data zeroing,
        ``/root/reference/src/lib/Dirac/baseline_utils.c``).
      freqs: (nchan,) channel frequencies in Hz.
      time_idx: (rows,) int32 timeslot index within the tile (0..tilesz-1).
      freq0: reference frequency (Hz) of the channel-averaged data.
      deltaf: total bandwidth (Hz), used for frequency smearing.
      deltat: integration time (s), used for time smearing.
      tilesz: static number of timeslots in this tile.
      nbase: static number of baselines per timeslot.
      nstations: static number of stations N.
    """

    u: jax.Array
    v: jax.Array
    w: jax.Array
    ant_p: jax.Array
    ant_q: jax.Array
    vis: jax.Array
    mask: jax.Array
    freqs: jax.Array
    time_idx: jax.Array
    freq0: float = struct.field(pytree_node=False, default=150e6)
    deltaf: float = struct.field(pytree_node=False, default=180e3)
    deltat: float = struct.field(pytree_node=False, default=1.0)
    tilesz: int = struct.field(pytree_node=False, default=1)
    nbase: int = struct.field(pytree_node=False, default=0)
    nstations: int = struct.field(pytree_node=False, default=0)

    @property
    def rows(self) -> int:
        return self.nbase * self.tilesz

    @property
    def nchan(self) -> int:
        return self.vis.shape[-3]


@struct.dataclass
class JonesSolution:
    """Per-cluster, per-chunk, per-station Jones solutions for one tile.

    ``jones``: (nclus, nchunk_max, nstations, 2, 2) complex. Clusters whose
    hybrid chunk count (cluster-file column 2; reference README section 2b)
    is smaller than ``nchunk_max`` repeat their last valid chunk — the
    padding is inert because chunk->row maps never reference it.
    ``nchunk``: (nclus,) int32 actual chunk counts.
    """

    jones: jax.Array
    nchunk: jax.Array


def params_to_jones(p: jax.Array) -> jax.Array:
    """Real parameter vector (..., 8N) -> complex Jones (..., N, 2, 2).

    Ordering per station (matches the reference solution-file contract,
    ``/root/reference/README.md`` section 6): ``[Re J00, Im J00, Re J10,
    Im J10, Re J01, Im J01, Re J11, Im J11]``.
    """
    s = p.reshape(p.shape[:-1] + (-1, 4, 2))  # (..., N, 4, 2) [S0S1|S2S3|S4S5|S6S7]
    z = jax.lax.complex(s[..., 0], s[..., 1])  # (..., N, 4): J00, J10, J01, J11
    j00, j10, j01, j11 = z[..., 0], z[..., 1], z[..., 2], z[..., 3]
    row0 = jnp.stack([j00, j01], axis=-1)
    row1 = jnp.stack([j10, j11], axis=-1)
    return jnp.stack([row0, row1], axis=-2)


def jones_to_params(jones: jax.Array) -> jax.Array:
    """Complex Jones (..., N, 2, 2) -> real parameter vector (..., 8N)."""
    j00 = jones[..., 0, 0]
    j10 = jones[..., 1, 0]
    j01 = jones[..., 0, 1]
    j11 = jones[..., 1, 1]
    z = jnp.stack([j00, j10, j01, j11], axis=-1)  # (..., N, 4)
    s = jnp.stack([jnp.real(z), jnp.imag(z)], axis=-1)  # (..., N, 4, 2)
    return s.reshape(s.shape[:-3] + (-1,))


def identity_jones(nstations: int, dtype=jnp.complex64) -> jax.Array:
    """(N, 2, 2) stack of identity Jones matrices (the reference's default
    initialization, fullbatch_mode.cpp:206-237)."""
    return jnp.broadcast_to(jnp.eye(2, dtype=dtype), (nstations, 2, 2))


def real_dtype_of(dtype) -> jnp.dtype:
    return jnp.finfo(dtype).dtype if jnp.issubdtype(dtype, jnp.floating) else jnp.real(
        jnp.zeros((), dtype)
    ).dtype


def herm(m: jax.Array) -> jax.Array:
    """Conjugate transpose on the trailing 2x2 axes."""
    return jnp.conj(jnp.swapaxes(m, -1, -2))


def mat2x2_inv(m: jax.Array) -> jax.Array:
    """Closed-form inverse of trailing 2x2 matrices."""
    a = m[..., 0, 0]
    b = m[..., 0, 1]
    c = m[..., 1, 0]
    d = m[..., 1, 1]
    det = a * d - b * c
    inv = jnp.stack(
        [
            jnp.stack([d, -b], axis=-1),
            jnp.stack([-c, a], axis=-1),
        ],
        axis=-2,
    )
    return inv / det[..., None, None]


def apply_gains(jones: jax.Array, coh: jax.Array, ant_p: jax.Array, ant_q: jax.Array) -> jax.Array:
    """The RIME corruption  V_pq = J_p C_pq J_q^H on SMALL mat-form arrays.

    jones: (N, 2, 2) complex; coh: (rows, ..., 2, 2); ant_p/ant_q: (rows,).
    Prefer :func:`corrupt_flat` for canonical flat-layout data — this
    trailing-2x2 form is kept for small per-source/per-station arrays
    (beam tables, tests).
    """
    jp = jones[ant_p]  # (rows, 2, 2)
    jq = jones[ant_q]
    extra = coh.ndim - jp.ndim
    for _ in range(extra):
        jp = jp[:, None]
        jq = jq[:, None]
    return jp @ coh @ herm(jq)


# ---------------------------------------------------------------------------
# canonical flat (F, 4, rows) layout: converters + component-wise RIME
# ---------------------------------------------------------------------------

def flat_of_mat(x: jax.Array) -> jax.Array:
    """(rows, F, 2, 2) matrix-form block -> canonical (F, 4, rows) flat."""
    rows, F = x.shape[0], x.shape[1]
    return jnp.moveaxis(x.reshape(rows, F, 4), 0, -1)


def mat_of_flat(x: jax.Array) -> jax.Array:
    """Canonical (..., F, 4, rows) flat block -> (..., rows, F, 2, 2).

    Boundary/test helper only — materializing the trailing-2x2 form for a
    large rows axis on TPU re-creates the 64x tile-padding this layout
    exists to avoid.
    """
    rows = x.shape[-1]
    y = jnp.moveaxis(x, -1, -3)  # (..., rows, F, 4)
    return y.reshape(y.shape[:-1] + (2, 2))


def reals_of_flat(x: jax.Array) -> jax.Array:
    """Complex flat block (..., 4, rows) -> real (..., 8, rows):
    [Re XX, Im XX, Re XY, Im XY, Re YX, Im YX, Re YY, Im YY] on axis -2
    (the reference's 8-double row ordering, Dirac.h:1617-1618)."""
    r = jnp.stack([jnp.real(x), jnp.imag(x)], axis=-2)  # (..., 4, 2, rows)
    return r.reshape(x.shape[:-2] + (8, x.shape[-1]))


def flat_of_reals(r: jax.Array) -> jax.Array:
    """Inverse of :func:`reals_of_flat`."""
    s = r.reshape(r.shape[:-2] + (4, 2, r.shape[-1]))
    return jax.lax.complex(s[..., 0, :], s[..., 1, :])


def gather_jones_rows(jones: jax.Array, ant: jax.Array, chunk_map: Optional[jax.Array] = None):
    """Per-row Jones components via a one-hot MATMUL, not a gather.

    jones: (N, 2, 2) or (nchunk, N, 2, 2) complex; ant: (rows,) station
    index; chunk_map: (rows,) hybrid-chunk index (required iff jones has
    a chunk axis).  Returns (j00, j01, j10, j11), each (rows,) complex.

    TPU note: XLA gathers over a long rows axis run ~100 ms at the
    62-station/60-timeslot tile (and their scatter-add transpose in the
    backward pass is worse) — measured 173 ms fwd+bwd per gather vs
    6.6 ms for the equivalent one-hot matmul, which also lands on the
    MXU.  The station table is tiny, so the (rows, K) one-hot is the
    cheap side of a skinny GEMM.
    """
    if jones.ndim == 3:
        K = jones.shape[0]
        idx = ant
    else:
        nchunk, N = jones.shape[0], jones.shape[1]
        K = nchunk * N
        idx = (chunk_map * N + ant) if chunk_map is not None else ant
    tab = jones.reshape(K, 4)  # row-major comps [00, 01, 10, 11]
    rdt = jnp.real(tab).dtype
    oh = (idx[:, None] == jnp.arange(K, dtype=idx.dtype)[None, :]).astype(rdt)
    v = jax.lax.complex(oh @ jnp.real(tab), oh @ jnp.imag(tab))  # (rows, 4)
    return v[:, 0], v[:, 1], v[:, 2], v[:, 3]


def corrupt_flat(
    jones: jax.Array,
    coh: jax.Array,
    ant_p: jax.Array,
    ant_q: jax.Array,
    chunk_map: Optional[jax.Array] = None,
) -> jax.Array:
    """The RIME corruption V = J_p C J_q^H in canonical flat layout.

    jones: (N, 2, 2) or (nchunk, N, 2, 2) complex; coh: (..., F, 4, rows);
    ant_p/ant_q/chunk_map: (rows,).  Returns (..., F, 4, rows).

    Expanded 2x2 component arithmetic — elementwise over the rows lane
    axis (the pthread-per-baseline loop of predict.c:110-260 and the
    one-thread-per-baseline kernel of predict_model.cu:1060 both
    dissolve into this single vectorized expression).
    """
    return corrupt_flat_2sided(jones, jones, coh, ant_p, ant_q, chunk_map)


def corrupt_flat_2sided(
    jones_p: jax.Array,
    jones_q: jax.Array,
    coh: jax.Array,
    ant_p: jax.Array,
    ant_q: jax.Array,
    chunk_map: Optional[jax.Array] = None,
) -> jax.Array:
    """V = G_p C H_q^H with distinct left/right Jones stacks (used by the
    residual-correction path where G = H = inv(J_ccid))."""
    pa, pb, pc, pd = gather_jones_rows(jones_p, ant_p, chunk_map)
    qa, qb, qc, qd = gather_jones_rows(jones_q, ant_q, chunk_map)
    qa, qb, qc, qd = jnp.conj(qa), jnp.conj(qb), jnp.conj(qc), jnp.conj(qd)
    c00 = coh[..., 0, :]
    c01 = coh[..., 1, :]
    c10 = coh[..., 2, :]
    c11 = coh[..., 3, :]
    t00 = pa * c00 + pb * c10
    t01 = pa * c01 + pb * c11
    t10 = pc * c00 + pd * c10
    t11 = pc * c01 + pd * c11
    v00 = t00 * qa + t01 * qb
    v01 = t00 * qc + t01 * qd
    v10 = t10 * qa + t11 * qb
    v11 = t10 * qc + t11 * qd
    return jnp.stack([v00, v01, v10, v11], axis=-2)
