"""Core data model: visibilities, Jones parameter layout, flags.

Design notes
------------
The reference stores a visibility row as 8 doubles (XX,XY,YX,YY x re,im;
ordering documented at ``/root/reference/src/lib/Dirac/Dirac.h:1617-1618``)
and a station's Jones solution as 8 reals ``S0..S7`` with
``J = [S0+jS1, S4+jS5; S2+jS3, S6+jS7]`` (``/root/reference/README.md``
section 6).  Here visibilities are native complex arrays of shape
``(rows, nchan, 2, 2)`` — the 2x2 coherency matrix is a trailing axis so
XLA batches the tiny matmuls of the RIME (J_p C J_q^H) across rows on the
MXU/VPU — and Jones solutions are ``(..., nstations, 2, 2)`` complex.  The
8-real S-ordering only exists at the text-file boundary
(:mod:`sagecal_tpu.io.solutions`) for byte-compatibility with the
reference's solution format.

Solver parameter vectors are *real* (shape ``(..., 8*N)``) like the
reference's ``p`` vectors (``/root/reference/src/lib/Dirac/lmfit.c``),
because LM / LBFGS line searches and trust regions are real-valued
optimizers.  :func:`params_to_jones` / :func:`jones_to_params` convert, and
their ordering matches the reference so solution files can be diffed
directly against ``sagecal`` output.

Everything is a pytree (``flax.struct``) so whole datasets can be passed
through ``jit`` / ``shard_map`` boundaries and sharded over a mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# Speed of light (m/s), used to convert metre uvw to wavelengths (the
# reference scales u,v,w by 1/c once per tile, fullbatch_mode.cpp:320-322).
C0 = 299792458.0


@struct.dataclass
class VisData:
    """One tile (solution interval) of visibility data, flattened over time.

    ``rows = nbase * tilesz`` with baseline varying fastest inside each
    timeslot (same layout the reference's ``Data::IOData`` uses,
    ``/root/reference/src/MS/data.h:48-73``).

    Attributes:
      u, v, w:  (rows,) baseline coordinates in *seconds* (metres / c).
      ant_p, ant_q: (rows,) int32 station indices of each baseline.
      vis: (rows, nchan, 2, 2) complex observed coherencies.
      mask: (rows, nchan) 1.0 = good, 0.0 = flagged. Multiplicative, so
        flagged rows contribute zero to every residual/gradient reduction
        (replaces the reference's preset_flags_and_data zeroing,
        ``/root/reference/src/lib/Dirac/baseline_utils.c``).
      freqs: (nchan,) channel frequencies in Hz.
      time_idx: (rows,) int32 timeslot index within the tile (0..tilesz-1).
      freq0: reference frequency (Hz) of the channel-averaged data.
      deltaf: total bandwidth (Hz), used for frequency smearing.
      deltat: integration time (s), used for time smearing.
      tilesz: static number of timeslots in this tile.
      nbase: static number of baselines per timeslot.
      nstations: static number of stations N.
    """

    u: jax.Array
    v: jax.Array
    w: jax.Array
    ant_p: jax.Array
    ant_q: jax.Array
    vis: jax.Array
    mask: jax.Array
    freqs: jax.Array
    time_idx: jax.Array
    freq0: float = struct.field(pytree_node=False, default=150e6)
    deltaf: float = struct.field(pytree_node=False, default=180e3)
    deltat: float = struct.field(pytree_node=False, default=1.0)
    tilesz: int = struct.field(pytree_node=False, default=1)
    nbase: int = struct.field(pytree_node=False, default=0)
    nstations: int = struct.field(pytree_node=False, default=0)

    @property
    def rows(self) -> int:
        return self.nbase * self.tilesz

    @property
    def nchan(self) -> int:
        return self.vis.shape[1]


@struct.dataclass
class JonesSolution:
    """Per-cluster, per-chunk, per-station Jones solutions for one tile.

    ``jones``: (nclus, nchunk_max, nstations, 2, 2) complex. Clusters whose
    hybrid chunk count (cluster-file column 2; reference README section 2b)
    is smaller than ``nchunk_max`` repeat their last valid chunk — the
    padding is inert because chunk->row maps never reference it.
    ``nchunk``: (nclus,) int32 actual chunk counts.
    """

    jones: jax.Array
    nchunk: jax.Array


def params_to_jones(p: jax.Array) -> jax.Array:
    """Real parameter vector (..., 8N) -> complex Jones (..., N, 2, 2).

    Ordering per station (matches the reference solution-file contract,
    ``/root/reference/README.md`` section 6): ``[Re J00, Im J00, Re J10,
    Im J10, Re J01, Im J01, Re J11, Im J11]``.
    """
    s = p.reshape(p.shape[:-1] + (-1, 4, 2))  # (..., N, 4, 2) [S0S1|S2S3|S4S5|S6S7]
    z = jax.lax.complex(s[..., 0], s[..., 1])  # (..., N, 4): J00, J10, J01, J11
    j00, j10, j01, j11 = z[..., 0], z[..., 1], z[..., 2], z[..., 3]
    row0 = jnp.stack([j00, j01], axis=-1)
    row1 = jnp.stack([j10, j11], axis=-1)
    return jnp.stack([row0, row1], axis=-2)


def jones_to_params(jones: jax.Array) -> jax.Array:
    """Complex Jones (..., N, 2, 2) -> real parameter vector (..., 8N)."""
    j00 = jones[..., 0, 0]
    j10 = jones[..., 1, 0]
    j01 = jones[..., 0, 1]
    j11 = jones[..., 1, 1]
    z = jnp.stack([j00, j10, j01, j11], axis=-1)  # (..., N, 4)
    s = jnp.stack([jnp.real(z), jnp.imag(z)], axis=-1)  # (..., N, 4, 2)
    return s.reshape(s.shape[:-3] + (-1,))


def identity_jones(nstations: int, dtype=jnp.complex64) -> jax.Array:
    """(N, 2, 2) stack of identity Jones matrices (the reference's default
    initialization, fullbatch_mode.cpp:206-237)."""
    return jnp.broadcast_to(jnp.eye(2, dtype=dtype), (nstations, 2, 2))


def real_dtype_of(dtype) -> jnp.dtype:
    return jnp.finfo(dtype).dtype if jnp.issubdtype(dtype, jnp.floating) else jnp.real(
        jnp.zeros((), dtype)
    ).dtype


def herm(m: jax.Array) -> jax.Array:
    """Conjugate transpose on the trailing 2x2 axes."""
    return jnp.conj(jnp.swapaxes(m, -1, -2))


def mat2x2_inv(m: jax.Array) -> jax.Array:
    """Closed-form inverse of trailing 2x2 matrices."""
    a = m[..., 0, 0]
    b = m[..., 0, 1]
    c = m[..., 1, 0]
    d = m[..., 1, 1]
    det = a * d - b * c
    inv = jnp.stack(
        [
            jnp.stack([d, -b], axis=-1),
            jnp.stack([-c, a], axis=-1),
        ],
        axis=-2,
    )
    return inv / det[..., None, None]


def apply_gains(jones: jax.Array, coh: jax.Array, ant_p: jax.Array, ant_q: jax.Array) -> jax.Array:
    """The RIME corruption  V_pq = J_p C_pq J_q^H.

    jones: (N, 2, 2) complex; coh: (rows, ..., 2, 2); ant_p/ant_q: (rows,).
    Batched 2x2 matmuls — XLA lowers these to MXU-batched GEMMs.
    """
    jp = jones[ant_p]  # (rows, 2, 2)
    jq = jones[ant_q]
    extra = coh.ndim - jp.ndim
    for _ in range(extra):
        jp = jp[:, None]
        jq = jq[:, None]
    return jp @ coh @ herm(jq)
