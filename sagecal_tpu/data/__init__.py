"""Simulated-sky fixtures with known ground truth (refine/spatial/
quality test surfaces and the synthetic modes of the refine/spatial
apps)."""

from sagecal_tpu.data.simsky import (
    SimulatedSky,
    make_multiband_skies,
    make_sky,
    perturb_flux,
    shapelet_source_batch,
)

__all__ = [
    "SimulatedSky",
    "make_multiband_skies",
    "make_sky",
    "perturb_flux",
    "shapelet_source_batch",
]
