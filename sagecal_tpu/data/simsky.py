"""Shared simulated-sky fixtures with known ground truth.

The refine, spatial, and quality test surfaces all need the same thing:
a physically consistent synthetic observation whose *generating*
parameters — per-source fluxes, spectral indices, shapelet mode
coefficients, true Jones gains — are known exactly, so recovery can be
asserted against ground truth instead of against another code path.
This module builds those skies on top of :mod:`sagecal_tpu.io.simulate`
(uvw tracks, gain corruption, noise) and returns everything a test or
app needs in one record.

Design notes for the refinement acceptance tests:

- Cluster 0 always holds MULTIPLE point sources.  A per-cluster flux
  scale ``s`` applied to a single-source cluster is exactly absorbed by
  gains scaled ``1/sqrt(s)`` (the flux/gain degeneracy); with several
  sources sharing one gain solution the individual fluxes are
  identifiable again, which is what lets ``refine`` recover a perturbed
  flux *through* the calibration solve.
- ``perturb_flux`` returns a cluster list with one source's flux scaled
  by a known factor — the refinement start point.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np
import jax.numpy as jnp

from sagecal_tpu.core.types import VisData
from sagecal_tpu.io.simulate import (
    corrupt_and_observe,
    make_visdata,
    random_jones,
)
from sagecal_tpu.ops.rime import (
    ST_SHAPELET,
    ShapeletTable,
    SourceBatch,
    point_source_batch,
)


def shapelet_source_batch(
    ll, mm, flux, modes, beta: float = 0.01, f0: float = 150e6,
    dtype=jnp.float32,
) -> tuple[SourceBatch, ShapeletTable]:
    """One ST_SHAPELET source at (ll, mm) with the given mode
    coefficients: ``modes`` is (n0, n0) (or flat n0*n0) — the ground
    truth the spatial/refine tests recover.  Returns (batch, table)."""
    modes = np.asarray(modes, dtype=np.float64)
    n0 = int(round(np.sqrt(modes.size)))
    if n0 * n0 != modes.size:
        raise ValueError(f"modes must be square, got {modes.size} coeffs")
    src = point_source_batch([ll], [mm], [flux], f0=f0, dtype=dtype)
    src = src.replace(
        stype=jnp.full((1,), ST_SHAPELET, jnp.int32),
        shapelet_idx=jnp.zeros((1,), jnp.int32),
    )
    tab = ShapeletTable(
        modes=jnp.asarray(modes.reshape(1, n0 * n0), dtype),
        beta=jnp.full((1,), beta, dtype),
        eX=jnp.ones((1,), dtype),
        eY=jnp.ones((1,), dtype),
        eP=jnp.zeros((1,), dtype),
        n0max=n0,
    )
    return src, tab


@dataclasses.dataclass
class SimulatedSky:
    """A synthetic observation plus the exact parameters that made it."""

    data: VisData
    clusters: List[SourceBatch]
    shapelet_tables: List[Optional[ShapeletTable]]
    jones: jnp.ndarray  # true gains (M, N, 2, 2); None-corruption = identity
    true_flux: List[np.ndarray]  # per-cluster ground-truth sI0
    true_spec_idx: List[np.ndarray]
    true_modes: Optional[np.ndarray]  # (n0, n0) shapelet truth, or None
    freq0: float
    dec0: float
    noise_sigma: float

    @property
    def nclusters(self) -> int:
        return len(self.clusters)


def make_sky(
    nstations: int = 8,
    tilesz: int = 2,
    nchan: int = 2,
    nclusters: int = 2,
    sources_per_cluster: int = 3,
    freq0: float = 150e6,
    chan_bw: float = 180e3,
    dec0: float = 0.9,
    gain_amp: float = 0.1,
    noise_sigma: float = 0.0,
    spectral: bool = False,
    shapelet_n0: int = 0,
    seed: int = 7,
    dtype=np.float64,
    wide_field: bool = False,
    nsources: int = 10000,
    fov: float = 1.1,
    cluster_scale: float = 0.004,
    flux_alpha: float = 2.0,
    flux_min: float = 0.05,
    extent_m: float = 3000.0,
) -> SimulatedSky:
    """Build a point(+shapelet) sky with known ground truth and observe
    it through random Jones gains.

    - cluster 0: ``sources_per_cluster`` point sources (multi-source by
      construction — see module docstring on the flux/gain degeneracy);
    - clusters 1..: single point sources at distinct directions;
    - ``shapelet_n0 > 0`` appends one all-shapelet cluster with an
      ``n0 x n0`` mode table drawn from a fixed RNG (ground truth in
      ``true_modes``);
    - ``spectral=True`` gives every source a known nonzero spectral
      index (exercises the spec_idx != 0 gate in ``_spectral_flux``);
    - ``gain_amp=0`` observes through identity gains (the refinement
      acceptance setting: at the true sky + identity anchor the outer
      misfit is exactly the noise floor).

    ``wide_field=True`` switches the sky generator to the buildsky-like
    regime the hierarchical predict targets: ``nsources`` point sources
    total, split over ``nclusters`` spatially compact blobs (Gaussian,
    sigma ``cluster_scale``) whose centres fill a disc of diameter
    ``fov`` direction-cosine units, with power-law (Pareto, index
    ``flux_alpha``) fluxes above ``flux_min``.  Each blob is one
    calibration direction with its own true Jones gains.  ``extent_m``
    shrinks the station layout to the compact-array/all-sky geometry
    (the default leaves it at the standard 3 km).  The default
    (``wide_field=False``) path is bit-identical to what it was before
    this knob existed — the wide branch only ever touches the RNG
    stream after the shared uvw draw.
    """
    rng = np.random.default_rng(seed)
    data = make_visdata(
        nstations=nstations, tilesz=tilesz, nchan=nchan, freq0=freq0,
        chan_bw=chan_bw, dec0=dec0, seed=seed, dtype=dtype,
        extent_m=extent_m,
    )
    jdtype = jnp.complex64 if dtype == np.float32 else jnp.complex128

    clusters: List[SourceBatch] = []
    tables: List[Optional[ShapeletTable]] = []
    true_flux: List[np.ndarray] = []
    true_si: List[np.ndarray] = []

    if wide_field:
        if shapelet_n0 > 0:
            raise ValueError(
                "wide_field skies are point-only (the hierarchical "
                "predict contract); shapelet_n0 must be 0")
        ncl = max(int(nclusters), 1)
        # blob centres: uniform over a disc of diameter ``fov``
        rr = 0.5 * fov * np.sqrt(rng.uniform(0.05, 1.0, ncl))
        ang = rng.uniform(0.0, 2.0 * np.pi, ncl)
        cx, cy = rr * np.cos(ang), rr * np.sin(ang)
        counts = np.full(ncl, int(nsources) // ncl, np.int64)
        counts[: int(nsources) % ncl] += 1
        for k in range(ncl):
            ns = int(counts[k])
            ll = cx[k] + cluster_scale * rng.standard_normal(ns)
            mm = cy[k] + cluster_scale * rng.standard_normal(ns)
            # keep strictly inside the unit direction-cosine disc
            r = np.sqrt(ll * ll + mm * mm)
            shrink = np.where(r > 0.97, 0.97 / np.maximum(r, 1e-12), 1.0)
            ll, mm = ll * shrink, mm * shrink
            flux = flux_min * (1.0 + rng.pareto(flux_alpha, ns))
            src = point_source_batch(
                ll, mm, flux, f0=freq0, dtype=data.u.dtype)
            si = np.zeros(ns)
            if spectral:
                si = rng.uniform(-0.9, -0.3, ns)
                src = src.replace(spec_idx=jnp.asarray(si, data.u.dtype))
            clusters.append(src)
            tables.append(None)
            true_flux.append(flux)
            true_si.append(si)
        M = len(clusters)
        jones = random_jones(M, nstations, seed=seed + 1, amp=gain_amp,
                             dtype=jdtype)
        data = corrupt_and_observe(
            data, clusters, jones=jones, noise_sigma=noise_sigma,
            seed=seed + 2,
        )
        return SimulatedSky(
            data=data, clusters=clusters, shapelet_tables=tables,
            jones=jones, true_flux=true_flux, true_spec_idx=true_si,
            true_modes=None, freq0=freq0, dec0=dec0,
            noise_sigma=noise_sigma,
        )

    for k in range(nclusters):
        ns = sources_per_cluster if k == 0 else 1
        ll = rng.uniform(-0.04, 0.04, ns)
        mm = rng.uniform(-0.04, 0.04, ns)
        flux = rng.uniform(1.0, 4.0, ns)
        src = point_source_batch(ll, mm, flux, f0=freq0, dtype=data.u.dtype)
        si = np.zeros(ns)
        if spectral:
            si = rng.uniform(-0.9, -0.3, ns)
            src = src.replace(spec_idx=jnp.asarray(si, data.u.dtype))
        clusters.append(src)
        tables.append(None)
        true_flux.append(flux)
        true_si.append(si)

    true_modes = None
    if shapelet_n0 > 0:
        modes = rng.normal(0.0, 1.0, (shapelet_n0, shapelet_n0))
        modes[0, 0] = 3.0  # dominant zeroth mode keeps the source bright
        src, tab = shapelet_source_batch(
            rng.uniform(-0.02, 0.02), rng.uniform(-0.02, 0.02), 1.0,
            modes, beta=0.01, f0=freq0, dtype=data.u.dtype,
        )
        clusters.append(src)
        tables.append(tab)
        true_flux.append(np.array([1.0]))
        true_si.append(np.zeros(1))
        true_modes = modes

    M = len(clusters)
    jones = random_jones(M, nstations, seed=seed + 1, amp=gain_amp,
                         dtype=jdtype)
    data = corrupt_and_observe(
        data, clusters, jones=jones, noise_sigma=noise_sigma,
        seed=seed + 2, shapelet_tables=tables if shapelet_n0 > 0 else None,
    )
    return SimulatedSky(
        data=data, clusters=clusters, shapelet_tables=tables, jones=jones,
        true_flux=true_flux, true_spec_idx=true_si, true_modes=true_modes,
        freq0=freq0, dec0=dec0, noise_sigma=noise_sigma,
    )


def make_multiband_skies(
    nbands: int = 4,
    freq0: float = 130e6,
    band_bw: float = 10e6,
    **kwargs,
) -> List[SimulatedSky]:
    """The distributed/spatial fixture: the SAME sky (same seed, same
    source parameters, same gains) observed in ``nbands`` frequency
    bands — what the consensus and spatial-regularization paths consume.
    Band b is centred at ``freq0 + b * band_bw``."""
    out = []
    for b in range(nbands):
        out.append(make_sky(freq0=freq0 + b * band_bw, **kwargs))
    return out


def perturb_flux(
    sky: SimulatedSky, factor: float = 1.15, cluster: int = 0,
    source: int = 0,
) -> List[SourceBatch]:
    """Cluster list with one source's flux scaled by ``factor`` — the
    known-wrong sky model that ``refine`` must pull back to truth."""
    out = list(sky.clusters)
    src = out[cluster]
    sI0 = np.asarray(src.sI0).copy()
    sI0[source] *= factor
    out[cluster] = src.replace(sI0=jnp.asarray(sI0, src.sI0.dtype))
    return out
