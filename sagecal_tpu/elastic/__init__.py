"""Elastic execution: crash-consistent checkpoint/resume for long runs.

Long LOFAR/SKA calibration runs on preemptible TPU pods must survive
restarts.  The flight recorder (obs/flight.py) DETECTS hangs, SIGTERM
and crashes; this package lets a restarted run RECOVER: per-tile solver
state (gain bundles, ADMM Z/duals/rho, RNG keys) is checkpointed
atomically at tile boundaries, a restart with ``--resume`` derives the
effective skip count from the newest valid checkpoint, truncates any
torn trailing solution interval, and warm-starts from the checkpointed
gains — which also cuts per-tile iterations because gains drift slowly
(temporal smoothness; ROADMAP item 4).
"""

from sagecal_tpu.elastic.checkpoint import (  # noqa: F401
    CHECKPOINT_SCHEMA_VERSION,
    CheckpointManager,
    ResumeRefused,
    config_fingerprint,
    find_latest_checkpoint,
    flatten_state,
    read_checkpoint,
    unflatten_state,
    write_checkpoint,
)
