"""Versioned, atomically-written solver checkpoints.

Format contract (schema v1): one ``.npz`` per checkpoint holding

- ``__meta__``: a UTF-8 JSON document (uint8 array) with
  ``schema_version``, ``app``, ``fingerprint``, ``tile_index``,
  ``intervals_written``, ``ts`` and app-specific scalars (RNG key,
  epoch/minibatch counters, ...);
- every other entry: one named solver-state array (gain bundles ``p``,
  ADMM ``Z``/``Y`` duals, ``rho``, trajectories).

Bounded-staleness ledger contract: an async consensus run
(``--consensus-staleness`` > 0 or a discount != 1, see
``parallel/async_consensus.py``) additionally stores ``ledger.ages``
(per-band rounds-since-refresh, -1 = never seen), ``ledger.zterms``
(the stored per-band Gram numerator terms) and ``ledger.round`` (the
global round counter).  These three arrays plus Z/Y ARE the complete
async trajectory state: a resume that restores them replays the exact
deterministic refresh schedule, so ``--resume`` stays bit-exact in
async mode too.  Checkpoints from sync runs simply omit the keys
(``StalenessLedger.present`` guards the restore).

Writes are crash-consistent: the payload goes to a temp file in the
checkpoint directory, is ``fsync``\\ ed, then ``os.replace``\\ d into
place (the same pattern as obs/flight.py heartbeats, plus the fsync the
solver state deserves) — a reader can never observe a torn checkpoint,
and a kill between two checkpoints simply resumes from the previous
one.  The directory entry is fsynced too so the rename itself survives
a power loss.

Resume safety: every checkpoint embeds a :func:`config_fingerprint` of
the run's identity (dataset paths and shapes, sky/cluster files, the
numerics-relevant solver options).  :meth:`CheckpointManager.resume`
REFUSES to resume when the fingerprint of the restarted run differs —
silently warm-starting tile 7 of a different observation would corrupt
the solution file without any detectable error.

Stdlib + numpy only at import time (the crash-path flusher must never
be the thing that initializes a wedged jax backend).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

CHECKPOINT_SCHEMA_VERSION = 1

_CKPT_RE = re.compile(r"^ckpt_t(\d+)\.npz$")


class ResumeRefused(RuntimeError):
    """--resume found a checkpoint that does not belong to this run
    configuration (fingerprint mismatch) or is from an incompatible
    schema.  The CLI maps this to its own exit code (see apps/cli.py)
    so supervisors can tell 'stale checkpoint dir' from a solver
    failure."""


def config_fingerprint(**fields) -> str:
    """Stable hex digest of a run's identity.

    Callers pass everything that must match for a resumed tile loop to
    be a continuation of the original run: dataset path(s) and shape
    metadata, sky/cluster file paths, and the solver options that
    change the numerics.  Values must be JSON-able scalars / lists."""
    doc = json.dumps(fields, sort_keys=True, separators=(",", ":"),
                     default=str)
    return hashlib.sha256(doc.encode("utf-8")).hexdigest()


def _fsync_dir(path: str) -> None:
    """Best-effort fsync of a directory entry (makes the rename itself
    durable; not supported on every platform/filesystem)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_checkpoint(path: str, arrays: Dict[str, np.ndarray],
                     meta: Dict[str, Any]) -> str:
    """Atomically write one checkpoint file (temp + fsync + rename)."""
    meta = dict(meta)
    meta.setdefault("schema_version", CHECKPOINT_SCHEMA_VERSION)
    meta.setdefault("ts", time.time())
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    payload = {"__meta__": np.frombuffer(
        json.dumps(meta, default=str).encode("utf-8"), dtype=np.uint8)}
    for k, v in arrays.items():
        if k == "__meta__":
            raise ValueError("array name '__meta__' is reserved")
        payload[k] = np.asarray(v)
    tmp = os.path.join(d, f".tmp.{os.getpid()}.{os.path.basename(path)}")
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    _fsync_dir(d)
    return path


def read_checkpoint(path: str) -> Tuple[Dict[str, Any],
                                        Dict[str, np.ndarray]]:
    """Read one checkpoint -> (meta, arrays).  Raises ``ValueError`` on
    a wrong/garbled schema (a torn file raises from numpy itself)."""
    with np.load(path, allow_pickle=False) as z:
        if "__meta__" not in z.files:
            raise ValueError(f"{path}: not a sagecal checkpoint "
                             f"(no __meta__ entry)")
        meta = json.loads(bytes(z["__meta__"].tobytes()).decode("utf-8"))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    ver = meta.get("schema_version")
    if ver != CHECKPOINT_SCHEMA_VERSION:
        raise ValueError(
            f"{path}: checkpoint schema v{ver} != "
            f"v{CHECKPOINT_SCHEMA_VERSION} (this build)")
    return meta, arrays


def checkpoint_path(directory: str, tile_index: int) -> str:
    return os.path.join(directory, f"ckpt_t{tile_index:06d}.npz")


def list_checkpoints(directory: str) -> List[str]:
    """Checkpoint files in ``directory``, newest (highest tile) first."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    found = []
    for n in names:
        m = _CKPT_RE.match(n)
        if m:
            found.append((int(m.group(1)), os.path.join(directory, n)))
    return [p for _, p in sorted(found, reverse=True)]


def find_latest_checkpoint(directory: str, log=None):
    """Newest checkpoint in ``directory`` that loads cleanly, as
    (meta, arrays, path); None when the directory holds no usable
    checkpoint.  An unreadable file is skipped (never fatal): the
    atomic writer means corruption is a disk-level event, and an older
    intact checkpoint is still a correct resume point."""
    for path in list_checkpoints(directory):
        try:
            meta, arrays = read_checkpoint(path)
            return meta, arrays, path
        except Exception as e:  # torn/garbled: fall through to older
            if log is not None:
                log(f"checkpoint {path} unreadable ({e}); trying older")
    return None


def check_owner_lease(meta: Dict[str, Any], owner: str,
                      now: Optional[float] = None) -> None:
    """Refuse to adopt a checkpoint another process still owns.

    Streaming/fleet runs stamp ``owner`` and ``lease_expires_at`` into
    every checkpoint's meta (renewed simply by the checkpoint cadence).
    A restarted or stolen-over process calls this before resuming: a
    live lease held by a DIFFERENT owner means the original worker is
    probably still writing, and adopting its state would fork the
    stream.  An expired lease (or one we hold ourselves) is adoptable.
    Raises :class:`ResumeRefused` on a live foreign lease; meta without
    lease fields (single-process runs) always passes."""
    holder = meta.get("owner")
    if holder is None or holder == owner:
        return
    expires = meta.get("lease_expires_at")
    if expires is None:
        return
    now = time.time() if now is None else float(now)
    if float(expires) > now:
        raise ResumeRefused(
            f"checkpoint owned by {holder!r} with a live lease "
            f"(expires in {float(expires) - now:.1f}s); refusing to "
            f"adopt a stream another worker is still writing")


class CheckpointManager:
    """Owns one run's checkpoint directory: cadence, retention, the
    final crash-time flush, and fingerprint-checked resume.

    The app calls :meth:`update` at every tile boundary with HOST
    (numpy) state; the manager writes a checkpoint every ``every``
    tiles and keeps the newest ``keep`` files.  :meth:`flush` writes
    any boundary state newer than the last file — it is registered
    with the obs/flight.py crash handlers so a SIGTERM or uncaught
    exception persists the last completed tile before the process
    dies (a mid-solve kill therefore resumes by recomputing only the
    interrupted tile)."""

    def __init__(self, directory: str, fingerprint: str, app: str,
                 every: int = 1, keep: int = 2, elog=None, log=None):
        self.directory = directory
        self.fingerprint = fingerprint
        self.app = app
        self.every = max(int(every), 1)
        self.keep = max(int(keep), 1)
        self.elog = elog
        self.log = log or (lambda *_: None)
        self._lock = threading.Lock()
        self._pending: Optional[Tuple[int, Dict[str, np.ndarray],
                                      Dict[str, Any]]] = None
        self._written_tile: Optional[int] = None
        self._registered = False
        self.last_path: Optional[str] = None

    # -- write side ---------------------------------------------------

    def _register(self) -> None:
        if self._registered:
            return
        from sagecal_tpu.obs.flight import register_crash_flusher

        register_crash_flusher(self.flush)
        self._registered = True

    def close(self) -> None:
        """Unhook from the crash handlers (success path; the state on
        disk stays — a finished run's checkpoints age out on the next
        run's retention sweep or an operator rm)."""
        if not self._registered:
            return
        from sagecal_tpu.obs.flight import unregister_crash_flusher

        unregister_crash_flusher(self.flush)
        self._registered = False

    def update(self, tile_index: int, arrays: Dict[str, Any],
               **meta) -> Optional[str]:
        """Record tile ``tile_index`` as COMPLETE with its end-of-tile
        solver state; writes a checkpoint when the cadence is due.
        Arrays are materialized to host numpy here, so a later
        signal-time flush never has to touch the device."""
        host = {k: np.asarray(v) for k, v in arrays.items()
                if v is not None}
        with self._lock:
            self._pending = (int(tile_index), host, dict(meta))
        self._register()
        due = (int(tile_index) + 1) % self.every == 0
        return self._write_pending() if due else None

    def flush(self) -> Optional[str]:
        """Write the newest boundary state if it is not on disk yet
        (idempotent; called from the SIGTERM/excepthook path)."""
        return self._write_pending()

    def _write_pending(self) -> Optional[str]:
        with self._lock:
            pending = self._pending
            if pending is None or pending[0] == self._written_tile:
                return None
            tile_index, arrays, meta = pending
        doc = {
            "app": self.app,
            "fingerprint": self.fingerprint,
            "tile_index": tile_index,
        }
        doc.update(meta)
        path = write_checkpoint(
            checkpoint_path(self.directory, tile_index), arrays, doc)
        with self._lock:
            self._written_tile = tile_index
            self.last_path = path
        self._retention_sweep(tile_index)
        if self.elog is not None:
            try:
                self.elog.emit("checkpoint_written", path=path,
                               tile_index=tile_index, app=self.app)
            except Exception:
                pass
        from sagecal_tpu.obs.flight import note_checkpoint

        note_checkpoint(path)
        return path

    def _retention_sweep(self, newest_tile: int) -> None:
        for path in list_checkpoints(self.directory)[self.keep:]:
            m = _CKPT_RE.match(os.path.basename(path))
            if m and int(m.group(1)) < newest_tile:
                try:
                    os.remove(path)
                except OSError:
                    pass

    # -- resume side --------------------------------------------------

    def resume(self):
        """Newest valid checkpoint as (meta, arrays, path), or None for
        a fresh start.  A checkpoint written by a DIFFERENT run
        configuration raises :class:`ResumeRefused` (after emitting a
        ``resume_refused`` event) — never silently recalibrates the
        wrong observation."""
        found = find_latest_checkpoint(self.directory, log=self.log)
        if found is None:
            return None
        meta, arrays, path = found
        if meta.get("app") != self.app or \
                meta.get("fingerprint") != self.fingerprint:
            detail = ("app" if meta.get("app") != self.app
                      else "config/data fingerprint")
            if self.elog is not None:
                try:
                    self.elog.emit(
                        "resume_refused", path=path, mismatch=detail,
                        checkpoint_app=meta.get("app"),
                        checkpoint_fingerprint=meta.get("fingerprint"),
                        run_fingerprint=self.fingerprint, app=self.app)
                except Exception:
                    pass
            raise ResumeRefused(
                f"checkpoint {path} was written by a different run "
                f"({detail} mismatch); refusing to resume — move or "
                f"delete the checkpoint directory to start fresh")
        if self.elog is not None:
            try:
                self.elog.emit("resume_started", path=path,
                               tile_index=meta.get("tile_index"),
                               app=self.app)
            except Exception:
                pass
        from sagecal_tpu.obs.flight import note_checkpoint

        note_checkpoint(path)
        with self._lock:
            self._written_tile = int(meta.get("tile_index", -1))
            self.last_path = path
        return meta, arrays, path


# ---------------------------------------------------------------------------
# pytree <-> named-array helpers (federated/minibatch state has nested
# structure; the npz format stores flat named arrays)


def flatten_state(prefix: str, tree) -> Dict[str, np.ndarray]:
    """Flatten a jax pytree of arrays into ``{prefix}.{i}`` entries
    (leaf order is the treedef order, so a template-based unflatten
    restores the exact structure)."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    return {f"{prefix}.{i}": np.asarray(x) for i, x in enumerate(leaves)}


def unflatten_state(prefix: str, arrays: Dict[str, np.ndarray], template):
    """Rebuild a pytree from :func:`flatten_state` entries using a
    same-structure ``template`` (e.g. a freshly initialized state)."""
    import jax

    treedef = jax.tree_util.tree_structure(template)
    n = treedef.num_leaves
    leaves = [arrays[f"{prefix}.{i}"] for i in range(n)]
    return jax.tree_util.tree_unflatten(treedef, leaves)
