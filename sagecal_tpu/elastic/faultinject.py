"""Fault-injection harness: prove elastic resume by actually killing runs.

Used by tests/test_elastic.py and tpu_kernel_check.sh's kill-and-resume
smoke step.  The harness runs a calibration as a SUBPROCESS (so SIGTERM
exercises the real signal path: obs/flight.py's handler runs the crash
flushers — final checkpoint write, prefetcher teardown, event-log
run_aborted — then re-delivers the signal), kills it either at a tile
boundary (just after the Nth checkpoint lands) or mid-solve (after a
caller-chosen delay), then re-runs with ``--resume`` and compares the
end-state solution files byte-for-byte against an uninterrupted run.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from sagecal_tpu.elastic.checkpoint import list_checkpoints


def run_subprocess(
    args: Sequence[str],
    env: Optional[Dict[str, str]] = None,
    timeout: float = 600.0,
    cwd: Optional[str] = None,
) -> Tuple[int, str, str]:
    """Run a command to completion.  Returns (returncode, stdout, stderr)."""
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    r = subprocess.run(
        list(args), env=full_env, timeout=timeout, cwd=cwd,
        capture_output=True, text=True,
    )
    return r.returncode, r.stdout, r.stderr


def _spawn(args, env, cwd):
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.Popen(
        list(args), env=full_env, cwd=cwd,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _finish(proc, timeout: float) -> Tuple[int, str, str]:
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, err = proc.communicate()
    return proc.returncode, out or "", err or ""


def kill_at_checkpoint(
    args: Sequence[str],
    ckpt_dir: str,
    n_checkpoints: int,
    sig: int = signal.SIGTERM,
    env: Optional[Dict[str, str]] = None,
    timeout: float = 600.0,
    poll: float = 0.1,
    cwd: Optional[str] = None,
) -> Tuple[int, str, str]:
    """Start the run, send ``sig`` as soon as ``n_checkpoints``
    checkpoints exist in ``ckpt_dir`` — i.e. kill at a tile boundary,
    right after a checkpoint landed.  Retention may cap the visible
    count (CheckpointManager keep=2), so the trigger counts DISTINCT
    tile indices ever observed, not files currently on disk.  If the
    run finishes before the trigger fires, its natural exit is returned
    (the caller should then pick a smaller ``n_checkpoints``)."""
    proc = _spawn(args, env, cwd)
    seen: set = set()
    deadline = time.monotonic() + timeout
    while proc.poll() is None and time.monotonic() < deadline:
        for p in list_checkpoints(ckpt_dir):
            seen.add(os.path.basename(p))
        if len(seen) >= n_checkpoints:
            proc.send_signal(sig)
            break
        time.sleep(poll)
    return _finish(proc, max(deadline - time.monotonic(), 5.0))


def kill_after_delay(
    args: Sequence[str],
    delay: float,
    sig: int = signal.SIGTERM,
    env: Optional[Dict[str, str]] = None,
    timeout: float = 600.0,
    cwd: Optional[str] = None,
) -> Tuple[int, str, str]:
    """Start the run and send ``sig`` after ``delay`` seconds — a
    mid-solve kill when the delay lands inside a tile's device work
    (pick the delay from a randomized range to sample different
    interrupt points).  If the run exits first, its natural exit is
    returned."""
    proc = _spawn(args, env, cwd)
    deadline = time.monotonic() + timeout
    t_kill = time.monotonic() + delay
    while proc.poll() is None and time.monotonic() < deadline:
        if time.monotonic() >= t_kill:
            proc.send_signal(sig)
            break
        time.sleep(min(0.05, max(t_kill - time.monotonic(), 0.0) + 0.01))
    return _finish(proc, max(deadline - time.monotonic(), 5.0))


def compare_files(
    reference: Sequence[str], candidate: Sequence[str]
) -> List[str]:
    """Byte-compare file pairs.  Returns human-readable mismatch
    descriptions (empty list = all pairs identical)."""
    problems = []
    for ref, cand in zip(reference, candidate):
        if not os.path.exists(ref):
            problems.append(f"missing reference file {ref}")
            continue
        if not os.path.exists(cand):
            problems.append(f"missing candidate file {cand}")
            continue
        with open(ref, "rb") as f:
            a = f.read()
        with open(cand, "rb") as f:
            b = f.read()
        if a != b:
            problems.append(
                f"{cand} differs from {ref} "
                f"({len(b)} vs {len(a)} bytes)")
    return problems


def interrupted_run_matches(
    run_args: Sequence[str],
    resume_args: Sequence[str],
    ckpt_dir: str,
    reference_files: Sequence[str],
    candidate_files: Sequence[str],
    kill_mode: str = "checkpoint",
    n_checkpoints: int = 1,
    delay: float = 1.0,
    env: Optional[Dict[str, str]] = None,
    timeout: float = 600.0,
    cwd: Optional[str] = None,
) -> Tuple[bool, str]:
    """One full fault-injection round: run ``run_args``, kill it
    (``kill_mode``: "checkpoint" = tile boundary via
    :func:`kill_at_checkpoint`, "delay" = mid-solve via
    :func:`kill_after_delay`), re-run ``resume_args`` to completion,
    then byte-compare candidate vs reference files.  Returns
    (matched, report)."""
    if kill_mode == "checkpoint":
        rc, out, err = kill_at_checkpoint(
            run_args, ckpt_dir, n_checkpoints, env=env, timeout=timeout,
            cwd=cwd)
    else:
        rc, out, err = kill_after_delay(
            run_args, delay, env=env, timeout=timeout, cwd=cwd)
    report = [f"interrupted run exit={rc}"]
    if rc == 0:
        report.append("(run finished before the kill trigger fired)")
    else:
        rc2, out2, err2 = run_subprocess(
            resume_args, env=env, timeout=timeout, cwd=cwd)
        report.append(f"resume exit={rc2}")
        if rc2 != 0:
            return False, "\n".join(report + [out2[-2000:], err2[-2000:]])
    problems = compare_files(reference_files, candidate_files)
    report.extend(problems if problems else ["all files bit-exact"])
    return not problems, "\n".join(report)


def main(argv=None):
    """``python -m sagecal_tpu.elastic.faultinject kill-at-ckpt N
    CKPT_DIR -- cmd...`` / ``kill-after SECONDS -- cmd...`` — the shell
    entry tpu_kernel_check.sh uses."""
    argv = sys.argv[1:] if argv is None else list(argv)
    if len(argv) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    mode = argv[0]
    if mode == "kill-at-ckpt":
        n, ckpt_dir = int(argv[1]), argv[2]
        cmd = argv[argv.index("--") + 1:]
        rc, out, err = kill_at_checkpoint(cmd, ckpt_dir, n)
    elif mode == "kill-after":
        delay = float(argv[1])
        cmd = argv[argv.index("--") + 1:]
        rc, out, err = kill_after_delay(cmd, delay)
    else:
        print(f"unknown mode {mode!r}", file=sys.stderr)
        return 2
    sys.stdout.write(out)
    sys.stderr.write(err)
    print(f"faultinject: child exit={rc}")
    # the kill is the EXPECTED outcome; exit 0 when the child died from
    # our signal (negative returncode) or finished cleanly
    return 0 if rc <= 0 else rc


if __name__ == "__main__":
    sys.exit(main())
