"""Fleet serving: coordinator + N workers over a filesystem work queue.

The single-process serve app (sagecal_tpu/serve/) drains one manifest
in one process.  This package turns it into a fleet:

- :mod:`~sagecal_tpu.fleet.queue` — the shared work queue: one item
  file per request, claimed through atomic O_EXCL lease files with TTL
  expiry, so a SIGKILL'd worker's requests requeue and exactly-once
  *effects* come from atomic result-manifest writes rather than from
  any coordination service.
- :mod:`~sagecal_tpu.fleet.admission` — admission control consuming
  :mod:`sagecal_tpu.obs.slo` burn rates: shed-or-degrade on overload,
  closing the report-only loop of the SLO monitor.
- :mod:`~sagecal_tpu.fleet.worker` — a claim-solve-complete loop that
  reuses the serve scheduler for vmapped batch lanes and places large
  solves on :func:`~sagecal_tpu.solvers.sharded.sharded_joint_fit`.
- :mod:`~sagecal_tpu.fleet.coordinator` — seeds the queue, spawns the
  workers, sweeps leases, and reports the merged fleet view.
- :mod:`~sagecal_tpu.fleet.stream` — the streaming workload: sliding
  windows over a visibility time stream, warm-started through the
  elastic chain.

Workers share compiled executables through the cross-worker AOT
artifact store (serve/aot_store.py): the first worker to touch a
bucket compiles and saves; every later worker loads, so a worker
joining a warm fleet compiles nothing.
"""

from sagecal_tpu.fleet.queue import LeaseLost, LeaseQueue, WorkItem

__all__ = ["LeaseQueue", "LeaseLost", "WorkItem"]
