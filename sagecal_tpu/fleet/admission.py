"""Admission control: act on the SLO burn rates PR 11 only reported.

The :class:`~sagecal_tpu.obs.slo.SLOMonitor` computes multi-window
error-budget burn per tenant and raises ``shed_recommended`` while the
short-window burn exceeds the tenant's ``shed_burn`` threshold.  This
module is the actuator: each worker asks :meth:`AdmissionController.
decide` before solving a claimed request, and on overload the answer
is one of

- ``"shed"`` — refuse the request: no solve, a result manifest with
  ``verdict: "shed"`` so the tenant gets a definitive (cheap, fast)
  answer instead of a deadline miss that burns MORE budget;
- ``"degrade"`` — solve with reduced iteration budgets
  (``degrade_emiter``/``degrade_lbfgs``); the quality watchdog still
  verdicts the degraded solution, so a tenant can see exactly which
  results were produced under pressure (their manifests carry
  ``degraded: true``);
- ``"accept"`` — the normal path, bit-identical to the PR 11 serve
  app (no knob is touched when no SLO is burning, and the policy
  ``"off"`` restores report-only behavior entirely).

Burn state is fed from the shared result-manifest directory: every
worker's completions are visible to every other worker's controller,
so the fleet converges on the same overload view without a central
scheduler (manifests are the ground truth, exactly as ``diag serve``
reads them post-hoc).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Set, Tuple

from sagecal_tpu.obs.slo import SLOMonitor, SLOSpec

#: manifest verdict for requests refused by admission control
SHED_VERDICT = "shed"

POLICIES = ("shed", "degrade", "off")


class AdmissionController:
    """Per-worker admission decisions from fleet-wide SLO burn.

    ``ingest_results`` feeds completed-request manifests (local or
    scanned from the shared out_dir) into the monitor; ``decide``
    answers accept/degrade/shed for the next claimed request of a
    tenant.  Shed manifests are NOT fed back as burn samples: burn
    must reflect how the tenant's *solved* requests are doing, or
    shedding would hold its own trigger high and latch the tenant out
    forever.  With sheds excluded the loop is stable — overload blows
    deadlines, burn trips, sheds relieve the queue, solved-request
    latencies recover, the short window drains, admission resumes."""

    def __init__(self, specs: Dict[str, SLOSpec],
                 policy: str = "degrade",
                 degrade_emiter: int = 1, degrade_lbfgs: int = 4,
                 clock=time.time):
        if policy not in POLICIES:
            raise ValueError(
                f"overload policy {policy!r} not in {POLICIES}")
        self.policy = policy
        self.clock = clock  # injectable so burn windows are checkable
        self.degrade_emiter = int(degrade_emiter)
        self.degrade_lbfgs = int(degrade_lbfgs)
        self.monitor = SLOMonitor(specs)
        self._seen: Set[str] = set()
        self.decisions: Dict[str, int] = {
            "accept": 0, "degrade": 0, "shed": 0}

    @property
    def enabled(self) -> bool:
        return self.policy != "off" and self.monitor.enabled

    # -- burn-state feed ----------------------------------------------

    def ingest_results(self, results) -> int:
        """Feed result manifests (dicts) not seen before; returns how
        many were new.  Idempotent per request_id, so workers can
        rescan the whole shared out_dir every claim cycle."""
        new = 0
        for r in results:
            rid = str(r.get("request_id", ""))
            if not rid or rid in self._seen:
                continue
            self._seen.add(rid)
            if str(r.get("verdict", "")) == SHED_VERDICT:
                continue  # sheds don't burn (see class docstring)
            self.monitor.observe(
                str(r.get("tenant", "")),
                float(r.get("completed_at") or 0.0) or self.clock(),
                float(r.get("latency_s", 0.0)),
                str(r.get("verdict", "")))
            new += 1
        return new

    def ingest_dir(self, out_dir: str) -> int:
        from sagecal_tpu.obs.aggregate import read_result_manifests

        return self.ingest_results(read_result_manifests(out_dir))

    # -- the decision --------------------------------------------------

    def decide(self, tenant: str, now: Optional[float] = None
               ) -> Tuple[str, Dict[str, Any]]:
        """(decision, detail) for one about-to-solve request.
        ``decision`` is ``"accept"`` | ``"degrade"`` | ``"shed"``;
        ``detail`` carries the burn status for the event log."""
        if not self.enabled:
            self.decisions["accept"] += 1
            return "accept", {}
        spec = self.monitor.specs.get(tenant)
        if spec is None:
            self.decisions["accept"] += 1
            return "accept", {}
        if self.monitor.shed_recommended(tenant, now=now):
            decision = "shed" if self.policy == "shed" else "degrade"
            self.decisions[decision] += 1
            return decision, {
                "policy": self.policy,
                "shed_burn": spec.shed_burn,
                "deadline_s": spec.deadline_s,
            }
        self.decisions["accept"] += 1
        return "accept", {}

    # -- actuation helpers --------------------------------------------

    def degrade_request(self, req_doc: Dict[str, Any]) -> Dict[str, Any]:
        """A copy of the request dict with iteration budgets clamped
        down to the degrade levels (never raised above what the
        request/service would have used)."""
        out = dict(req_doc)
        cur_em = out.get("max_emiter")
        out["max_emiter"] = self.degrade_emiter if cur_em is None \
            else min(int(cur_em), self.degrade_emiter)
        cur_lb = out.get("max_lbfgs")
        out["max_lbfgs"] = self.degrade_lbfgs if cur_lb is None \
            else min(int(cur_lb), self.degrade_lbfgs)
        return out

    def shed_result(self, item, out_dir: str,
                    detail: Dict[str, Any]) -> Dict[str, Any]:
        """Write the definitive refusal manifest for a shed request
        (marked seen locally so a later rescan doesn't re-ingest it)."""
        from sagecal_tpu.serve.request import write_result_manifest

        now = self.clock()
        req = item.request
        result = {
            "request_id": item.request_id,
            "tenant": item.tenant,
            "dataset": req.get("dataset", ""),
            "t0": req.get("t0", 0), "tilesz": req.get("tilesz", 0),
            "verdict": SHED_VERDICT,
            "reasons": [f"slo_overload:shed_burn={detail.get('shed_burn')}"],
            "enqueued_at": item.enqueued_at,
            "started_at": now, "completed_at": now,
            "queue_wait_s": max(now - item.enqueued_at, 0.0),
            "latency_s": max(now - item.enqueued_at, 0.0),
            "trace_id": req.get("trace_id", "") or
            f"req-{item.request_id}",
        }
        write_result_manifest(out_dir, result)
        self.ingest_results([result])
        try:
            from sagecal_tpu.obs.registry import get_registry

            get_registry().counter_inc(
                "serve_requests_shed_total", tenant=item.tenant,
                help="requests refused by admission control")
        except Exception:
            pass
        return result


def build_controller(cfg, requests_path: str = "") -> AdmissionController:
    """Controller from a FleetConfig: specs from ``cfg.slo`` or the
    request manifest's ``"slos"`` key, policy/budgets from the config."""
    import os

    from sagecal_tpu.obs.slo import load_slo_specs

    specs: Dict[str, SLOSpec] = {}
    if getattr(cfg, "slo", ""):
        specs = load_slo_specs(cfg.slo)
    elif requests_path and os.path.exists(requests_path):
        specs = load_slo_specs(requests_path)
    return AdmissionController(
        specs, policy=getattr(cfg, "overload_policy", "degrade"),
        degrade_emiter=getattr(cfg, "degrade_emiter", 1),
        degrade_lbfgs=getattr(cfg, "degrade_lbfgs", 4))
