"""The fleet coordinator: seed the queue, spawn workers, watch leases.

The coordinator is deliberately thin — the queue's lease protocol does
the actual scheduling, so the coordinator only has to

1. **seed** the shared queue from a request manifest, stamping each
   item with its scheduling metadata: the absolute deadline (enqueue
   time + the tenant's SLO ``deadline_s``), a ``bucket_hint`` (the
   coarse shape class, read once per dataset so workers can claim by
   affinity without opening the HDF5 themselves), and the ``large``
   placement flag (``nstations >= large_stations``);
2. **spawn** N worker subprocesses (``sagecal-tpu fleet --role
   worker``), each with a stable ``SAGECAL_WORKER_ID`` so metric
   snapshots and lease files carry worker lineage;
3. **watch** — poll queue stats (surfacing expired leases, i.e. dead
   workers, which any live worker will steal), and finish when every
   item has a done marker or every worker has exited;
4. **report** the merged fleet view (obs/aggregate.py) plus post-hoc
   SLO evaluation over the result manifests.

Killing a worker (even SIGKILL) loses nothing: its leases expire,
survivors steal and re-solve, and the atomic manifest writes keep the
result set duplicate- and torn-free.
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Set

from sagecal_tpu.fleet.queue import LeaseQueue, WorkItem


def bucket_hint_for(meta, tilesz: int, nchan_avg: bool = True) -> str:
    """Coarse shape-affinity key for a request: enough to group
    same-shape work (stations × tile × channels decide the compiled
    program's shape class) without loading any sky model."""
    nchan = 1 if nchan_avg else meta.nchan
    return f"N{meta.nstations}xT{tilesz}xF{nchan}"


def seed_queue(queue: LeaseQueue, requests, specs,
               large_stations: int = 0,
               log=print, now: Optional[float] = None) -> List[WorkItem]:
    """One WorkItem per request.  ``specs`` is the tenant SLO map
    (deadline_s -> absolute EDF deadlines); datasets are opened once
    each for their shape metadata."""
    from sagecal_tpu.io.dataset import VisDataset

    metas: Dict[str, Any] = {}
    items: List[WorkItem] = []
    now = queue.clock() if now is None else float(now)
    for r in requests:
        path = os.path.abspath(r.dataset)
        meta = metas.get(path)
        if meta is None:
            ds = VisDataset(path, "r")
            meta = ds.meta
            ds.close()
            metas[path] = meta
        spec = specs.get(r.tenant)
        item = WorkItem(
            request_id=r.request_id, tenant=r.tenant,
            request={k: v for k, v in r.__dict__.items()},
            deadline=(now + spec.deadline_s) if spec is not None
            else float("inf"),
            bucket_hint=bucket_hint_for(meta, r.tilesz),
            enqueued_at=now,
            large=bool(large_stations
                       and meta.nstations >= large_stations))
        queue.put(item)
        items.append(item)
    log(f"fleet: seeded {len(items)} requests into {queue.root} "
        f"({len(metas)} datasets, "
        f"{sum(1 for i in items if i.large)} large)")
    return items


def worker_argv(cfg, index: int) -> List[str]:
    """The command line for one worker subprocess, reproducing the
    coordinator's config with ``--role worker``."""
    argv = [sys.executable, "-m", "sagecal_tpu.apps.fleet",
            "--role", "worker",
            "--requests", cfg.requests,
            "--out-dir", cfg.out_dir,
            "--queue-dir", cfg.queue_dir or
            os.path.join(cfg.out_dir, "queue"),
            "--aot-store", cfg.aot_store or
            os.path.join(cfg.out_dir, "aot-store"),
            "--worker-id", f"w{index}",
            "--batch", str(cfg.batch),
            "--lease-ttl", str(cfg.lease_ttl_s),
            "--poll", str(cfg.poll_s),
            "--max-idle", str(cfg.max_idle_s),
            "--large-stations", str(cfg.large_stations),
            "--overload-policy", cfg.overload_policy,
            "--degrade-emiter", str(cfg.degrade_emiter),
            "--degrade-lbfgs", str(cfg.degrade_lbfgs),
            "--max-streams", str(cfg.max_streams),
            "-e", str(cfg.max_emiter), "-g", str(cfg.max_iter),
            "-l", str(cfg.max_lbfgs), "-m", str(cfg.lbfgs_m),
            "-j", str(cfg.solver_mode)]
    if cfg.slo:
        argv += ["--slo", cfg.slo]
    if getattr(cfg, "open_loop", False):
        argv += ["--open-loop"]
    if not cfg.use_f64:
        argv += ["--f32"]
    if getattr(cfg, "use_fused_predict", False):
        argv += ["--fused"]
    if getattr(cfg, "coh_dtype", "f32") != "f32":
        argv += ["--coh-dtype", cfg.coh_dtype]
    if float(getattr(cfg, "shadow_rate", 0.0) or 0.0) > 0.0:
        argv += ["--shadow-rate", str(cfg.shadow_rate),
                 "--shadow-budget-s",
                 str(getattr(cfg, "shadow_budget_s", 120.0)),
                 "--shadow-seed",
                 str(getattr(cfg, "shadow_seed", 0))]
        if getattr(cfg, "abort_on_drift", False):
            argv += ["--abort-on-drift"]
    if cfg.verbose:
        argv += ["-V"]
    return argv


class FleetCoordinator:
    """Seed + spawn + watch + report."""

    def __init__(self, cfg, log=print, clock=time.time):
        self.cfg = cfg
        self.log = log
        self.clock = clock  # injectable so watch deadlines are checkable
        self.queue = LeaseQueue(
            cfg.queue_dir or os.path.join(cfg.out_dir, "queue"),
            worker="coordinator", ttl_s=cfg.lease_ttl_s, clock=clock)
        self.procs: List[subprocess.Popen] = []
        # worker-slot table: slot index -> CURRENT Popen for that
        # SAGECAL_WORKER_ID.  A respawn replaces the slot's proc (same
        # wid, so obs/aggregate.dedupe_snapshots supersedes the dead
        # predecessor's snapshot); retired slots never respawn.
        self._slots: Dict[int, subprocess.Popen] = {}
        self._next_slot = 0
        self._respawns: Dict[int, int] = {}
        self._retired: Set[int] = set()
        self._handled: Set[int] = set()  # dead pids already triaged
        self.elog = None
        self._sampler = None
        self._recommender = None

    # -- observability (live timeline + report-only recommender) -------

    def setup_observability(self, specs=None, elog=None) -> None:
        """Arm the live timeline sampler and the autoscale recommender
        for this run.  Pure observation plus an advisory in-memory
        recommendation — only ``cfg.elastic_workers`` makes
        :meth:`poll_duties` act on it."""
        self.elog = elog
        if not getattr(self.cfg, "timeline", True):
            return
        from sagecal_tpu.obs.capacity import (
            AutoscaleRecommender, RecommenderConfig,
        )
        from sagecal_tpu.obs.timeline import TimelineSampler, timeline_path

        os.makedirs(self.cfg.out_dir, exist_ok=True)
        self._sampler = TimelineSampler(
            timeline_path(self.cfg.out_dir), queue=self.queue,
            out_dir=self.cfg.out_dir, slo_specs=specs,
            aot_store=self.cfg.aot_store or
            os.path.join(self.cfg.out_dir, "aot-store"),
            clock=self.clock)
        lo = max(int(getattr(self.cfg, "min_workers", 1)), 1)
        hi = int(getattr(self.cfg, "max_workers", 0)) or max(
            self.cfg.workers, lo)
        self._recommender = AutoscaleRecommender(
            RecommenderConfig(min_workers=lo,
                              max_workers=max(hi, lo)),
            self.cfg.workers)

    def close_observability(self) -> None:
        sampler, self._sampler = self._sampler, None
        if sampler is not None:
            sampler.close()
        self._recommender = None

    # -- worker lifecycle ----------------------------------------------

    def _spawn_slot(self, slot: int) -> subprocess.Popen:
        env = dict(os.environ, SAGECAL_WORKER_ID=f"w{slot}")
        # the fleet view (compile/AOT-hit accounting, snapshots) is
        # metrics-registry-driven, and the registry is telemetry-
        # gated — default it ON for workers; an explicit operator
        # setting (even "0") still wins
        env.setdefault("SAGECAL_TELEMETRY", "1")
        p = subprocess.Popen(worker_argv(self.cfg, slot), env=env)
        self.procs.append(p)
        self._slots[slot] = p
        return p

    def spawn_workers(self, n: Optional[int] = None) -> None:
        n = self.cfg.workers if n is None else n
        pids = []
        for _ in range(n):
            slot = self._next_slot
            self._next_slot += 1
            pids.append(self._spawn_slot(slot).pid)
        self.log(f"fleet: spawned {n} workers (pids {pids})")

    def _respawn_crashed(self, now: float) -> None:
        """Bounded respawn of crashed workers: a slot whose proc died
        with a nonzero exit while work remains gets a replacement with
        the SAME worker id, up to ``cfg.max_respawns`` times per slot —
        a load measurement must not silently degrade to fewer workers.
        Clean exits (idle drain) and retired slots are not crashes."""
        cap = int(getattr(self.cfg, "max_respawns", 2))
        for slot, p in list(self._slots.items()):
            rc = p.poll()
            if rc is None or p.pid in self._handled:
                continue
            self._handled.add(p.pid)
            if rc == 0 or slot in self._retired:
                continue
            if self.queue.all_done(empty=False):
                continue
            count = self._respawns.get(slot, 0)
            if count >= cap:
                self.log(f"fleet: worker w{slot} crashed (rc={rc}) "
                         f"with respawn budget exhausted "
                         f"({count}/{cap})")
                continue
            self._respawns[slot] = count + 1
            np_ = self._spawn_slot(slot)
            self.log(f"fleet: respawned crashed worker w{slot} "
                     f"(rc={rc}, attempt {count + 1}/{cap}, "
                     f"pid {np_.pid})")
            if self.elog is not None:
                self.elog.emit("worker_respawned", slot=slot,
                               worker=f"w{slot}", exit_code=rc,
                               attempt=count + 1, max_respawns=cap,
                               pid=np_.pid)

    def _live_slots(self) -> List[int]:
        return sorted(s for s, p in self._slots.items()
                      if p.poll() is None and s not in self._retired)

    def _apply_scale(self, target: int) -> None:
        """Honor the in-memory recommendation (``--elastic-workers``):
        spawn up to ``target`` live workers, or retire down to it by
        SIGTERMing the highest slots — the worker's existing SIGTERM →
        SystemExit path releases its leases in its finally block (the
        stop-claiming-then-clean-exit contract), so retirement adds no
        new coordination file to the lease protocol."""
        lo = max(int(getattr(self.cfg, "min_workers", 1)), 1)
        hi = int(getattr(self.cfg, "max_workers", 0)) or max(
            self.cfg.workers, lo)
        target = max(lo, min(int(target), max(hi, lo)))
        live = self._live_slots()
        if len(live) < target:
            for _ in range(target - len(live)):
                slot = self._next_slot
                self._next_slot += 1
                p = self._spawn_slot(slot)
                self.log(f"fleet: elastic scale-up -> w{slot} "
                         f"(pid {p.pid}, {len(self._live_slots())} "
                         f"live)")
                if self.elog is not None:
                    self.elog.emit("worker_scaled_up", slot=slot,
                                   worker=f"w{slot}", pid=p.pid,
                                   target=target)
        elif len(live) > target:
            for slot in reversed(live[target:]):
                self._retired.add(slot)
                self._slots[slot].terminate()
                self.log(f"fleet: elastic retire -> w{slot} "
                         f"(SIGTERM; leases release on exit)")
                if self.elog is not None:
                    self.elog.emit("worker_retired", slot=slot,
                                   worker=f"w{slot}", target=target)

    def poll_duties(self, now: Optional[float] = None) -> None:
        """The coordinator's once-per-poll housekeeping: triage dead
        workers (bounded respawn), append one live timeline row, feed
        the recommender, and — only under ``--elastic-workers`` —
        act on its recommendation."""
        now = self.clock() if now is None else float(now)
        self._respawn_crashed(now)
        if self._sampler is None or self._sampler.closed:
            return
        alive = sum(1 for p in self.procs if p.poll() is None)
        row = self._sampler.sample(now=now, alive_workers=alive)
        if self._recommender is None:
            return
        rec = self._recommender.update(row)
        if rec is not None:
            from sagecal_tpu.obs.capacity import write_recommendation

            write_recommendation(self.cfg.out_dir, rec)
            self.log(
                f"fleet: scale recommendation -> "
                f"{rec['recommended_workers']} workers "
                f"(was {rec['previous_workers']}, {rec['reason']})")
            if self.elog is not None:
                self.elog.emit("scale_recommendation", **{
                    k: v for k, v in rec.items()
                    if k != "schema_version"})
        if getattr(self.cfg, "elastic_workers", False):
            self._apply_scale(self._recommender.recommended)

    def watch(self, timeout_s: float = 0.0,
              poll_s: float = 1.0) -> bool:
        """Poll until every item is done or every worker exited.
        Returns True iff the queue fully drained."""
        t0 = self.clock()
        last_stats = ""
        while True:
            if self.queue.all_done():
                return True
            self.poll_duties()
            alive = [p for p in self.procs if p.poll() is None]
            stats = self.queue.stats()
            line = (f"fleet: {stats['done']}/{stats['items']} done, "
                    f"{stats['waiting']} waiting, "
                    f"{stats['leased']} leased, "
                    f"{stats['expired_leases']} expired leases, "
                    f"{len(alive)} workers alive")
            if line != last_stats:
                self.log(line)
                last_stats = line
            if not alive:
                return self.queue.all_done()
            if timeout_s and self.clock() - t0 > timeout_s:
                return self.queue.all_done()
            time.sleep(poll_s)

    def await_armed_profiles(self, grace_s: float = 30.0) -> None:
        """A worker armed for device profiling (obs/devprof.py fleet
        arming) flushes its trace and retires the arm flag to ``.done``
        in a finally block after its profiled cycle — which is often
        the cycle that drains the queue.  Terminating it mid-flush
        loses the capture, so give live armed workers a short grace
        window before shutdown.  Returns as soon as no un-retired flag
        remains or every worker has exited on its own."""
        deadline = self.clock() + grace_s
        pat = os.path.join(self.cfg.out_dir, "device_profile_arm.*.json")
        while self.clock() < deadline:
            if not glob.glob(pat):
                return
            if not any(p.poll() is None for p in self.procs):
                return
            time.sleep(0.2)

    def shutdown(self, grace_s: float = 10.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = self.clock() + grace_s
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(deadline - self.clock(), 0.1))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    def summary(self, requests) -> Dict[str, Any]:
        """Merged fleet view + post-hoc SLO evaluation."""
        from sagecal_tpu.obs.aggregate import (
            read_result_manifests, state_counter_total,
        )
        from sagecal_tpu.obs.aggregate import (
            dedupe_snapshots, merge_states, read_metrics_snapshots,
        )
        from sagecal_tpu.obs.slo import evaluate_results, load_slo_specs

        results = read_result_manifests(self.cfg.out_dir)
        snaps = dedupe_snapshots(
            read_metrics_snapshots(self.cfg.out_dir))
        state = merge_states(d["state"] for d in snaps)
        lat = sorted(float(r.get("latency_s", 0.0)) for r in results
                     if r.get("verdict") not in ("shed",))
        specs = {}
        if self.cfg.slo:
            specs = load_slo_specs(self.cfg.slo)
        elif self.cfg.requests and os.path.exists(self.cfg.requests):
            specs = load_slo_specs(self.cfg.requests)
        out = {
            "requests": len(requests),
            "manifests": len(results),
            "done": self.queue.stats()["done"],
            "shed": sum(1 for r in results
                        if r.get("verdict") == "shed"),
            "degraded": sum(1 for r in results if r.get("degraded")),
            "errors": sum(1 for r in results
                          if r.get("verdict") == "error"),
            "workers": len(self.procs),
            "snapshots": len(snaps),
            "fleet_compiles": state_counter_total(
                state, "serve_executable_cache_compiles_total"),
            "fleet_aot_hits": state_counter_total(
                state, "serve_executable_cache_aot_hits_total"),
            "p50_latency_s": lat[len(lat) // 2] if lat else 0.0,
            "p95_latency_s": lat[int(len(lat) * 0.95)] if lat else 0.0,
        }
        if specs:
            out["slo"] = evaluate_results(specs, results)
        return out

    def run(self, requests, elog=None) -> Dict[str, Any]:
        from sagecal_tpu.obs.slo import load_slo_specs

        t0 = self.clock()
        os.makedirs(self.cfg.out_dir, exist_ok=True)
        specs = {}
        if self.cfg.slo:
            specs = load_slo_specs(self.cfg.slo)
        elif self.cfg.requests and os.path.exists(self.cfg.requests):
            specs = load_slo_specs(self.cfg.requests)
        seed_queue(self.queue, requests, specs,
                   large_stations=self.cfg.large_stations,
                   log=self.log)
        if elog is not None:
            elog.emit("fleet_seeded", n=len(requests),
                      queue=self.queue.root,
                      workers=self.cfg.workers)
        self.setup_observability(specs=specs, elog=elog)
        try:
            self.spawn_workers()
            drained = self.watch()
            self.await_armed_profiles()
        finally:
            self.shutdown()
            self.close_observability()
        summary = self.summary(requests)
        summary["drained"] = drained
        summary["wall_s"] = self.clock() - t0
        if elog is not None:
            elog.emit("fleet_done", **{
                k: v for k, v in summary.items() if k != "slo"})
        self.log(
            f"fleet: {summary['done']}/{summary['requests']} done "
            f"({summary['shed']} shed, {summary['degraded']} degraded, "
            f"{summary['errors']} errors) in {summary['wall_s']:.1f}s; "
            f"{summary['fleet_compiles']:g} compiles / "
            f"{summary['fleet_aot_hits']:g} AOT hits fleet-wide")
        return summary
