"""Synthetic-tenant load harness: seeded open-loop arrivals vs a live fleet.

ROADMAP item 3 wants scaling decisions driven by measured saturation,
which needs a load generator with three properties the ad-hoc benches
lack:

1. **deterministic** — the whole tenant population and every arrival
   instant derive from one seed (``random.Random``), so a load run is
   replayable and a schedule regression is byte-diffable;
2. **open-loop** — arrivals follow the schedule regardless of how the
   fleet is coping (closed-loop generators back off exactly when the
   system saturates, hiding the knee this harness exists to find);
3. **honest ground truth** — the offered load per step is recorded at
   submission time (``load_steps.json``), so the capacity analysis
   (obs/capacity.py) compares served throughput against what was
   *actually offered*, not against a nominal rate.

The population is heterogeneous on purpose: tenants cycle the serve
shape classes (different buckets), get staggered deadlines and
harmonically-decaying traffic weights — enough spread to exercise
bucket affinity, EDF ordering and per-tenant burn accounting in one
run.  Arrival processes are pluggable:

- ``poisson`` — exponential inter-arrivals at a constant mean rate;
- ``onoff``   — MMPP-style bursts: alternating ON/OFF phases with
  exponential phase lengths, each phase a Poisson process at its own
  rate;
- ``ramp``    — stepped offered rates (the saturation-sweep mode: each
  step is one point on the throughput-vs-offered-load curve).

:class:`LoadRunner` submits the schedule as real queue items against a
live coordinator+worker fleet (reusing FleetCoordinator for spawn /
respawn / timeline / elastic duties), then runs the capacity analysis
and writes ``load_report.json``.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import random
import time
from typing import Any, Dict, List, Tuple

ARRIVAL_KINDS = ("poisson", "onoff", "ramp")

# v2: the doc carries a writer-identity stamp (obs/ledger.py accepts
# both versions)
LOAD_STEPS_SCHEMA_VERSION = 2


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """One load run: the population and the arrival process."""

    arrival: str = "ramp"          # poisson | onoff | ramp
    # poisson / onoff
    rate: float = 1.0              # mean arrivals/s (ON-phase for onoff)
    rate_off: float = 0.0          # onoff OFF-phase rate
    mean_on_s: float = 8.0         # onoff mean phase lengths
    mean_off_s: float = 8.0
    duration_s: float = 30.0       # poisson/onoff run length
    # ramp (the saturation sweep)
    rates: Tuple[float, ...] = (0.25, 0.75, 2.0)
    step_s: float = 12.0
    # population
    tenants: int = 2
    seed: int = 23
    tilesz: int = 2
    deadline_s: float = 4.0        # base deadline; odd tenants get 1.5x
    availability: float = 0.9
    shed_burn: float = 3.0
    alert_burn: float = 2.0
    windows_s: Tuple[float, float] = (30.0, 120.0)
    # drain after the last arrival (0 = wait for full drain)
    drain_timeout_s: float = 0.0
    # lead-in between worker spawn and the schedule clock: workers pay
    # interpreter+jax startup before their first claim, and a capacity
    # sweep that starts submitting into that window mislabels startup
    # lag as saturation of the first step
    warmup_s: float = 0.0

    def __post_init__(self):
        if self.arrival not in ARRIVAL_KINDS:
            raise ValueError(
                f"arrival must be one of {ARRIVAL_KINDS}, "
                f"got {self.arrival!r}")
        if self.tenants < 1:
            raise ValueError("tenants must be >= 1")
        if self.arrival == "ramp" and not self.rates:
            raise ValueError("ramp arrival needs at least one rate")


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One synthetic tenant: traffic share, request shape, SLO."""

    name: str
    weight: float
    shape: Tuple[int, int, int]    # (nstations, ntime, nchan)
    deadline_s: float
    availability: float
    shed_burn: float
    alert_burn: float
    windows_s: Tuple[float, float]


def build_population(spec: LoadSpec) -> List[TenantSpec]:
    """Deterministic heterogeneous tenant set: shapes cycle the serve
    shape classes (mixed buckets), weights decay harmonically (tenant 0
    dominates traffic), odd tenants get a 1.5x looser deadline."""
    from sagecal_tpu.serve.synthetic import SHAPE_CLASSES

    pop: List[TenantSpec] = []
    norm = sum(1.0 / (i + 1) for i in range(spec.tenants))
    for i in range(spec.tenants):
        pop.append(TenantSpec(
            name=f"tenant{i}",
            weight=(1.0 / (i + 1)) / norm,
            shape=SHAPE_CLASSES[i % len(SHAPE_CLASSES)],
            deadline_s=spec.deadline_s * (1.5 if i % 2 else 1.0),
            availability=spec.availability,
            shed_burn=spec.shed_burn,
            alert_burn=spec.alert_burn,
            windows_s=spec.windows_s))
    return pop


# ---------------------------------------------------------------------------
# seeded arrival schedules


def _poisson_times(rng: random.Random, rate: float, t0: float,
                   t1: float) -> List[float]:
    out: List[float] = []
    if rate <= 0.0:
        return out
    t = t0 + rng.expovariate(rate)
    while t < t1:
        out.append(t)
        t += rng.expovariate(rate)
    return out


def build_schedule(spec: LoadSpec) -> Tuple[List[Dict[str, Any]],
                                            List[Dict[str, Any]]]:
    """The full run plan from one seed: ``(arrivals, steps)`` with
    run-relative times.  Each arrival is ``{"t", "request_id",
    "tenant"}``; each step is ``{"index", "t0", "t1", "offered_rate",
    "arrivals"}`` — the per-step offered-load ground truth the
    capacity curve is plotted against.  Same seed, same spec ->
    byte-identical schedule (pinned by a test)."""
    rng = random.Random(spec.seed)
    pop = build_population(spec)
    names = [t.name for t in pop]
    weights = [t.weight for t in pop]
    times: List[float] = []
    steps: List[Dict[str, Any]] = []
    if spec.arrival == "poisson":
        times = _poisson_times(rng, spec.rate, 0.0, spec.duration_s)
        steps = [{"index": 0, "t0": 0.0, "t1": spec.duration_s,
                  "offered_rate": spec.rate}]
    elif spec.arrival == "ramp":
        for k, r in enumerate(spec.rates):
            t0, t1 = k * spec.step_s, (k + 1) * spec.step_s
            times += _poisson_times(rng, float(r), t0, t1)
            steps.append({"index": k, "t0": t0, "t1": t1,
                          "offered_rate": float(r)})
    else:  # onoff (MMPP-style alternating-phase Poisson)
        t = 0.0
        k = 0
        on = True
        while t < spec.duration_s:
            mean = spec.mean_on_s if on else spec.mean_off_s
            rate = spec.rate if on else spec.rate_off
            dur = rng.expovariate(1.0 / mean) if mean > 0 else 0.0
            t1 = min(t + max(dur, 1e-6), spec.duration_s)
            times += _poisson_times(rng, rate, t, t1)
            steps.append({"index": k, "t0": t, "t1": t1,
                          "offered_rate": rate,
                          "phase": "on" if on else "off"})
            t = t1
            k += 1
            on = not on
    times.sort()
    arrivals = [{"t": round(t, 6),
                 "request_id": f"load-{i:05d}",
                 "tenant": rng.choices(names, weights=weights)[0]}
                for i, t in enumerate(times)]
    for s in steps:
        s["arrivals"] = sum(1 for a in arrivals
                            if s["t0"] <= a["t"] < s["t1"])
    return arrivals, steps


def schedule_json(spec: LoadSpec) -> str:
    """Canonical serialization of the schedule (the determinism
    fixture diffs these bytes across rebuilds)."""
    arrivals, steps = build_schedule(spec)
    return json.dumps({"arrivals": arrivals, "steps": steps},
                      sort_keys=True, separators=(",", ":"))


# ---------------------------------------------------------------------------
# workload materialization (datasets + manifests)


def materialize_workload(workdir: str, spec: LoadSpec,
                         arrivals) -> Dict[str, str]:
    """Simulate one dataset per tenant shape, write ``slo.json`` and a
    ``requests.json`` covering every scheduled arrival (small solver
    budgets — load runs measure the fleet, not the solver).  Returns
    ``{"requests": ..., "slo": ...}`` paths."""
    import numpy as np

    from sagecal_tpu.io.dataset import simulate_dataset
    from sagecal_tpu.io.simulate import random_jones
    from sagecal_tpu.io.skymodel import load_sky
    from sagecal_tpu.serve.synthetic import _CLUSTER, _SKY

    os.makedirs(workdir, exist_ok=True)
    pop = build_population(spec)
    sky = os.path.join(workdir, "sky.txt")
    with open(sky, "w") as f:
        f.write(_SKY)
    with open(sky + ".cluster", "w") as f:
        f.write(_CLUSTER)
    dec0 = math.radians(51.0)
    clusters, _, _ = load_sky(sky, sky + ".cluster", 0.0, dec0,
                              dtype=np.float64)
    datasets: Dict[str, str] = {}
    for i, ten in enumerate(pop):
        import h5py

        nstations, ntime, nchan = ten.shape
        path = os.path.join(workdir,
                            f"{ten.name}_N{nstations}.vis.h5")
        simulate_dataset(
            path, nstations=nstations, ntime=ntime, nchan=nchan,
            clusters=clusters,
            jones=random_jones(len(clusters), nstations,
                               seed=17 + i, amp=0.1,
                               dtype=np.complex128),
            noise_sigma=1e-4, seed=i, dec0=dec0)
        with h5py.File(path, "r+") as f:
            f.attrs["ra0"] = 0.0
            f.attrs["dec0"] = dec0
        datasets[ten.name] = path
    slo_path = os.path.join(workdir, "slo.json")
    with open(slo_path, "w") as f:
        json.dump({"slos": [
            {"tenant": t.name, "deadline_s": t.deadline_s,
             "availability": t.availability,
             "windows_s": list(t.windows_s),
             "alert_burn": t.alert_burn,
             "shed_burn": t.shed_burn} for t in pop]}, f, indent=1)
    by_name = {t.name: t for t in pop}
    counters: Dict[str, int] = {}
    requests: List[dict] = []
    for a in arrivals:
        ten = by_name[a["tenant"]]
        _, ntime, _ = ten.shape
        ntiles = max(ntime // spec.tilesz, 1)
        k = counters.get(ten.name, 0)
        counters[ten.name] = k + 1
        requests.append({
            "request_id": a["request_id"],
            "tenant": ten.name,
            "dataset": datasets[ten.name],
            "sky_model": sky,
            "t0": (k % ntiles) * spec.tilesz,
            "tilesz": spec.tilesz,
            "solver_mode": 1,
            "max_emiter": 1, "max_iter": 2, "max_lbfgs": 4,
        })
    manifest = os.path.join(workdir, "requests.json")
    tmp = f"{manifest}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"requests": requests}, f, indent=1)
    os.replace(tmp, manifest)
    return {"requests": manifest, "slo": slo_path}


# ---------------------------------------------------------------------------
# the open-loop runner


class LoadRunner:
    """Drive one load run against a live fleet.

    Reuses :class:`fleet.coordinator.FleetCoordinator` for everything
    fleet-shaped (spawn, timeline sampling, bounded respawn, elastic
    honor, shutdown, summary); owns only the open-loop submission —
    items enter the shared queue at their scheduled instants whether
    or not the fleet is keeping up."""

    def __init__(self, cfg, spec: LoadSpec, log=print,
                 clock=time.time):
        self.cfg = cfg
        self.spec = spec
        self.log = log
        self.clock = clock

    def _make_item(self, req, deadline_s: float, hint: str,
                   large: bool, now: float):
        from sagecal_tpu.fleet.queue import WorkItem

        return WorkItem(
            request_id=req.request_id, tenant=req.tenant,
            request={k: v for k, v in req.__dict__.items()},
            deadline=now + deadline_s,
            bucket_hint=hint, enqueued_at=now, large=large)

    def run(self, elog=None) -> Dict[str, Any]:
        from sagecal_tpu.fleet.coordinator import (
            FleetCoordinator, bucket_hint_for,
        )
        from sagecal_tpu.io.dataset import VisDataset
        from sagecal_tpu.obs.capacity import (
            analyze_load_run, format_load_report,
        )
        from sagecal_tpu.obs.slo import load_slo_specs
        from sagecal_tpu.serve.request import load_requests

        cfg, spec = self.cfg, self.spec
        os.makedirs(cfg.out_dir, exist_ok=True)
        arrivals, steps = build_schedule(spec)
        if not arrivals:
            raise ValueError("load schedule is empty — raise the "
                             "rate or the duration")
        paths = materialize_workload(
            os.path.join(cfg.out_dir, "workload"), spec, arrivals)
        cfg.requests = paths["requests"]
        cfg.slo = cfg.slo or paths["slo"]
        specs = load_slo_specs(cfg.slo)
        requests = {r.request_id: r
                    for r in load_requests(cfg.requests)}
        # one meta probe per dataset: bucket hints + placement flags
        # without reopening HDF5 at submit time
        meta_by_path: Dict[str, Any] = {}
        for r in requests.values():
            p = os.path.abspath(r.dataset)
            if p not in meta_by_path:
                with VisDataset(p, "r") as ds:
                    meta_by_path[p] = ds.meta
        coord = FleetCoordinator(cfg, log=self.log, clock=self.clock)
        coord.setup_observability(specs=specs, elog=elog)
        self.log(
            f"load: {len(arrivals)} arrivals over {len(steps)} steps "
            f"({spec.arrival}, seed {spec.seed}, "
            f"{spec.tenants} tenants) vs {cfg.workers} workers")
        if elog is not None:
            elog.emit("load_started", arrival=spec.arrival,
                      seed=spec.seed, tenants=spec.tenants,
                      arrivals=len(arrivals), steps=len(steps),
                      workers=cfg.workers)
        submitted: List[Dict[str, Any]] = []
        try:
            coord.spawn_workers()
            t_ready = self.clock() + max(spec.warmup_s, 0.0)
            while True:
                now = self.clock()
                if now >= t_ready:
                    break
                coord.poll_duties(now)
                time.sleep(min(max(cfg.poll_s, 0.05), t_ready - now))
            t_start = self.clock()
            for a in arrivals:
                target = t_start + a["t"]
                while True:
                    now = self.clock()
                    if now >= target:
                        break
                    coord.poll_duties(now)
                    time.sleep(min(max(cfg.poll_s, 0.05),
                                   target - now))
                req = requests[a["request_id"]]
                meta = meta_by_path[os.path.abspath(req.dataset)]
                sp = specs.get(req.tenant)
                now = self.clock()
                coord.queue.put(self._make_item(
                    req,
                    sp.deadline_s if sp else float("inf"),
                    bucket_hint_for(meta, req.tilesz),
                    bool(cfg.large_stations
                         and meta.nstations >= cfg.large_stations),
                    now))
                submitted.append(dict(a, submitted_at=now))
            self._write_load_steps(t_start, steps, submitted)
            drained = coord.watch(timeout_s=spec.drain_timeout_s,
                                  poll_s=max(cfg.poll_s, 0.05))
        finally:
            coord.shutdown()
            coord.close_observability()
        report = analyze_load_run(cfg.out_dir, specs)
        report["drained"] = drained
        report["wall_s"] = self.clock() - t_start
        report["workers"] = cfg.workers
        rpath = os.path.join(cfg.out_dir, "load_report.json")
        tmp = f"{rpath}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        os.replace(tmp, rpath)
        if elog is not None:
            elog.emit("load_done", drained=drained,
                      wall_s=report["wall_s"],
                      manifests=report["manifests"],
                      served=report["served"], shed=report["shed"],
                      errors=report["errors"],
                      saturation_throughput_solves_per_sec=report[
                          "saturation_throughput_solves_per_sec"],
                      shed_rate_under_overload=report[
                          "shed_rate_under_overload"],
                      goodput_fraction_at_saturation=report[
                          "goodput_fraction_at_saturation"])
        self.log(format_load_report(report))
        return report

    def _write_load_steps(self, t_start: float, steps, submitted
                          ) -> None:
        """The offered-load ground truth, stamped at submission time:
        planned step windows shifted to absolute timestamps plus the
        realized arrival record (scheduled vs actual submit instants).
        Written before the drain so a killed run keeps its truth."""
        doc = {
            "schema_version": LOAD_STEPS_SCHEMA_VERSION,
            "kind": "load_steps",
            "seed": self.spec.seed,
            "arrival": dataclasses.asdict(self.spec),
            "t_start": t_start,
            "steps": [dict(s, t0=t_start + s["t0"],
                           t1=t_start + s["t1"]) for s in steps],
            "submitted": submitted,
        }
        from sagecal_tpu.obs.events import writer_identity

        doc["writer"] = writer_identity()
        doc["pid"] = os.getpid()
        path = os.path.join(self.cfg.out_dir, "load_steps.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
