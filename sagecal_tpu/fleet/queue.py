"""Filesystem work queue with atomic lease files.

The queue is a directory three kinds of file live in, one per request:

- ``item-<rid>.json`` — the work item (the request dict plus scheduling
  metadata: absolute ``deadline``, ``bucket_hint``, ``enqueued_at``).
  Written once by the coordinator, never mutated.
- ``lease-<rid>.json`` — present while some worker holds the claim:
  ``{worker, acquired_at, expires_at}``.  Created with
  ``O_CREAT|O_EXCL`` (the atomic claim — exactly one creator wins),
  renewed via tmp + ``os.replace`` (readers never see a torn lease),
  and *stolen* after expiry by renaming it to a unique tombstone first
  (rename is atomic, so exactly one stealer wins even when several
  workers notice the same dead lease) and then re-creating with
  ``O_EXCL``.
- ``done-<rid>.json`` — the completion marker, written atomically
  AFTER the result manifest is on disk.  Claims check it first and
  last, so a request completed between a steal decision and the new
  lease is released untouched.

Exactly-once *effects* come from the result-manifest layer, not the
queue: a zombie worker whose lease was stolen may finish its solve in
parallel with the stealer, but both write the same deterministic
result (per-request RNG is derived from the request id and vmapped
lanes are independent) through atomic ``os.replace``, so the manifest
set contains no duplicates and no torn files.

Claim ordering is deadline-first (EDF) with bucket affinity: a worker
prefers items whose ``bucket_hint`` it has already compiled/claimed —
that is what lets same-shape requests land on the same worker and fill
its vmapped batch lanes — but never at the cost of an earlier deadline
in a different bucket beyond the batch window.

Everything here is stdlib-only and safe on any POSIX filesystem with
atomic rename (the same contract the elastic checkpoints rely on).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Set

ITEM_PREFIX = "item-"
LEASE_PREFIX = "lease-"
DONE_PREFIX = "done-"
FAIL_PREFIX = "fail-"


class LeaseLost(RuntimeError):
    """Raised by :meth:`LeaseQueue.renew` when the caller's lease no
    longer exists or is held by another worker (it expired and was
    stolen).  The holder must treat the request as no longer its own."""


@dataclasses.dataclass
class WorkItem:
    """One queued request plus its scheduling metadata."""

    request_id: str
    tenant: str
    request: Dict[str, Any]     # the SolveRequest fields, verbatim
    deadline: float = math.inf  # absolute unix deadline (EDF key)
    bucket_hint: str = ""       # shape-affinity key (coordinator-set)
    enqueued_at: float = 0.0
    large: bool = False         # place via sharded_joint_fit

    def to_doc(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        if math.isinf(self.deadline):
            d["deadline"] = None
        return d

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "WorkItem":
        d = dict(doc)
        if d.get("deadline") is None:
            d["deadline"] = math.inf
        return cls(**{k: d[k] for k in
                      ("request_id", "tenant", "request", "deadline",
                       "bucket_hint", "enqueued_at", "large") if k in d})


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True, default=float)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


class LeaseQueue:
    """One worker's (or the coordinator's) handle on a shared queue
    directory.  All methods are safe to call concurrently from any
    number of processes."""

    def __init__(self, root: str, worker: Optional[str] = None,
                 ttl_s: float = 30.0):
        from sagecal_tpu.obs.aggregate import worker_id

        self.root = root
        self.worker = worker or worker_id()
        self.ttl_s = float(ttl_s)
        os.makedirs(root, exist_ok=True)

    # -- paths ---------------------------------------------------------

    def item_path(self, rid: str) -> str:
        return os.path.join(self.root, f"{ITEM_PREFIX}{rid}.json")

    def lease_path(self, rid: str) -> str:
        return os.path.join(self.root, f"{LEASE_PREFIX}{rid}.json")

    def done_path(self, rid: str) -> str:
        return os.path.join(self.root, f"{DONE_PREFIX}{rid}.json")

    # -- producer side -------------------------------------------------

    def put(self, item: WorkItem) -> str:
        if not item.enqueued_at:
            item.enqueued_at = time.time()
        path = self.item_path(item.request_id)
        _atomic_write_json(path, item.to_doc())
        return path

    # -- introspection -------------------------------------------------

    def items(self) -> List[WorkItem]:
        out: List[WorkItem] = []
        for name in sorted(os.listdir(self.root)):
            if not (name.startswith(ITEM_PREFIX)
                    and name.endswith(".json")):
                continue
            doc = _read_json(os.path.join(self.root, name))
            if doc and doc.get("request_id"):
                out.append(WorkItem.from_doc(doc))
        return out

    def done_ids(self) -> Set[str]:
        n, s = len(DONE_PREFIX), len(".json")
        return {name[n:-s] for name in os.listdir(self.root)
                if name.startswith(DONE_PREFIX)
                and name.endswith(".json")}

    def read_lease(self, rid: str) -> Optional[Dict[str, Any]]:
        return _read_json(self.lease_path(rid))

    def read_done(self, rid: str) -> Optional[Dict[str, Any]]:
        return _read_json(self.done_path(rid))

    def pending(self, now: Optional[float] = None) -> List[WorkItem]:
        """Items with no done marker and no LIVE lease, i.e. claimable
        right now (unleased, or leased-but-expired)."""
        now = time.time() if now is None else float(now)
        done = self.done_ids()
        out: List[WorkItem] = []
        for it in self.items():
            if it.request_id in done:
                continue
            lease = self.read_lease(it.request_id)
            if lease is not None \
                    and float(lease.get("expires_at", 0.0)) > now:
                continue
            out.append(it)
        return out

    def stats(self, now: Optional[float] = None) -> Dict[str, int]:
        now = time.time() if now is None else float(now)
        items = self.items()
        done = self.done_ids()
        leased = expired = 0
        for it in items:
            if it.request_id in done:
                continue
            lease = self.read_lease(it.request_id)
            if lease is None:
                continue
            if float(lease.get("expires_at", 0.0)) > now:
                leased += 1
            else:
                expired += 1
        return {"items": len(items),
                "done": sum(1 for i in items if i.request_id in done),
                "leased": leased, "expired_leases": expired}

    def all_done(self) -> bool:
        done = self.done_ids()
        return all(it.request_id in done for it in self.items())

    # -- claim protocol ------------------------------------------------

    def claim(self, rid: str, now: Optional[float] = None) -> bool:
        """Try to acquire the lease on one request.  True iff THIS
        worker now holds it.  Never blocks, never raises on contention."""
        now = time.time() if now is None else float(now)
        if os.path.exists(self.done_path(rid)):
            return False
        lpath = self.lease_path(rid)
        lease = _read_json(lpath)
        if lease is not None:
            if float(lease.get("expires_at", 0.0)) > now:
                return False
            # expired: steal via unique-tombstone rename — atomic, so
            # of N workers racing on the same dead lease exactly one
            # rename succeeds and the rest fall through to the O_EXCL
            # create below (which the winner also races for, fairly)
            tomb = f"{lpath}.expired.{uuid.uuid4().hex[:8]}"
            try:
                os.rename(lpath, tomb)
            except OSError:
                pass
            else:
                try:
                    os.unlink(tomb)
                except OSError:
                    pass
        try:
            fd = os.open(lpath, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            return False
        try:
            doc = {"worker": self.worker, "request_id": rid,
                   "acquired_at": now, "renewed_at": now,
                   "expires_at": now + self.ttl_s}
            os.write(fd, (json.dumps(doc, sort_keys=True) + "\n")
                     .encode("utf-8"))
            os.fsync(fd)
        finally:
            os.close(fd)
        if os.path.exists(self.done_path(rid)):
            # completed between our expiry check and the create: the
            # work is finished, back out
            self.release(rid)
            return False
        return True

    def renew(self, rid: str, now: Optional[float] = None) -> float:
        """Extend this worker's lease by ``ttl_s``.  Returns the new
        expiry; raises :class:`LeaseLost` when the lease is gone or
        held by someone else (stolen after expiry)."""
        now = time.time() if now is None else float(now)
        lpath = self.lease_path(rid)
        lease = _read_json(lpath)
        if lease is None or lease.get("worker") != self.worker:
            raise LeaseLost(
                f"lease on {rid} lost (now held by "
                f"{(lease or {}).get('worker', 'nobody')!r})")
        lease["renewed_at"] = now
        lease["expires_at"] = now + self.ttl_s
        _atomic_write_json(lpath, lease)
        return lease["expires_at"]

    def release(self, rid: str) -> None:
        try:
            os.unlink(self.lease_path(rid))
        except OSError:
            pass

    def complete(self, rid: str, **info) -> str:
        """Write the done marker (atomic) and drop the lease.  Call
        only after the request's result manifest is on disk."""
        path = self.done_path(rid)
        _atomic_write_json(path, dict(info, request_id=rid,
                                      worker=self.worker,
                                      completed_at=time.time()))
        self.release(rid)
        return path

    # -- failure accounting -------------------------------------------

    def record_failure(self, rid: str, error: str) -> int:
        """Leave a durable failure marker for one solve attempt (one
        unique file per attempt, so markers from concurrent workers
        never clobber each other) and return the total attempt count.
        Workers release a failed lease for retry until the count
        reaches their attempt budget, then complete the request with an
        error manifest so a poisoned input can't loop forever."""
        path = os.path.join(
            self.root,
            f"{FAIL_PREFIX}{rid}.{uuid.uuid4().hex[:8]}.json")
        _atomic_write_json(path, {
            "request_id": rid, "worker": self.worker,
            "ts": time.time(), "error": str(error)[:2000]})
        return self.failure_count(rid)

    def failure_count(self, rid: str) -> int:
        prefix = f"{FAIL_PREFIX}{rid}."
        return sum(1 for name in os.listdir(self.root)
                   if name.startswith(prefix) and name.endswith(".json"))

    # -- scheduling ----------------------------------------------------

    def select(self, affinity: Set[str] = frozenset(),
               limit: int = 1, now: Optional[float] = None,
               affinity_window_s: float = 10.0) -> List[WorkItem]:
        """Claim candidates in scheduling order: earliest deadline
        first (EDF), with bucket affinity deciding WITHIN a deadline
        window — two items due within ``affinity_window_s`` of each
        other are interchangeable deadline-wise, so the worker prefers
        the one whose shape it already holds an executable for (filling
        its vmapped batch lanes) without ever jumping a strictly
        earlier deadline window.  Does NOT claim — callers iterate the
        returned order and :meth:`claim`."""
        cands = self.pending(now)
        w = max(float(affinity_window_s), 1e-9)

        def key(it: WorkItem):
            dwin = math.floor(it.deadline / w) \
                if math.isfinite(it.deadline) else math.inf
            return (dwin,
                    0 if it.bucket_hint and it.bucket_hint in affinity
                    else 1,
                    it.deadline, it.enqueued_at, it.request_id)

        cands.sort(key=key)
        return cands[:max(int(limit), 0)] if limit else cands
