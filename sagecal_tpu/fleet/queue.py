"""Filesystem work queue with atomic lease files.

The queue is a directory three kinds of file live in, one per request:

- ``item-<rid>.json`` — the work item (the request dict plus scheduling
  metadata: absolute ``deadline``, ``bucket_hint``, ``enqueued_at``).
  Written once by the coordinator, never mutated.
- ``lease-<rid>.e<K>.json`` — the lease *epoch chain*.  Epoch files are
  **published atomically** (staged to a tmp name, then hard-linked into
  place: the name appears with its full content in one step, and
  ``link`` fails with ``EEXIST`` if someone else won) and **never
  rewritten**: every state change of the lease (claim, renew, steal,
  release) is the publication of the next epoch file, and the head of
  the chain (highest ``K``) is the current lease.  Exclusive publish on
  a never-reused name is the linearization point — of N workers racing
  to advance the chain, exactly one creates ``e<K+1>`` and the rest
  observe it and back off.  A plain ``O_CREAT|O_EXCL`` create followed
  by a separate content write would NOT do: the head is visible but
  empty between the two ops, and a peer that reads the torn head while
  its creator is alive mid-write would treat the lease as dead and
  advance over it (the model checker demonstrates that double claim —
  see the ``torn-publish`` mutation).
- ``done-<rid>.json`` — the completion marker, written atomically
  AFTER the result manifest is on disk.  Claims check it first and
  last, so a request completed between the expiry check and the new
  epoch is released untouched.

Why an epoch chain instead of delete + recreate: a steal that unlinks
(or renames away) the dead lease file and then re-creates it has an
ABA window — a second stealer that read the same dead lease can rename
or unlink the *winner's freshly created live lease* (rename/unlink act
on a name, not on the content the stealer validated), yielding two
workers that both believe they hold the claim.  The protocol model
checker (sagecal_tpu/analysis/protocol_check.py) finds that
interleaving mechanically.  With the chain, nothing is ever deleted or
rewritten while a request is in flight, so the content a stealer
validated ("head epoch K is expired") is immutable, and two further
properties make observed expiry *stable*:

- :meth:`renew` refuses an already-expired head (``LeaseLost``), so an
  expired epoch can never be resurrected by its old holder;
- an unparsable head (external corruption, or garbage left by an older
  protocol) is treated as expired, so nothing can wedge a request
  un-claimably — and because epoch files are immutable once published,
  "this head is dead" is a stable observation, never a torn-write
  transient.

Exactly-once *effects* come from the result-manifest layer, not the
queue: a zombie worker whose lease was stolen may finish its solve in
parallel with the stealer, but both write the same deterministic
result (per-request RNG is derived from the request id and vmapped
lanes are independent) through atomic ``os.replace``, so the manifest
set contains no duplicates and no torn files.  :meth:`complete` sweeps
the (inert) epoch files after the done marker lands.

Claim ordering is deadline-first (EDF) with bucket affinity: a worker
prefers items whose ``bucket_hint`` it has already compiled/claimed —
that is what lets same-shape requests land on the same worker and fill
its vmapped batch lanes — but never at the cost of an earlier deadline
in a different bucket beyond the batch window.

Everything here is stdlib-only and safe on any POSIX filesystem with
atomic rename.  All filesystem access goes through an injectable
``fs`` object (:class:`RealFS` by default) and all time reads through
an injectable ``clock`` — the two seams the model checker uses to
drive this exact code through simulated interleavings, crashes, and
logical time (see sagecal_tpu/analysis/fsmodel.py).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import math
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Set, Tuple

ITEM_PREFIX = "item-"
LEASE_PREFIX = "lease-"
DONE_PREFIX = "done-"
FAIL_PREFIX = "fail-"


class LeaseLost(RuntimeError):
    """Raised by :meth:`LeaseQueue.renew` when the caller's lease no
    longer exists, is held by another worker (it expired and was
    stolen), or has already expired (renewing it could resurrect a
    lease a stealer has validated as dead).  The holder must treat the
    request as no longer its own."""


class RealFS:
    """The production filesystem, at the op granularity the lease
    protocol relies on.  Each method is one crash-atomic step:

    - ``publish_excl`` — unique tmp + fsync + ``os.link`` into place:
      the name appears with its full content in one step, exactly one
      publisher wins (``EEXIST``), and a crash loses only invisible
      tmp state — never a visible torn file;
    - ``write_atomic`` — unique tmp + fsync + ``os.replace`` (readers
      see the old content or the new, never a torn file; a crash loses
      only un-renamed tmp state);
    - ``unlink_matching`` — one cleanup sweep over a name prefix;
    - ``open_excl`` / ``commit`` / ``create`` — the torn-window
      primitives, NOT used by the shipped protocol; they exist so the
      checker's seeded mutations can express the buggy variants.

    The simulator (sagecal_tpu/analysis/fsmodel.py) implements the same
    surface deterministically; the differential test in
    tests/test_protocol.py pins that both behave identically on
    crash-free schedules.
    """

    _seq = itertools.count()

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.listdir(path))

    def read_text(self, path: str) -> str:
        with open(path, "r", encoding="utf-8") as f:
            return f.read()

    def open_excl(self, path: str) -> int:
        return os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)

    def create(self, path: str) -> int:
        """Plain truncating create — NOT used by the protocol (claims
        must win ``publish_excl``); present so the simulator and the
        real fs expose the same surface to the checker's mutations."""
        return os.open(path, os.O_CREAT | os.O_TRUNC | os.O_WRONLY)

    def publish_excl(self, path: str, text: str) -> None:
        """Atomically publish ``text`` at ``path``, failing with
        :class:`FileExistsError` if the name already exists.  The hard
        link makes the name appear with its full content in one step —
        a reader can never observe a half-written file, unlike
        ``open_excl`` + ``commit``."""
        tmp = f"{path}.tmp.{self.unique_suffix()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def commit(self, fd: int, text: str) -> None:
        try:
            os.write(fd, text.encode("utf-8"))
            os.fsync(fd)
        finally:
            os.close(fd)

    def write_atomic(self, path: str, text: str) -> None:
        tmp = f"{path}.tmp.{self.unique_suffix()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def unlink(self, path: str) -> None:
        os.unlink(path)

    def unlink_matching(self, dirpath: str, prefix: str) -> int:
        n = 0
        try:
            names = os.listdir(dirpath)
        except OSError:
            return 0
        for name in names:
            if name.startswith(prefix):
                try:
                    os.unlink(os.path.join(dirpath, name))
                    n += 1
                except OSError:
                    pass
        return n

    def unique_suffix(self) -> str:
        return f"{os.getpid()}.{next(self._seq)}.{uuid.uuid4().hex[:8]}"


_REAL_FS = RealFS()


@dataclasses.dataclass
class WorkItem:
    """One queued request plus its scheduling metadata."""

    request_id: str
    tenant: str
    request: Dict[str, Any]     # the SolveRequest fields, verbatim
    deadline: float = math.inf  # absolute unix deadline (EDF key)
    bucket_hint: str = ""       # shape-affinity key (coordinator-set)
    enqueued_at: float = 0.0
    large: bool = False         # place via sharded_joint_fit

    def to_doc(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        if math.isinf(self.deadline):
            d["deadline"] = None
        return d

    @classmethod
    def from_doc(cls, doc: Dict[str, Any]) -> "WorkItem":
        d = dict(doc)
        if d.get("deadline") is None:
            d["deadline"] = math.inf
        return cls(**{k: d[k] for k in
                      ("request_id", "tenant", "request", "deadline",
                       "bucket_hint", "enqueued_at", "large") if k in d})


def _dump_json(doc: Dict[str, Any]) -> str:
    return json.dumps(doc, sort_keys=True, default=float) + "\n"


def _parse_json(text: str) -> Optional[Dict[str, Any]]:
    try:
        doc = json.loads(text)
    except (ValueError, TypeError):
        return None
    return doc if isinstance(doc, dict) else None


class LeaseQueue:
    """One worker's (or the coordinator's) handle on a shared queue
    directory.  All methods are safe to call concurrently from any
    number of processes."""

    def __init__(self, root: str, worker: Optional[str] = None,
                 ttl_s: float = 30.0, fs=None, clock=None):
        from sagecal_tpu.obs.aggregate import worker_id

        self.root = root
        self.worker = worker or worker_id()
        self.ttl_s = float(ttl_s)
        self.fs = fs if fs is not None else _REAL_FS
        self.clock = clock if clock is not None else time.time
        self.fs.makedirs(root)

    def _now(self, now: Optional[float]) -> float:
        return self.clock() if now is None else float(now)

    def _read_json(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            text = self.fs.read_text(path)
        except OSError:
            return None
        return _parse_json(text)

    # -- paths ---------------------------------------------------------

    def item_path(self, rid: str) -> str:
        return os.path.join(self.root, f"{ITEM_PREFIX}{rid}.json")

    def lease_path(self, rid: str, epoch: int = 0) -> str:
        return os.path.join(self.root,
                            f"{LEASE_PREFIX}{rid}.e{epoch:06d}.json")

    def done_path(self, rid: str) -> str:
        return os.path.join(self.root, f"{DONE_PREFIX}{rid}.json")

    # -- the lease chain ----------------------------------------------

    def _head_epoch(self, rid: str) -> int:
        """Highest existing epoch for ``rid``, or -1 for no lease."""
        prefix = f"{LEASE_PREFIX}{rid}.e"
        head = -1
        for name in self.fs.listdir(self.root):
            if not (name.startswith(prefix) and name.endswith(".json")):
                continue
            try:
                head = max(head, int(name[len(prefix):-len(".json")]))
            except ValueError:
                continue
        return head

    def _lease_head(self, rid: str) -> Tuple[int,
                                             Optional[Dict[str, Any]]]:
        """(head epoch, parsed doc).  ``(-1, None)`` when no epoch file
        exists; ``(k, None)`` for an unparsable head (corruption or
        older-protocol garbage; the atomic publish never leaves one) —
        treated as expired, which is stable because epoch files are
        immutable."""
        epoch = self._head_epoch(rid)
        if epoch < 0:
            return -1, None
        return epoch, self._read_json(self.lease_path(rid, epoch))

    def _advance(self, rid: str, epoch: int,
                 doc: Dict[str, Any]) -> bool:
        """Try to publish epoch ``epoch+1`` with ``doc``.  True iff
        this worker won the publish (the only mutation point of the
        chain).  The publish is a single atomic step — the new head
        appears with its full content, so no peer can ever read it
        half-written and mistake a live lease for a dead one."""
        try:
            self.fs.publish_excl(self.lease_path(rid, epoch + 1),
                                 _dump_json(dict(doc, epoch=epoch + 1)))
        except (FileExistsError, OSError):
            return False
        return True

    @staticmethod
    def _live(doc: Optional[Dict[str, Any]], now: float) -> bool:
        return doc is not None \
            and float(doc.get("expires_at", 0.0)) > now

    # -- producer side -------------------------------------------------

    def put(self, item: WorkItem, now: Optional[float] = None) -> str:
        if not item.enqueued_at:
            item.enqueued_at = self._now(now)
        path = self.item_path(item.request_id)
        self.fs.write_atomic(path, _dump_json(item.to_doc()))
        return path

    # -- introspection -------------------------------------------------

    def items(self) -> List[WorkItem]:
        out: List[WorkItem] = []
        for name in self.fs.listdir(self.root):
            if not (name.startswith(ITEM_PREFIX)
                    and name.endswith(".json")):
                continue
            doc = self._read_json(os.path.join(self.root, name))
            if doc and doc.get("request_id"):
                out.append(WorkItem.from_doc(doc))
        return out

    def done_ids(self) -> Set[str]:
        n, s = len(DONE_PREFIX), len(".json")
        return {name[n:-s] for name in self.fs.listdir(self.root)
                if name.startswith(DONE_PREFIX)
                and name.endswith(".json")}

    def read_lease(self, rid: str) -> Optional[Dict[str, Any]]:
        return self._lease_head(rid)[1]

    def read_done(self, rid: str) -> Optional[Dict[str, Any]]:
        return self._read_json(self.done_path(rid))

    def pending(self, now: Optional[float] = None) -> List[WorkItem]:
        """Items with no done marker and no LIVE lease, i.e. claimable
        right now (unleased, or leased-but-expired)."""
        now = self._now(now)
        done = self.done_ids()
        out: List[WorkItem] = []
        for it in self.items():
            if it.request_id in done:
                continue
            if self._live(self.read_lease(it.request_id), now):
                continue
            out.append(it)
        return out

    def stats(self, now: Optional[float] = None) -> Dict[str, int]:
        now = self._now(now)
        items = self.items()
        done = self.done_ids()
        leased = expired = 0
        for it in items:
            if it.request_id in done:
                continue
            epoch, doc = self._lease_head(it.request_id)
            if epoch < 0:
                continue
            if self._live(doc, now):
                leased += 1
            else:
                expired += 1
        ndone = sum(1 for i in items if i.request_id in done)
        return {"items": len(items), "done": ndone,
                "leased": leased, "expired_leases": expired,
                # never-leased items still waiting to be claimed (the
                # live-timeline waiting-room gauge)
                "waiting": max(len(items) - ndone - leased - expired,
                               0)}

    def all_done(self, empty: bool = True) -> bool:
        """True iff every queued request has a done marker.  ``empty``
        picks the answer for a queue with no items at all: a seeded
        fleet treats that as drained (vacuous truth), while open-loop
        load harnesses pass ``empty=False`` because arrivals are still
        being submitted and an empty queue just means "no work YET"."""
        items = self.items()
        if not items:
            return empty
        done = self.done_ids()
        return all(it.request_id in done for it in items)

    # -- claim protocol ------------------------------------------------

    def claim(self, rid: str, now: Optional[float] = None) -> bool:
        """Try to acquire the lease on one request.  True iff THIS
        worker now holds it.  Never blocks, never raises on contention.

        A vacant, expired, released, or unparsable head is claimable;
        the claim is winning the exclusive publish of the next epoch
        file.  The observed head can never become live again in
        between (expired heads are immutable and un-renewable), so
        winning the publish IS acquiring the lease — there is no
        recreate window for a second stealer to clobber."""
        now = self._now(now)
        if self.fs.exists(self.done_path(rid)):
            return False
        epoch, doc = self._lease_head(rid)
        if self._live(doc, now):
            return False
        won = self._advance(rid, epoch, {
            "worker": self.worker, "request_id": rid,
            "acquired_at": now, "renewed_at": now,
            "expires_at": now + self.ttl_s})
        if not won:
            return False
        if self.fs.exists(self.done_path(rid)):
            # completed between our expiry check and the create: the
            # work is finished, back out
            self.release(rid, now=now)
            return False
        return True

    def renew(self, rid: str, now: Optional[float] = None) -> float:
        """Extend this worker's lease by ``ttl_s``.  Returns the new
        expiry; raises :class:`LeaseLost` when the lease is gone, held
        by someone else (stolen after expiry), or already expired.

        Refusing an expired lease is load-bearing, not cosmetic: it is
        what makes "this head is expired" a STABLE observation, so a
        stealer that validated the head as dead can win the next epoch
        without racing a resurrection."""
        now = self._now(now)
        epoch, doc = self._lease_head(rid)
        if doc is None or doc.get("worker") != self.worker:
            raise LeaseLost(
                f"lease on {rid} lost (now held by "
                f"{(doc or {}).get('worker', 'nobody')!r})")
        if not self._live(doc, now):
            raise LeaseLost(
                f"lease on {rid} expired at "
                f"{float(doc.get('expires_at', 0.0)):.3f} "
                f"(now {now:.3f}); it may already be stolen")
        doc = dict(doc, renewed_at=now, expires_at=now + self.ttl_s)
        if not self._advance(rid, epoch, doc):
            raise LeaseLost(
                f"lease on {rid} lost (chain advanced past epoch "
                f"{epoch} underneath this worker)")
        return doc["expires_at"]

    def release(self, rid: str, now: Optional[float] = None) -> None:
        """Give the claim up (no-op unless this worker holds the live
        head): the next epoch records an immediately-expired lease, so
        any worker may claim without waiting out the TTL."""
        now = self._now(now)
        epoch, doc = self._lease_head(rid)
        if doc is None or doc.get("worker") != self.worker \
                or not self._live(doc, now):
            return
        self._advance(rid, epoch, {
            "worker": self.worker, "request_id": rid,
            "acquired_at": doc.get("acquired_at", now),
            "renewed_at": now, "released_at": now,
            "expires_at": 0.0})

    def complete(self, rid: str, now: Optional[float] = None,
                 **info) -> str:
        """Write the done marker (atomic), then sweep the now-inert
        lease epoch files.  Call only after the request's result
        manifest is on disk."""
        now = self._now(now)
        path = self.done_path(rid)
        self.fs.write_atomic(path, _dump_json(
            dict(info, request_id=rid, worker=self.worker,
                 completed_at=now)))
        # every claim checks the done marker before and after acquiring,
        # so once it is on disk the epoch chain is unreachable garbage
        self.fs.unlink_matching(self.root, f"{LEASE_PREFIX}{rid}.e")
        return path

    # -- failure accounting -------------------------------------------

    def record_failure(self, rid: str, error: str,
                       now: Optional[float] = None) -> int:
        """Leave a durable failure marker for one solve attempt (one
        unique file per attempt, so markers from concurrent workers
        never clobber each other) and return the total attempt count.
        Workers release a failed lease for retry until the count
        reaches their attempt budget, then complete the request with an
        error manifest so a poisoned input can't loop forever."""
        path = os.path.join(
            self.root,
            f"{FAIL_PREFIX}{rid}.{self.fs.unique_suffix()}.json")
        self.fs.write_atomic(path, _dump_json({
            "request_id": rid, "worker": self.worker,
            "ts": self._now(now), "error": str(error)[:2000]}))
        return self.failure_count(rid)

    def failure_count(self, rid: str) -> int:
        prefix = f"{FAIL_PREFIX}{rid}."
        return sum(1 for name in self.fs.listdir(self.root)
                   if name.startswith(prefix) and name.endswith(".json"))

    # -- scheduling ----------------------------------------------------

    def select(self, affinity: Set[str] = frozenset(),
               limit: int = 1, now: Optional[float] = None,
               affinity_window_s: float = 10.0) -> List[WorkItem]:
        """Claim candidates in scheduling order: earliest deadline
        first (EDF), with bucket affinity deciding WITHIN a deadline
        window — two items due within ``affinity_window_s`` of each
        other are interchangeable deadline-wise, so the worker prefers
        the one whose shape it already holds an executable for (filling
        its vmapped batch lanes) without ever jumping a strictly
        earlier deadline window.  Does NOT claim — callers iterate the
        returned order and :meth:`claim`."""
        cands = self.pending(now)
        w = max(float(affinity_window_s), 1e-9)

        def key(it: WorkItem):
            dwin = math.floor(it.deadline / w) \
                if math.isfinite(it.deadline) else math.inf
            return (dwin,
                    0 if it.bucket_hint and it.bucket_hint in affinity
                    else 1,
                    it.deadline, it.enqueued_at, it.request_id)

        cands.sort(key=key)
        return cands[:max(int(limit), 0)] if limit else cands
