"""Streaming calibration: sliding-window solves on a live time stream.

The ``stream`` workload treats a dataset as an arriving time series:
each window of ``window`` time samples (advanced by ``hop``) is solved
as soon as its data is available, and the figure of merit is
**latency-to-first-solution** — how long after a window's data lands
does a usable gain solution exist.

Two mechanisms keep that latency low:

1. **the elastic warm-start chain** — window ``w`` starts from window
   ``w-1``'s converged gains.  Sky and instrument drift slowly across
   one hop, so the warm start is near-converged and a reduced budget
   (``warm_emiter``/``warm_lbfgs``) suffices; only the cold window 0
   pays full iteration budgets.  The chain is exactly the temporal
   warm start the fullbatch tile loop exploits, made load-bearing: the
   reduced warm budgets are only sound BECAUSE the chain exists, and
   the quality watchdog verdicts every window so a chain gone stale
   (divergence) is detected and reset to identity.
2. **executable reuse** — all warm windows share one jit program (one
   SageConfig), so steady state runs compile-free; the stream pays at
   most two compiles (cold config + warm config), both up front.

The chain itself is checkpointed through the elastic layer with an
*owner lease* stamped into the checkpoint meta (renewed by the
checkpoint cadence): a second stream process pointed at the same
checkpoint directory refuses to adopt a chain whose owner's lease is
still live (``check_owner_lease``) and only takes over once the lease
expires — the same dead-worker-takeover contract as the fleet queue,
applied to stream state.

Every window writes a serve-style result manifest
(``<request_id>-wNNNN.result.json``) carrying ``latency_s`` (window
data ready -> solution on disk) and the ``warm`` flag, so ``diag
serve``, the SLO evaluator, and the bench gate consume stream runs
with zero new plumbing.
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Dict, List

import numpy as np


def stream_windows(ntime: int, window: int, hop: int,
                   max_windows: int = 0) -> List[int]:
    """Start indices of the sliding windows: ``t0 = w * hop`` while a
    full window of data exists.  A degenerate stream (window > ntime)
    yields nothing rather than a short read."""
    window = max(int(window), 1)
    hop = max(int(hop), 1)
    out = [t0 for t0 in range(0, int(ntime) - window + 1, hop)]
    if max_windows:
        out = out[: int(max_windows)]
    return out


def steady_state_latency(latencies: List[float]) -> float:
    """The banked ``latency_to_first_solution_s``: median per-window
    latency over the steady state.  Windows 0 and 1 are excluded when
    the stream is long enough — they carry the cold and warm program
    compiles respectively, which are one-time costs, not the per-window
    latency a streaming consumer sees."""
    if not latencies:
        return 0.0
    steady = latencies[2:] if len(latencies) > 2 else latencies[-1:]
    s = sorted(steady)
    return float(s[len(s) // 2])


def make_synthetic_stream(workdir: str, nstations: int = 7,
                          ntime: int = 6, nchan: int = 2,
                          noise_sigma: float = 0.0, seed: int = 7):
    """Simulate one stream fixture (dataset + sky/cluster files) in
    ``workdir``; returns ``(dataset, sky, cluster)`` paths.  Same
    two-source sky as the serve synthetic workload so stream and serve
    benches exercise identical model complexity."""
    import h5py

    from sagecal_tpu.io.dataset import simulate_dataset
    from sagecal_tpu.io.simulate import random_jones
    from sagecal_tpu.io.skymodel import load_sky
    from sagecal_tpu.serve.synthetic import _CLUSTER, _SKY

    os.makedirs(workdir, exist_ok=True)
    sky = os.path.join(workdir, "stream_sky.txt")
    with open(sky, "w") as f:
        f.write(_SKY)
    cluster = sky + ".cluster"
    with open(cluster, "w") as f:
        f.write(_CLUSTER)
    dec0 = math.radians(51.0)
    path = os.path.join(workdir, f"stream_N{nstations}.vis.h5")
    clusters, _, _ = load_sky(sky, cluster, 0.0, dec0, dtype=np.float64)
    simulate_dataset(
        path, nstations=nstations, ntime=ntime, nchan=nchan,
        clusters=clusters,
        jones=random_jones(len(clusters), nstations, seed=seed,
                           amp=0.1, dtype=np.complex128),
        noise_sigma=noise_sigma, seed=seed, dec0=dec0)
    with h5py.File(path, "r+") as f:
        f.attrs["ra0"] = 0.0
        f.attrs["dec0"] = dec0
    return path, sky, cluster


class StreamCalibrator:
    """One stream process: window loop + warm-start chain + lease-aware
    checkpoints + per-window result manifests."""

    def __init__(self, cfg, log=print, device=None, clock=time.time):
        from sagecal_tpu.obs.aggregate import worker_id

        self.cfg = cfg
        self.log = log
        self.device = device
        self.clock = clock  # injectable so lease logic is checkable
        self.owner = worker_id()

    # -- config plumbing ----------------------------------------------

    def _sage_configs(self):
        """(cold, warm) solver configs.  Warm budgets only shrink the
        cold ones — a degenerate config (warm > cold) silently clamps
        so the warm window never does MORE work than the cold one."""
        from sagecal_tpu.obs import telemetry_enabled
        from sagecal_tpu.solvers.sage import SageConfig

        cfg = self.cfg
        common = dict(
            max_iter=cfg.max_iter, lbfgs_m=cfg.lbfgs_m,
            solver_mode=cfg.solver_mode,
            nulow=cfg.nulow, nuhigh=cfg.nuhigh,
            randomize=cfg.randomize,
            collect_telemetry=telemetry_enabled(),
            collect_quality=True,
        )
        cold = SageConfig(max_emiter=cfg.max_emiter,
                          max_lbfgs=cfg.max_lbfgs, **common)
        warm = SageConfig(
            max_emiter=min(max(cfg.warm_emiter, 1), cfg.max_emiter),
            max_lbfgs=min(cfg.warm_lbfgs or cfg.max_lbfgs,
                          cfg.max_lbfgs),
            **common)
        return cold, warm

    def _fingerprint(self, meta, M: int, nchunk_max: int) -> str:
        from sagecal_tpu.elastic.checkpoint import config_fingerprint

        cfg = self.cfg
        return config_fingerprint(
            app="stream", dataset=os.path.abspath(cfg.dataset),
            sky_model=os.path.abspath(cfg.sky_model),
            cluster_file=os.path.abspath(cfg.cluster_file),
            nstations=meta.nstations, ntime=meta.ntime,
            nchan=meta.nchan, freq0=meta.freq0,
            n_clusters=M, nchunk_max=nchunk_max,
            window=cfg.window, hop=cfg.hop,
            warm_start=cfg.warm_start, warm_emiter=cfg.warm_emiter,
            warm_lbfgs=cfg.warm_lbfgs, solver_mode=cfg.solver_mode,
            max_emiter=cfg.max_emiter, max_iter=cfg.max_iter,
            max_lbfgs=cfg.max_lbfgs, lbfgs_m=cfg.lbfgs_m,
            nulow=cfg.nulow, nuhigh=cfg.nuhigh,
            randomize=cfg.randomize, use_f64=cfg.use_f64,
            in_column=cfg.in_column,
        )

    # -- the stream loop ----------------------------------------------

    def run(self, elog=None) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        from sagecal_tpu.core.types import (
            identity_jones, jones_to_params, params_to_jones,
        )
        from sagecal_tpu.io import solutions as solio
        from sagecal_tpu.io.dataset import VisDataset
        from sagecal_tpu.io.skymodel import load_sky
        from sagecal_tpu.obs.quality import check_and_emit
        from sagecal_tpu.serve.request import write_result_manifest
        from sagecal_tpu.solvers.sage import build_cluster_data, solve_tile

        cfg = self.cfg
        t_start = self.clock()
        dtype = np.float64 if cfg.use_f64 else np.float32
        cdtype = np.complex128 if cfg.use_f64 else np.complex64
        os.makedirs(cfg.out_dir, exist_ok=True)

        ds = VisDataset(cfg.dataset, "r")
        meta = ds.meta
        clusters, cdefs, shapelets = load_sky(
            cfg.sky_model, cfg.cluster_file, meta.ra0, meta.dec0,
            dtype=dtype)
        M = len(clusters)
        nchunks = [cd.nchunk for cd in cdefs]
        nchunk_max = max(nchunks)
        N = meta.nstations
        windows = stream_windows(meta.ntime, cfg.window, cfg.hop,
                                 cfg.max_windows)
        stem = os.path.splitext(
            os.path.basename(cfg.dataset))[0].replace(".vis", "")

        eye = jones_to_params(identity_jones(N, cdtype))
        pinit = jnp.broadcast_to(eye, (M, nchunk_max, 8 * N)).astype(dtype)
        p = pinit
        rng_key = jax.random.PRNGKey(cfg.seed)
        cold_cfg, warm_cfg = self._sage_configs()

        # lease-aware checkpointing of the warm-start chain
        ckmgr = None
        resume_done = 0
        if cfg.resume or cfg.checkpoint_every > 0:
            from sagecal_tpu.elastic.checkpoint import (
                CheckpointManager, check_owner_lease,
            )

            ckmgr = CheckpointManager(
                cfg.checkpoint_dir
                or os.path.join(cfg.out_dir, "stream.ckpt"),
                self._fingerprint(meta, M, nchunk_max), "stream",
                every=max(cfg.checkpoint_every, 1), elog=elog,
                log=self.log)
            if cfg.resume:
                # Three-phase adoption (read -> gate -> confirm), the
                # shape the protocol model checker verifies: gating on
                # a checkpoint that is no longer the newest would let
                # us adopt a window the live owner has already moved
                # past (the stale-read fork).  The confirm re-read
                # detects a chain that advanced between our read and
                # the lease gate and restarts the adoption attempt.
                for _ in range(8):
                    found = ckmgr.resume()
                    if found is None:
                        break
                    rmeta, rarr, rpath = found
                    # refuse a chain another live process still owns
                    check_owner_lease(rmeta, self.owner)
                    again = ckmgr.resume()
                    if again is not None and again[2] != rpath:
                        continue
                    resume_done = int(rmeta["windows_done"])
                    p = jnp.asarray(rarr["p"])
                    rng_key = jnp.asarray(rarr["rng_key"])
                    self.log(f"stream: adopted chain at window "
                             f"{resume_done} from {rpath} (previous "
                             f"owner {rmeta.get('owner')!r})")
                    break
                else:
                    # the chain advanced on every attempt: somebody is
                    # actively writing it, whatever their lease file
                    # said at the instants we sampled it
                    from sagecal_tpu.elastic.checkpoint import \
                        ResumeRefused

                    raise ResumeRefused(
                        "checkpoint chain kept advancing during "
                        "adoption; a live owner is writing it")

        sol_path = os.path.join(cfg.out_dir, f"{stem}.stream.solutions")
        # jaxlint: disable=JL008 — deliberate append-chain: solutions
        # must grow across resumed runs (tmp+replace cannot express an
        # append); consumed post-hoc by readers that tolerate a torn
        # tail, and no protocol decision reads this file
        if resume_done:
            sol_fh = open(sol_path, "a")  # jaxlint: disable=JL008 — see above
        else:
            sol_fh = open(sol_path, "w")  # jaxlint: disable=JL008 — see above
            solio.write_header(
                sol_fh, meta.freq0, meta.deltaf,
                meta.deltat * cfg.window / 60.0, N, M, M * nchunk_max)

        latencies: List[float] = []
        results: List[Dict[str, Any]] = []
        warm_count = resets = 0
        # our own lease deadline (0.0 until the first checkpoint is
        # published); once it passes, a successor may have adopted the
        # chain, so we fence off ALL further chain writes.  A TTL of 0
        # disables leasing — every lease is born expired, so there is
        # no ownership to fence and the deadline stays unarmed.
        lease_deadline = 0.0
        fenced = False
        try:
            for w, t0 in enumerate(windows):
                if w < resume_done:
                    continue
                # window data "arrives": everything after this read is
                # the latency a live stream consumer would experience
                data = ds.load_tile(t0, cfg.window,
                                    average_channels=True, dtype=dtype,
                                    column=cfg.in_column)
                data_ready = self.clock()
                cdata = build_cluster_data(data, clusters, nchunks,
                                           shapelets=shapelets)
                warm = bool(cfg.warm_start and w > 0)
                scfg = warm_cfg if warm else cold_cfg
                p0 = p if warm else pinit
                out = solve_tile(data, cdata, p0, scfg, key=rng_key,
                                 device=self.device)
                res0, res1 = float(out.res_0), float(out.res_1)
                diverged = (not np.isfinite(res1) or res1 == 0.0
                            or res1 > cfg.res_ratio * res0)
                # a diverged window breaks the chain: reset to identity
                # so the NEXT window re-converges cold instead of
                # warm-starting from a bad state
                p = pinit if diverged else jnp.asarray(np.asarray(out.p))
                rng_key = jax.random.fold_in(rng_key, w)

                q_verdict, q_reasons = "ok", []
                if out.quality is not None:
                    q_verdict, q_reasons = check_and_emit(
                        elog, out.quality, log=self.log, tile=t0,
                        app="stream")
                if diverged:
                    q_verdict = "diverged"
                    q_reasons = q_reasons + [
                        f"residual_ratio:{res0:.3e}->{res1:.3e}"]
                    resets += 1

                jsol = np.asarray(params_to_jones(p)).reshape(
                    M * nchunk_max, N, 2, 2)
                solio.append_solutions(sol_fh, jsol)
                sol_fh.flush()
                done = self.clock()
                latency = done - data_ready
                latencies.append(latency)
                warm_count += int(warm)

                result = {
                    "request_id": f"{stem}-w{w:04d}",
                    "tenant": "stream",
                    "dataset": cfg.dataset,
                    "t0": t0, "tilesz": cfg.window, "window": w,
                    "warm": warm, "verdict": q_verdict,
                    "reasons": q_reasons,
                    "res0": res0, "res1": res1,
                    "started_at": data_ready, "completed_at": done,
                    "enqueued_at": data_ready,
                    "latency_s": latency,
                    "latency_to_first_solution_s": latency,
                }
                write_result_manifest(cfg.out_dir, result)
                results.append(result)
                if ckmgr is not None and not fenced:
                    now = self.clock()
                    if 0.0 < lease_deadline <= now:
                        # self-fence: our lease expired before this
                        # renewal, so a successor may already own the
                        # chain — republishing would resurrect our
                        # stale state over its writes.  Keep solving
                        # (manifests are deterministic and idempotent)
                        # but never touch the chain again, not even
                        # from the signal-time crash flusher.
                        fenced = True
                        ckmgr.close()
                        self.log(
                            f"stream: owner lease expired "
                            f"{now - lease_deadline:.1f}s ago; fencing "
                            "off checkpoint writes — a successor may "
                            "own the chain")
                        if elog is not None:
                            elog.emit("stream_lease_fenced", window=w,
                                      deadline=lease_deadline, now=now)
                    else:
                        ckmgr.update(
                            w,
                            {"p": np.asarray(p),
                             "rng_key": np.asarray(rng_key)},
                            windows_done=w + 1, owner=self.owner,
                            lease_expires_at=now + cfg.lease_ttl_s)
                        if cfg.lease_ttl_s > 0:
                            lease_deadline = now + cfg.lease_ttl_s
                if elog is not None:
                    elog.emit("stream_window", window=w, t0=t0,
                              warm=warm, latency_s=latency,
                              res0=res0, res1=res1, verdict=q_verdict)
                self.log(f"window {w} (t0={t0}): "
                         f"{'warm' if warm else 'cold'} "
                         f"residual {res0:.6f} -> {res1:.6f} "
                         f"({latency:.2f}s to solution)")
            if ckmgr is not None and not fenced:
                # clean completion: RELEASE the owner lease so a
                # successor process can adopt the chain immediately
                # (only a crashed run — this line never reached —
                # holds its lease until the TTL runs out).  The same
                # fence applies: an expired lease means the release is
                # no longer ours to publish.
                now = self.clock()
                if 0.0 < lease_deadline <= now:
                    fenced = True
                    ckmgr.close()
                    self.log("stream: owner lease expired before "
                             "release; leaving the chain to its "
                             "successor")
                else:
                    ckmgr.update(len(windows),
                                 {"p": np.asarray(p),
                                  "rng_key": np.asarray(rng_key)},
                                 windows_done=len(windows),
                                 owner=self.owner, lease_expires_at=0.0)
        finally:
            sol_fh.close()
            if ckmgr is not None:
                if not fenced:
                    ckmgr.flush()
                ckmgr.close()
            ds.close()

        summary = {
            "windows": len(windows),
            "solved": len(latencies) + resume_done,
            "resumed_from": resume_done,
            "warm": warm_count,
            "resets": resets,
            "lease_fenced": fenced,
            "first_window_latency_s": latencies[0] if latencies else 0.0,
            "latency_to_first_solution_s":
                steady_state_latency(latencies),
            "latencies_s": latencies,
            "solutions": sol_path,
            "wall_s": self.clock() - t_start,
        }
        if elog is not None:
            elog.emit("stream_done", **{
                k: v for k, v in summary.items() if k != "latencies_s"})
        self.log(
            f"stream: {summary['solved']}/{summary['windows']} windows "
            f"({warm_count} warm, {resets} chain resets), steady-state "
            f"latency {summary['latency_to_first_solution_s']:.2f}s, "
            f"first {summary['first_window_latency_s']:.2f}s")
        return summary
