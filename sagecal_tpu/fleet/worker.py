"""The fleet worker: claim -> admit -> solve -> complete.

One worker process runs this loop against the shared
:class:`~sagecal_tpu.fleet.queue.LeaseQueue`:

1. **scan** the shared out_dir so admission control sees every
   worker's completions (burn state converges fleet-wide without a
   central scheduler);
2. **claim** up to ``batch`` requests in EDF + bucket-affinity order,
   restricted to one ``bucket_hint`` per cycle so the claims stack
   into full vmapped batch lanes;
3. **admit** each claimed request (accept / degrade / shed per the
   tenant's SLO burn);
4. **solve** — small requests ride the serve scheduler
   (:class:`~sagecal_tpu.serve.service.CalibrationService`) with this
   worker's persistent :class:`~sagecal_tpu.serve.cache.
   ExecutableCache` injected (in-process tier + the cross-worker AOT
   artifact store, so only the FIRST worker in the fleet ever
   compiles a bucket); large requests (``nstations >=
   large_stations`` with >1 local device) are placed on
   :func:`~sagecal_tpu.solvers.sharded.sharded_joint_fit`;
5. **complete** — done markers written only after the result
   manifests are on disk.  A lease this worker lost mid-solve (it
   stalled past the TTL and another worker stole the request) is NOT
   completed here; both workers' manifests are deterministic-identical
   and atomic, so the stolen request still yields exactly one
   manifest.

Failed attempts leave durable failure markers; after ``MAX_ATTEMPTS``
the worker writes an error manifest and completes the request, so one
poisoned input can't wedge the fleet.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from sagecal_tpu.fleet.admission import build_controller
from sagecal_tpu.fleet.queue import LeaseLost, LeaseQueue, WorkItem

#: solve attempts per request before it is completed as an error
MAX_ATTEMPTS = 3


def _sigterm_to_exit(signum, frame):
    raise SystemExit(143)


def _request_from_item(item: WorkItem):
    from sagecal_tpu.serve.request import SolveRequest

    fields = {f.name for f in dataclasses.fields(SolveRequest)}
    kw = {k: v for k, v in item.request.items() if k in fields}
    if item.enqueued_at:
        # the fleet queue is the tenant-visible queue: manifests must
        # report wait since WorkItem enqueue, not since worker claim
        kw["enqueued_at"] = item.enqueued_at
    return SolveRequest(**kw)


class FleetWorker:
    """One claim-solve-complete loop over the shared queue."""

    def __init__(self, cfg, log=print, device=None, clock=time.time):
        from sagecal_tpu.obs.aggregate import worker_id
        from sagecal_tpu.serve.aot_store import AOTArtifactStore
        from sagecal_tpu.serve.cache import ExecutableCache

        self.cfg = cfg
        self.log = log
        self.device = device
        self.clock = clock  # injectable so deadline logic is checkable
        self.wid = cfg.worker_id or worker_id()
        self.queue = LeaseQueue(
            cfg.queue_dir or os.path.join(cfg.out_dir, "queue"),
            worker=self.wid, ttl_s=cfg.lease_ttl_s, clock=clock)
        self.store = AOTArtifactStore(
            cfg.aot_store or os.path.join(cfg.out_dir, "aot-store"))
        # ONE executable cache for the worker's whole life: the
        # in-process tier survives across claim cycles, the store tier
        # shares compiles across the fleet
        self.cache = ExecutableCache(store=self.store)
        self.admission = build_controller(cfg, cfg.requests)
        self.affinity: Set[str] = set()
        self._held: Set[str] = set()
        self._lost: Set[str] = set()
        self._hold_lock = threading.Lock()
        self.cycles = 0
        self.solved = 0
        # ONE shadow auditor for the worker's whole life (like the
        # executable cache): the wall-clock budget is per WORKER, not
        # per claim cycle, and every cycle's service gets it injected
        self.shadow = None
        if float(getattr(cfg, "shadow_rate", 0.0) or 0.0) > 0.0:
            from sagecal_tpu.obs.shadow import ShadowAuditor

            self.shadow = ShadowAuditor(
                cfg.out_dir, rate=cfg.shadow_rate,
                budget_s=float(getattr(cfg, "shadow_budget_s", 120.0)),
                seed=int(getattr(cfg, "shadow_seed", 0)),
                device=device, log=log)

    # -- config plumbing ----------------------------------------------

    def _serve_cfg(self):
        """The ServeConfig one claim cycle's CalibrationService runs
        under.  Elastic checkpointing is OFF on purpose: the queue's
        done markers are the fleet's durable progress record, so a
        restarted worker re-claims instead of resuming."""
        from sagecal_tpu.apps.config import ServeConfig

        c = self.cfg
        return ServeConfig(
            requests="", out_dir=c.out_dir, batch=c.batch,
            max_emiter=c.max_emiter, max_iter=c.max_iter,
            max_lbfgs=c.max_lbfgs, lbfgs_m=c.lbfgs_m,
            solver_mode=c.solver_mode, nulow=c.nulow, nuhigh=c.nuhigh,
            randomize=c.randomize, res_ratio=c.res_ratio,
            abort_on_divergence=False, resume=False,
            checkpoint_every=0, checkpoint_dir=None,
            use_f64=c.use_f64,
            use_fused_predict=getattr(c, "use_fused_predict", False),
            coh_dtype=getattr(c, "coh_dtype", "f32"),
            verbose=c.verbose, slo="",
            max_streams=c.max_streams,
            # shadow auditing rides the per-cycle service: every worker
            # appends to the SHARED <out_dir>/drift.jsonl (O_APPEND
            # single-write rows never interleave); the sampler is a
            # pure function of (seed, request_id) so the fleet agrees
            # on the sample with no coordination
            shadow_rate=float(getattr(c, "shadow_rate", 0.0) or 0.0),
            shadow_seed=int(getattr(c, "shadow_seed", 0)),
            shadow_budget_s=float(getattr(c, "shadow_budget_s", 120.0)),
            abort_on_drift=bool(getattr(c, "abort_on_drift", False)))

    # -- lease upkeep --------------------------------------------------

    def _renew_loop(self, stop: threading.Event) -> None:
        period = self.cfg.lease_renew_s or self.cfg.lease_ttl_s / 3.0
        while not stop.wait(max(period, 0.05)):
            with self._hold_lock:
                held = list(self._held)
            for rid in held:
                try:
                    self.queue.renew(rid)
                except LeaseLost:
                    with self._hold_lock:
                        self._held.discard(rid)
                        self._lost.add(rid)
                except OSError:
                    pass

    def _drop(self, rid: str) -> None:
        with self._hold_lock:
            self._held.discard(rid)

    # -- claiming ------------------------------------------------------

    def claim_cycle(self) -> List[WorkItem]:
        """Claim up to ``batch`` requests sharing one bucket hint."""
        cands = self.queue.select(
            self.affinity, limit=max(self.cfg.batch * 4, 8))
        claimed: List[WorkItem] = []
        hint: Optional[str] = None
        for it in cands:
            if hint is not None and it.bucket_hint != hint:
                continue
            if self.queue.claim(it.request_id):
                claimed.append(it)
                hint = it.bucket_hint
                if it.bucket_hint:
                    self.affinity.add(it.bucket_hint)
                if len(claimed) >= self.cfg.batch:
                    break
        return claimed

    # -- solving -------------------------------------------------------

    def _solve_small(self, items: List[Tuple[WorkItem, bool]],
                     elog) -> None:
        from sagecal_tpu.serve.service import CalibrationService

        reqs = [_request_from_item(it) for it, _ in items]
        svc = CalibrationService(self._serve_cfg(), log=self.log,
                                 device=self.device)
        svc.cache = self.cache  # persistent in-proc + AOT store tiers
        svc.shadow = self.shadow  # worker-lifetime audit budget
        svc.run(reqs, elog=elog)
        for it, degraded in items:
            if degraded:
                self._annotate_degraded(it.request_id)

    def _annotate_degraded(self, rid: str) -> None:
        """Stamp ``degraded: true`` into an existing result manifest
        (atomic rewrite) so tenants can see which results were
        produced under admission pressure."""
        import json

        from sagecal_tpu.serve.request import (
            result_manifest_path, write_result_manifest,
        )

        path = result_manifest_path(self.cfg.out_dir, rid)
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            return
        doc["degraded"] = True
        doc["degrade_emiter"] = self.admission.degrade_emiter
        doc["degrade_lbfgs"] = self.admission.degrade_lbfgs
        write_result_manifest(self.cfg.out_dir, doc)

    def _can_shard(self) -> bool:
        import jax

        return self.cfg.large_stations > 0 and len(jax.devices()) > 1

    def _solve_large(self, item: WorkItem, degraded: bool,
                     elog) -> None:
        """Place one large solve on the row-sharded joint-LBFGS path
        across every local device (instead of a vmapped batch lane)."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh

        from sagecal_tpu.core.types import (
            identity_jones, jones_to_params, params_to_jones,
        )
        from sagecal_tpu.io import solutions as solio
        from sagecal_tpu.io.dataset import VisDataset
        from sagecal_tpu.io.skymodel import load_sky
        from sagecal_tpu.obs.quality import check_and_emit
        from sagecal_tpu.serve.request import write_result_manifest
        from sagecal_tpu.solvers.sage import build_cluster_data
        from sagecal_tpu.solvers.sharded import (
            pad_rows_to, sharded_joint_fit,
        )

        req = _request_from_item(item)
        cfg = self.cfg
        t_start = self.clock()
        dtype = np.float64 if cfg.use_f64 else np.float32
        cdtype = np.complex128 if cfg.use_f64 else np.complex64
        with VisDataset(req.dataset, "r") as ds:
            meta = ds.meta
            data = ds.load_tile(req.t0, req.tilesz, dtype=dtype,
                                column=req.in_column)
        clusters, cdefs, shapelets = load_sky(
            req.sky_model, req.cluster_file, meta.ra0, meta.dec0,
            dtype=dtype)
        nchunks = [cd.nchunk for cd in cdefs]
        nchunk_max = max(nchunks)
        M, N = len(clusters), meta.nstations
        cdata = build_cluster_data(data, clusters, nchunks,
                                   shapelets=shapelets)
        eye = jones_to_params(identity_jones(N, cdtype))
        p0 = jnp.broadcast_to(
            eye, (M, nchunk_max, 8 * N)).astype(dtype)
        devs = np.asarray(jax.devices())
        data, cdata = pad_rows_to(data, cdata, len(devs))
        mesh = Mesh(devs, ("rows",))
        itmax = (self.admission.degrade_lbfgs if degraded
                 else cfg.max_lbfgs)
        out = sharded_joint_fit(data, cdata, p0, mesh,
                                itmax=itmax, lbfgs_m=cfg.lbfgs_m,
                                collect_quality=True)
        p, cost, iterations, quality = out
        verdict, reasons = check_and_emit(
            elog, jax.tree_util.tree_map(np.asarray, quality),
            log=self.log, tile=req.t0, app="fleet",
            tenant=req.tenant, request_id=req.request_id)
        out_path = req.out_solutions or os.path.join(
            cfg.out_dir, f"{req.request_id}.solutions")
        jsol = np.asarray(params_to_jones(np.asarray(p))).reshape(
            M * nchunk_max, N, 2, 2)
        # tmp + replace: a zombie whose lease was stolen may write the
        # same solutions path concurrently with the stealer — both
        # produce identical bytes, and the atomic rename keeps the
        # published file whole at every instant
        tmp_path = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp_path, "w") as fh:
            solio.write_header(
                fh, meta.freq0, meta.deltaf,
                meta.deltat * req.tilesz / 60.0, N, M, M * nchunk_max)
            solio.append_solutions(fh, jsol)
        os.replace(tmp_path, out_path)
        now = self.clock()
        result = {
            "request_id": req.request_id, "tenant": req.tenant,
            "dataset": req.dataset, "t0": req.t0,
            "tilesz": req.tilesz, "verdict": verdict,
            "reasons": reasons, "res_0": float(cost),
            "res_1": float(cost), "mean_nu": 0.0,
            "bucket": f"sharded:{len(devs)}dev", "batch": 1, "lane": 0,
            "placed": "sharded_joint_fit",
            "kernel_path": "sharded",
            "kernel_path_reason": (
                f"nstations={N} >= large_stations="
                f"{cfg.large_stations}: row-sharded joint fit over "
                f"{len(devs)} devices"),
            "iterations": int(iterations),
            "solutions": out_path,
            "enqueued_at": item.enqueued_at, "started_at": t_start,
            "completed_at": now,
            "queue_wait_s": max(t_start - item.enqueued_at, 0.0),
            "latency_s": now - item.enqueued_at,
            "trace_id": req.trace_id,
        }
        if degraded:
            result["degraded"] = True
        write_result_manifest(cfg.out_dir, result)
        if elog is not None:
            elog.emit("request_done", **result)

    # -- one cycle -----------------------------------------------------

    def process(self, claimed: List[WorkItem], elog=None) -> int:
        """Admit + solve + complete one batch of claimed requests.
        Returns how many completed."""
        from sagecal_tpu.serve.request import (
            result_manifest_path, write_result_manifest,
        )

        with self._hold_lock:
            self._held = {it.request_id for it in claimed}
            self._lost = set()
        stop = threading.Event()
        renewer = threading.Thread(
            target=self._renew_loop, args=(stop,), daemon=True,
            name=f"lease-renew-{self.wid}")
        renewer.start()
        done = 0
        try:
            self.admission.ingest_dir(self.cfg.out_dir)
            to_solve: List[Tuple[WorkItem, bool]] = []
            for it in claimed:
                decision, detail = self.admission.decide(it.tenant)
                if decision == "shed":
                    self.admission.shed_result(
                        it, self.cfg.out_dir, detail)
                    if elog is not None:
                        elog.emit("request_shed",
                                  request_id=it.request_id,
                                  tenant=it.tenant, worker=self.wid,
                                  **detail)
                    self.queue.complete(it.request_id, verdict="shed")
                    self._drop(it.request_id)
                    done += 1
                    continue
                if decision == "degrade":
                    it.request = self.admission.degrade_request(
                        it.request)
                    if elog is not None:
                        elog.emit("request_degraded",
                                  request_id=it.request_id,
                                  tenant=it.tenant, worker=self.wid,
                                  **detail)
                to_solve.append((it, decision == "degrade"))

            small = [(it, d) for it, d in to_solve
                     if not (it.large and self._can_shard())]
            large = [(it, d) for it, d in to_solve
                     if it.large and self._can_shard()]
            try:
                if small:
                    self._solve_small(small, elog)
                for it, d in large:
                    self._solve_large(it, d, elog)
            except Exception as e:  # noqa: BLE001 — fleet must survive
                self.log(f"worker {self.wid}: solve cycle failed: "
                         f"{e!r}")
                for it, _ in to_solve:
                    rid = it.request_id
                    if rid in self._lost:
                        continue
                    attempts = self.queue.record_failure(rid, repr(e))
                    if attempts >= MAX_ATTEMPTS:
                        now = self.clock()
                        write_result_manifest(self.cfg.out_dir, {
                            "request_id": rid, "tenant": it.tenant,
                            "verdict": "error",
                            "reasons": [f"attempts={attempts}",
                                        repr(e)[:500]],
                            "enqueued_at": it.enqueued_at,
                            "started_at": now, "completed_at": now,
                            "queue_wait_s": 0.0,
                            "latency_s": max(now - it.enqueued_at,
                                             0.0),
                        })
                        self.queue.complete(rid, verdict="error")
                        done += 1
                    else:
                        self.queue.release(rid)
                    self._drop(rid)
                return done

            for it, _ in to_solve:
                rid = it.request_id
                if rid in self._lost:
                    # stolen mid-solve: the stealer owns completion
                    continue
                manifest = result_manifest_path(self.cfg.out_dir, rid)
                if os.path.exists(manifest):
                    self.queue.complete(rid, manifest=manifest)
                    self.solved += 1
                    done += 1
                else:
                    self.queue.release(rid)
                self._drop(rid)
        finally:
            stop.set()
            renewer.join(timeout=5.0)
            with self._hold_lock:
                for rid in list(self._held):
                    self.queue.release(rid)
                self._held = set()
        return done

    # -- the loop ------------------------------------------------------

    def run(self, elog=None) -> Dict[str, Any]:
        from sagecal_tpu.obs.registry import get_registry

        # Coordinator shutdown sends SIGTERM the moment the queue
        # drains; the default action kills the process without running
        # finally blocks, which loses an in-flight device-profile flush
        # (obs/devprof.py fleet arming) and leaves the arm flag
        # un-retired.  Convert to SystemExit so cleanup runs.  Only
        # possible from the main thread — in-process test harnesses
        # driving run() from a worker thread keep default handling.
        if threading.current_thread() is threading.main_thread():
            try:
                signal.signal(signal.SIGTERM, _sigterm_to_exit)
            except (ValueError, OSError):
                pass

        cfg, reg = self.cfg, get_registry()
        os.makedirs(cfg.out_dir, exist_ok=True)
        t0 = self.clock()
        idle_since: Optional[float] = None
        while True:
            claimed = self.claim_cycle()
            if claimed:
                idle_since = None
                self.cycles += 1
                if elog is not None:
                    elog.emit("fleet_claimed", worker=self.wid,
                              n=len(claimed),
                              hint=claimed[0].bucket_hint,
                              ids=[it.request_id for it in claimed])
                # coordinator-armed device profiling (obs/devprof.py):
                # when the arm flag for THIS worker sits in the shared
                # out_dir, capture exactly one claimed cycle, then
                # retire the flag to .done with the trace path — one
                # worker of a live fleet gets profiled, no restart
                from sagecal_tpu.obs.devprof import (
                    check_fleet_arm,
                    complete_fleet_arm,
                    start_device_profile,
                    stop_device_profile,
                )

                arm = check_fleet_arm(cfg.out_dir, self.wid)
                if arm is not None:
                    started = start_device_profile(arm["profile_dir"])
                    try:
                        self.process(claimed, elog=elog)
                    finally:
                        trace_path = (stop_device_profile()
                                      if started else None)
                        # retire the flag even when the profiler was
                        # busy — a failing capture must not re-arm
                        # itself every cycle
                        complete_fleet_arm(arm, trace_path)
                        if elog is not None:
                            elog.emit("fleet_worker_profiled",
                                      worker=self.wid,
                                      trace_path=trace_path)
                else:
                    self.process(claimed, elog=elog)
                continue
            if (not getattr(cfg, "open_loop", False)
                    and self.queue.all_done(empty=False)):
                # under open-loop load the queue repeatedly LOOKS
                # drained between arrivals; only idle timeout or the
                # coordinator's SIGTERM ends an open-loop worker
                break
            now = self.clock()
            if idle_since is None:
                idle_since = now
            elif now - idle_since > cfg.max_idle_s:
                # nothing claimable for a while (live leases held by
                # peers): let the coordinator's view decide the end
                break
            time.sleep(cfg.poll_s)
        wall = self.clock() - t0
        summary = {
            "worker": self.wid, "cycles": self.cycles,
            "solved": self.solved, "wall_s": wall,
            "cache": self.cache.stats(),
            "admission": dict(self.admission.decisions),
        }
        if self.shadow is not None:
            summary["shadow"] = self.shadow.stats()
            self.shadow.close()
        if reg.enabled:
            from sagecal_tpu.obs.aggregate import (
                metrics_snapshot_path, write_metrics_snapshot,
            )

            try:
                write_metrics_snapshot(
                    metrics_snapshot_path(cfg.out_dir, self.wid),
                    registry=reg)
            except OSError:
                pass
        if elog is not None:
            elog.emit("fleet_worker_done", **summary)
        self.log(f"worker {self.wid}: {self.solved} solved in "
                 f"{self.cycles} cycles ({wall:.1f}s), "
                 f"cache {self.cache.stats()}, "
                 f"admission {self.admission.decisions}")
        return summary
