from sagecal_tpu.io import simulate, skymodel, solutions  # noqa: F401
