"""Visibility dataset I/O: tiled loading, channel averaging, writing back.

The reference reads CASA MeasurementSets through casacore
(``/root/reference/src/MS/data.cpp``, ``Data::IOData`` layout
``data.h:48-73``).  casacore is optional here: the native storage is an
HDF5 container ("vis.h5") with the same information content, and
:func:`ms_to_h5` / :func:`h5_to_ms` convert when ``python-casacore`` is
importable.  All solver-facing arrays come out as the
:class:`sagecal_tpu.core.types.VisData` pytree.

Reproduced data.cpp semantics:
- per-tile loading of ``tilesz`` timeslots (MSIter chunking);
- channel averaging into the solver's ``x`` with the "at least half the
  channels unflagged" rule (data.cpp:665-700): rows failing it get
  mask 0;
- uv-cut flagging (rows outside [min_uvcut, max_uvcut] wavelengths);
- u,v,w stored in metres, converted to seconds at load
  (fullbatch_mode.cpp:320-322);
- writing residuals back to a chosen output column.

HDF5 layout (all datasets chunked by timeslot for tile streaming):
  /u /v /w           (ntime, nbase) float64   [metres]
  /ant_p /ant_q      (nbase,) int32
  /vis               (ntime, nbase, nchan, 2, 2) complex64/128
  /flag              (ntime, nbase, nchan) bool
  /freqs             (nchan,) float64
  attrs: freq0, deltaf, deltat, ra0, dec0, nstations, time_jd0
  optional /beam group (the LBeam metadata of data.h:76-106 — station
  geometry + element offsets read from LOFAR_ANTENNA_FIELD):
    longitude latitude (N,) rad; elem_x elem_y elem_z elem_mask
    (N, Kmax) metres/bool; attrs b_ra0 b_dec0 (beam pointing) and
    bf_type (STAT_* beamformer type)
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Optional

import h5py
import jax.numpy as jnp
import numpy as np

from sagecal_tpu.core.types import C0, VisData


@dataclasses.dataclass
class DatasetMeta:
    nstations: int
    nbase: int
    ntime: int
    nchan: int
    freq0: float
    deltaf: float
    deltat: float
    ra0: float
    dec0: float
    freqs: np.ndarray
    time_jd0: float = 0.0


class VisDataset:
    """Tile-streaming reader/writer over the vis.h5 container."""

    def __init__(self, path: str, mode: str = "r"):
        self.path = path
        self._f = h5py.File(path, mode)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    @property
    def meta(self) -> DatasetMeta:
        f = self._f
        return DatasetMeta(
            nstations=int(f.attrs["nstations"]),
            nbase=f["u"].shape[1],
            ntime=f["u"].shape[0],
            nchan=f["freqs"].shape[0],
            freq0=float(f.attrs["freq0"]),
            deltaf=float(f.attrs["deltaf"]),
            deltat=float(f.attrs["deltat"]),
            ra0=float(f.attrs["ra0"]),
            dec0=float(f.attrs["dec0"]),
            freqs=np.asarray(f["freqs"]),
            time_jd0=float(f.attrs.get("time_jd0", 0.0)),
        )

    def load_tile(
        self,
        t0: int,
        tilesz: int,
        average_channels: bool = True,
        min_uvcut: float = 0.0,
        max_uvcut: float = 1e20,
        dtype=np.float64,
        column: str = "vis",
    ) -> VisData:
        """Load timeslots [t0, t0+tilesz) as a :class:`VisData`.

        ``average_channels=True`` mirrors loadData's solver input: one
        effective channel = mean over channels with >= nchan/2 unflagged
        (data.cpp:665-700); False returns the raw multichannel data
        (the residual-writing path's view).

        ``column`` selects the input dataset (the reference's -I
        DATA/CORRECTED_DATA choice, data.h:140-211): 'vis',
        'corrected', 'model', ... — any (ntime, nbase, nchan, 2, 2)
        complex dataset in the file.
        """
        f = self._f
        m = self.meta
        if column not in f:
            raise KeyError(
                f"{self.path}: no input column {column!r} "
                f"(available: {sorted(k for k in f.keys())})"
            )
        t1 = min(t0 + tilesz, m.ntime)
        nt = t1 - t0
        u = np.asarray(f["u"][t0:t1]).reshape(-1)  # (nt*nbase,)
        v = np.asarray(f["v"][t0:t1]).reshape(-1)
        w = np.asarray(f["w"][t0:t1]).reshape(-1)
        vis = np.asarray(f[column][t0:t1])  # (nt, nbase, nchan, 2, 2)
        flag = np.asarray(f["flag"][t0:t1])  # (nt, nbase, nchan)
        rows = nt * m.nbase
        vis = vis.reshape(rows, m.nchan, 2, 2)
        flag = flag.reshape(rows, m.nchan)
        ant_p = np.tile(np.asarray(f["ant_p"]), nt)
        ant_q = np.tile(np.asarray(f["ant_q"]), nt)
        time_idx = np.repeat(np.arange(nt, dtype=np.int32), m.nbase)

        # uv cut (data.cpp:650-656), in wavelengths at freq0
        uvd = np.sqrt(u * u + v * v) / C0 * m.freq0
        uvcut_bad = (uvd < min_uvcut) | (uvd > max_uvcut)

        cdtype = np.complex64 if dtype == np.float32 else np.complex128
        if average_channels and m.nchan > 1:
            good = ~flag  # (rows, nchan)
            ngood = good.sum(axis=1)
            ok = ngood > m.nchan // 2
            wsum = np.where(good[..., None, None], vis, 0.0).sum(axis=1)
            x = np.where(
                ok[:, None, None],
                wsum / np.maximum(ngood, 1)[:, None, None],
                0.0,
            )[:, None]  # (rows, 1, 2, 2)
            mask = (ok & ~uvcut_bad).astype(dtype)[:, None]
            freqs = np.asarray([m.freq0])
            fd = m.deltaf
        else:
            x = vis
            mask = ((~flag) & (~uvcut_bad[:, None])).astype(dtype)
            freqs = m.freqs
            fd = m.deltaf / max(m.nchan, 1)
        # -> canonical flat (F, 4, rows) / (F, rows) device layout
        nch = x.shape[1]
        x_flat = np.moveaxis(x.reshape(rows, nch, 4), 0, -1)
        mask_flat = np.moveaxis(mask, 0, -1)
        return VisData(
            u=jnp.asarray(u / C0, dtype),
            v=jnp.asarray(v / C0, dtype),
            w=jnp.asarray(w / C0, dtype),
            ant_p=jnp.asarray(ant_p),
            ant_q=jnp.asarray(ant_q),
            vis=jnp.asarray(x_flat, cdtype),
            mask=jnp.asarray(mask_flat, dtype),
            freqs=jnp.asarray(freqs, dtype),
            time_idx=jnp.asarray(time_idx),
            freq0=m.freq0,
            deltaf=fd,
            deltat=m.deltat,
            tilesz=nt,
            nbase=m.nbase,
            nstations=m.nstations,
        )

    def load_beam(self):
        """Beam metadata -> (StationGeometry, BeamPointing) or None when
        the dataset carries no /beam group (the readAuxData beam path,
        data.cpp LBeam; element offsets from LOFAR_ANTENNA_FIELD)."""
        if "beam" not in self._f:
            return None
        from sagecal_tpu.ops.beam import BeamPointing, StationGeometry

        g = self._f["beam"]
        m = self.meta
        geom = StationGeometry(
            longitude=jnp.asarray(g["longitude"]),
            latitude=jnp.asarray(g["latitude"]),
            x=jnp.asarray(g["elem_x"]),
            y=jnp.asarray(g["elem_y"]),
            z=jnp.asarray(g["elem_z"]),
            elem_mask=jnp.asarray(np.asarray(g["elem_mask"], np.float64)),
            bf_type=int(g.attrs.get("bf_type", 1)),
        )
        pointing = BeamPointing(
            ra0=m.ra0, dec0=m.dec0,
            b_ra0=float(g.attrs.get("b_ra0", m.ra0)),
            b_dec0=float(g.attrs.get("b_dec0", m.dec0)),
            f0=float(g.attrs.get("beam_f0", m.freq0)),
        )
        return geom, pointing

    def time_jd(self, t0: int, nt: int) -> np.ndarray:
        """Julian dates of timeslots [t0, t0+nt) (beam evaluation epochs,
        predict_withbeam.c time_utc)."""
        m = self.meta
        return m.time_jd0 + (t0 + np.arange(nt)) * m.deltat / 86400.0

    def write_tile(self, t0: int, vis: np.ndarray, column: str = "vis"):
        """Write (rows, nchan, 2, 2) visibilities back at timeslot t0
        (the writeData role; ``column`` creates e.g. 'corrected')."""
        m = self.meta
        nt = vis.shape[0] // m.nbase
        out = np.asarray(vis).reshape(nt, m.nbase, vis.shape[1], 2, 2)
        if column not in self._f:
            self._f.create_dataset(
                column,
                shape=self._f["vis"].shape,
                dtype=self._f["vis"].dtype,
                chunks=(1,) + self._f["vis"].shape[1:],
            )
        self._f[column][t0:t0 + nt] = out

    def tiles(self, tilesz: int):
        """Iterate tile start indices."""
        m = self.meta
        return range(0, m.ntime, tilesz)


# Live prefetchers, for the crash path (obs/flight.py SIGTERM /
# excepthook): a preempted run must be able to reap reader threads
# without unwinding to each app's finally block, so the checkpoint
# flush is never stuck behind thread teardown.  Entries register in
# __enter__ and leave in __exit__.
_ACTIVE_PREFETCHERS: list = []


def cancel_active_prefetchers() -> None:
    """Cancel + join every live TilePrefetcher worker (bounded wait;
    workers are daemon threads, so a reader wedged inside HDF5 cannot
    block process exit either way)."""
    for pf in list(_ACTIVE_PREFETCHERS):
        try:
            pf.cancel()
        except Exception:
            pass


class TilePrefetcher:
    """Background-thread tile prefetch: overlaps the HDF5 read +
    host-side packing of the NEXT tile with the solve of the current
    one — the role the reference's loadData/writeData threading plays
    around its solver pipeline (src/MS/fullbatch_mode.cpp tile loop).

    Opens an INDEPENDENT read-only handle so the main thread's solution
    /residual write-backs never share a File object with the reader
    (h5py serializes HDF5 calls process-wide, so concurrent use is safe;
    the overlap won is the numpy packing + any compute the solver does
    while the reader waits on the library lock).

    Usage::

        with TilePrefetcher(path, t0_list, [spec1, spec2]) as pf:
            for t0, (tile1, tile2) in pf:
                ...

    ``specs``: list of ``load_tile`` kwarg dicts — each yielded item
    carries one loaded VisData per spec, in order.
    """

    _SENTINEL = object()

    def __init__(self, path: str, t0_list, specs, tilesz: int, depth: int = 1):
        import queue
        import threading

        self._path = path
        self._t0s = list(t0_list)
        self._specs = [dict(s) for s in specs]
        self._tilesz = tilesz
        self._q = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._started = False

    def _worker(self):
        import jax

        from sagecal_tpu.utils.platform import cpu_device

        ds = None
        try:
            ds = VisDataset(self._path, "r")
            for t0 in self._t0s:
                if self._stop.is_set():
                    return
                try:
                    # host-pinned: prefetched tiles must NOT occupy
                    # device HBM (up to current+queued+in-flight tiles
                    # coexist); the consumer's first jitted use moves
                    # them over
                    with jax.default_device(cpu_device()):
                        loads = tuple(
                            ds.load_tile(t0, self._tilesz, **spec)
                            for spec in self._specs
                        )
                except Exception as e:  # propagate into the consumer
                    self._q.put((t0, e))
                    return
                self._q.put((t0, loads))
        except Exception as e:
            # a failed open (file locking, deleted file) must reach the
            # consumer instead of deadlocking its queue get
            self._q.put((None, e))
        finally:
            if ds is not None:
                try:
                    ds.close()
                except Exception:
                    pass
            self._q.put(self._SENTINEL)

    def __enter__(self):
        self._thread.start()
        self._started = True
        if self not in _ACTIVE_PREFETCHERS:
            _ACTIVE_PREFETCHERS.append(self)
        return self

    def cancel(self, join_timeout: float = 2.0) -> None:
        """Stop the worker and drain its queue with a BOUNDED wait —
        the crash-path variant of ``__exit__`` (obs/flight.py calls
        this via :func:`cancel_active_prefetchers`): a dying process
        must not wait behind a long HDF5 read, only give the worker a
        chance to notice the stop event and release its handle."""
        self._stop.set()
        if not self._started:
            return
        deadline = _time.monotonic() + max(join_timeout, 0.1)
        while self._thread.is_alive() and _time.monotonic() < deadline:
            try:
                item = self._q.get(timeout=0.1)
                if item is self._SENTINEL:
                    break
            except Exception:
                continue
        self._thread.join(timeout=max(deadline - _time.monotonic(), 0.1))

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self) -> None:
        """Full teardown: signal cancellation, drain so the worker can
        exit even on early break (without the event it would load every
        remaining tile before seeing the sentinel consumed), join, and
        unregister from the crash-path registry.  Idempotent — the
        serve path calls this per tenant queue as each drains, and a
        SIGTERM between drains may race a second call from
        :func:`cancel_active_prefetchers`."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        self._stop.set()
        try:
            _ACTIVE_PREFETCHERS.remove(self)
        except ValueError:
            pass
        if self._started:
            while self._thread.is_alive():
                try:
                    item = self._q.get(timeout=0.1)
                    if item is self._SENTINEL:
                        break
                except Exception:
                    continue
            self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                # a worker stuck in a long HDF5 read is outliving the
                # context while holding an open read handle; make that
                # visible instead of silently leaking the daemon thread
                import warnings
                warnings.warn(
                    f"TilePrefetcher worker for {self._path!r} did not "
                    "exit within 5 s of context exit; it still holds an "
                    "open read handle", RuntimeWarning, stacklevel=2)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._SENTINEL:
                return
            t0, payload = item
            if isinstance(payload, Exception):
                raise payload
            yield t0, payload


def create_dataset(
    path: str,
    u, v, w,  # (ntime, nbase) metres
    ant_p, ant_q,  # (nbase,)
    vis,  # (ntime, nbase, nchan, 2, 2)
    flag,  # (ntime, nbase, nchan) bool
    freqs,
    nstations: int,
    deltaf: float,
    deltat: float = 1.0,
    ra0: float = 0.0,
    dec0: float = 0.0,
    time_jd0: float = 0.0,
    beam: Optional[dict] = None,
) -> None:
    """``beam``: optional dict with keys longitude, latitude (N,),
    elem_x/elem_y/elem_z/elem_mask (N, Kmax) and optional b_ra0, b_dec0,
    bf_type, beam_f0 — stored as the /beam group (LBeam metadata)."""
    with h5py.File(path, "w") as f:
        for name, arr in (("u", u), ("v", v), ("w", w)):
            f.create_dataset(name, data=np.asarray(arr, np.float64),
                             chunks=(1, np.asarray(arr).shape[1]))
        f.create_dataset("ant_p", data=np.asarray(ant_p, np.int32))
        f.create_dataset("ant_q", data=np.asarray(ant_q, np.int32))
        va = np.asarray(vis)
        f.create_dataset("vis", data=va, chunks=(1,) + va.shape[1:])
        fa = np.asarray(flag, bool)
        f.create_dataset("flag", data=fa, chunks=(1,) + fa.shape[1:])
        fr = np.asarray(freqs, np.float64)
        f.create_dataset("freqs", data=fr)
        f.attrs["nstations"] = nstations
        f.attrs["freq0"] = float(np.mean(fr))
        f.attrs["deltaf"] = deltaf
        f.attrs["deltat"] = deltat
        f.attrs["ra0"] = ra0
        f.attrs["dec0"] = dec0
        f.attrs["time_jd0"] = time_jd0
        if beam is not None:
            g = f.create_group("beam")
            for k in ("longitude", "latitude", "elem_x", "elem_y",
                      "elem_z", "elem_mask"):
                g.create_dataset(k, data=np.asarray(beam[k]))
            for k in ("b_ra0", "b_dec0", "bf_type", "beam_f0"):
                if k in beam:
                    g.attrs[k] = beam[k]


def simulate_dataset(
    path: str,
    nstations: int = 8,
    ntime: int = 8,
    nchan: int = 4,
    freq0: float = 150e6,
    chan_bw: float = 180e3,
    clusters=None,
    jones=None,
    noise_sigma: float = 0.0,
    seed: int = 0,
    dec0: float = 0.9,
    with_beam: bool = False,
    nelem: int = 24,
) -> None:
    """Build a synthetic vis.h5 (the hermetic stand-in for the
    reference's packaged test MS, test/Calibration/README.md).

    ``with_beam=True`` attaches a synthetic /beam group: per-station
    random dipole layouts in a 30 m disk (the role of the
    LOFAR_ANTENNA_FIELD element offsets)."""
    from sagecal_tpu.core.baselines import tile_baselines
    from sagecal_tpu.io.simulate import station_layout, uvw_track
    from sagecal_tpu.ops.rime import predict_model

    nbase = nstations * (nstations - 1) // 2
    ant_p1, ant_q1, _ = tile_baselines(nstations, 1)
    xyz = station_layout(nstations, seed=seed)
    ap = np.tile(ant_p1, ntime)
    aq = np.tile(ant_q1, ntime)
    tidx = np.repeat(np.arange(ntime), nbase)
    us, vs, ws = uvw_track(xyz, ap, aq, tidx, dec0=dec0)  # seconds
    freqs = freq0 + chan_bw * (np.arange(nchan) - (nchan - 1) / 2.0)
    rng = np.random.default_rng(seed)
    if clusters is not None:
        from sagecal_tpu.core.types import mat_of_flat

        visr = predict_model(
            jnp.asarray(us), jnp.asarray(vs), jnp.asarray(ws),
            jnp.asarray(freqs, np.float64), clusters, 0.0,
            jones=jones,
            ant_p=jnp.asarray(ap), ant_q=jnp.asarray(aq),
        )
        visr = np.asarray(mat_of_flat(visr))  # (rows, nchan, 2, 2) on disk
    else:
        visr = np.zeros((ntime * nbase, nchan, 2, 2), np.complex128)
    if noise_sigma > 0:
        visr = visr + noise_sigma * (
            rng.standard_normal(visr.shape) + 1j * rng.standard_normal(visr.shape)
        )
    beam = None
    if with_beam:
        brng = np.random.default_rng(seed + 1)
        r = 30.0 * np.sqrt(brng.uniform(0.2, 1.0, (nstations, nelem)))
        th = brng.uniform(0, 2 * np.pi, (nstations, nelem))
        beam = dict(
            longitude=np.full(nstations, 0.12),  # ~LOFAR core lon (rad)
            latitude=np.full(nstations, 0.92),
            elem_x=r * np.cos(th),
            elem_y=r * np.sin(th),
            elem_z=np.zeros((nstations, nelem)),
            elem_mask=np.ones((nstations, nelem), bool),
            b_ra0=0.0, b_dec0=dec0, bf_type=1, beam_f0=freq0,
        )
    create_dataset(
        path,
        u=(us * C0).reshape(ntime, nbase),
        v=(vs * C0).reshape(ntime, nbase),
        w=(ws * C0).reshape(ntime, nbase),
        ant_p=ant_p1, ant_q=ant_q1,
        vis=visr.reshape(ntime, nbase, nchan, 2, 2),
        flag=np.zeros((ntime, nbase, nchan), bool),
        freqs=freqs,
        nstations=nstations,
        deltaf=chan_bw * nchan,
        dec0=dec0,
        time_jd0=2460000.5,
        beam=beam,
    )


# --------------------------------------------------------------------------
# optional casacore bridge (gated: python-casacore is not in this image)
# --------------------------------------------------------------------------

def have_casacore() -> bool:
    try:
        import casacore.tables  # noqa: F401

        return True
    except ImportError:
        return False


def _ms_spw_rows(t, ms_path: str, spw: int):
    """Boolean row mask selecting spectral window ``spw`` of the main
    table, via DATA_DESC_ID -> DATA_DESCRIPTION/SPECTRAL_WINDOW_ID (the
    casacore indirection; the reference assumes one SPW per MS and reads
    CHAN_FREQ row 0, data.cpp:185-188 — multi-SPW MSs there are split
    into per-band files for sagecal-mpi).  An MS without DATA_DESC_ID
    is treated as single-SPW."""
    from casacore.tables import table

    if "DATA_DESC_ID" not in t.colnames():
        n = t.nrows()
        return np.ones((n,), bool)
    ddid = np.asarray(t.getcol("DATA_DESC_ID"))
    try:
        dd = table(f"{ms_path}/DATA_DESCRIPTION")
    except Exception:
        # no DATA_DESCRIPTION subtable: DATA_DESC_ID indexes SPWs
        # directly.  (Read errors INSIDE the subtable propagate below —
        # silently reinterpreting ids there would select wrong rows.)
        row_spw = ddid
    else:
        spw_of_dd = np.asarray(dd.getcol("SPECTRAL_WINDOW_ID"))
        row_spw = spw_of_dd[ddid]
    return row_spw == spw


def _corr_to_jones(data, ncorr):
    """(rows, nchan, ncorr) -> (rows, nchan, 4) in [XX, XY, YX, YY]
    order: ncorr==2 is dual-pol XX/YY with zero cross-hands (the
    reference's n_corr==2 path fills only slots 0-1 and 6-7,
    data.cpp:684-695); ncorr==1 is XX only."""
    if ncorr == 4:
        return data
    out = np.zeros(data.shape[:-1] + (4,), data.dtype)
    out[..., 0] = data[..., 0]
    if ncorr == 2:
        out[..., 3] = data[..., 1]
    elif ncorr != 1:
        raise ValueError(f"unsupported correlation count {ncorr}")
    return out


def ms_to_h5(ms_path: str, h5_path: str, data_column: str = "DATA",
             spw: int = 0) -> None:
    """Convert a CASA MeasurementSet to the vis.h5 container (requires
    python-casacore; mirrors Data::readAuxData + loadData,
    src/MS/data.cpp).

    ``spw``: spectral window to extract (multi-SPW MSs carry several
    windows behind DATA_DESC_ID; the reference expects pre-split
    per-band MSs and always reads window 0).  Correlation counts 4
    (full), 2 (XX/YY) and 1 (XX) are accepted as in the reference's
    loadData; WEIGHT_SPECTRUM (or WEIGHT) is carried into an optional
    ``weight`` column, (ntime, nbase, nchan), averaged over
    correlations — the solvers' robust weighting is internal (as in the
    reference, which reads no MS weights), but the column survives the
    round trip for downstream use."""
    if not have_casacore():
        raise RuntimeError(
            "python-casacore is not installed; convert the MS on a host "
            "that has it, then ship the .h5"
        )
    from casacore.tables import table

    t = table(ms_path)
    ant = table(f"{ms_path}/ANTENNA")
    spwt = table(f"{ms_path}/SPECTRAL_WINDOW")
    fld = table(f"{ms_path}/FIELD")
    nstations = ant.nrows()
    if not (0 <= spw < spwt.nrows()):
        raise ValueError(
            f"{ms_path}: spectral window {spw} out of range "
            f"(SPECTRAL_WINDOW has {spwt.nrows()} rows)"
        )
    # per-window getcell, NOT getcol: with heterogeneous windows
    # (different NUM_CHAN) casacore cannot return CHAN_FREQ as one
    # rectangular array
    freqs = np.asarray(spwt.getcell("CHAN_FREQ", spw))
    ra0, dec0 = np.asarray(fld.getcol("PHASE_DIR"))[0, 0]
    if data_column not in t.colnames():
        raise KeyError(
            f"{ms_path} has no column {data_column!r} "
            f"(available: {sorted(t.colnames())})"
        )
    # select rows FIRST (scalar columns only), then read array columns
    # through the selection: a full-table getcol on DATA/FLAG raises a
    # conformance error when other windows have different channel counts
    a1 = t.getcol("ANTENNA1")
    a2 = t.getcol("ANTENNA2")
    sel = (a1 != a2) & _ms_spw_rows(t, ms_path, spw)
    tsel = t.selectrows(np.flatnonzero(sel))
    times = tsel.getcol("TIME")
    utimes = np.unique(times)
    ntime = utimes.shape[0]
    uvw = tsel.getcol("UVW")
    data = np.asarray(tsel.getcol(data_column))
    ncorr = data.shape[-1]
    data = _corr_to_jones(data, ncorr)
    if "FLAG" in t.colnames():
        flag = np.asarray(tsel.getcol("FLAG")).any(-1)
    else:
        flag = np.zeros(data.shape[:-1][:2], bool)
    a1, a2 = a1[sel], a2[sel]
    nbase = nstations * (nstations - 1) // 2
    nchan = freqs.shape[0]
    if data.shape[1] != nchan:
        raise ValueError(
            f"{ms_path}: {data_column} has {data.shape[1]} channels but "
            f"SPECTRAL_WINDOW row {spw} has {nchan}"
        )
    # order rows as (time, baseline)
    order = np.lexsort((a2, a1, times))
    if order.shape[0] != ntime * nbase:
        raise ValueError(
            f"{ms_path}: {order.shape[0]} cross rows in SPW {spw} != "
            f"{ntime} times x {nbase} baselines — irregular MS layouts "
            "(missing baselines) are not supported; fill with flagged "
            "rows first"
        )
    shape = (ntime, nbase)
    vis = data[order].reshape(ntime, nbase, nchan, 2, 2)
    # bandwidth from CHAN_WIDTH when present (readAuxDataFirstPart,
    # data.cpp:214-216), else the channel span; abs() because
    # lower-sideband windows store negative widths
    if "CHAN_WIDTH" in spwt.colnames():
        deltaf = float(
            nchan * abs(np.asarray(spwt.getcell("CHAN_WIDTH", spw))[0])
        )
    else:
        deltaf = float(abs(freqs[-1] - freqs[0])) if nchan > 1 else 180e3
    create_dataset(
        h5_path,
        u=uvw[order, 0].reshape(shape),
        v=uvw[order, 1].reshape(shape),
        w=uvw[order, 2].reshape(shape),
        ant_p=a1[order][:nbase], ant_q=a2[order][:nbase],
        vis=vis,
        flag=flag[order].reshape(ntime, nbase, nchan),
        freqs=freqs,
        nstations=nstations,
        deltaf=deltaf,
        deltat=float(np.median(np.diff(utimes))) if ntime > 1 else 1.0,
        ra0=float(ra0), dec0=float(dec0),
    )
    # per-visibility weights: WEIGHT_SPECTRUM (rows, nchan, ncorr) or
    # WEIGHT (rows, ncorr) broadcast over channels — read through the
    # row selection for the same conformance reason as DATA
    wcol = None
    if "WEIGHT_SPECTRUM" in t.colnames():
        wcol = np.asarray(tsel.getcol("WEIGHT_SPECTRUM")).mean(-1)
    elif "WEIGHT" in t.colnames():
        w2 = np.asarray(tsel.getcol("WEIGHT")).mean(-1)
        wcol = np.broadcast_to(w2[:, None], (w2.shape[0], nchan))
    if wcol is not None:
        with h5py.File(h5_path, "r+") as f:
            f.create_dataset(
                "weight", data=wcol[order].reshape(ntime, nbase, nchan)
            )


def h5_to_ms(
    h5_path: str,
    ms_path: str,
    column: str = "corrected",
    ms_column: str = "CORRECTED_DATA",
    spw: int = 0,
) -> None:
    """Write a vis.h5 data column back into a CASA MeasurementSet
    (requires python-casacore; the ``Data::writeData`` direction,
    src/MS/data.h:124 / data.cpp).

    ``column``: h5 dataset to export ('vis', 'corrected', 'model',
    'influence'); ``ms_column``: target MS column, created from the
    DATA column's description if absent.  Rows are matched by the same
    (time, baseline) lexsort ordering :func:`ms_to_h5` uses;
    autocorrelation rows in the MS are left untouched.
    """
    if not have_casacore():
        raise RuntimeError(
            "python-casacore is not installed; write back on a host "
            "that has it"
        )
    from casacore.tables import table, makecoldesc

    with h5py.File(h5_path, "r") as f:
        if column not in f:
            raise KeyError(f"{h5_path} has no column {column!r}")
        vals = np.asarray(f[column])  # (ntime, nbase, nchan, 2, 2)
    ntime, nbase, nchan = vals.shape[:3]
    flat = vals.reshape(ntime * nbase, nchan, 4)

    t = table(ms_path, readonly=False)
    a1 = t.getcol("ANTENNA1")
    a2 = t.getcol("ANTENNA2")
    cross = (a1 != a2) & _ms_spw_rows(t, ms_path, spw)
    times = t.getcol("TIME")[cross]
    order = np.lexsort((a2[cross], a1[cross], times))
    if order.shape[0] != ntime * nbase:
        raise ValueError(
            f"{ms_path}: {order.shape[0]} cross rows in SPW {spw} != "
            f"{ntime}x{nbase} in {h5_path}"
        )
    created = ms_column not in t.colnames()
    if created:
        t.addcols(makecoldesc(ms_column, t.getcoldesc("DATA")))
        # seed the untouched rows (autocorrelations, other windows)
        # from DATA so the new column is fully defined — per
        # DATA_DESC group, since one full-table getcol would fail on
        # heterogeneous windows
        other = ~cross
        groups = (np.asarray(t.getcol("DATA_DESC_ID"))
                  if "DATA_DESC_ID" in t.colnames()
                  else np.zeros(other.shape, np.int32))
        for g in np.unique(groups[other]):
            tg = t.selectrows(np.flatnonzero(other & (groups == g)))
            tg.putcol(ms_column, tg.getcol("DATA"))
    # read/write ONLY the selected rows: a full-table getcol/putcol
    # raises a conformance error when other windows differ in shape
    tsel = t.selectrows(np.flatnonzero(cross))
    out = np.asarray(tsel.getcol("DATA" if created else ms_column),
                     np.complex128)
    ncorr = out.shape[-1]
    # component axis is [XX, XY, YX, YY]; map by correlation count
    if ncorr == 4:
        sel = [0, 1, 2, 3]
    elif ncorr == 2:
        sel = [0, 3]  # dual-pol XX, YY
    elif ncorr == 1:
        sel = [0]
    else:
        raise ValueError(f"{ms_path}: unsupported correlation count {ncorr}")
    out[order] = flat.reshape(ntime * nbase, nchan, 4)[:, :, sel]
    tsel.putcol(ms_column, out)
    t.close()
