"""Minimal FITS image I/O (no cfitsio/astropy dependency).

The reference's offline tools read and write FITS through cfitsio +
wcslib (``/root/reference/src/restore/restore.c``,
``src/buildsky/buildsky.c``).  Neither library is in this image, and
the tools only need simple 2-D (or trailing-degenerate-axis) float
images with a linear/SIN celestial WCS — which the FITS standard
encodes in plain 2880-byte ASCII header blocks.  This is a standards
implementation (FITS 4.0, NASA/IAUFWG), not a port.

Supported: BITPIX -32/-64/8/16/32 primary HDUs, NAXIS up to 4 with
degenerate trailing axes, BSCALE/BZERO, CRPIX/CRVAL/CDELT/CTYPE for the
first two axes.  Written files use BITPIX=-32 with a SIN projection —
the radio-interferometric default the reference's tools assume.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import numpy as np

_BLOCK = 2880


@dataclasses.dataclass
class FitsWCS:
    """Linear WCS of the first two image axes (degrees, FITS 1-based
    reference pixel)."""

    crval1: float = 0.0
    crval2: float = 0.0
    crpix1: float = 1.0
    crpix2: float = 1.0
    cdelt1: float = -1.0 / 3600.0
    cdelt2: float = 1.0 / 3600.0
    ctype1: str = "RA---SIN"
    ctype2: str = "DEC--SIN"

    def pixel_to_lm(self, px, py):
        """Pixel (0-based) -> direction cosines (l, m) about the
        reference direction (SIN projection: l,m ARE the projected
        coordinates, in radians)."""
        d2r = math.pi / 180.0
        l = (np.asarray(px) + 1.0 - self.crpix1) * self.cdelt1 * d2r
        m = (np.asarray(py) + 1.0 - self.crpix2) * self.cdelt2 * d2r
        return l, m

    def lm_to_pixel(self, l, m):
        d2r = math.pi / 180.0
        px = np.asarray(l) / (self.cdelt1 * d2r) + self.crpix1 - 1.0
        py = np.asarray(m) / (self.cdelt2 * d2r) + self.crpix2 - 1.0
        return px, py

    def pixel_to_radec(self, px, py):
        """Pixel -> (ra, dec) radians via the inverse SIN projection
        about (crval1, crval2)."""
        l, m = self.pixel_to_lm(px, py)
        ra0 = self.crval1 * math.pi / 180.0
        dec0 = self.crval2 * math.pi / 180.0
        n = np.sqrt(np.maximum(1.0 - l * l - m * m, 0.0))
        dec = np.arcsin(m * np.cos(dec0) + n * np.sin(dec0))
        ra = ra0 + np.arctan2(l, n * np.cos(dec0) - m * np.sin(dec0))
        return ra, dec


def _card(key: str, value, comment: str = "") -> bytes:
    if isinstance(value, bool):
        v = "T" if value else "F"
        s = f"{key:<8}= {v:>20}"
    elif isinstance(value, (int, np.integer)):
        s = f"{key:<8}= {value:>20d}"
    elif isinstance(value, float):
        s = f"{key:<8}= {value:>20.12E}"
    else:
        s = f"{key:<8}= '{value:<8}'"
    if comment:
        s += f" / {comment}"
    return s[:80].ljust(80).encode("ascii")


def write_fits_image(
    path: str,
    image: np.ndarray,
    wcs: Optional[FitsWCS] = None,
    extra: Optional[Dict[str, float]] = None,
) -> None:
    """Write a 2-D image (ny, nx) as a BITPIX=-32 primary HDU."""
    wcs = wcs or FitsWCS()
    ny, nx = image.shape
    cards = [
        _card("SIMPLE", True, "minimal FITS (sagecal-tpu)"),
        _card("BITPIX", -32),
        _card("NAXIS", 2),
        _card("NAXIS1", nx),
        _card("NAXIS2", ny),
        _card("CTYPE1", wcs.ctype1),
        _card("CRVAL1", float(wcs.crval1)),
        _card("CRPIX1", float(wcs.crpix1)),
        _card("CDELT1", float(wcs.cdelt1)),
        _card("CTYPE2", wcs.ctype2),
        _card("CRVAL2", float(wcs.crval2)),
        _card("CRPIX2", float(wcs.crpix2)),
        _card("CDELT2", float(wcs.cdelt2)),
        _card("BUNIT", "JY/PIXEL"),
    ]
    for k, v in (extra or {}).items():
        cards.append(_card(k[:8].upper(), float(v)))
    cards.append(b"END".ljust(80))
    hdr = b"".join(cards)
    hdr += b" " * (-len(hdr) % _BLOCK)
    data = np.asarray(image, ">f4").tobytes()
    data += b"\x00" * (-len(data) % _BLOCK)
    with open(path, "wb") as fp:
        fp.write(hdr)
        fp.write(data)


def read_fits_image(path: str) -> Tuple[np.ndarray, FitsWCS, Dict[str, float]]:
    """Read the primary HDU image; returns (image (ny, nx), wcs, header).

    Degenerate trailing axes (frequency/Stokes of radio images) are
    squeezed, mirroring the reference tools' use of the first plane.
    """
    with open(path, "rb") as fp:
        raw = fp.read()
    # parse header cards until END
    hdr: Dict[str, object] = {}
    off = 0
    done = False
    while not done:
        block = raw[off:off + _BLOCK]
        if len(block) < _BLOCK:
            raise ValueError(f"{path}: truncated FITS header")
        for i in range(0, _BLOCK, 80):
            card = block[i:i + 80].decode("ascii", "replace")
            key = card[:8].strip()
            if key == "END":
                done = True
                break
            if card[8:10] != "= ":
                continue
            val = card[10:].split("/")[0].strip()
            if val.startswith("'"):
                hdr[key] = val.strip("'").strip()
            elif val in ("T", "F"):
                hdr[key] = val == "T"
            else:
                try:
                    hdr[key] = int(val)
                except ValueError:
                    try:
                        hdr[key] = float(val)
                    except ValueError:
                        hdr[key] = val
        off += _BLOCK
    bitpix = int(hdr["BITPIX"])
    naxis = int(hdr["NAXIS"])
    shape = [int(hdr[f"NAXIS{i}"]) for i in range(naxis, 0, -1)]
    count = int(np.prod(shape)) if shape else 0
    dt = {-64: ">f8", -32: ">f4", 8: ">u1", 16: ">i2", 32: ">i4"}[bitpix]
    nbytes = count * np.dtype(dt).itemsize
    data = np.frombuffer(raw[off:off + nbytes], dt).reshape(shape)
    data = np.asarray(data, np.float64)
    data = data * float(hdr.get("BSCALE", 1.0)) + float(hdr.get("BZERO", 0.0))
    while data.ndim > 2:
        data = data[0]
    wcs = FitsWCS(
        crval1=float(hdr.get("CRVAL1", 0.0)),
        crval2=float(hdr.get("CRVAL2", 0.0)),
        crpix1=float(hdr.get("CRPIX1", 1.0)),
        crpix2=float(hdr.get("CRPIX2", 1.0)),
        cdelt1=float(hdr.get("CDELT1", -1.0 / 3600.0)),
        cdelt2=float(hdr.get("CDELT2", 1.0 / 3600.0)),
        ctype1=str(hdr.get("CTYPE1", "RA---SIN")),
        ctype2=str(hdr.get("CTYPE2", "DEC--SIN")),
    )
    numeric = {k: float(v) for k, v in hdr.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
    return data, wcs, numeric
