"""Synthetic interferometer data generation.

The reference's only test fixture is a packaged LOFAR MeasurementSet
(``/root/reference/test/Calibration/README.md``); casacore is not available
in this environment, so the framework's hermetic test path generates
physically consistent synthetic observations: an earth-rotation-synthesis
uvw track for a random station layout, model visibilities from the RIME
predict, corruption by known Jones gains, and Gaussian or Student's-t
noise.  This doubles as the ``-a 1`` simulation mode's compute core
(fullbatch_mode.cpp:536-591).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from sagecal_tpu.core.baselines import tile_baselines
from sagecal_tpu.core.types import C0, VisData
from sagecal_tpu.ops.rime import SourceBatch, predict_model


def station_layout(nstations: int, extent_m: float = 3000.0, seed: int = 0) -> np.ndarray:
    """Random station positions (N, 3) in a local equatorial frame, metres."""
    rng = np.random.default_rng(seed)
    r = extent_m * np.sqrt(rng.uniform(0.1, 1.0, nstations))
    th = rng.uniform(0, 2 * np.pi, nstations)
    z = rng.uniform(-20.0, 20.0, nstations)
    return np.stack([r * np.cos(th), r * np.sin(th), z], axis=1)


def uvw_track(
    xyz: np.ndarray,
    ant_p: np.ndarray,
    ant_q: np.ndarray,
    time_idx: np.ndarray,
    dec0: float = 0.9,
    ha_start: float = -0.1,
    dt_s: float = 10.0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Earth-rotation uvw (seconds) for each flattened row.

    Standard synthesis relation: baseline L = xyz[p]-xyz[q] in equatorial
    coordinates, rotated by hour angle h and declination dec0.
    """
    omega = 7.2921150e-5  # rad/s
    h = ha_start + omega * dt_s * time_idx.astype(np.float64)
    L = xyz[ant_p] - xyz[ant_q]  # (rows, 3)
    lx, ly, lz = L[:, 0], L[:, 1], L[:, 2]
    sh, ch = np.sin(h), np.cos(h)
    sd, cd = np.sin(dec0), np.cos(dec0)
    u = sh * lx + ch * ly
    v = -sd * ch * lx + sd * sh * ly + cd * lz
    w = cd * ch * lx - cd * sh * ly + sd * lz
    return u / C0, v / C0, w / C0


def make_visdata(
    nstations: int = 8,
    tilesz: int = 2,
    nchan: int = 1,
    freq0: float = 150e6,
    chan_bw: float = 180e3,
    dec0: float = 0.9,
    seed: int = 0,
    dtype=np.float32,
    extent_m: float = 3000.0,
) -> VisData:
    """An empty (zero-visibility) tile with a consistent uvw track.

    ``extent_m`` is the station-layout radius — compact values model
    the dense-core / all-sky regime the wide-field workload targets."""
    ant_p, ant_q, time_idx = tile_baselines(nstations, tilesz)
    xyz = station_layout(nstations, extent_m=extent_m, seed=seed)
    u, v, w = uvw_track(xyz, ant_p, ant_q, time_idx, dec0=dec0)
    rows = ant_p.shape[0]
    freqs = freq0 + chan_bw * (np.arange(nchan) - (nchan - 1) / 2.0)
    cdtype = np.complex64 if dtype == np.float32 else np.complex128
    return VisData(
        u=jnp.asarray(u, dtype),
        v=jnp.asarray(v, dtype),
        w=jnp.asarray(w, dtype),
        ant_p=jnp.asarray(ant_p),
        ant_q=jnp.asarray(ant_q),
        vis=jnp.zeros((nchan, 4, rows), cdtype),
        mask=jnp.ones((nchan, rows), dtype),
        freqs=jnp.asarray(freqs, dtype),
        time_idx=jnp.asarray(time_idx),
        freq0=float(freq0),
        deltaf=float(chan_bw * nchan),
        deltat=10.0,
        tilesz=tilesz,
        nbase=nstations * (nstations - 1) // 2,
        nstations=nstations,
    )


def random_jones(
    nclus: int, nstations: int, seed: int = 0, amp: float = 0.3, dtype=np.complex64
) -> jnp.ndarray:
    """(nclus, N, 2, 2) gains: identity + complex perturbation of scale amp."""
    rng = np.random.default_rng(seed)
    pert = amp * (
        rng.standard_normal((nclus, nstations, 2, 2))
        + 1j * rng.standard_normal((nclus, nstations, 2, 2))
    )
    return jnp.asarray(np.eye(2)[None, None] + pert, dtype)


def corrupt_and_observe(
    data: VisData,
    clusters: list[SourceBatch],
    jones=None,
    noise_sigma: float = 0.0,
    seed: int = 1,
    fdelta: float = 0.0,
    shapelet_tables=None,
) -> VisData:
    """Fill ``data.vis`` with sum_k J_p^k C_pq^k J_q^kH + noise.

    ``shapelet_tables``: optional per-cluster ShapeletTable list for
    clusters carrying ST_SHAPELET members (simulated diffuse skies,
    sagecal_tpu/data)."""
    rng = np.random.default_rng(seed)
    total = predict_model(
        data.u, data.v, data.w, data.freqs, clusters, fdelta,
        jones=jones, ant_p=data.ant_p, ant_q=data.ant_q,
        shapelet_tables=shapelet_tables,
    )
    if noise_sigma > 0.0:
        nre = rng.standard_normal(total.shape)
        nim = rng.standard_normal(total.shape)
        total = total + noise_sigma * jnp.asarray(nre + 1j * nim, total.dtype)
    return data.replace(vis=total)
