"""Sky-model (LSM) and cluster text-file parsing, reference-compatible.

Formats are the reference's documented contracts
(``/root/reference/README.md`` sections 2b/2c; parser behavior verified
against ``/root/reference/src/lib/Radio/readsky.c:285-500``):

- sky line: ``name h m s d m s I Q U V si [si1 si2] RM eX eY eP f0``
  (RA in hours->rad via pi/12, dec in degrees->rad, negative-zero aware);
- cluster line: ``cluster_id chunk_size source1 source2 ...``; negative
  cluster_id means "do not subtract from data";
- source type selected purely by the first character of the source name
  (G/g Gaussian, D/d disk, R/r ring, S/s shapelet, anything else point) —
  the extent columns play NO role in the type decision (readsky.c:425-509);
- shapelet mode files ``<name>.fits.modes`` (readsky.c:143-163).

Parsing is plain numpy on the host — it happens once per run; the output
:class:`~sagecal_tpu.ops.rime.SourceBatch` pytrees are what cross into jit.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Optional

import numpy as np

from sagecal_tpu.ops.rime import (
    ST_DISK,
    ST_GAUSSIAN,
    ST_POINT,
    ST_RING,
    ST_SHAPELET,
    SourceBatch,
)

_FWHM_TO_SIGMA = 1.0 / (2.0 * math.sqrt(2.0 * math.log(2.0)))


@dataclasses.dataclass
class SkySource:
    name: str
    ra: float
    dec: float
    sI: float
    sQ: float
    sU: float
    sV: float
    spec_idx: float
    spec_idx1: float
    spec_idx2: float
    eX: float
    eY: float
    eP: float
    f0: float


@dataclasses.dataclass
class ClusterDef:
    cluster_id: int
    nchunk: int
    source_names: list
    subtract: bool  # False when cluster_id < 0 (README section 2b note)


def _hms_to_rad(h: float, m: float, s: float) -> float:
    neg = h < 0.0 or (h == 0.0 and math.copysign(1.0, h) < 0)
    mag = (abs(h) + m / 60.0 + s / 3600.0) * math.pi / 12.0
    return -mag if neg else mag


def _dms_to_rad(d: float, m: float, s: float) -> float:
    neg = d < 0.0 or (d == 0.0 and math.copysign(1.0, d) < 0)
    mag = (abs(d) + m / 60.0 + s / 3600.0) * math.pi / 180.0
    return -mag if neg else mag


def parse_skymodel(path: str, three_term_spectra: Optional[bool] = None) -> dict:
    """Parse an LSM sky-model file -> {name: SkySource}.

    ``three_term_spectra`` mirrors the reference's ``-F 1`` flag; when None
    the format is auto-detected from the token count (17 vs 19).
    """
    sources: dict = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or line.startswith("//"):
                continue
            tok = line.split()
            if len(tok) < 17:
                continue
            fmt3 = (
                three_term_spectra
                if three_term_spectra is not None
                else len(tok) >= 19
            )
            name = tok[0]
            vals = [float(x) for x in tok[1 : 19 if fmt3 else 17]]
            (rahr, ramin, rasec, decd, decmin, decsec, sI, sQ, sU, sV) = vals[:10]
            if fmt3:
                si, si1, si2, _rm, eX, eY, eP, f0 = vals[10:18]
            else:
                si, _rm, eX, eY, eP, f0 = vals[10:16]
                si1 = si2 = 0.0
            if f0 <= 0.0:
                f0 = 1.0
            sources[name] = SkySource(
                name=name,
                ra=_hms_to_rad(rahr, ramin, rasec),
                dec=_dms_to_rad(decd, decmin, decsec),
                sI=sI,
                sQ=sQ,
                sU=sU,
                sV=sV,
                spec_idx=si,
                spec_idx1=si1,
                spec_idx2=si2,
                eX=eX,
                eY=eY,
                eP=eP,
                f0=f0,
            )
    return sources


def parse_clusters(path: str) -> list:
    """Parse a cluster file -> [ClusterDef] (README section 2b)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or line.startswith("//"):
                continue
            tok = line.split()
            if len(tok) < 3:
                continue
            cid = int(tok[0])
            out.append(
                ClusterDef(
                    # keep the RAW signed id: -G / -z / -E files refer to
                    # clusters by the signed id as written (readsky.c);
                    # the no-subtract semantics live in ``subtract``
                    cluster_id=cid,
                    nchunk=max(1, int(tok[1])),
                    source_names=tok[2:],
                    subtract=cid >= 0,
                )
            )
    return out


def _source_type(s: SkySource) -> int:
    """readsky.c:425-509: type selected purely by the name's first character
    (G/g=gaussian, D/d=disk, R/r=ring, S/s=shapelet, anything else=point);
    the extent columns play no role in the type decision."""
    c = s.name[0].upper()
    if c == "G":
        return ST_GAUSSIAN
    if c == "D":
        return ST_DISK
    if c == "R":
        return ST_RING
    if c == "S":
        return ST_SHAPELET
    return ST_POINT


def build_source_batch(
    srcs: list, ra0: float, dec0: float, dtype=np.float32
) -> SourceBatch:
    """Numpy SourceBatch for a list of SkySource at phase center (ra0, dec0).

    lmn per readsky.c:343-346 (nn stored as n-1, :628); projection angles
    per readsky.c:398-422; Gaussian fwhm->sigma per :415-416.
    """
    import jax.numpy as jnp

    S = len(srcs)
    g = lambda: np.zeros(S, np.float64)
    ll, mm, nn = g(), g(), g()
    sI0, sQ0, sU0, sV0 = g(), g(), g(), g()
    f0, si, si1, si2 = np.ones(S), g(), g(), g()
    stype = np.zeros(S, np.int32)
    ex_a, ex_b, ex_cp, ex_sp = g(), g(), np.ones(S), g()
    cxi, sxi, cphi, sphi = np.ones(S), g(), np.ones(S), g()
    shapelet_idx = np.full(S, -1, np.int32)
    n_shap = 0
    for i, s in enumerate(srcs):
        dra = s.ra - ra0
        ll[i] = math.cos(s.dec) * math.sin(dra)
        mm[i] = math.sin(s.dec) * math.cos(dec0) - math.cos(s.dec) * math.sin(
            dec0
        ) * math.cos(dra)
        n_raw = math.sin(s.dec) * math.sin(dec0) + math.cos(s.dec) * math.cos(
            dec0
        ) * math.cos(dra)
        nn[i] = n_raw - 1.0
        sI0[i], sQ0[i], sU0[i], sV0[i] = s.sI, s.sQ, s.sU, s.sV
        f0[i], si[i], si1[i], si2[i] = s.f0, s.spec_idx, s.spec_idx1, s.spec_idx2
        st = _source_type(s)
        stype[i] = st
        if st != ST_POINT:
            # projection angles use |n| (readsky.c:347-348 "use |n| for
            # projection") and are only *applied* when |n| < PROJ_CUT=0.998
            # (Dirac_common.h:90).  gaussian_contrib honors that gate
            # (predict.c:38-44); disk/ring apply the rotation
            # unconditionally (predict.c:66-68,80-82) — we reproduce the
            # gaussian gate by storing an identity rotation.
            n_abs = abs(n_raw)
            phi = math.acos(min(1.0, n_abs))
            xi = math.atan2(-ll[i], mm[i])
            use_projection = n_abs < 0.998
            if st == ST_GAUSSIAN and not use_projection:
                cxi[i], sxi[i], cphi[i], sphi[i] = 1.0, 0.0, 1.0, 0.0
            else:
                cxi[i], sxi[i] = math.cos(xi), math.sin(-xi)
                cphi[i], sphi[i] = math.cos(phi), math.sin(-phi)
            if st == ST_GAUSSIAN:
                ex_a[i] = s.eX * _FWHM_TO_SIGMA
                ex_b[i] = s.eY * _FWHM_TO_SIGMA
                ex_cp[i], ex_sp[i] = math.cos(s.eP), math.sin(s.eP)
            elif st in (ST_DISK, ST_RING):
                ex_a[i] = s.eX
            elif st == ST_SHAPELET:
                ex_a[i] = s.eX if s.eX else 1.0
                ex_b[i] = s.eY if s.eY else 1.0
                ex_cp[i], ex_sp[i] = math.cos(s.eP), math.sin(s.eP)
                shapelet_idx[i] = n_shap
                n_shap += 1
    cast = lambda x: jnp.asarray(x, dtype)
    return SourceBatch(
        ll=cast(ll), mm=cast(mm), nn=cast(nn),
        sI0=cast(sI0), sQ0=cast(sQ0), sU0=cast(sU0), sV0=cast(sV0),
        f0=cast(f0), spec_idx=cast(si), spec_idx1=cast(si1), spec_idx2=cast(si2),
        stype=jnp.asarray(stype),
        ex_a=cast(ex_a), ex_b=cast(ex_b), ex_cp=cast(ex_cp), ex_sp=cast(ex_sp),
        cxi=cast(cxi), sxi=cast(sxi), cphi=cast(cphi), sphi=cast(sphi),
        shapelet_idx=jnp.asarray(shapelet_idx),
    )


def load_sky(
    sky_path: str,
    cluster_path: str,
    ra0: float,
    dec0: float,
    dtype=np.float32,
    three_term_spectra=None,
) -> tuple[list, list, object]:
    """Full pipeline: files ->
    ([SourceBatch per cluster], [ClusterDef], ShapeletTable | None).

    Shapelet (S-type) sources additionally load their
    ``<name>.fits.modes`` file from the sky file's directory
    (readsky.c:143-200) into ONE sky-global :class:`ShapeletTable`;
    each batch's ``shapelet_idx`` is remapped from cluster-local to
    global rows.  Returns None for the table when the sky has no
    shapelet sources."""
    import jax.numpy as jnp

    from sagecal_tpu.ops.rime import ST_SHAPELET

    sky = parse_skymodel(sky_path, three_term_spectra)
    cdefs = parse_clusters(cluster_path)
    directory = os.path.dirname(os.path.abspath(sky_path))
    batches = []
    shap_entries = []  # (n0, beta, modes, eX, eY, eP) in global order
    for cd in cdefs:
        srcs = [sky[n] for n in cd.source_names if n in sky]
        missing = [n for n in cd.source_names if n not in sky]
        if missing:
            raise ValueError(f"cluster {cd.cluster_id}: unknown sources {missing}")
        batch = build_source_batch(srcs, ra0, dec0, dtype)
        stype_np = np.asarray(batch.stype)
        shap_srcs = [s for i, s in enumerate(srcs)
                     if int(stype_np[i]) == ST_SHAPELET]
        if shap_srcs:
            offset = len(shap_entries)
            for s in shap_srcs:
                n0, beta, modes = read_shapelet_modes(s.name, directory)
                shap_entries.append(
                    (n0, beta, modes, s.eX or 1.0, s.eY or 1.0, s.eP)
                )
            idx = np.asarray(batch.shapelet_idx)
            batch = batch.replace(shapelet_idx=jnp.asarray(
                np.where(idx >= 0, idx + offset, -1), np.int32))
        batches.append(batch)
    tab = build_shapelet_table(shap_entries, dtype) if shap_entries else None
    return batches, cdefs, tab


def build_shapelet_table(entries, dtype=np.float32):
    """Assemble a global :class:`ShapeletTable` from
    ``(n0, beta, modes, eX, eY, eP)`` tuples.  Models with n0 < n0max
    zero-pad their (n2, n1) mode grid — exact, since unused basis
    coefficients contribute nothing (mode (n1, n2) lives at flat index
    n2*n0 + n1, ops/shapelets.uv_mode_vectors)."""
    import jax.numpy as jnp

    from sagecal_tpu.ops.rime import ShapeletTable

    n0max = max(e[0] for e in entries)
    K = len(entries)
    modes = np.zeros((K, n0max * n0max))
    beta = np.empty(K)
    eX = np.empty(K)
    eY = np.empty(K)
    eP = np.empty(K)
    for k, (n0, b, m, ex, ey, ep) in enumerate(entries):
        grid = np.zeros((n0max, n0max))
        grid[:n0, :n0] = np.asarray(m).reshape(n0, n0)  # (n2, n1)
        modes[k] = grid.reshape(-1)
        beta[k], eX[k], eY[k], eP[k] = b, ex, ey, ep
    cast = lambda x: jnp.asarray(x, dtype)
    return ShapeletTable(modes=cast(modes), beta=cast(beta), eX=cast(eX),
                         eY=cast(eY), eP=cast(eP), n0max=int(n0max))


def read_cluster_rho(
    path: str, cdefs: list, spatialreg: bool = False
):
    """Per-cluster ADMM regularization file (the ``-G`` option;
    ``read_arho_fromfile``, readsky.c:783-860, format decl
    Dirac_radio.h:120-144): one line per cluster,

        cluster_id  hybrid  admm_rho  [spatial_alpha]

    Values are aligned to ``cdefs`` order by cluster_id when every id
    matches, else taken in file order.  Returns (rho (M,), alpha (M,) or
    None)."""
    entries = []
    with open(path) as fh:
        for line in fh:
            s = line.strip()
            if not s or s.startswith("#") or s.startswith("//"):
                continue
            tok = s.split()
            if len(tok) < 3:
                continue
            cid, hyb, rho = int(tok[0]), int(tok[1]), float(tok[2])
            alpha = float(tok[3]) if (spatialreg and len(tok) > 3) else 0.0
            entries.append((cid, hyb, rho, alpha))
    M = len(cdefs)
    if len(entries) < M:
        raise ValueError(
            f"{path}: {len(entries)} entries for {M} clusters"
        )
    by_id = {e[0]: e for e in entries}
    ordered = (
        [by_id[cd.cluster_id] for cd in cdefs]
        if all(cd.cluster_id in by_id for cd in cdefs)
        else entries[:M]
    )
    rho = np.asarray([e[2] for e in ordered])
    alpha = np.asarray([e[3] for e in ordered]) if spatialreg else None
    return rho, alpha


def read_shapelet_modes(name: str, directory: str = ".") -> tuple[int, float, np.ndarray]:
    """Read ``<name>.fits.modes`` -> (n0, beta, modes[n0*n0])
    (format per readsky.c:143-200: first non-comment number pair is n0 and
    beta, then mode index/value pairs)."""
    path = os.path.join(directory, name + ".fits.modes")
    vals = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            vals.extend(float(t) for t in line.split())
    # first 6 numbers are RA/Dec (ignored by the reference too)
    n0 = int(vals[6])
    beta = vals[7]
    rest = vals[8:]
    # sequential (index, value) pairs; the index token is read-and-ignored
    # by the reference (values stored in file order, readsky.c:180-186)
    modes = np.array([rest[2 * k + 1] for k in range(n0 * n0)])
    return n0, beta, modes
