"""Solution text files, byte-compatible with the reference format.

Format contract (``/root/reference/README.md`` section 6; writer at
``/root/reference/src/MS/fullbatch_mode.cpp:595-605``):

- '#' comment lines;
- first non-comment line: ``freq(MHz) bandwidth(MHz) time_interval(min)
  stations clusters effective_clusters``;
- then, per solution interval, 8N rows with 1+K columns: a repeating
  0..8N-1 counter followed by K effective-cluster columns.  Station s owns
  rows 8s..8s+7 = S0..S7 with ``J = [S0+jS1, S4+jS5; S2+jS3, S6+jS7]`` —
  identical to :func:`sagecal_tpu.core.types.params_to_jones` ordering, so
  a column is literally a parameter vector.
"""

from __future__ import annotations

import os

import numpy as np


def write_header(fh, freq_hz: float, bw_hz: float, tint_min: float, nstations: int,
                 nclus: int, nclus_eff: int) -> None:
    fh.write("# solution file created by sagecal-tpu\n")
    fh.write("# freq(MHz) bandwidth(MHz) time_interval(min) stations clusters effective_clusters\n")
    fh.write(
        f"{freq_hz * 1e-6:f} {bw_hz * 1e-6:f} {tint_min:f} {nstations} {nclus} {nclus_eff}\n"
    )


def append_solutions(fh, jones_cols: np.ndarray, flush: bool = True) -> None:
    """Write one solution interval.  ``jones_cols``: (K, N, 2, 2) complex —
    one column per effective cluster (cluster x hybrid chunk).

    Crash-safety contract (elastic resume): the whole interval is built
    as ONE buffer, written with a single ``fh.write`` and flushed, so a
    kill between intervals can never leave a torn interval behind — a
    kill DURING the OS-level write still can, which is exactly what
    :func:`validate_solutions` detects and truncates."""
    K, N = jones_cols.shape[0], jones_cols.shape[1]
    # (K, N, 8) S-ordering: [Re00, Im00, Re10, Im10, Re01, Im01, Re11, Im11]
    z = np.stack(
        [
            jones_cols[..., 0, 0].real, jones_cols[..., 0, 0].imag,
            jones_cols[..., 1, 0].real, jones_cols[..., 1, 0].imag,
            jones_cols[..., 0, 1].real, jones_cols[..., 0, 1].imag,
            jones_cols[..., 1, 1].real, jones_cols[..., 1, 1].imag,
        ],
        axis=-1,
    )
    cols = z.reshape(K, 8 * N).T  # (8N, K)
    buf = "".join(
        str(r) + " " + " ".join(f"{x:e}" for x in cols[r]) + "\n"
        for r in range(8 * N)
    )
    fh.write(buf)
    if flush:
        fh.flush()


def _validate_interval_file(path: str, rows_per_interval_fn,
                            truncate: bool = False,
                            max_intervals=None) -> dict:
    """Shared torn-interval detector for the fixed-rows-per-interval
    text formats (solution files: 8N rows; global-Z files: Npoly*8N).

    A body row is valid iff it is newline-terminated, has the same
    column count as the first row, its leading counter sits at the
    expected cycle position, and every token parses as a float; the
    first invalid row (a torn tail from a mid-write kill) invalidates
    everything after it.  ``truncate=True`` atomically rewrites the
    file keeping only the complete leading intervals — resume re-opens
    it in append mode afterwards."""
    with open(path) as f:
        lines = f.readlines()
    header_end = None
    rows_per = None
    for i, ln in enumerate(lines):
        s = ln.strip()
        if not s or s.startswith("#"):
            continue
        rows_per = rows_per_interval_fn(s.split())
        header_end = i + 1
        break
    if rows_per is None or rows_per <= 0:
        raise ValueError(f"{path}: no parseable header line")
    body = lines[header_end:]
    ncols = None
    good = 0
    for ln in body:
        if not ln.endswith("\n"):
            break  # torn final line (no newline = interrupted write)
        toks = ln.split()
        if not toks:
            break
        if ncols is None:
            ncols = len(toks)
        if len(toks) != ncols:
            break
        if toks[0] != str(good % rows_per):
            break  # counter out of cycle: rows lost or interleaved
        try:
            for t in toks[1:]:
                float(t)
        except ValueError:
            break
        good += 1
    n_intervals = good // rows_per
    if max_intervals is not None and n_intervals > max_intervals:
        # intervals past the newest checkpoint: complete but about to
        # be recomputed by the resumed loop — drop them so the re-run
        # tile appends exactly once
        n_intervals = int(max_intervals)
    torn_rows = len(body) - n_intervals * rows_per
    result = {
        "n_intervals": n_intervals,
        "torn_rows": torn_rows,
        "rows_per_interval": rows_per,
        "truncated": False,
    }
    if truncate and torn_rows:
        keep = lines[: header_end + n_intervals * rows_per]
        tmp = f"{path}.tmp.validate"
        with open(tmp, "w") as f:
            f.writelines(keep)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        result["truncated"] = True
    return result


def validate_solutions(path: str, truncate: bool = False,
                       max_intervals=None) -> dict:
    """Detect (and optionally truncate) a partial trailing interval in
    a solution file.  Returns ``{"n_intervals", "torn_rows",
    "rows_per_interval", "truncated"}``.  Used by elastic resume to
    re-open a crashed run's solution file append-consistently: every
    interval is exactly 8N rows with a cycling 0..8N-1 counter, so any
    remainder is a torn tail from a mid-write kill.  ``max_intervals``
    additionally drops complete intervals past the resume point."""
    return _validate_interval_file(
        path, lambda tok: 8 * int(tok[3]), truncate=truncate,
        max_intervals=max_intervals)


def validate_global_z(path: str, truncate: bool = False,
                      max_intervals=None) -> dict:
    """:func:`validate_solutions` for the distributed driver's global-Z
    file (header ``freq(MHz) npoly stations clusters eff``; one
    timeslot = ``npoly * 8N`` rows)."""
    return _validate_interval_file(
        path, lambda tok: int(tok[1]) * 8 * int(tok[2]), truncate=truncate,
        max_intervals=max_intervals)


def read_solutions(path: str):
    """Read a solution file -> (meta dict, array (ntiles, K, N, 2, 2) complex).

    Mirrors ``read_solutions`` (``/root/reference/src/lib/Radio/readsky.c``,
    decl Dirac_radio.h:110) but returns all intervals, not just the first.
    """
    meta = None
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            tok = line.split()
            if meta is None:
                meta = {
                    "freq_hz": float(tok[0]) * 1e6,
                    "bw_hz": float(tok[1]) * 1e6,
                    "tint_min": float(tok[2]),
                    "nstations": int(tok[3]),
                    "nclus": int(tok[4]),
                    "nclus_eff": int(tok[5]),
                }
                continue
            rows.append([float(x) for x in tok[1:]])
    N = meta["nstations"]
    arr = np.asarray(rows)  # (ntiles*8N, K)
    K = arr.shape[1]
    ntiles = arr.shape[0] // (8 * N)
    a = arr.reshape(ntiles, N, 8, K).transpose(0, 3, 1, 2)  # (ntiles, K, N, 8)
    jones = np.empty((ntiles, K, N, 2, 2), np.complex128)
    jones[..., 0, 0] = a[..., 0] + 1j * a[..., 1]
    jones[..., 1, 0] = a[..., 2] + 1j * a[..., 3]
    jones[..., 0, 1] = a[..., 4] + 1j * a[..., 5]
    jones[..., 1, 1] = a[..., 6] + 1j * a[..., 7]
    return meta, jones
