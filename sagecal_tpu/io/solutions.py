"""Solution text files, byte-compatible with the reference format.

Format contract (``/root/reference/README.md`` section 6; writer at
``/root/reference/src/MS/fullbatch_mode.cpp:595-605``):

- '#' comment lines;
- first non-comment line: ``freq(MHz) bandwidth(MHz) time_interval(min)
  stations clusters effective_clusters``;
- then, per solution interval, 8N rows with 1+K columns: a repeating
  0..8N-1 counter followed by K effective-cluster columns.  Station s owns
  rows 8s..8s+7 = S0..S7 with ``J = [S0+jS1, S4+jS5; S2+jS3, S6+jS7]`` —
  identical to :func:`sagecal_tpu.core.types.params_to_jones` ordering, so
  a column is literally a parameter vector.
"""

from __future__ import annotations

import numpy as np


def write_header(fh, freq_hz: float, bw_hz: float, tint_min: float, nstations: int,
                 nclus: int, nclus_eff: int) -> None:
    fh.write("# solution file created by sagecal-tpu\n")
    fh.write("# freq(MHz) bandwidth(MHz) time_interval(min) stations clusters effective_clusters\n")
    fh.write(
        f"{freq_hz * 1e-6:f} {bw_hz * 1e-6:f} {tint_min:f} {nstations} {nclus} {nclus_eff}\n"
    )


def append_solutions(fh, jones_cols: np.ndarray) -> None:
    """Write one solution interval.  ``jones_cols``: (K, N, 2, 2) complex —
    one column per effective cluster (cluster x hybrid chunk)."""
    K, N = jones_cols.shape[0], jones_cols.shape[1]
    # (K, N, 8) S-ordering: [Re00, Im00, Re10, Im10, Re01, Im01, Re11, Im11]
    z = np.stack(
        [
            jones_cols[..., 0, 0].real, jones_cols[..., 0, 0].imag,
            jones_cols[..., 1, 0].real, jones_cols[..., 1, 0].imag,
            jones_cols[..., 0, 1].real, jones_cols[..., 0, 1].imag,
            jones_cols[..., 1, 1].real, jones_cols[..., 1, 1].imag,
        ],
        axis=-1,
    )
    cols = z.reshape(K, 8 * N).T  # (8N, K)
    for r in range(8 * N):
        fh.write(str(r) + " " + " ".join(f"{x:e}" for x in cols[r]) + "\n")


def read_solutions(path: str):
    """Read a solution file -> (meta dict, array (ntiles, K, N, 2, 2) complex).

    Mirrors ``read_solutions`` (``/root/reference/src/lib/Radio/readsky.c``,
    decl Dirac_radio.h:110) but returns all intervals, not just the first.
    """
    meta = None
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            tok = line.split()
            if meta is None:
                meta = {
                    "freq_hz": float(tok[0]) * 1e6,
                    "bw_hz": float(tok[1]) * 1e6,
                    "tint_min": float(tok[2]),
                    "nstations": int(tok[3]),
                    "nclus": int(tok[4]),
                    "nclus_eff": int(tok[5]),
                }
                continue
            rows.append([float(x) for x in tok[1:]])
    N = meta["nstations"]
    arr = np.asarray(rows)  # (ntiles*8N, K)
    K = arr.shape[1]
    ntiles = arr.shape[0] // (8 * N)
    a = arr.reshape(ntiles, N, 8, K).transpose(0, 3, 1, 2)  # (ntiles, K, N, 8)
    jones = np.empty((ntiles, K, N, 2, 2), np.complex128)
    jones[..., 0, 0] = a[..., 0] + 1j * a[..., 1]
    jones[..., 1, 0] = a[..., 2] + 1j * a[..., 3]
    jones[..., 0, 1] = a[..., 4] + 1j * a[..., 5]
    jones[..., 1, 1] = a[..., 6] + 1j * a[..., 7]
    return meta, jones
