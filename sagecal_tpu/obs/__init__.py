"""Structured telemetry: metrics, per-iteration traces, manifests, events.

Four pieces (SURVEY section 5 "observability"):

- :mod:`sagecal_tpu.obs.registry` — host-side counters/gauges/histograms
  with Prometheus text export; a shared no-op registry when telemetry is
  off so instrumented call sites never branch.
- :mod:`sagecal_tpu.obs.records` — fixed-shape per-iteration solver
  trace records (``IterTrace``) carried *through* jit/scan/while_loop as
  auxiliary pytree outputs; host-callback-free by construction.
- :mod:`sagecal_tpu.obs.events` — ``RunManifest`` + append-only JSONL
  event log (``SAGECAL_TELEMETRY=1`` / ``SAGECAL_EVENT_LOG=...``).
- :mod:`sagecal_tpu.obs.perf` — performance observability:
  ``instrumented_jit`` compile/recompile tracking, device-memory
  watermarks, the transfer-guard audit, and the bench regression gate.
- :mod:`sagecal_tpu.obs.contracts` — opt-in ``SAGECAL_CHECKIFY=1``
  runtime contracts: checkify NaN/div/index checks on every
  ``instrumented_jit`` entry, surfaced as ``contract_violation``
  events (CLI exit 4).
- :mod:`sagecal_tpu.obs.trace` — hierarchical execution spans
  (``SAGECAL_TRACE=1``): span-tree JSONL + Chrome-trace export, ADMM
  per-band straggler attribution.
- :mod:`sagecal_tpu.obs.flight` — in-process flight recorder
  (``SAGECAL_FLIGHT=1``): bounded activity ring, heartbeat file, hang
  watchdog, and crash handlers dumping all-thread stacks.
- :mod:`sagecal_tpu.obs.devprof` — device-profiler capture
  (``SAGECAL_DEVICE_PROFILE=dir`` / ``--device-profile``), the
  zero-dependency trace parser, and per-kernel-family attribution.
- :mod:`sagecal_tpu.obs.roofline` — per-``device_kind`` peak table,
  arithmetic-intensity classification, per-kernel MFU/BW-util.
- :mod:`sagecal_tpu.obs.evidence` — evidence classes (tpu-wallclock /
  cpu-wallclock / aot-bytes / aot-hlo) stamped on every banked metric;
  the gate/trend cross-evidence refusal logic.
- :mod:`sagecal_tpu.obs.diag` — the ``sagecal-tpu diag`` CLI.

This package root imports neither jax nor numpy (obs.perf defers its
jax imports to call time), so ``from sagecal_tpu.obs import
telemetry_enabled`` is safe anywhere, including before backend
selection.
"""

from sagecal_tpu.obs.registry import (  # noqa: F401
    MetricsRegistry,
    NullRegistry,
    get_registry,
    set_telemetry,
    telemetry,
    telemetry_enabled,
)
from sagecal_tpu.obs.events import (  # noqa: F401
    EventLog,
    RunManifest,
    default_event_log,
    read_events,
    read_events_merged,
    validate_manifest,
)
from sagecal_tpu.obs.trace import (  # noqa: F401
    NullTracer,
    Tracer,
    band_attribution,
    close_tracer,
    configure_tracer,
    get_tracer,
    read_spans,
    set_trace,
    straggler_stats,
    trace_enabled,
    write_chrome_trace,
)
from sagecal_tpu.obs.flight import (  # noqa: F401
    FlightRecorder,
    close_flight_recorder,
    flight_enabled,
    get_flight_recorder,
    install_crash_handlers,
    note_activity,
    set_flight,
)
from sagecal_tpu.obs.contracts import (  # noqa: F401
    ContractViolation,
    checkify_enabled,
    drain_contract_events,
    emit_contract_events,
)
from sagecal_tpu.obs.perf import (  # noqa: F401
    TransferAudit,
    append_bench_history,
    bench_trend,
    device_memory_snapshot,
    dump_memory_profile,
    emit_perf_events,
    instrumented_jit,
    read_bench_history,
    record_memory_watermark,
)
from sagecal_tpu.obs.aggregate import (  # noqa: F401
    check_lifecycle,
    dedupe_snapshots,
    fleet_view,
    lifecycle_report,
    merge_states,
    metrics_snapshot_path,
    quantile_bounds_from_state,
    read_metrics_snapshots,
    write_metrics_snapshot,
)
from sagecal_tpu.obs.devprof import (  # noqa: F401
    attribute_trace,
    classify_kernel,
    device_profile,
    last_trace_path,
    read_trace_events,
    start_device_profile,
    stop_device_profile,
)
from sagecal_tpu.obs.evidence import (  # noqa: F401
    EVIDENCE_CLASSES,
    metric_evidence,
    record_evidence,
    wallclock_evidence,
)
from sagecal_tpu.obs.roofline import (  # noqa: F401
    PEAK_TABLE,
    bw_util,
    lookup_peaks,
    mfu,
)
from sagecal_tpu.obs.slo import (  # noqa: F401
    SLOMonitor,
    SLOSpec,
    evaluate_results,
    format_slo_report,
    load_slo_specs,
)

# obs.quality names resolve lazily (PEP 562): the module needs numpy,
# and this package root must stay importable without it
_QUALITY_NAMES = (
    "DivergenceAbort",
    "abort_if_diverged",
    "analyze_events",
    "assess_consensus",
    "assess_quality",
    "check_and_emit",
    "quality_summary",
    "quality_to_host",
    "write_baseline_heatmap",
    "write_station_heatmap",
)


def __getattr__(name):
    if name in _QUALITY_NAMES:
        from sagecal_tpu.obs import quality as _quality

        return getattr(_quality, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    *_QUALITY_NAMES,
    "MetricsRegistry",
    "NullRegistry",
    "get_registry",
    "set_telemetry",
    "telemetry",
    "telemetry_enabled",
    "EventLog",
    "RunManifest",
    "default_event_log",
    "read_events",
    "read_events_merged",
    "validate_manifest",
    "NullTracer",
    "Tracer",
    "band_attribution",
    "close_flight_recorder",
    "close_tracer",
    "configure_tracer",
    "get_tracer",
    "read_spans",
    "set_trace",
    "straggler_stats",
    "trace_enabled",
    "write_chrome_trace",
    "FlightRecorder",
    "flight_enabled",
    "get_flight_recorder",
    "install_crash_handlers",
    "note_activity",
    "set_flight",
    "ContractViolation",
    "checkify_enabled",
    "drain_contract_events",
    "emit_contract_events",
    "TransferAudit",
    "append_bench_history",
    "bench_trend",
    "device_memory_snapshot",
    "dump_memory_profile",
    "emit_perf_events",
    "instrumented_jit",
    "read_bench_history",
    "record_memory_watermark",
    "check_lifecycle",
    "dedupe_snapshots",
    "fleet_view",
    "lifecycle_report",
    "merge_states",
    "metrics_snapshot_path",
    "quantile_bounds_from_state",
    "read_metrics_snapshots",
    "write_metrics_snapshot",
    "SLOMonitor",
    "SLOSpec",
    "evaluate_results",
    "format_slo_report",
    "load_slo_specs",
    "attribute_trace",
    "classify_kernel",
    "device_profile",
    "last_trace_path",
    "read_trace_events",
    "start_device_profile",
    "stop_device_profile",
    "EVIDENCE_CLASSES",
    "metric_evidence",
    "record_evidence",
    "wallclock_evidence",
    "PEAK_TABLE",
    "bw_util",
    "lookup_peaks",
    "mfu",
]
