"""Cross-process telemetry aggregation: the fleet view.

A multi-worker serve deployment leaves per-process artifacts behind:
metric snapshots (``metrics-<pid>.json``, written by each worker at
shutdown), JSONL event logs (possibly pid-suffixed, see
``SAGECAL_EVENT_LOG_PER_PROCESS``), span files, and one result manifest
per completed request.  This module merges them after the fact into a
single *fleet view* — the ``expand_event_paths`` pattern of
:mod:`sagecal_tpu.obs.events` generalized to metrics — so ``diag
serve`` can report p50/p95/p99, cache hit ratios and SLO status for the
whole fleet from any set of workers' droppings.

Histograms merge exactly (bucket counts add; see
``registry._Histogram.merge``), so quantile *bounds* computed from the
merged state are exact: the true fleet quantile provably lies inside
the reported ``[lo, hi]`` bucket interval no matter how the
observations were sharded across processes.

Import-light by design (stdlib only): aggregation runs in ``diag`` on
machines that may have no jax at all.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from sagecal_tpu.obs.registry import MetricsRegistry, _Histogram

METRICS_SNAPSHOT_SCHEMA_VERSION = 1

#: per-request lifecycle phases every accepted serve request must log
#: (in order); ``compile``/``cache_hit`` is the alternation between a
#: bucket's first dispatch and every later one
LIFECYCLE_PHASES = ("enqueue", "schedule", "pack", "execute", "unpack",
                    "write_manifest")
LIFECYCLE_ALTERNATIVES = ("compile", "cache_hit")
LIFECYCLE_ROOT = "serve.request"


# ---------------------------------------------------------------------------
# metric snapshots: one JSON file per process, merged after the fact


def worker_id() -> str:
    """Stable identity of this worker for snapshot lineage:
    ``SAGECAL_WORKER_ID`` when the deployment sets one (so a resumed
    replacement supersedes its predecessor's snapshot), else the pid."""
    return os.environ.get("SAGECAL_WORKER_ID", "").strip() \
        or str(os.getpid())


def metrics_snapshot_path(out_dir: str,
                          worker: Optional[str] = None) -> str:
    """Canonical snapshot path for one worker under a serve output
    directory.  Snapshots are CUMULATIVE (a worker rewrites its own
    file), so the path must be stable per worker identity."""
    return os.path.join(out_dir, f"metrics-{worker or worker_id()}.json")


def write_metrics_snapshot(path: str, registry=None, **extra) -> str:
    """Atomically dump one process's registry state (tmp + replace so a
    concurrent aggregator never reads a torn file).  Returns the path."""
    if registry is None:
        from sagecal_tpu.obs.registry import get_registry

        registry = get_registry()
    doc = {
        "kind": "metrics_snapshot",
        "schema_version": METRICS_SNAPSHOT_SCHEMA_VERSION,
        "ts": time.time(),
        "pid": os.getpid(),
        "worker_id": worker_id(),
        "state": registry.export_state(),
    }
    for k, v in extra.items():
        doc.setdefault(k, v)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.write("\n")
    os.replace(tmp, path)
    return path


def expand_snapshot_paths(path: str) -> List[str]:
    """Resolve a snapshot argument to the files it names: a directory
    expands to its ``metrics-*.json`` members, a file to itself."""
    if os.path.isdir(path):
        return sorted(_glob.glob(os.path.join(path, "metrics-*.json")))
    return [path] if os.path.exists(path) else []


def read_metrics_snapshots(*paths: str) -> List[dict]:
    """Load every snapshot document the arguments name (skipping
    unreadable/corrupt files rather than failing — a preempted worker
    may never have written one)."""
    out: List[dict] = []
    for p in paths:
        for f in expand_snapshot_paths(p):
            try:
                with open(f, "r", encoding="utf-8") as fh:
                    doc = json.load(fh)
            except (OSError, json.JSONDecodeError):
                continue
            if isinstance(doc, dict) and doc.get("state") is not None:
                out.append(doc)
    out.sort(key=lambda d: float(d.get("ts", 0.0)))
    return out


def dedupe_snapshots(docs: Sequence[dict]) -> List[dict]:
    """Keep only the newest snapshot per worker id.  Snapshots are
    cumulative registry dumps — merging two generations of the SAME
    worker would double-count everything the older one already held
    (including counts a --resume restored from a checkpoint)."""
    latest: Dict[str, dict] = {}
    for d in docs:
        wid = str(d.get("worker_id") or d.get("pid") or id(d))
        prev = latest.get(wid)
        if prev is None or float(d.get("ts", 0.0)) >= float(
                prev.get("ts", 0.0)):
            latest[wid] = d
    return sorted(latest.values(), key=lambda d: float(d.get("ts", 0.0)))


def merge_states(states: Iterable[dict]) -> dict:
    """Fold any number of ``export_state`` documents into one merged
    state: counters add, histograms merge bucket-by-bucket, gauges keep
    the first (i.e. for snapshot lists sorted by ts, the earliest)
    value per series.  Associative and order-independent for counters
    and histograms."""
    reg = MetricsRegistry()
    for st in states:
        reg.restore_state(st)
    return reg.export_state()


def _labels_match(entry_labels: Sequence[Sequence[str]],
                  want: Dict[str, str]) -> bool:
    have = {k: v for k, v in entry_labels}
    return all(have.get(k) == str(v) for k, v in want.items())


def state_counter_total(state: dict, name: str, **labels) -> float:
    """Sum of every counter series in ``state`` matching ``name`` and
    the given label subset."""
    return sum(float(e["value"]) for e in state.get("counters", ())
               if e["name"] == name and _labels_match(e["labels"], labels))


def state_histogram(state: dict, name: str, **labels
                    ) -> Optional[_Histogram]:
    """Merge every histogram series matching ``name`` + label subset
    into one :class:`_Histogram` (None when nothing matches)."""
    merged: Optional[_Histogram] = None
    for e in state.get("histograms", ()):
        if e["name"] != name or not _labels_match(e["labels"], labels):
            continue
        h = _Histogram.from_snapshot(e)
        if merged is None:
            merged = h
        else:
            merged.merge(h)
    return merged


def state_label_values(state: dict, name: str, label: str) -> List[str]:
    """Distinct values of one label across every series of a metric
    (counters + histograms), sorted."""
    vals = set()
    for kind in ("counters", "gauges", "histograms"):
        for e in state.get(kind, ()):
            if e["name"] != name:
                continue
            for k, v in e["labels"]:
                if k == label:
                    vals.add(v)
    return sorted(vals)


def quantile_bounds_from_state(state: dict, name: str,
                               qs: Sequence[float] = (0.5, 0.95, 0.99),
                               **labels) -> Dict[float, Tuple[float, float]]:
    """Exact quantile bounds per requested quantile from the merged
    histogram of a metric (empty dict when no observations)."""
    h = state_histogram(state, name, **labels)
    if h is None or h.count == 0:
        return {}
    out = {}
    for q in qs:
        b = h.quantile_bounds(q)
        if b is not None:
            out[float(q)] = b
    return out


# ---------------------------------------------------------------------------
# result manifests (the per-request ground truth)


def read_result_manifests(*out_dirs: str) -> List[dict]:
    """Every ``*.result.json`` under the given serve output dirs, in
    completion-time order (falls back to request_id order for pre-PR
    manifests without timestamps)."""
    out: List[dict] = []
    for d in out_dirs:
        for p in sorted(_glob.glob(os.path.join(d, "*.result.json"))):
            try:
                with open(p, "r", encoding="utf-8") as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if isinstance(doc, dict) and doc.get("request_id"):
                out.append(doc)
    out.sort(key=lambda r: (float(r.get("completed_at", 0.0)),
                            str(r.get("request_id", ""))))
    return out


def queue_depth_series(results: Sequence[dict]) -> List[Tuple[float, int]]:
    """Reconstruct the waiting-room depth from result manifests alone:
    +1 at ``enqueued_at``, -1 at ``started_at``, ABSOLUTE timestamps.
    Shed manifests participate (a to-be-shed request occupied the queue
    until its shed decision — ``started_at`` — exactly like the live
    view counts it); they are excluded from *served-work* accounting by
    obs/capacity.served_results, not from depth.  At equal timestamps
    arrivals apply before departures, so a zero-wait disposition (e.g.
    an instant shed with ``started_at == enqueued_at``) can never swing
    the reconstructed depth negative."""
    edges: List[Tuple[float, int]] = []
    for r in results:
        enq = r.get("enqueued_at")
        sta = r.get("started_at")
        if enq is None or sta is None:
            continue
        edges.append((float(enq), +1))
        edges.append((float(sta), -1))
    if not edges:
        return []
    edges.sort(key=lambda e: (e[0], -e[1]))
    depth = 0
    line: List[Tuple[float, int]] = []
    for t, d in edges:
        depth += d
        line.append((t, depth))
    return line


def queue_depth_timeline(results: Sequence[dict],
                         max_points: int = 64) -> List[Tuple[float, int]]:
    """:func:`queue_depth_series` rebased to run-relative seconds and
    down-sampled to ``max_points`` (the ``diag serve`` rendering)."""
    series = queue_depth_series(results)
    if not series:
        return []
    t0 = series[0][0]
    line = [(t - t0, depth) for t, depth in series]
    if len(line) > max_points:
        step = len(line) / float(max_points)
        line = [line[int(i * step)] for i in range(max_points)]
    return line


# ---------------------------------------------------------------------------
# lifecycle (span-chain) completeness across the manifest boundary


def lifecycle_traces(spans: Sequence[dict]) -> Dict[str, List[dict]]:
    """Group spans by trace id, keeping only traces that contain a
    ``serve.request`` root (run-level spans keep their own trace id and
    are excluded)."""
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(s)
    return {t: ss for t, ss in by_trace.items()
            if any(s.get("name") == LIFECYCLE_ROOT for s in ss)}


def check_lifecycle(spans: Sequence[dict]) -> dict:
    """Validate one request's span chain: exactly one root, every
    required phase present, exactly one of ``compile``/``cache_hit``,
    every child parented to the root.  Returns a verdict dict with a
    ``complete`` bool and the list of ``problems``."""
    problems: List[str] = []
    roots = [s for s in spans if s.get("name") == LIFECYCLE_ROOT]
    if len(roots) != 1:
        problems.append(f"expected 1 {LIFECYCLE_ROOT} root, got {len(roots)}")
    names = [s.get("name") for s in spans]
    for ph in LIFECYCLE_PHASES:
        if ph not in names:
            problems.append(f"missing phase: {ph}")
    alts = [n for n in names if n in LIFECYCLE_ALTERNATIVES]
    if len(alts) != 1:
        problems.append(
            f"expected exactly one of {'|'.join(LIFECYCLE_ALTERNATIVES)}, "
            f"got {alts or 'none'}")
    if roots:
        root_id = roots[0].get("span_id")
        for s in spans:
            if s is roots[0]:
                continue
            if s.get("parent_id") != root_id:
                problems.append(
                    f"span {s.get('name')} not parented to root")
    return {
        "complete": not problems,
        "problems": problems,
        "phases": [n for n in names if n != LIFECYCLE_ROOT],
        "path": alts[0] if len(alts) == 1 else None,
    }


def lifecycle_report(spans: Sequence[dict],
                     results: Sequence[dict] = ()) -> dict:
    """Fleet-wide lifecycle audit: every result manifest carrying a
    ``trace_id`` must have a complete span chain somewhere in ``spans``
    (possibly written by a different process — the ids inside the
    manifests are what carry the lifecycle across that boundary)."""
    traces = lifecycle_traces(spans)
    verdicts: Dict[str, dict] = {
        t: check_lifecycle(ss) for t, ss in traces.items()}
    missing: List[str] = []
    matched = 0
    for r in results:
        tid = r.get("trace_id")
        if not tid:
            continue
        v = verdicts.get(tid)
        if v is None:
            missing.append(f"{r.get('request_id')}: no spans for trace "
                           f"{tid}")
        elif not v["complete"]:
            missing.append(f"{r.get('request_id')}: "
                           + "; ".join(v["problems"]))
        else:
            matched += 1
    incomplete = {t: v["problems"] for t, v in verdicts.items()
                  if not v["complete"]}
    return {
        "traces": len(verdicts),
        "complete": sum(1 for v in verdicts.values() if v["complete"]),
        "incomplete": incomplete,
        "manifests_with_trace": sum(
            1 for r in results if r.get("trace_id")),
        "manifests_matched": matched,
        "manifest_problems": missing,
        "cache_hit_traces": sum(
            1 for v in verdicts.values() if v.get("path") == "cache_hit"),
        "compile_traces": sum(
            1 for v in verdicts.values() if v.get("path") == "compile"),
        "ok": not missing and not incomplete,
    }


# ---------------------------------------------------------------------------
# the fleet view


def fleet_view(out_dirs: Sequence[str],
               snapshot_paths: Sequence[str] = (),
               event_paths: Sequence[str] = (),
               span_paths: Sequence[str] = ()) -> Dict[str, Any]:
    """One merged view of a multi-worker serve deployment.

    ``out_dirs`` are scanned for result manifests AND metric snapshots;
    extra snapshot/event/span paths (files or directories, pid-suffix
    companions included) widen the net.  Returns a dict with ``results``
    (per-request manifests), ``state`` (merged metrics), ``events``,
    ``spans`` and ``snapshots`` (count of snapshot files merged)."""
    from sagecal_tpu.obs.events import read_events_merged
    from sagecal_tpu.obs.trace import read_spans

    snaps = dedupe_snapshots(read_metrics_snapshots(
        *(list(out_dirs) + list(snapshot_paths))))
    events: List[dict] = []
    for p in event_paths:
        events.extend(read_events_merged(p))
    events.sort(key=lambda e: float(e.get("ts", 0.0)))
    spans: List[dict] = []
    for p in span_paths:
        from sagecal_tpu.obs.events import expand_event_paths

        for f in expand_event_paths(p):
            spans.extend(read_spans(f))
    return {
        "results": read_result_manifests(*out_dirs),
        "state": merge_states(d["state"] for d in snaps),
        "snapshots": len(snaps),
        "events": events,
        "spans": spans,
    }
