"""Conservation-law auditing of a replayed fleet run.

Takes the deterministic reconstruction from obs/replay.py and asserts
the global invariants that MUST hold if the run's telemetry is complete
and truthful:

==========================  ===========================================
violation kind              invariant
==========================  ===========================================
``torn_record``             no unparseable lines in any record file
                            (every shipped emitter writes one line per
                            ``os.write`` on an O_APPEND fd, or stages
                            through tmp+rename — torn lines cannot
                            happen without a writer bug or tampering)
``foreign_record``          every line belongs to its file's family
``out_of_schema``           every record carries its family's required
                            keys and a known schema version
``conservation``            enqueued == served + shed + failed + pending
``forged_manifest``         exactly one result manifest per request,
                            each matching a queued item and its done
                            marker
``lease_epoch``             surviving lease chains strictly monotonic +
                            contiguous, steals only after genuine TTL
                            expiry (in skew-corrected time)
``span_chain``              every manifest trace_id resolves to a
                            complete lifecycle span chain (evaluated
                            when the run traced)
``counter_regression``      cumulative timeline counters never decrease
``timeline_bounds``         sampled depth rows stay inside the bounds
                            the replayed queue admits around each
                            sample instant
``clock_skew``              per-writer clock offsets feasible and
                            within the skew bound
``sequence_hole``           per-writer record sequences have no gaps
``observability_gap``       no unregistered record files, no missing
                            load-bearing event kinds
==========================  ===========================================

Exit codes (``diag audit``): 0 all invariants hold, 1 any violation or
gap, 2 insufficient records to audit (no queue items found — nothing to
conserve).

``SAGECAL_AUDIT_INJECT=drop_event|tear_record|forge_manifest|
skew_clock`` perturbs the loaded records IN MEMORY before checking (the
files are never touched), proving each detector actually detects; the
pinned kinds are in :data:`INJECTION_KINDS`.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional

from sagecal_tpu.obs import ledger
from sagecal_tpu.obs.replay import (
    FAILED, PENDING, SERVED, SHED, ReplayState, RunRecords, domain_of,
    format_replay, load_run, replay,
)

# pinned violation kinds
KIND_TORN = "torn_record"
KIND_FOREIGN = "foreign_record"
KIND_OUT_OF_SCHEMA = "out_of_schema"
KIND_CONSERVATION = "conservation"
KIND_FORGED_MANIFEST = "forged_manifest"
KIND_LEASE_EPOCH = "lease_epoch"
KIND_SPAN_CHAIN = "span_chain"
KIND_COUNTER_REGRESSION = "counter_regression"
KIND_TIMELINE_BOUNDS = "timeline_bounds"
KIND_CLOCK_SKEW = "clock_skew"
KIND_SEQUENCE_HOLE = "sequence_hole"
KIND_GAP = "observability_gap"

#: fault-injection arm -> the violation kind it must produce
INJECTION_KINDS = {
    "drop_event": KIND_SEQUENCE_HOLE,
    "tear_record": KIND_TORN,
    "forge_manifest": KIND_FORGED_MANIFEST,
    "skew_clock": KIND_CLOCK_SKEW,
}

#: exit codes (diag audit / diag replay)
EXIT_OK = 0
EXIT_VIOLATION = 1
EXIT_INSUFFICIENT = 2


@dataclasses.dataclass
class Violation:
    kind: str
    subject: str
    message: str

    def render(self) -> str:
        return f"VIOLATION [{self.kind}] {self.subject}: {self.message}"


@dataclasses.dataclass
class AuditReport:
    out_dir: str
    state: Optional[ReplayState]
    violations: List[Violation]
    checks: List[Dict[str, Any]]     # {name, status, detail}
    insufficient: bool = False
    insufficient_reason: str = ""
    injected: str = ""

    @property
    def ok(self) -> bool:
        return not self.violations and not self.insufficient

    def exit_code(self) -> int:
        if self.insufficient:
            return EXIT_INSUFFICIENT
        return EXIT_OK if not self.violations else EXIT_VIOLATION

    def kinds(self) -> List[str]:
        return sorted({v.kind for v in self.violations})

    def to_doc(self) -> Dict[str, Any]:
        return {
            "out_dir": self.out_dir,
            "ok": self.ok,
            "exit_code": self.exit_code(),
            "insufficient": self.insufficient,
            "insufficient_reason": self.insufficient_reason,
            "injected": self.injected,
            "violations": [dataclasses.asdict(v)
                           for v in self.violations],
            "checks": self.checks,
            "replay": self.state.to_doc() if self.state else None,
        }


# ----------------------------------------------------------- injection


def apply_injection(rec: RunRecords, mode: str) -> str:
    """Perturb the loaded records in memory (never the files) so the
    auditor can prove its detectors fire.  Returns a note describing
    what was injected."""
    if mode == "drop_event":
        # drop a mid-sequence event from the busiest writer: a lost
        # record in the middle of a stream leaves a sequence hole
        by_writer: Dict[str, List[dict]] = {}
        for e in rec.events:
            w = e.get("writer")
            if isinstance(w, str) and isinstance(e.get("seq"), int):
                by_writer.setdefault(w, []).append(e)
        best = max(by_writer.values(), key=len, default=None)
        if not best or len(best) < 3:
            return "drop_event: no writer with >=3 sequenced events"
        best.sort(key=lambda e: e["seq"])
        victim = best[len(best) // 2]
        rec.events.remove(victim)
        return (f"drop_event: removed seq={victim['seq']} of "
                f"{victim['writer']}")
    if mode == "tear_record":
        # reclassify the tail record of the first event file as torn —
        # exactly what a mid-write crash of a buggy buffered writer
        # would leave behind
        for vf in rec.scan.files:
            if vf.family == "event" and vf.records:
                tail = vf.records[-1]
                tail.status = ledger.TORN
                tail.reason = "injected: line truncated mid-write"
                if tail.record in rec.events:
                    rec.events.remove(tail.record)
                tail.record = None
                return f"tear_record: tore tail line of {vf.path}"
        return "tear_record: no event file to tear"
    if mode == "forge_manifest":
        if not rec.manifests:
            return "forge_manifest: no manifest to forge"
        forged = dict(rec.manifests[0])
        forged["request_id"] = f"{forged.get('request_id')}~forged"
        rec.manifests.append(forged)
        return (f"forge_manifest: duplicated manifest under forged id "
                f"{forged['request_id']}")
    if mode == "skew_clock":
        # step one worker domain's event clock back 3 minutes
        doms = sorted({domain_of(d.get("worker"))
                       for d in rec.done.values()} - {None})
        if not doms:
            doms = sorted({domain_of(e.get("writer"))
                           for e in rec.events} - {None})
        if not doms:
            return "skew_clock: no writer domain to skew"
        victim = doms[0]
        shifted = 0
        for e in rec.events:
            if domain_of(e.get("writer")) == victim and isinstance(
                    e.get("ts"), (int, float)):
                e["ts"] = float(e["ts"]) + 180.0
                shifted += 1
        return (f"skew_clock: stepped {victim} events +180s "
                f"({shifted} records)")
    raise ValueError(
        f"unknown SAGECAL_AUDIT_INJECT mode {mode!r} "
        f"(known: {', '.join(sorted(INJECTION_KINDS))})")


# ------------------------------------------------------------- checks


def _check(checks: List[Dict[str, Any]], name: str, status: str,
           detail: str = "") -> None:
    checks.append({"name": name, "status": status, "detail": detail})


def _monotone_counters(state: ReplayState, vs: List[Violation]) -> str:
    rows = state.records.timeline
    keys = ("items", "done", "results_total", "shed_total",
            "error_total", "aot_store_entries")
    by_writer: Dict[str, List[dict]] = {}
    for r in rows:
        by_writer.setdefault(str(r.get("writer", "")), []).append(r)
    bad = 0
    for w, ws in by_writer.items():
        ws.sort(key=lambda r: (r.get("seq", -1), float(r.get("ts", 0))))
        last: Dict[str, float] = {}
        for i, r in enumerate(ws):
            for k in keys:
                v = r.get(k)
                if not isinstance(v, (int, float)):
                    continue
                if k in last and v < last[k]:
                    bad += 1
                    vs.append(Violation(
                        KIND_COUNTER_REGRESSION, f"timeline[{i}]",
                        f"{k} regressed {last[k]} -> {v} "
                        f"(writer {w or '?'})"))
                last[k] = float(v)
    return f"{len(rows)} rows, {bad} regressions"


def _timeline_bounds(state: ReplayState, slack_s: float,
                     vs: List[Violation]) -> str:
    rec = state.records
    rows = rec.timeline
    if not rows:
        return "no timeline rows"
    enq_ts = sorted(float(i.get("enqueued_at") or 0.0)
                    for i in rec.items.values())
    done_ts = []
    for rid, d in rec.done.items():
        dom = domain_of(d.get("worker"))
        off = state.clocks[dom].est if dom in state.clocks else 0.0
        t = d.get("completed_at")
        if isinstance(t, (int, float)):
            done_ts.append(float(t) + off)
    done_ts.sort()

    import bisect

    def counts_at(ts: float) -> tuple:
        return (bisect.bisect_right(enq_ts, ts),
                bisect.bisect_right(done_ts, ts))

    bad = 0
    for i, row in enumerate(rows):
        ts = float(row.get("ts", 0.0))
        lo_e, lo_d = counts_at(ts - slack_s)
        hi_e, hi_d = counts_at(ts + slack_s)
        items, done = row.get("items"), row.get("done")
        if isinstance(items, int) and not (lo_e <= items <= hi_e):
            bad += 1
            vs.append(Violation(
                KIND_TIMELINE_BOUNDS, f"timeline[{i}]",
                f"items={items} outside replayed [{lo_e}, {hi_e}] "
                f"at ts={ts:.3f}±{slack_s:.1f}s"))
        if isinstance(done, int) and not (lo_d <= done <= hi_d):
            bad += 1
            vs.append(Violation(
                KIND_TIMELINE_BOUNDS, f"timeline[{i}]",
                f"done={done} outside replayed [{lo_d}, {hi_d}] "
                f"at ts={ts:.3f}±{slack_s:.1f}s"))
    return f"{len(rows)} rows within ±{slack_s:.1f}s bounds, {bad} out"


def _lease_epochs(state: ReplayState, slack_s: float,
                  vs: List[Violation]) -> str:
    rec = state.records
    chains = 0
    for rid, chain in sorted(rec.leases.items()):
        if not chain:
            continue
        chains += 1
        epochs = [ep for ep, _ in chain]
        if len(set(epochs)) != len(epochs):
            vs.append(Violation(
                KIND_LEASE_EPOCH, rid,
                f"duplicate lease epochs {epochs}"))
            continue
        if epochs != list(range(epochs[0], epochs[0] + len(epochs))):
            vs.append(Violation(
                KIND_LEASE_EPOCH, rid,
                f"epoch chain not contiguous/monotonic: {epochs}"))
        for (ep_a, a), (ep_b, b) in zip(chain, chain[1:]):
            if str(a.get("request_id")) != rid or str(
                    b.get("request_id")) != rid:
                vs.append(Violation(
                    KIND_LEASE_EPOCH, rid,
                    f"lease doc request_id mismatch in epoch "
                    f"{ep_a}/{ep_b}"))
            if a.get("worker") == b.get("worker"):
                continue
            # a steal: only legitimate after the previous epoch's
            # lease genuinely expired (or was released), judged in
            # skew-corrected time
            exp = a.get("expires_at")
            if exp == 0.0:        # released — handover is free
                continue
            dom_a = domain_of(a.get("worker"))
            dom_b = domain_of(b.get("worker"))
            off_a = state.clocks[dom_a].est if dom_a in state.clocks else 0.0
            off_b = state.clocks[dom_b].est if dom_b in state.clocks else 0.0
            acq = b.get("acquired_at")
            if (isinstance(exp, (int, float))
                    and isinstance(acq, (int, float))
                    and float(exp) + off_a > float(acq) + off_b + slack_s):
                vs.append(Violation(
                    KIND_LEASE_EPOCH, rid,
                    f"epoch {ep_b} stolen by {b.get('worker')} "
                    f"{float(exp) + off_a - float(acq) - off_b:.3f}s "
                    f"before epoch {ep_a} ({a.get('worker')}) expired"))
    return f"{chains} surviving chains"


def _span_chains(state: ReplayState, vs: List[Violation]) -> str:
    rec = state.records
    if not rec.spans:
        return ""
    from sagecal_tpu.obs.aggregate import lifecycle_report

    traced = [m for m in rec.manifests if m.get("trace_id")
              and str(m.get("verdict", "")) not in ("shed", "error")]
    rep = lifecycle_report(rec.spans, traced)
    for problem in rep["manifest_problems"]:
        vs.append(Violation(KIND_SPAN_CHAIN, "manifest", str(problem)))
    return (f"{rep['manifests_matched']}/{len(traced)} manifests with "
            f"complete chains, {rep['traces']} traces")


def run_audit(out_dir: str, events_path: Optional[str] = None,
              queue_dir: Optional[str] = None,
              max_skew_s: float = 30.0, slack_s: float = 3.0,
              inject: Optional[str] = None) -> AuditReport:
    """Load + replay + audit one run directory.  ``inject`` defaults to
    ``SAGECAL_AUDIT_INJECT``."""
    if inject is None:
        inject = os.environ.get("SAGECAL_AUDIT_INJECT", "").strip()
    rec = load_run(out_dir, events_path=events_path,
                   queue_dir=queue_dir)
    injected = apply_injection(rec, inject) if inject else ""

    vs: List[Violation] = []
    checks: List[Dict[str, Any]] = []

    if not rec.items:
        return AuditReport(
            out_dir=out_dir, state=None, violations=[], checks=checks,
            insufficient=True,
            insufficient_reason="no queue items found (nothing to "
            "conserve) — pass --queue if the queue dir lives outside "
            "the out-dir",
            injected=injected)

    state = replay(rec)

    # --- record hygiene: the validating reader's classifications
    counts = rec.scan.counts()
    for vf in rec.scan.files:
        for c in vf.records:
            where = f"{os.path.basename(vf.path)}:{c.line_no}"
            if c.status == ledger.TORN:
                vs.append(Violation(KIND_TORN, where, c.reason))
            elif c.status == ledger.FOREIGN:
                vs.append(Violation(KIND_FOREIGN, where,
                                    f"[{vf.family}] {c.reason}"))
            elif c.status == ledger.OUT_OF_SCHEMA:
                vs.append(Violation(KIND_OUT_OF_SCHEMA, where,
                                    f"[{vf.family}] {c.reason}"))
    _check(checks, "record-hygiene",
           "PASS" if counts[ledger.TORN] == counts[ledger.FOREIGN]
           == counts[ledger.OUT_OF_SCHEMA] == 0 else "FAIL",
           f"{counts[ledger.OK]} ok / {counts[ledger.TORN]} torn / "
           f"{counts[ledger.FOREIGN]} foreign / "
           f"{counts[ledger.OUT_OF_SCHEMA]} out-of-schema")

    # --- conservation: enqueued == served + shed + failed + pending
    c = state.counts
    total = c[SERVED] + c[SHED] + c[FAILED] + c[PENDING]
    if c["enqueued"] != total:
        vs.append(Violation(
            KIND_CONSERVATION, "queue",
            f"enqueued {c['enqueued']} != served {c[SERVED]} + shed "
            f"{c[SHED]} + failed {c[FAILED]} + pending {c[PENDING]}"))
    _check(checks, "conservation",
           "PASS" if c["enqueued"] == total else "FAIL",
           f"{c['enqueued']} = {c[SERVED]}+{c[SHED]}+{c[FAILED]}"
           f"+{c[PENDING]}")

    # --- manifest uniqueness / provenance
    n_mf = len(vs)
    by_rid: Dict[str, int] = {}
    for m in rec.manifests:
        by_rid[str(m.get("request_id"))] = by_rid.get(
            str(m.get("request_id")), 0) + 1
    for rid, n in sorted(by_rid.items()):
        if n > 1:
            vs.append(Violation(
                KIND_FORGED_MANIFEST, rid,
                f"{n} result manifests for one request"))
        if rid not in rec.items:
            vs.append(Violation(
                KIND_FORGED_MANIFEST, rid,
                "manifest has no queued item (forged or cross-run)"))
    for rid, d in sorted(rec.done.items()):
        if rid not in by_rid:
            vs.append(Violation(
                KIND_FORGED_MANIFEST, rid,
                f"done marker (worker {d.get('worker')}) without a "
                f"result manifest"))
    _check(checks, "manifest-uniqueness",
           "PASS" if len(vs) == n_mf else "FAIL",
           f"{len(by_rid)} manifested requests, {len(rec.done)} done "
           f"markers")

    # --- lease epoch chains
    n0 = len(vs)
    detail = _lease_epochs(state, slack_s, vs)
    _check(checks, "lease-epochs", "PASS" if len(vs) == n0 else "FAIL",
           detail)

    # --- span chains (only provable when the run traced)
    n0 = len(vs)
    detail = _span_chains(state, vs)
    if detail:
        _check(checks, "span-chains",
               "PASS" if len(vs) == n0 else "FAIL", detail)
    else:
        _check(checks, "span-chains", "SKIP",
               "no spans recorded (tracing off)")

    # --- counters monotone across resume
    n0 = len(vs)
    detail = _monotone_counters(state, vs)
    _check(checks, "counter-monotonicity",
           "PASS" if len(vs) == n0 else "FAIL", detail)

    # --- timeline depth rows inside replayed bounds
    n0 = len(vs)
    skew_pad = max((abs(cl.est) for cl in state.clocks.values()),
                   default=0.0)
    detail = _timeline_bounds(state, slack_s + skew_pad, vs)
    _check(checks, "timeline-bounds",
           "PASS" if len(vs) == n0 else "FAIL", detail)

    # --- clock skew
    n0 = len(vs)
    worst = 0.0
    for dom, cl in sorted(state.clocks.items()):
        if dom == state.reference_domain:
            continue
        worst = max(worst, abs(cl.est))
        if not cl.feasible:
            vs.append(Violation(
                KIND_CLOCK_SKEW, dom,
                f"happens-before constraints unsatisfiable "
                f"(offset lo {cl.lo:+.3f}s > hi {cl.hi:+.3f}s)"))
        elif abs(cl.est) > max_skew_s:
            vs.append(Violation(
                KIND_CLOCK_SKEW, dom,
                f"estimated clock offset {cl.est:+.3f}s exceeds "
                f"bound ±{max_skew_s:.1f}s"))
    for a in state.clock_anomalies:
        vs.append(Violation(KIND_CLOCK_SKEW, "same-writer", a))
    _check(checks, "clock-skew", "PASS" if len(vs) == n0 else "FAIL",
           f"max |offset| {worst:.3f}s over "
           f"{max(len(state.clocks) - 1, 0)} domains")

    # --- sequence holes
    n0 = len(vs)
    holes = ledger.sequence_holes(rec.events)
    for w, missing in sorted(holes.items()):
        head = ", ".join(str(i) for i in missing[:5])
        vs.append(Violation(
            KIND_SEQUENCE_HOLE, w,
            f"{len(missing)} missing seq number(s): {head}"
            + ("…" if len(missing) > 5 else "")))
    row_holes = ledger.sequence_holes(rec.timeline)
    for w, missing in sorted(row_holes.items()):
        vs.append(Violation(
            KIND_SEQUENCE_HOLE, f"timeline:{w}",
            f"{len(missing)} missing timeline seq number(s)"))
    _check(checks, "sequence-holes",
           "PASS" if len(vs) == n0 else "FAIL",
           f"{len(holes) + len(row_holes)} writers with holes")

    # --- observability gaps
    n0 = len(vs)
    for rel in rec.scan.unregistered:
        vs.append(Violation(
            KIND_GAP, rel,
            "record-looking file owned by no registered family "
            "(register it in obs/ledger.py or add it to "
            "IGNORED_PATTERNS)"))
    if not rec.events:
        vs.append(Violation(
            KIND_GAP, "events",
            "no event log found (run with SAGECAL_TELEMETRY=1, or "
            "pass --events)"))
    else:
        kinds = {e.get("type") for e in rec.events}
        expected = ["run_manifest"]
        if rec.done:
            expected.append("fleet_claimed")
        for k in expected:
            if k not in kinds:
                vs.append(Violation(
                    KIND_GAP, "events",
                    f"expected event kind {k!r} never observed"))
    _check(checks, "observability-gaps",
           "PASS" if len(vs) == n0 else "FAIL",
           f"{len(rec.scan.unregistered)} unregistered files, "
           f"{len(rec.events)} events")

    return AuditReport(out_dir=out_dir, state=state, violations=vs,
                       checks=checks, injected=injected)


def format_audit(report: AuditReport, verbose: bool = False) -> str:
    lines: List[str] = [f"fleet audit: {report.out_dir}"]
    if report.injected:
        lines.append(f"  injected fault: {report.injected}")
    if report.insufficient:
        lines.append(f"AUDIT: INSUFFICIENT RECORDS — "
                     f"{report.insufficient_reason}")
        return "\n".join(lines)
    if report.state is not None:
        lines.append(format_replay(report.state, verbose=verbose))
    lines.append("  invariants:")
    for ch in report.checks:
        lines.append(f"    {ch['name']:<22} {ch['status']:<4} "
                     f"{ch['detail']}")
    for v in report.violations:
        lines.append(v.render())
    if report.ok:
        lines.append("AUDIT: OK (zero conservation-law violations)")
    else:
        kinds = ", ".join(report.kinds())
        lines.append(
            f"AUDIT: {len(report.violations)} violation(s) [{kinds}]")
    return "\n".join(lines)
