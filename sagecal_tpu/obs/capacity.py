"""Saturation analysis + autoscale recommendations for fleet load runs.

The load harness (fleet/loadgen.py) records offered-load ground truth
per step (``load_steps.json``), the coordinator samples the live
timeline (obs/timeline.py), and the workers write per-request result
manifests.  This module joins the three into the capacity picture ROADMAP
item 3 asks for:

- **throughput / goodput vs offered load** — per load step: served
  completions per second (shed and error manifests are dispositions,
  *not* served work) and the deadline-met subset (goodput);
- **knee detection** — the first offered-load step whose served
  throughput falls more than ``tol`` below the offered rate: below the
  knee the fleet keeps up, above it work queues or sheds;
- **shed rate under overload** — the fraction of the highest offered
  step's arrivals that ended shed, attributed by *arrival* step
  (under overload most sheds complete during the drain, after the
  last window — window attribution would read 0);
- **queue growth rate** — least-squares slope of the waiting depth;
- **Little's law cross-check** — for the waiting room, ``L = λW``
  must hold between three independently-measured views: L from the
  live timeline, L from the post-hoc manifest reconstruction
  (obs/aggregate.queue_depth_series), and λ·W from manifest counts
  and recorded queue waits.  Disagreement beyond tolerance means one
  of the observability paths is lying — that is the cross-check's
  whole point;
- **:class:`AutoscaleRecommender`** — a report-only controller fed
  one timeline row per poll.  It votes scale-up on sustained queue
  growth or SLO fast-burn, scale-down on sustained idleness, requires
  ``fire_samples`` consecutive votes before changing its
  recommendation (hysteresis), emits a ``scale_recommendation`` event
  on each change and mirrors the latest recommendation into an atomic
  ``recommended_workers.json``.  The file is advisory output with a
  single writer (the coordinator) — never read for coordination, so
  the PR-13 lease-protocol model is untouched; the optional
  ``--elastic-workers`` honor path acts on the in-memory value only.

Import-light (stdlib only): ``diag load`` runs on machines without jax.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

CAPACITY_SCHEMA_VERSION = 1

#: advisory recommendation mirror (single writer, atomic replace)
RECOMMENDED_WORKERS_FILE = "recommended_workers.json"

#: default knee tolerance: served throughput this far below offered is
#: "not keeping up"
KNEE_TOL = 0.10

#: knee absolute guard (requests): the shortfall must also be worth
#: this many whole requests over the step window, so one completion
#: spilling into the next window at a low offered rate (tiny counts)
#: cannot fire a false knee
KNEE_ABS_TOL = 2.0

#: verdicts that count as a disposition but NOT as served work
UNSERVED_VERDICTS = ("shed", "error")


def served_results(results: Sequence[dict]) -> List[dict]:
    """Manifests that represent actually-served work: sheds are the
    controller refusing work and errors are failed work — neither may
    count as served in any throughput/goodput view."""
    return [r for r in results
            if str(r.get("verdict", "")) not in UNSERVED_VERDICTS]


# ---------------------------------------------------------------------------
# offered-load steps + throughput/goodput curve


def load_steps(path_or_dir: str) -> Dict[str, Any]:
    """Read a ``load_steps.json`` (or the out-dir containing one)."""
    path = path_or_dir
    if os.path.isdir(path):
        path = os.path.join(path, "load_steps.json")
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "steps" not in doc:
        raise ValueError(f"{path}: not a load_steps document")
    return doc


def throughput_curve(steps: Sequence[dict], results: Sequence[dict],
                     specs=None) -> List[Dict[str, Any]]:
    """One row per offered-load step.  Completions are attributed to
    steps by ``completed_at`` (dispositions happen when they happen —
    a backlogged step can complete more than it offered); ``served``
    excludes sheds and errors; ``goodput`` is the served-ok subset
    whose latency met the tenant's deadline (requests of tenants
    without a spec count as good when the verdict is ok)."""
    specs = specs or {}
    rows: List[Dict[str, Any]] = []
    for step in steps:
        t0, t1 = float(step["t0"]), float(step["t1"])
        dur = max(t1 - t0, 1e-9)
        inwin = [r for r in results
                 if t0 <= float(r.get("completed_at") or 0.0) < t1]
        served = served_results(inwin)
        shed = sum(1 for r in inwin if r.get("verdict") == "shed")
        errors = sum(1 for r in inwin if r.get("verdict") == "error")
        good = 0
        for r in served:
            if str(r.get("verdict")) != "ok":
                continue
            spec = specs.get(str(r.get("tenant")))
            if spec is None or (float(r.get("latency_s", 0.0))
                                <= spec.deadline_s):
                good += 1
        dispositions = len(inwin)
        rows.append({
            "index": int(step.get("index", len(rows))),
            "t0": t0, "t1": t1, "duration_s": dur,
            "offered_rate": float(step.get("offered_rate", 0.0)),
            "arrivals": int(step.get("arrivals", 0)),
            "dispositions": dispositions,
            "served": len(served),
            "throughput": len(served) / dur,
            "goodput": good,
            "goodput_rate": good / dur,
            "goodput_fraction": good / max(len(served), 1),
            "shed": shed,
            "shed_rate": shed / max(dispositions, 1),
            "errors": errors,
        })
    rows.sort(key=lambda r: r["offered_rate"])
    return rows


def arrival_dispositions(doc: Dict[str, Any], results: Sequence[dict]
                         ) -> Dict[int, Dict[str, Any]]:
    """Per-step disposition mix attributed by ARRIVAL step: what
    happened to the load offered in step k, wherever it completed.
    The completion-window view (:func:`throughput_curve`) measures the
    fleet's service rate; this view measures each step's fate — under
    overload most of a step's sheds complete during the drain, after
    the last window, and a window-attributed shed rate would read 0.
    Keyed by ``submitted`` request_ids against the planned windows
    (scheduled offset ``t``, immune to submit jitter)."""
    steps = doc.get("steps") or []
    t_start = float(doc.get("t_start") or 0.0)
    step_of: Dict[str, int] = {}
    for a in doc.get("submitted") or []:
        t = t_start + float(a.get("t", 0.0))
        for s in steps:
            if float(s["t0"]) <= t < float(s["t1"]):
                step_of[str(a["request_id"])] = int(s["index"])
                break
    if not step_of:
        # no realized arrival record (synthetic fixture / killed run):
        # leave the curve's window attribution unmasked
        return {}
    mix: Dict[int, Dict[str, Any]] = {
        int(s["index"]): {"arrival_dispositions": 0,
                          "arrival_served": 0, "arrival_shed": 0,
                          "arrival_errors": 0, "arrival_shed_rate": 0.0}
        for s in steps}
    for r in results:
        idx = step_of.get(str(r.get("request_id")))
        if idx is None or idx not in mix:
            continue
        row = mix[idx]
        row["arrival_dispositions"] += 1
        verdict = str(r.get("verdict", ""))
        if verdict == "shed":
            row["arrival_shed"] += 1
        elif verdict == "error":
            row["arrival_errors"] += 1
        else:
            row["arrival_served"] += 1
    for row in mix.values():
        row["arrival_shed_rate"] = (
            row["arrival_shed"] / max(row["arrival_dispositions"], 1))
    return mix


def find_knee(curve: Sequence[dict], tol: float = KNEE_TOL,
              abs_tol: float = KNEE_ABS_TOL) -> Dict[str, Any]:
    """Locate the saturation knee on an offered-rate-sorted curve: the
    first step whose served throughput is more than ``tol`` below its
    offered rate AND whose shortfall is worth more than ``abs_tol``
    whole requests over the window (the absolute guard: at 0.5/s a
    single completion landing just past the window edge is 10% of the
    step — batching latency, not saturation).
    ``saturation_throughput`` is the best served rate observed
    anywhere on the curve (the capacity estimate)."""
    sat = max((r["throughput"] for r in curve), default=0.0)
    sat_row = None
    for r in curve:
        if r["throughput"] >= sat:
            sat_row = r
            break
    knee = None
    for r in curve:
        if r["offered_rate"] <= 0.0:
            continue
        planned = float(r.get("arrivals", 0)
                        or r["offered_rate"] * r["duration_s"])
        shortfall = planned - r["served"]
        if (r["throughput"] < (1.0 - tol) * r["offered_rate"]
                and shortfall > abs_tol):
            knee = r
            break
    return {
        "saturated": knee is not None,
        "knee_offered_rate": knee["offered_rate"] if knee else None,
        "knee_index": knee["index"] if knee else None,
        "saturation_throughput": sat,
        "saturation_index": sat_row["index"] if sat_row else None,
        "tol": tol,
    }


# ---------------------------------------------------------------------------
# waiting-depth series algebra (shared by Little + reconcile + growth)


def timeline_waiting_series(rows: Sequence[dict]) -> List[Tuple[float, float]]:
    """Live waiting-room depth over time: ``waiting + expired_leases``
    (an expired lease is an item back in the waiting room until it is
    stolen), absolute timestamps."""
    return [(float(r["ts"]),
             float(r.get("waiting", 0)) + float(r.get("expired_leases", 0)))
            for r in rows if "ts" in r]


def time_weighted_mean(series: Sequence[Tuple[float, float]],
                       t0: Optional[float] = None,
                       t1: Optional[float] = None) -> float:
    """Mean of a piecewise-constant series over [t0, t1] (defaults to
    the series' own span).  Each sample holds until the next one."""
    pts = sorted((float(t), float(v)) for t, v in series)
    if not pts:
        return 0.0
    t0 = pts[0][0] if t0 is None else float(t0)
    t1 = pts[-1][0] if t1 is None else float(t1)
    if t1 <= t0:
        return pts[-1][1]
    area = 0.0
    for i, (t, v) in enumerate(pts):
        nxt = pts[i + 1][0] if i + 1 < len(pts) else t1
        lo, hi = max(t, t0), min(nxt, t1)
        if hi > lo:
            area += v * (hi - lo)
    # before the first sample the depth is unknown: treat as 0 (queue
    # starts empty), which the [t0 >= first-sample] default avoids
    return area / (t1 - t0)


def slope(series: Sequence[Tuple[float, float]],
          t0: Optional[float] = None,
          t1: Optional[float] = None) -> float:
    """Least-squares slope (units/s) of a (t, value) series over the
    window; 0 with fewer than two points."""
    pts = [(float(t), float(v)) for t, v in series
           if (t0 is None or t >= t0) and (t1 is None or t <= t1)]
    if len(pts) < 2:
        return 0.0
    n = float(len(pts))
    mt = sum(t for t, _ in pts) / n
    mv = sum(v for _, v in pts) / n
    num = sum((t - mt) * (v - mv) for t, v in pts)
    den = sum((t - mt) ** 2 for t, _ in pts)
    return num / den if den > 0 else 0.0


def littles_law_check(timeline_rows: Sequence[dict],
                      results: Sequence[dict],
                      t0: Optional[float] = None,
                      t1: Optional[float] = None,
                      rtol: float = 0.35,
                      atol: float = 1.0) -> Dict[str, Any]:
    """Cross-check L = λW for the waiting room over [t0, t1].

    Three independent measurements must agree:

    - ``L_live``     — time-weighted mean waiting depth from the live
      timeline (sampled by the coordinator while the run happened);
    - ``L_posthoc``  — the same mean from the manifest reconstruction
      (+1 at ``enqueued_at``, -1 at ``started_at``);
    - ``lambda_w``   — λ·W from manifests alone: departures from the
      waiting room per second times the mean recorded queue wait.

    A view disagrees when it differs from λ·W by more than
    ``max(atol, rtol * max(L, λW))``."""
    from sagecal_tpu.obs.aggregate import queue_depth_series

    starts = sorted(float(r["started_at"]) for r in results
                    if r.get("started_at") is not None)
    if t0 is None:
        t0 = starts[0] if starts else None
    if t1 is None:
        t1 = starts[-1] if starts else None
    inwin = [r for r in results
             if r.get("started_at") is not None
             and (t0 is None or float(r["started_at"]) >= t0)
             and (t1 is None or float(r["started_at"]) <= t1)]
    dur = (t1 - t0) if (t0 is not None and t1 is not None
                        and t1 > t0) else 0.0
    lam = len(inwin) / dur if dur > 0 else 0.0
    waits = [float(r.get("queue_wait_s", 0.0)) for r in inwin]
    w = sum(waits) / len(waits) if waits else 0.0
    lam_w = lam * w
    live = time_weighted_mean(
        timeline_waiting_series(timeline_rows), t0, t1)
    posthoc = time_weighted_mean(queue_depth_series(results), t0, t1)

    def _agrees(val: float) -> bool:
        return abs(val - lam_w) <= max(atol, rtol * max(val, lam_w))

    return {
        "t0": t0, "t1": t1, "duration_s": dur,
        "lambda_per_s": lam, "mean_wait_s": w, "lambda_w": lam_w,
        "L_live": live, "L_posthoc": posthoc,
        "live_ok": _agrees(live),
        "posthoc_ok": _agrees(posthoc),
        "ok": _agrees(live) and _agrees(posthoc),
        "rtol": rtol, "atol": atol,
    }


def reconcile_queue_views(timeline_rows: Sequence[dict],
                          results: Sequence[dict],
                          rtol: float = 0.25,
                          atol: float = 1.5) -> Dict[str, Any]:
    """Compare the live waiting-depth view against the post-hoc
    manifest reconstruction over their common window: time-weighted
    means and peaks must agree within tolerance.  This is the
    cross-check that caught the shed/served counting rules drifting
    between the two views."""
    from sagecal_tpu.obs.aggregate import queue_depth_series

    live_series = timeline_waiting_series(timeline_rows)
    post_series = queue_depth_series(results)
    if not live_series or not post_series:
        return {"comparable": False,
                "reason": "missing live timeline or manifests",
                "ok": False}
    t0 = max(live_series[0][0], post_series[0][0])
    t1 = min(live_series[-1][0], post_series[-1][0])
    live_mean = time_weighted_mean(live_series, t0, t1)
    post_mean = time_weighted_mean(post_series, t0, t1)
    live_peak = max((v for t, v in live_series if t0 <= t <= t1),
                    default=0.0)
    post_peak = max((v for t, v in post_series if t0 <= t <= t1),
                    default=0.0)

    def _close(a: float, b: float) -> bool:
        return abs(a - b) <= max(atol, rtol * max(a, b))

    return {
        "comparable": True, "t0": t0, "t1": t1,
        "live_mean_depth": live_mean, "posthoc_mean_depth": post_mean,
        "live_peak_depth": live_peak, "posthoc_peak_depth": post_peak,
        "mean_ok": _close(live_mean, post_mean),
        "peak_ok": _close(live_peak, post_peak),
        "ok": _close(live_mean, post_mean) and _close(live_peak,
                                                      post_peak),
        "rtol": rtol, "atol": atol,
    }


# ---------------------------------------------------------------------------
# autoscale recommender (report-only controller)


@dataclasses.dataclass(frozen=True)
class RecommenderConfig:
    """Thresholds + hysteresis of the autoscale recommender."""

    min_workers: int = 1
    max_workers: int = 8
    #: sustained waiting-depth growth (items/s) that votes scale-up
    up_queue_growth: float = 0.05
    #: short-window SLO burn that votes scale-up (budget burning 2x)
    up_burn: float = 2.0
    #: waiting depth at or below this (with no growth and an idle
    #: worker) votes scale-down
    down_idle_waiting: int = 0
    #: consecutive same-direction votes before the recommendation moves
    fire_samples: int = 3
    #: trailing window the growth slope is fit over
    growth_window_s: float = 30.0


class AutoscaleRecommender:
    """Feed one timeline row per poll; emits a recommendation dict on
    each CHANGE of ``recommended_workers`` (None otherwise).

    Votes, not actions: scale-up when the waiting room grows faster
    than ``up_queue_growth`` with more waiters than live workers, or
    when any tenant's short-window burn reaches ``up_burn`` with a
    backlog; scale-down when the queue is idle (nothing waiting, no
    growth, at least one worker without an active lease).  A change
    requires ``fire_samples`` consecutive votes in the same direction
    and moves one worker at a time — the fire/clear hysteresis that
    keeps a noisy signal from flapping the fleet."""

    def __init__(self, cfg: RecommenderConfig, workers: int):
        self.cfg = cfg
        self.recommended = max(cfg.min_workers,
                               min(int(workers), cfg.max_workers))
        self._hist: List[Tuple[float, float]] = []
        self._up = 0
        self._down = 0
        self.last: Optional[Dict[str, Any]] = None

    def update(self, row: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        cfg = self.cfg
        ts = float(row.get("ts", 0.0))
        waiting = float(row.get("waiting", 0)) + float(
            row.get("expired_leases", 0))
        leased = float(row.get("leased", 0))
        alive = int(row.get("alive_workers", 0))
        burn = float(row.get("slo_burn_max_short", 0.0))
        self._hist.append((ts, waiting))
        horizon = ts - cfg.growth_window_s
        while self._hist and self._hist[0][0] < horizon:
            self._hist.pop(0)
        growth = slope(self._hist)
        utilization = leased / max(alive, 1)
        up_vote = ((growth > cfg.up_queue_growth and waiting > alive)
                   or (burn >= cfg.up_burn and waiting > 0))
        down_vote = (not up_vote
                     and waiting <= cfg.down_idle_waiting
                     and growth <= 0.0
                     and leased < max(alive, 1)
                     and burn < cfg.up_burn)
        if up_vote:
            self._up += 1
            self._down = 0
        elif down_vote:
            self._down += 1
            self._up = 0
        else:
            self._up = self._down = 0
        prev = self.recommended
        reason = None
        if self._up >= cfg.fire_samples and prev < cfg.max_workers:
            self.recommended = prev + 1
            reason = ("slo_burn" if burn >= cfg.up_burn
                      else "queue_growth")
            self._up = 0
        elif self._down >= cfg.fire_samples and prev > cfg.min_workers:
            self.recommended = prev - 1
            reason = "idle"
            self._down = 0
        if self.recommended == prev:
            return None
        rec = {
            "schema_version": CAPACITY_SCHEMA_VERSION,
            "ts": ts,
            "recommended_workers": self.recommended,
            "previous_workers": prev,
            "reason": reason,
            "signals": {
                "queue_growth_per_s": growth,
                "waiting": waiting,
                "leased": leased,
                "alive_workers": alive,
                "utilization": utilization,
                "slo_burn_max_short": burn,
            },
        }
        self.last = rec
        return rec


def write_recommendation(out_dir: str, rec: Dict[str, Any]) -> str:
    """Atomically mirror the latest recommendation (tmp + replace, so
    a reader never sees a torn file).  Advisory output only — nothing
    in the fleet protocol reads it back."""
    path = os.path.join(out_dir, RECOMMENDED_WORKERS_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(rec, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def read_recommendation(out_dir: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(out_dir, RECOMMENDED_WORKERS_FILE)
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


# ---------------------------------------------------------------------------
# the full report (diag load / loadgen / bench entry point)


def analyze_load_run(out_dir: str, specs=None,
                     knee_tol: float = KNEE_TOL,
                     littles_rtol: float = 0.35,
                     littles_atol: float = 1.0) -> Dict[str, Any]:
    """Join load_steps.json + timeline.jsonl + result manifests under
    ``out_dir`` into the capacity report: the curve, the knee, the
    banked headline metrics, the Little's-law cross-check, the
    live-vs-posthoc reconciliation, and the latest recommendation."""
    from sagecal_tpu.obs.aggregate import read_result_manifests
    from sagecal_tpu.obs.timeline import read_timeline, timeline_path

    doc = load_steps(out_dir)
    results = read_result_manifests(out_dir)
    rows = read_timeline(timeline_path(out_dir))
    curve = throughput_curve(doc["steps"], results, specs)
    mix = arrival_dispositions(doc, results)
    for r in curve:
        r.update(mix.get(r["index"], {}))
    knee = find_knee(curve, tol=knee_tol)
    overload = curve[-1] if curve else None
    sat_idx = knee.get("saturation_index")
    sat_row = next((r for r in curve if r["index"] == sat_idx), None)
    for r in curve:
        r["queue_growth_per_s"] = slope(
            timeline_waiting_series(rows), r["t0"], r["t1"])
    littles = littles_law_check(rows, results,
                                rtol=littles_rtol, atol=littles_atol)
    return {
        "schema_version": CAPACITY_SCHEMA_VERSION,
        "out_dir": os.path.abspath(out_dir),
        "seed": doc.get("seed"),
        "arrival": doc.get("arrival"),
        "steps": curve,
        "knee": knee,
        "saturation_throughput_solves_per_sec":
            knee["saturation_throughput"],
        # arrival-attributed: the fate of the load offered in the
        # highest step, wherever its dispositions completed (window
        # attribution would miss sheds landing during the drain)
        "shed_rate_under_overload":
            (overload.get("arrival_shed_rate", overload["shed_rate"])
             if overload else 0.0),
        "goodput_fraction_at_saturation":
            sat_row["goodput_fraction"] if sat_row else 0.0,
        "littles_law": littles,
        "reconcile": reconcile_queue_views(rows, results),
        "timeline_rows": len(rows),
        "manifests": len(results),
        "served": len(served_results(results)),
        "shed": sum(1 for r in results if r.get("verdict") == "shed"),
        "errors": sum(1 for r in results
                      if r.get("verdict") == "error"),
        "recommendation": read_recommendation(out_dir),
    }


def format_load_report(report: Dict[str, Any]) -> str:
    """Human-readable rendering for ``diag load``."""
    lines: List[str] = []
    lines.append(
        f"load run: {report['manifests']} manifests "
        f"({report['served']} served, {report['shed']} shed, "
        f"{report['errors']} errors), "
        f"{report['timeline_rows']} timeline samples")
    lines.append(
        f"{'step':>4s} {'offered/s':>10s} {'served':>7s} "
        f"{'thru/s':>8s} {'goodput':>8s} {'shed%':>6s} "
        f"{'growth/s':>9s}")
    for r in report["steps"]:
        lines.append(
            f"{r['index']:>4d} {r['offered_rate']:>10.3f} "
            f"{r['served']:>7d} {r['throughput']:>8.3f} "
            f"{r['goodput_fraction']:>7.1%} {r['shed_rate']:>5.1%} "
            f"{r['queue_growth_per_s']:>9.3f}")
    knee = report["knee"]
    if knee["saturated"]:
        lines.append(
            f"knee: saturates at offered {knee['knee_offered_rate']:.3f}"
            f"/s (step {knee['knee_index']}); capacity ≈ "
            f"{knee['saturation_throughput']:.3f} served/s")
    else:
        lines.append(
            f"knee: not reached (peak served "
            f"{knee['saturation_throughput']:.3f}/s kept up with "
            f"every offered step)")
    lines.append(
        f"shed under overload: "
        f"{report['shed_rate_under_overload']:.1%}; goodput at "
        f"saturation: {report['goodput_fraction_at_saturation']:.1%}")
    ll = report["littles_law"]
    lines.append(
        f"Little's law: λ={ll['lambda_per_s']:.3f}/s "
        f"W={ll['mean_wait_s']:.2f}s -> λW={ll['lambda_w']:.2f}; "
        f"L_live={ll['L_live']:.2f} "
        f"({'ok' if ll['live_ok'] else 'DISAGREES'}), "
        f"L_posthoc={ll['L_posthoc']:.2f} "
        f"({'ok' if ll['posthoc_ok'] else 'DISAGREES'})")
    rc = report["reconcile"]
    if rc.get("comparable"):
        lines.append(
            f"live vs post-hoc depth: mean {rc['live_mean_depth']:.2f}"
            f"/{rc['posthoc_mean_depth']:.2f}, peak "
            f"{rc['live_peak_depth']:.0f}/{rc['posthoc_peak_depth']:.0f}"
            f" -> {'reconciled' if rc['ok'] else 'MISMATCH'}")
    rec = report.get("recommendation")
    if rec:
        sig = rec.get("signals", {})
        lines.append(
            f"recommendation: {rec['recommended_workers']} workers "
            f"(was {rec.get('previous_workers')}, reason "
            f"{rec.get('reason')}, growth "
            f"{sig.get('queue_growth_per_s', 0.0):.3f}/s, burn "
            f"{sig.get('slo_burn_max_short', 0.0):.1f}x)")
    else:
        lines.append("recommendation: none recorded (report-only "
                     "recommender never fired)")
    return "\n".join(lines)
