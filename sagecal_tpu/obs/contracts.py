"""Runtime contracts: opt-in checkify wrapping of the solver jit entries.

The static rules (sagecal_tpu/analysis) prove discipline *shapes* hold;
this module checks the *values* at runtime.  ``SAGECAL_CHECKIFY=1``
reroutes every :func:`~sagecal_tpu.obs.perf.instrumented_jit` call
through ``jax.experimental.checkify`` with NaN/div/index checks
(``float_checks | index_checks``).  A tripped check raises
:class:`ContractViolation` on the host and records a structured
``contract_violation`` event that the apps drain into their JSONL logs
(exit code 4 at the CLI, next to the existing divergence-abort 3).

Off (the default) the instrumented-jit fast path is untouched — the env
flag is read per call, nothing else changes, and solver outputs stay
bit-identical (pinned by tests/test_analysis.py).  On, expect roughly
2x trace size and a modest runtime cost from the error-state threading;
this is a debugging harness, not a production mode.

Functions checkify cannot wrap (Pallas kernels, exotic shardings) fall
back to the unchecked path once, recording a ``contract_unsupported``
event instead of failing the run.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Callable, List, Optional

from sagecal_tpu.obs.registry import get_registry, telemetry_enabled

CHECKIFY_ENV = "SAGECAL_CHECKIFY"
_TRUTHY = ("1", "true", "yes", "on")

_LOCK = threading.Lock()
# pending contract events, drained by the apps into their JSONL logs
# (bounded: a NaN-spewing loop must not grow host memory without bound)
_CONTRACT_EVENTS: List[dict] = []
_MAX_CONTRACT_EVENTS = 1024


class ContractViolation(RuntimeError):
    """A checkify contract (NaN/div/index) tripped inside a jitted fn."""

    def __init__(self, fn_name: str, detail: str):
        super().__init__(f"contract violation in `{fn_name}`: {detail}")
        self.fn_name = fn_name
        self.detail = detail


def checkify_enabled() -> bool:
    return os.environ.get(CHECKIFY_ENV, "").lower() in _TRUTHY


def checkify_active() -> bool:
    """Enabled AND at an outermost (non-traced) call.

    An instrumented entry reached from inside another trace (jit/vmap of
    a caller) must stay unchecked there: the checkify error value would
    itself be a tracer and ``err.get()`` cannot run on it.  The outer
    checked entry already covers those inner frames.
    """
    if not checkify_enabled():
        return False
    import jax.core

    return jax.core.trace_state_clean()


def error_set():
    """NaN + div + out-of-bounds-index checks (the contract surface)."""
    from jax.experimental import checkify

    return checkify.float_checks | checkify.index_checks


def checked_jit(fn: Callable, jit_kwargs: dict) -> Callable:
    """jit(checkify(fn)) with the original static-arg declarations.

    ``checkify.checkify`` returns a ``(*args, **kwargs)``-signature
    callable, which breaks ``static_argnames`` resolution; re-wrapping
    it with ``functools.wraps(fn)`` restores the original signature so
    the jit kwargs apply unchanged.
    """
    import jax
    from jax.experimental import checkify

    checked = checkify.checkify(fn, errors=error_set())
    wrapper = functools.wraps(fn)(
        lambda *args, **kwargs: checked(*args, **kwargs))
    return jax.jit(wrapper, **jit_kwargs)


def note_violation(fn_name: str, detail: str) -> None:
    ev = {
        "fn": fn_name, "detail": detail,
        "unix_time": round(time.time(), 3),
    }
    with _LOCK:
        if len(_CONTRACT_EVENTS) < _MAX_CONTRACT_EVENTS:
            _CONTRACT_EVENTS.append(dict(ev, kind="contract_violation"))
    if telemetry_enabled():
        get_registry().counter_inc(
            "contract_violations_total", 1.0,
            help="checkify contract failures (NaN/div/index) per "
                 "instrumented function", fn=fn_name,
        )


def note_unsupported(fn_name: str, reason: str) -> None:
    """checkify could not wrap ``fn_name``; the call fell back to the
    unchecked path (recorded once per wrapper)."""
    with _LOCK:
        if len(_CONTRACT_EVENTS) < _MAX_CONTRACT_EVENTS:
            _CONTRACT_EVENTS.append({
                "kind": "contract_unsupported", "fn": fn_name,
                "detail": reason[:500],
                "unix_time": round(time.time(), 3),
            })


def raise_if_error(err, fn_name: str) -> None:
    """Host-side check of a checkify error value: record + raise."""
    msg: Optional[str] = err.get()
    if msg is None:
        return
    note_violation(fn_name, msg)
    raise ContractViolation(fn_name, msg)


def drain_contract_events() -> List[dict]:
    """Return and clear the pending contract events (app -> JSONL)."""
    with _LOCK:
        evs, _CONTRACT_EVENTS[:] = list(_CONTRACT_EVENTS), []
    return evs


def emit_contract_events(elog) -> int:
    """Drain pending contract events into an :class:`EventLog`."""
    n = 0
    for ev in drain_contract_events():
        kind = ev.pop("kind", "contract_violation")
        elog.emit(kind, **ev)
        n += 1
    return n


def reset_contract_events() -> None:
    """Clear the module-level store (tests)."""
    with _LOCK:
        _CONTRACT_EVENTS.clear()
