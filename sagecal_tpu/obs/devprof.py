"""Device-profiler ingestion: capture, parse, per-kernel attribution.

Everything below the dispatch boundary was invisible to the obs stack:
``obs/perf.py`` reports whole-program ``cost_analysis`` flops/bytes,
so nobody could say which PERF.md lever (DMA overlap, the ~65 ms
dispatch floor, VMEM-ceiling splits) dominates the lost 99.86% of the
0.14%-MFU headline.  This module is the hardware-truth half of PR 16:

- **Capture** — :func:`start_device_profile` / :func:`stop_device_profile`
  / :func:`device_profile` wrap ``jax.profiler.start_trace`` with the
  same idempotent-owner discipline as ``utils/profiling.trace`` but a
  separate opt-in (``SAGECAL_DEVICE_PROFILE=dir`` or the apps'
  ``--device-profile`` flag), because this capture is consumed by our
  own parser, not TensorBoard.  ``stop`` locates the newest emitted
  ``*.trace.json(.gz)`` and remembers it for flight dumps and
  ``tpu_recovery_attempted`` events.
- **Fleet arming** — a coordinator drops an atomic JSON flag file in
  the fleet's shared out_dir (:func:`arm_fleet_profile`); the targeted
  worker's loop polls :func:`check_fleet_arm` and profiles exactly one
  claimed cycle, then renames the flag to ``.done`` with the trace
  path (:func:`complete_fleet_arm`) — one worker of a live fleet gets
  profiled without restarting anything.
- **Parse** — :func:`read_trace_events` is a zero-dependency reader for
  the Chrome-trace JSON jax emits (gzipped on real runs, plain JSON
  accepted for fixtures).  Device op events are the ``X`` events
  carrying ``args.hlo_op`` (CPU thunk runtime) or sitting on ``XLA
  Ops`` threads (TPU); ``args.hlo_module`` is ``jit_<fn>``, which is
  exactly the ``instrumented_jit`` ledger name — the join key.
- **Attribute** — :func:`attribute_trace` buckets device time into the
  kernel families of ROADMAP item 1 (fused grid, batched grid, XLA
  predict, LBFGS vector work, DMA/infeed, other), computes total
  device time as the union of per-track busy intervals, counts
  per-module executions *within the trace window* (min single-op-name
  count — ops outside any loop emit exactly once per dispatch, while
  loop-body ops emit once per iteration), and measures dispatch
  gaps between device busy windows: the tunnel's ~65 ms floor and how
  far whole-solve jits amortize it.

Import-light: ``jax`` is imported inside the capture functions only,
so ``diag roofline`` can parse traces on a box with no accelerator.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

_DEVPROF_ENV = "SAGECAL_DEVICE_PROFILE"

_active_dir: Optional[str] = None
_last_trace: Optional[str] = None


# ------------------------------------------------------------- capture


def start_device_profile(log_dir: Optional[str] = None) -> Optional[str]:
    """Begin a device-profile capture (idempotent).  Returns the capture
    directory, or None when not requested.  Tolerates an already-active
    profiler session (e.g. ``SAGECAL_PROFILE_DIR`` tracing is live):
    jax allows one trace at a time, so we log-and-skip rather than
    kill the run that asked for observability."""
    global _active_dir
    if _active_dir is not None:
        return _active_dir
    log_dir = log_dir or os.environ.get(_DEVPROF_ENV)
    if not log_dir:
        return None
    os.makedirs(log_dir, exist_ok=True)
    import jax

    try:
        jax.profiler.start_trace(log_dir)
    except Exception as e:  # another trace already owns the profiler
        try:
            from sagecal_tpu.obs.flight import note_activity

            note_activity(f"device_profile skipped: {e}")
        except Exception:
            pass
        return None
    _active_dir = log_dir
    try:
        from sagecal_tpu.obs.flight import note_activity

        note_activity(f"device_profile started: {log_dir}")
    except Exception:
        pass
    return log_dir


def stop_device_profile() -> Optional[str]:
    """Stop the capture this module started and return the path of the
    newest emitted trace file (also retained for flight dumps)."""
    global _active_dir, _last_trace
    if _active_dir is None:
        return None
    import jax

    d, _active_dir = _active_dir, None
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass
    path = newest_trace_path(d)
    if path:
        _last_trace = path
        try:
            from sagecal_tpu.obs.flight import note_activity

            note_activity(f"device_profile trace: {path}")
        except Exception:
            pass
    return path


@contextlib.contextmanager
def device_profile(log_dir: Optional[str] = None) -> Iterator[Optional[str]]:
    """Exception-safe capture scope: profiles the body when requested
    (argument or ``SAGECAL_DEVICE_PROFILE``), no-op otherwise; always
    stops a capture it started, so a crash still flushes a parseable
    trace."""
    d = start_device_profile(log_dir)
    try:
        yield d
    finally:
        if d is not None:
            stop_device_profile()


def last_trace_path() -> Optional[str]:
    """Path of the newest trace captured by this process, or None —
    what flight dumps and ``tpu_recovery_attempted`` attach."""
    return _last_trace


def newest_trace_path(root: str) -> Optional[str]:
    """Newest ``*.trace.json[.gz]`` under ``root`` (jax writes
    ``<root>/plugins/profile/<timestamp>/<host>.trace.json.gz``)."""
    hits: List[str] = []
    for pat in ("*.trace.json.gz", "*.trace.json"):
        hits.extend(glob.glob(os.path.join(root, "**", pat),
                              recursive=True))
    if not hits:
        return None
    return max(hits, key=lambda p: (os.path.getmtime(p), p))


# -------------------------------------------------------- fleet arming


def _arm_path(out_dir: str, worker_id: str) -> str:
    return os.path.join(out_dir, f"device_profile_arm.{worker_id}.json")


def arm_fleet_profile(out_dir: str, worker_id: str,
                      profile_dir: Optional[str] = None) -> str:
    """Coordinator side: atomically drop the flag file that arms one
    worker of a live fleet for a single profiled cycle."""
    profile_dir = profile_dir or os.path.join(
        out_dir, f"devprof_{worker_id}")
    os.makedirs(out_dir, exist_ok=True)
    path = _arm_path(out_dir, worker_id)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"worker_id": worker_id, "profile_dir": profile_dir}, f)
    os.replace(tmp, path)
    return path


def check_fleet_arm(out_dir: str, worker_id: str) -> Optional[dict]:
    """Worker side: the arm request for this worker, or None.  A
    corrupt/partial flag reads as un-armed (the coordinator's write is
    atomic, but the shared dir may not be POSIX)."""
    path = _arm_path(out_dir, worker_id)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            req = json.load(f)
    except Exception:
        return None
    req.setdefault("profile_dir",
                   os.path.join(out_dir, f"devprof_{worker_id}"))
    req["_path"] = path
    return req


def complete_fleet_arm(req: dict, trace_path: Optional[str]) -> str:
    """Worker side: retire the arm flag to ``.done`` carrying the trace
    path, so the coordinator (and a human tailing the dir) sees where
    the capture landed and the worker never re-profiles."""
    path = req["_path"]
    done = path + ".done"
    tmp = done + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"worker_id": req.get("worker_id"),
                   "trace_path": trace_path}, f)
    os.replace(tmp, done)
    try:
        os.remove(path)
    except OSError:
        pass
    return done


# --------------------------------------------------------------- parse


def read_trace_events(path: str) -> Tuple[List[dict], Dict[str, str]]:
    """Load a Chrome-trace file (gz or plain JSON) and return
    ``(trace_events, track_names)`` where track_names maps
    ``"pid/tid"`` to ``"process name/thread name"`` from the metadata
    events — the zero-dependency half of the parser."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", [])
    procs: Dict[str, str] = {}
    threads: Dict[str, str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            procs[str(e.get("pid"))] = str(args.get("name", ""))
        elif e.get("name") == "thread_name":
            threads[f"{e.get('pid')}/{e.get('tid')}"] = \
                str(args.get("name", ""))
    tracks: Dict[str, str] = {}
    for key, tname in threads.items():
        pid = key.split("/", 1)[0]
        tracks[key] = f"{procs.get(pid, '')}/{tname}"
    return events, tracks


def device_op_events(events: List[dict],
                     tracks: Dict[str, str]) -> List[dict]:
    """The complete ``X`` events that represent device-op execution:
    events carrying ``args.hlo_op`` (CPU thunk runtime stamps every op)
    or sitting on an ``XLA Ops`` thread (TPU device tracks)."""
    out: List[dict] = []
    for e in events:
        if e.get("ph") != "X" or e.get("dur") is None:
            continue
        args = e.get("args") or {}
        if "hlo_op" in args:
            out.append(e)
            continue
        track = tracks.get(f"{e.get('pid')}/{e.get('tid')}", "")
        if "XLA Ops" in track:
            out.append(e)
    return out


# ------------------------------------------------------- classification

# Ordered DMA rules run on the OP name first (a transfer inside any
# module is still a transfer), then module rules — batch patterns
# before fused ones because "fused_cost_packed_batch" contains both.
_DMA_OP_RE = re.compile(
    r"infeed|outfeed|copy|transfer|dma|send|recv|reshard|host.?to.?device"
    r"|device.?to.?host", re.I)
_MODULE_RULES: List[Tuple[re.Pattern, str]] = [
    (re.compile(r"packed_batch|minibatch_batch|serve_batch|_batch\b", re.I),
     "batched_grid"),
    (re.compile(r"fused_cost|fused_predict|bench_step_fused|mosaic"
                r"|tpu_custom_call|pallas", re.I), "fused_grid"),
    (re.compile(r"predict|coherency|hier", re.I), "xla_predict"),
    (re.compile(r"lbfgs|sagefit|lm_solve|rtr_solve|bench_step_xla"
                r"|robust|solve|step", re.I), "lbfgs_vector"),
]

KERNEL_FAMILIES = ("fused_grid", "batched_grid", "xla_predict",
                   "lbfgs_vector", "dma_infeed", "other")


def classify_kernel(module: str, op: str = "") -> str:
    """Kernel family for one (hlo_module, hlo_op) pair — the single
    classifier used for both trace events and ledger names, so the
    roofline join buckets both sides identically."""
    if op and _DMA_OP_RE.search(op):
        return "dma_infeed"
    name = module or op
    for pat, fam in _MODULE_RULES:
        if pat.search(name):
            return fam
    return "other"


# --------------------------------------------------------- attribution


def _self_durations(track_events: List[Tuple[float, float, int]]
                    ) -> Dict[int, float]:
    """Exclusive (self) duration per event on ONE track: a container
    event (the CPU thunk runtime nests while-loop/fusion bodies inside
    their parent's X event) is billed only for the time not covered by
    its children, so attribution sums to the track's busy union instead
    of double-counting every level of the nesting."""
    out: Dict[int, float] = {}
    stack: List[Tuple[float, int]] = []  # (end, event index)
    for ts, dur, idx in sorted(track_events):
        end = ts + dur
        out[idx] = dur
        while stack and stack[-1][0] <= ts:
            stack.pop()
        if stack:
            parent_end, parent = stack[-1]
            out[parent] -= min(end, parent_end) - ts
        stack.append((end, idx))
    return out


def _union_us(ivals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end) intervals (µs)."""
    if not ivals:
        return 0.0
    ivals.sort()
    total = 0.0
    cur_s, cur_e = ivals[0]
    for s, e in ivals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def _merged_windows(ivals: List[Tuple[float, float]],
                    gap_threshold_us: float) -> List[Tuple[float, float]]:
    """Busy windows: intervals merged whenever the gap between them is
    below the threshold — what's left between windows is host/dispatch
    time, the quantity the ~65 ms floor lives in."""
    if not ivals:
        return []
    ivals = sorted(ivals)
    out = [list(ivals[0])]
    for s, e in ivals[1:]:
        if s - out[-1][1] <= gap_threshold_us:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def attribute_trace(path: str,
                    gap_threshold_us: float = 1000.0) -> dict:
    """Parse one trace and attribute device time to kernel families.

    Returns ``{"trace_path", "n_op_events", "total_device_us",
    "span_us", "families": {fam: {time_us, events, top_ops}},
    "modules": {mod: {time_us, n_exec, family}},
    "dispatch": {n_windows, n_gaps, gap_total_us, gap_mean_us,
    gap_p50_us, gap_max_us, amortization}}``.

    - total device time is the union of per-track busy intervals (two
      ops overlapping on different device tracks count once) — the
      denominator the ≥95%-attribution acceptance check divides by;
      family times are summed SELF durations (container events like
      the CPU runtime's while-loop/fusion wrappers are billed only for
      time not covered by their nested children), so attribution can
      only fall short of 100% via unclassifiable events, never
      overshoot from double-counting nesting levels.
    - per-module ``n_exec`` is the MIN single-op-name count within the
      module: an op outside any loop emits exactly once per dispatch,
      so its count IS the number of executions inside the trace window
      (no process-lifetime counters trusted); loop-body ops emit once
      per *iteration* and would overcount by the trip count (a 20-iter
      LBFGS ``while_loop`` measured 280x), which is why max is wrong.
      Ops on a rarely-taken conditional branch could undercount — the
      lesser error for a ledger join that scales flops by ``n_exec``.
    - dispatch gaps are measured between merged busy windows; the
      ``amortization`` ratio (busy/(busy+gaps)) is how far whole-solve
      jits have amortized the dispatch floor.
    """
    events, tracks = read_trace_events(path)
    ops = device_op_events(events, tracks)

    families: Dict[str, dict] = {}
    modules: Dict[str, dict] = {}
    mod_op_counts: Dict[str, Dict[str, int]] = {}
    fam_op_times: Dict[str, Dict[str, float]] = {}
    per_track: Dict[str, List[Tuple[float, float]]] = {}
    track_idx: Dict[str, List[Tuple[float, float, int]]] = {}
    all_ivals: List[Tuple[float, float]] = []

    for i, e in enumerate(ops):
        dur = float(e.get("dur", 0.0))
        ts = float(e.get("ts", 0.0))
        key = f"{e.get('pid')}/{e.get('tid')}"
        per_track.setdefault(key, []).append((ts, ts + dur))
        track_idx.setdefault(key, []).append((ts, dur, i))
        all_ivals.append((ts, ts + dur))

    self_us: Dict[int, float] = {}
    for tevs in track_idx.values():
        self_us.update(_self_durations(tevs))

    for i, e in enumerate(ops):
        args = e.get("args") or {}
        mod = str(args.get("hlo_module", ""))
        op = str(args.get("hlo_op", e.get("name", "")))
        dur = max(self_us.get(i, 0.0), 0.0)
        fam = classify_kernel(mod, op)

        f = families.setdefault(fam, {"time_us": 0.0, "events": 0})
        f["time_us"] += dur
        f["events"] += 1
        fam_op_times.setdefault(fam, {})
        fam_op_times[fam][op] = fam_op_times[fam].get(op, 0.0) + dur

        if mod:
            m = modules.setdefault(mod, {"time_us": 0.0, "family": fam})
            m["time_us"] += dur
            mod_op_counts.setdefault(mod, {})
            mod_op_counts[mod][op] = mod_op_counts[mod].get(op, 0) + 1

    total_us = sum(_union_us(iv) for iv in per_track.values())
    for fam, f in families.items():
        tops = sorted(fam_op_times.get(fam, {}).items(),
                      key=lambda kv: -kv[1])
        f["top_ops"] = [{"op": k, "time_us": round(v, 1)}
                        for k, v in tops[:5]]
        f["time_us"] = round(f["time_us"], 3)
    for mod, m in modules.items():
        counts = mod_op_counts.get(mod, {})
        m["n_exec"] = min(counts.values()) if counts else 1
        m["time_us"] = round(m["time_us"], 3)

    dispatch: dict = {}
    if all_ivals:
        windows = _merged_windows(all_ivals, gap_threshold_us)
        gaps = [windows[i + 1][0] - windows[i][1]
                for i in range(len(windows) - 1)]
        gaps = [g for g in gaps if g > 0]
        busy = sum(e - s for s, e in windows)
        span = windows[-1][1] - windows[0][0]
        gaps_sorted = sorted(gaps)
        dispatch = {
            "n_windows": len(windows),
            "n_gaps": len(gaps),
            "gap_total_us": round(sum(gaps), 1),
            "gap_mean_us": round(sum(gaps) / len(gaps), 1) if gaps else 0.0,
            "gap_p50_us": round(gaps_sorted[len(gaps) // 2], 1)
            if gaps else 0.0,
            "gap_max_us": round(max(gaps), 1) if gaps else 0.0,
            "amortization": round(busy / span, 4) if span > 0 else 1.0,
        }
    span_us = (max(e for _, e in all_ivals) - min(s for s, _ in all_ivals)) \
        if all_ivals else 0.0

    return {
        "trace_path": path,
        "n_op_events": len(ops),
        "total_device_us": round(total_us, 3),
        "span_us": round(span_us, 3),
        "families": families,
        "modules": modules,
        "dispatch": dispatch,
    }


# --------------------------------------------------------- ledger join


def ledger_from_perf_stats() -> Dict[str, dict]:
    """Live ledger: the in-process ``instrumented_jit`` cost-analysis
    stats keyed by trace module name (``jit_<fn>``)."""
    from sagecal_tpu.obs.perf import perf_stats

    out: Dict[str, dict] = {}
    for name, st in perf_stats().items():
        out[f"jit_{name}"] = {"flops": st.get("flops"),
                              "bytes_accessed": st.get("bytes_accessed")}
    return out


def ledger_from_events(events_path: str) -> Dict[str, dict]:
    """Offline ledger: rebuild per-fn flops/bytes from the
    ``jit_compile`` events of a JSONL event log (last compile wins,
    matching the live ledger's semantics)."""
    out: Dict[str, dict] = {}
    try:
        with open(events_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except Exception:
                    continue
                # event logs stamp the kind under "type" (events.py);
                # accept "event" too for hand-rolled ledgers
                if ev.get("type", ev.get("event")) != "jit_compile":
                    continue
                fn = ev.get("fn")
                if not fn:
                    continue
                out[f"jit_{fn}"] = {
                    "flops": ev.get("flops"),
                    "bytes_accessed": ev.get("bytes_accessed"),
                }
    except OSError:
        pass
    return out
